"""k8s policy object -> api.Rule parsing.

Reference: pkg/k8s/network_policy.go — both CiliumNetworkPolicy CRDs
(whose spec *is* an api.Rule, namespace-scoped on parse) and native
k8s NetworkPolicy objects (podSelector/namespaceSelector/ipBlock
translated into selectors and CIDR sets).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..labels import SOURCE_K8S, Label, LabelArray
from ..policy.api import (CIDRRule, EgressRule, EndpointSelector,
                          IngressRule, PolicyError, PortProtocol,
                          PortRule, Rule)
from ..policy.api import Operator, Requirement
from ..policy.jsonio import rule_from_dict, selector_from_dict

# Reference: pkg/k8s/network_policy.go k8sConst — the namespace label
# every pod carries and the derived-policy bookkeeping labels.
NAMESPACE_LABEL_KEY = "io.kubernetes.pod.namespace"
POLICY_LABEL_NAME = "io.cilium.k8s.policy.name"
POLICY_LABEL_NAMESPACE = "io.cilium.k8s.policy.namespace"


def _ns_requirement(namespace: str) -> Dict[str, str]:
    return {f"k8s:{NAMESPACE_LABEL_KEY}": namespace}


def _scope_selector(sel: EndpointSelector,
                    namespace: str) -> EndpointSelector:
    """Inject the namespace match unless the selector already pins a
    namespace (network_policy.go parseToCiliumRule)."""
    key = f"k8s.{NAMESPACE_LABEL_KEY}"
    ml = dict(sel.match_labels)
    if any(k.endswith(NAMESPACE_LABEL_KEY) for k in ml):
        return sel
    ml[key] = namespace
    return EndpointSelector(match_labels=ml,
                            match_expressions=[
                                r for r in sel.requirements
                                if r.key not in sel.match_labels],
                            _raw_keys=True)


def _derived_labels(name: str, namespace: str) -> LabelArray:
    return LabelArray([
        Label(key=POLICY_LABEL_NAME, value=name, source=SOURCE_K8S),
        Label(key=POLICY_LABEL_NAMESPACE, value=namespace,
              source=SOURCE_K8S),
    ])


def _scope_rule(rule: Rule, namespace: str, name: str) -> Rule:
    rule.endpoint_selector = _scope_selector(rule.endpoint_selector,
                                             namespace)
    for ing in rule.ingress:
        ing.from_endpoints = [_scope_selector(s, namespace)
                              for s in ing.from_endpoints]
        ing.from_requires = [_scope_selector(s, namespace)
                             for s in ing.from_requires]
    for eg in rule.egress:
        eg.to_endpoints = [_scope_selector(s, namespace)
                           for s in eg.to_endpoints]
        eg.to_requires = [_scope_selector(s, namespace)
                          for s in eg.to_requires]
    rule.labels = LabelArray(tuple(rule.labels) +
                             tuple(_derived_labels(name, namespace)))
    return rule


def parse_cnp(obj: Dict) -> List[Rule]:
    """CiliumNetworkPolicy -> namespace-scoped rules.

    Accepts ``spec`` (one rule) or ``specs`` (list) —
    network_policy.go's CNP parse path."""
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace", "default")
    if not name:
        raise PolicyError("CNP missing metadata.name")
    specs = []
    if obj.get("spec"):
        specs.append(obj["spec"])
    specs.extend(obj.get("specs") or [])
    if not specs:
        raise PolicyError(f"CNP {name}: neither spec nor specs present")
    rules = []
    for spec in specs:
        rule = rule_from_dict(spec)
        rules.append(_scope_rule(rule, namespace, name).sanitize())
    return rules


# shared with the watcher's endpoint-label side: selectors built from
# namespaceSelector use "k8s." + this base as their key prefix, and the
# watcher stamps endpoint labels with source k8s + the same base —
# they must stay in lockstep or namespaceSelector policies silently
# stop matching
NS_LABELS_BASE = "io.cilium.k8s.namespace.labels"
_NS_LABELS_PREFIX = f"k8s.{NS_LABELS_BASE}."


def _parse_np_peer(peer: Dict, namespace: str):
    """One NetworkPolicyPeer -> (selector | None, cidr_rule | None)."""
    ip_block = peer.get("ipBlock")
    if ip_block:
        return None, CIDRRule(
            cidr=ip_block["cidr"],
            except_cidrs=tuple(ip_block.get("except", ())))
    pod = peer.get("podSelector")
    ns = peer.get("namespaceSelector")
    ml: Dict[str, str] = {}
    exprs: List[Requirement] = []
    if ns is not None:
        # namespaceSelector matches namespace *labels*; the reference
        # prefixes them into the namespace-labels key space
        for k, v in (ns.get("matchLabels") or {}).items():
            ml[f"{_NS_LABELS_PREFIX}{k}"] = v
        for e in ns.get("matchExpressions") or []:
            exprs.append(Requirement(
                key=f"{_NS_LABELS_PREFIX}{e['key']}",
                operator=Operator(e["operator"]),
                values=tuple(e.get("values") or ())))
        # empty namespaceSelector == all namespaces (no constraint)
    else:
        ml[f"k8s.{NAMESPACE_LABEL_KEY}"] = namespace
    if pod is not None:
        scoped = selector_from_dict(pod)
        for k, v in scoped.match_labels.items():
            ml[k] = v
        # keep matchExpressions — dropping them would over-match
        exprs.extend(r for r in scoped.requirements
                     if r.key not in scoped.match_labels)
    sel = EndpointSelector(match_labels=ml, match_expressions=exprs,
                           _raw_keys=True)
    return sel, None


def _parse_np_ports(ports: List[Dict]) -> List[PortRule]:
    if not ports:
        return []
    pps = []
    for p in ports:
        port = p.get("port")
        if port is None:
            continue
        pps.append(PortProtocol(port=str(port),
                                protocol=p.get("protocol", "TCP")))
    return [PortRule(ports=pps)] if pps else []


def parse_network_policy(obj: Dict) -> List[Rule]:
    """Native k8s NetworkPolicy -> rules (network_policy.go
    ParseNetworkPolicy)."""
    meta = obj.get("metadata") or {}
    name = meta.get("name", "")
    namespace = meta.get("namespace", "default")
    spec = obj.get("spec") or {}
    pod_sel = selector_from_dict(spec.get("podSelector") or {})
    pod_sel = _scope_selector(pod_sel, namespace)

    ingress: List[IngressRule] = []
    for ing in spec.get("ingress") or []:
        froms = ing.get("from") or []
        selectors, cidr_rules = [], []
        for peer in froms:
            sel, cidr = _parse_np_peer(peer, namespace)
            if sel is not None:
                selectors.append(sel)
            if cidr is not None:
                cidr_rules.append(cidr)
        ports = _parse_np_ports(ing.get("ports") or [])
        # L3 member exclusivity: selectors and CIDRs become separate
        # IngressRules; CIDR peers carry no L4 restriction in this rule
        # model (rule_validation.go: FromCIDRSet + ToPorts unsupported)
        if selectors or not cidr_rules:
            ingress.append(IngressRule(from_endpoints=selectors,
                                       to_ports=list(ports)))
        if cidr_rules:
            ingress.append(IngressRule(from_cidr_set=cidr_rules))
    egress: List[EgressRule] = []
    for eg in spec.get("egress") or []:
        tos = eg.get("to") or []
        selectors, cidr_rules = [], []
        for peer in tos:
            sel, cidr = _parse_np_peer(peer, namespace)
            if sel is not None:
                selectors.append(sel)
            if cidr is not None:
                cidr_rules.append(cidr)
        ports = _parse_np_ports(eg.get("ports") or [])
        if selectors or not cidr_rules:
            egress.append(EgressRule(to_endpoints=selectors,
                                     to_ports=list(ports)))
        if cidr_rules:
            # ToCIDRSet supports L4 on egress (rule_validation.go)
            egress.append(EgressRule(to_cidr_set=cidr_rules,
                                     to_ports=list(ports)))
    rule = Rule(endpoint_selector=pod_sel, ingress=ingress, egress=egress,
                labels=_derived_labels(name, namespace))
    return [rule.sanitize()]
