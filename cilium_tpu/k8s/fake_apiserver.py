"""In-repo fake Kubernetes apiserver speaking the real list/watch wire.

Reference: the agent's informers talk HTTP to a real apiserver
(daemon/k8s_watcher.go:70-78 builds client-go informers).  This
environment has zero egress, so the transport is tested against this
fake instead: a threaded HTTP server implementing the protocol subset
client-go's Reflector actually uses —

- ``GET <prefix>/<resource>``: list; returns ``{"kind": ..., "items":
  [...], "metadata": {"resourceVersion": "<R>"}}`` where R is the
  store's current global version;
- ``GET <prefix>/<resource>?watch=true&resourceVersion=<R>``: a
  chunked, newline-delimited JSON stream of ``{"type": "ADDED" |
  "MODIFIED" | "DELETED", "object": {...}}`` events with version > R,
  held open until the client or the server drops it;
- **410 Gone**: the event history is bounded (and compactable on
  demand); a watch from a compacted-away version streams one
  ``{"type": "ERROR", "object": {"kind": "Status", "code": 410}}``
  event — the reflector must full-relist (client-go's
  ``resourceVersion too old`` path).

The Python-level control surface (``upsert``/``delete``/
``disconnect_watchers``/``compact``) is the test's hand on the cluster:
existing replay fixtures become scripts driving it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple
from urllib.parse import parse_qs, urlparse

# resource path -> canonical resource name; mirrors the group/version
# layout the reference watches (daemon/k8s_watcher.go:549-560)
RESOURCE_PATHS = {
    "/apis/cilium.io/v2/ciliumnetworkpolicies": "ciliumnetworkpolicies",
    "/apis/networking.k8s.io/v1/networkpolicies": "networkpolicies",
    "/api/v1/services": "services",
    "/api/v1/endpoints": "endpoints",
    "/api/v1/pods": "pods",
    "/api/v1/nodes": "nodes",
    "/api/v1/namespaces": "namespaces",
    "/apis/networking.k8s.io/v1/ingresses": "ingresses",
}

LIST_KINDS = {
    "ciliumnetworkpolicies": "CiliumNetworkPolicyList",
    "networkpolicies": "NetworkPolicyList",
    "services": "ServiceList",
    "endpoints": "EndpointsList",
    "pods": "PodList",
    "nodes": "NodeList",
    "namespaces": "NamespaceList",
    "ingresses": "IngressList",
}


class _Store:
    """One resource's objects + the shared event history."""

    def __init__(self):
        self.objects: Dict[Tuple[str, str], Dict] = {}


class FakeAPIServer:
    """Threaded fake apiserver; start() binds an ephemeral port."""

    def __init__(self, history_limit: int = 1024):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rv = 0
        self._stores: Dict[str, _Store] = {
            name: _Store() for name in RESOURCE_PATHS.values()}
        # (rv, resource, type, object snapshot); bounded
        self._history: List[Tuple[int, str, str, Dict]] = []
        self._history_limit = history_limit
        self._oldest_rv = 0      # lowest rv still replayable
        self._watch_epoch = 0    # bump = kill live watch streams
        self.watch_requests = 0
        self.list_requests = 0
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        httpd.fake = self
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._thread = threading.Thread(
            target=httpd.serve_forever, daemon=True, name="fake-apiserver")

    # ------------------------------------------------------- lifecycle

    def start(self) -> "FakeAPIServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        with self._cond:
            self._watch_epoch += 1
            self._cond.notify_all()
        self._httpd.shutdown()
        self._httpd.server_close()

    @property
    def base_url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    # --------------------------------------------------- control plane

    def upsert(self, resource: str, obj: Dict) -> int:
        """Create or replace an object; stamps metadata.resourceVersion
        and records an ADDED/MODIFIED event.  Returns the new rv."""
        meta = obj.setdefault("metadata", {})
        key = (meta.get("namespace", ""), meta.get("name", ""))
        with self._cond:
            self._rv += 1
            meta["resourceVersion"] = str(self._rv)
            store = self._stores[resource]
            etype = "MODIFIED" if key in store.objects else "ADDED"
            snapshot = json.loads(json.dumps(obj))
            store.objects[key] = snapshot
            self._append_history(resource, etype, snapshot)
            self._cond.notify_all()
            return self._rv

    def delete(self, resource: str, namespace: str, name: str) -> bool:
        with self._cond:
            store = self._stores[resource]
            obj = store.objects.pop((namespace, name), None)
            if obj is None:
                return False
            self._rv += 1
            # deep copy: the popped snapshot's metadata dict is shared
            # with the history's ADDED/MODIFIED entries — stamping the
            # delete rv in place would corrupt their recorded versions
            obj = json.loads(json.dumps(obj))
            obj.setdefault("metadata", {})["resourceVersion"] = \
                str(self._rv)
            self._append_history(resource, "DELETED", obj)
            self._cond.notify_all()
            return True

    def disconnect_watchers(self) -> None:
        """Drop every live watch stream (network blip / apiserver
        restart simulation).  Clients must reconnect from their last
        seen resourceVersion."""
        with self._cond:
            self._watch_epoch += 1
            self._cond.notify_all()

    def compact(self) -> None:
        """Discard the whole event history: any watch from a version
        before now gets 410 Gone (etcd compaction analog)."""
        with self._cond:
            self._history.clear()
            self._oldest_rv = self._rv
            self._cond.notify_all()

    def _append_history(self, resource, etype, obj) -> None:
        self._history.append((self._rv, resource, etype, obj))
        if len(self._history) > self._history_limit:
            drop = len(self._history) - self._history_limit
            self._oldest_rv = self._history[drop - 1][0]
            del self._history[:drop]


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):  # quiet
        pass

    def do_GET(self):  # noqa: N802 — http.server contract
        fake: FakeAPIServer = self.server.fake
        url = urlparse(self.path)
        resource = RESOURCE_PATHS.get(url.path)
        if resource is None:
            self._json(404, {"kind": "Status", "code": 404,
                             "message": f"unknown path {url.path}"})
            return
        qs = parse_qs(url.query)
        if qs.get("watch", ["false"])[0] in ("true", "1"):
            self._watch(fake, resource, qs)
        else:
            self._list(fake, resource)

    # ------------------------------------------------------------ list

    def _list(self, fake: FakeAPIServer, resource: str) -> None:
        with fake._cond:
            fake.list_requests += 1
            items = list(fake._stores[resource].objects.values())
            rv = fake._rv
        self._json(200, {"kind": LIST_KINDS[resource],
                         "apiVersion": "v1",
                         "metadata": {"resourceVersion": str(rv)},
                         "items": items})

    # ----------------------------------------------------------- watch

    def _watch(self, fake: FakeAPIServer, resource: str, qs) -> None:
        try:
            since = int(qs.get("resourceVersion", ["0"])[0])
        except ValueError:
            since = 0
        with fake._cond:
            fake.watch_requests += 1
            gone = since < fake._oldest_rv
            epoch = fake._watch_epoch
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        if gone:
            # client-go's "resourceVersion too old": one ERROR event,
            # then the stream ends; the reflector must relist
            self._chunk({"type": "ERROR",
                         "object": {"kind": "Status", "code": 410,
                                    "reason": "Expired",
                                    "message": "resourceVersion too "
                                               "old"}})
            self._chunk_end()
            return
        cursor = since
        try:
            while True:
                with fake._cond:
                    idle = False
                    while True:
                        if fake._watch_epoch != epoch:
                            raise ConnectionAbortedError
                        pending = [
                            (rv, et, obj)
                            for rv, res, et, obj in fake._history
                            if res == resource and rv > cursor]
                        if pending:
                            break
                        if not fake._cond.wait(timeout=0.5):
                            idle = True
                            break
                    rv_now = fake._rv
                if idle:
                    # heartbeat on idle ticks (watch BOOKMARK analog,
                    # mirroring mini_etcd's progress notify): the
                    # write is what surfaces an abandoned client as
                    # BrokenPipeError so this handler thread exits
                    # instead of spinning on cond.wait forever
                    self._chunk({"type": "BOOKMARK", "object": {
                        "metadata": {"resourceVersion": str(rv_now)}}})
                    continue
                for rv, etype, obj in pending:
                    self._chunk({"type": etype, "object": obj})
                    cursor = rv
        except (ConnectionAbortedError, BrokenPipeError, OSError):
            try:
                self._chunk_end()
            except OSError:
                pass
            # tell http.server not to reuse the half-dead stream
            self.close_connection = True

    # ------------------------------------------------------------ util

    def _chunk(self, obj: Dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
        self.wfile.flush()

    def _chunk_end(self) -> None:
        self.wfile.write(b"0\r\n\r\n")
        self.wfile.flush()

    def _json(self, code: int, obj: Dict) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)
