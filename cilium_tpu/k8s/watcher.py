"""k8s event watcher driving the daemon.

Reference: daemon/k8s_watcher.go — informers for CNPs, k8s
NetworkPolicies, Services, Endpoints, Pods, Nodes, Namespaces and
Ingresses feed the policy repository, the service/endpoint state, the
ipcache, and node tunneling; the agent reports per-node CNP status
back (k8s_watcher.go:1748 cnpNodeStatusController).  Here the watcher
is a sink for an event stream (dicts shaped like k8s watch events);
any source — a test, a file replay, or a real apiserver client —
pushes into it.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ..identity import RESERVED_UNMANAGED
from ..labels import LabelArray, Label, SOURCE_K8S
from ..node import Node, NodeAddress
from ..utils.serializer import FunctionQueue
from .policy import (NS_LABELS_BASE, POLICY_LABEL_NAME,
                     POLICY_LABEL_NAMESPACE, parse_cnp,
                     parse_network_policy)
from .translate import endpoints_to_ips, translate_to_services

# namespace meta labels carried onto pods in that namespace
# (reference: ciliumio.PodNamespaceMetaLabels prefix) — one constant
# shared with the selector side (k8s/policy.py) so namespaceSelector
# matching can't silently drift
NS_META_PREFIX = NS_LABELS_BASE


def _policy_key_labels(name: str, namespace: str) -> LabelArray:
    return LabelArray([
        Label(key=POLICY_LABEL_NAME, value=name, source=SOURCE_K8S),
        Label(key=POLICY_LABEL_NAMESPACE, value=namespace,
              source=SOURCE_K8S)])


class K8sWatcher:
    """Apply k8s object events to a Daemon."""

    def __init__(self, daemon, ingress_host_ip: str = "192.168.254.1"):
        self.daemon = daemon
        self._lock = threading.Lock()
        # (namespace, service) -> backend ips, for ToServices
        self._endpoints: Dict[tuple, List[str]] = {}
        # (namespace, service) -> {"headless": bool, "ports": [...]}
        self._services: Dict[tuple, Dict] = {}
        # (namespace, cnp name) -> {node: status dict} — the per-node
        # CNP status the reference writes back to the apiserver
        # (k8s_watcher.go:1834 updateCNPNodeStatus)
        self.cnp_status: Dict[tuple, Dict[str, Dict]] = {}
        # namespace -> its labels (for pod namespace meta labels)
        self._ns_labels: Dict[str, Dict[str, str]] = {}
        # the address ingress frontends resolve to on this node
        # (reference: option.Config.HostV4Addr)
        self.ingress_host_ip = ingress_host_ip
        # (namespace, ingress name) -> (service name, servicePort)
        self._ingresses: Dict[tuple, tuple] = {}
        # (namespace, ingress name) -> last programmed frontend port
        self._ingress_ports: Dict[tuple, int] = {}
        # (namespace, pod name) -> last known podIP (for IP-change
        # cleanup on modified events)
        self._pod_ips: Dict[tuple, str] = {}
        self.events_processed = 0
        self.events_by_kind: Dict[str, int] = {}
        # async dispatch state: one ordered FunctionQueue per resource
        # kind + last applied resourceVersion per object (staleness
        # dedup, pkg/versioned analog)
        self._queues: Dict[str, FunctionQueue] = {}
        self._resource_versions: Dict[tuple, str] = {}
        self._apply_lock = threading.RLock()
        self._stopped = False

    # ------------------------------------------------------------ policy

    def on_cnp(self, action: str, obj: Dict) -> None:
        """action: added | modified | deleted
        (k8s_watcher.go addCiliumNetworkPolicyV2 et al.).  Records the
        per-node enforcement status the reference writes back into the
        CNP's Status.Nodes map (cnpNodeStatusController): ok/enforcing
        with the realized revision on success, the import error
        otherwise."""
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        skey = (namespace, name)
        key = _policy_key_labels(name, namespace)
        node = self.daemon.node_name
        if action in ("added", "modified"):
            try:
                rules = parse_cnp(obj)
                self._retranslate(rules)
                rev = self.daemon.policy_add(rules, replace=True)
            except Exception as e:  # noqa: BLE001 — report, don't die
                self.cnp_status.setdefault(skey, {})[node] = {
                    "ok": False, "enforcing": False, "error": repr(e),
                    "lastUpdated": time.time()}
                self._count("cnp")
                return
            # enforcing = every endpoint realized the revision; the
            # reference waits via a controller — one shared status
            # worker drains a queue (per-event threads would pile up
            # under CNP churn, all polling the endpoint list)
            self.cnp_status.setdefault(skey, {})[node] = {
                "ok": True, "enforcing": False, "revision": rev,
                "lastUpdated": time.time()}
            self._status_queue_put(skey, node, rev)
        elif action == "deleted":
            self.daemon.policy_delete(key)
            self.cnp_status.pop(skey, None)
        self._count("cnp")

    def get_cnp_status(self, namespace: str, name: str
                       ) -> Dict[str, Dict]:
        """The CNP's per-node status map (Status.Nodes analog)."""
        return dict(self.cnp_status.get((namespace, name), {}))

    def _status_queue_put(self, skey: tuple, node: str,
                          rev: int) -> None:
        import queue as _queue
        with self._lock:
            if not hasattr(self, "_status_q"):
                self._status_q: "_queue.Queue" = _queue.Queue()
                threading.Thread(target=self._status_worker,
                                 daemon=True,
                                 name="cnp-status").start()
        self._status_q.put((skey, node, rev))

    def _status_worker(self) -> None:
        """Single controller draining enforcement-status work items
        (cnpNodeStatusController analog)."""
        while True:
            skey, node, rev = self._status_q.get()
            ok = self.daemon.wait_for_policy_revision(rev, timeout=30)
            st = self.cnp_status.get(skey, {}).get(node)
            if ok and st is not None and st.get("revision") == rev:
                st["enforcing"] = True
                st["lastUpdated"] = time.time()

    def on_network_policy(self, action: str, obj: Dict) -> None:
        meta = obj.get("metadata") or {}
        key = _policy_key_labels(meta.get("name", ""),
                                 meta.get("namespace", "default"))
        if action in ("added", "modified"):
            rules = parse_network_policy(obj)
            self.daemon.policy_add(rules, replace=True)
        elif action == "deleted":
            self.daemon.policy_delete(key)
        self._count("network-policy")

    # --------------------------------------------------------- services

    def on_service(self, action: str, obj: Dict) -> None:
        """ClusterIP services program the LB (k8s_watcher.go
        addK8sServiceV1)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        vip = spec.get("clusterIP")
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        if not vip or vip == "None":
            # headless service: tracked (its Endpoints still drive
            # ToServices translation) but never programmed into the LB
            # (k8s_watcher.go:801-805, :957)
            if action == "deleted":
                self._services.pop(key, None)
            else:
                self._services[key] = {"headless": True,
                                       "ports": spec.get("ports") or []}
            self._count("service")
            return
        if action == "deleted":
            self._services.pop(key, None)
            for p in spec.get("ports") or []:
                self.daemon.service_delete(vip, int(p.get("port", 0)))
        else:
            # a modified spec that drops a port must tear that
            # frontend down, or it keeps forwarding forever
            old = self._services.get(key) or {}
            new_ports = {int(p.get("port", 0))
                         for p in spec.get("ports") or []}
            for p in old.get("ports") or []:
                if int(p.get("port", 0)) not in new_ports:
                    self.daemon.service_delete(
                        old.get("vip", vip), int(p.get("port", 0)))
            self._services[key] = {"headless": False, "vip": vip,
                                   "ports": spec.get("ports") or []}
            backends = self._endpoints.get(key, [])
            for p in spec.get("ports") or []:
                port = int(p.get("port", 0))
                try:
                    target = int(p.get("targetPort") or port)
                except (TypeError, ValueError):
                    # named targetPort: resolving it needs pod specs;
                    # fall back to the service port (reference resolves
                    # through Endpoints ports)
                    target = port
                self.daemon.service_upsert(
                    vip, port, [(ip, target) for ip in backends])
        # the service spec (e.g. targetPort) feeds ingress frontends
        self._resync_ingresses_for(key[0], key[1])
        self._count("service")

    def on_endpoints(self, action: str, obj: Dict) -> None:
        """Endpoints drive both LB backends and ToServices translation
        (k8s_watcher.go addK8sEndpointV1 + rule_translate)."""
        meta = obj.get("metadata") or {}
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        ips = [] if action == "deleted" else endpoints_to_ips(obj)
        rules = self.daemon.repo.rules
        with self._lock:
            # translate inside the lock: two events for the same service
            # applied out of order would leave a decommissioned
            # backend's generated CIDR allowed forever (old_ips of the
            # later event would never name it again)
            old_ips = self._endpoints.get(key, [])
            self._endpoints[key] = ips
            touched = translate_to_services(rules, key[1], key[0], ips,
                                            old_backend_ips=old_ips)
            if touched:
                # Heal shared backends: when two services select the
                # same pod IP, removing this service's old CIDRs also
                # removed the sibling's (ownership can't be inferred
                # from IP containment alone).  Re-translating every
                # other known service re-adds anything it still owns —
                # idempotent, since translate replaces-in-place.
                for (ns, svc), sips in self._endpoints.items():
                    if (ns, svc) != key:
                        translate_to_services(rules, svc, ns, sips)
        if touched:
            # the new backend /32s need CIDR identities + ipcache
            # entries before the regenerated policy can match them
            self.daemon.resync_rule_prefixes(rules)
            self.daemon.trigger_policy_updates("k8s-endpoints")
        self._resync_ingresses_for(key[0], key[1])
        self._count("endpoints")

    # ------------------------------------------------------------- pods

    def on_pod(self, action: str, obj: Dict) -> None:
        """Pods feed the ipcache (podIP -> unmanaged identity until the
        allocator decides — k8s_watcher.go:1964 updatePodHostIP) and
        pod label changes re-resolve the endpoint's identity
        (:2041 updateK8sPodV1)."""
        meta = obj.get("metadata") or {}
        status = obj.get("status") or {}
        spec = obj.get("spec") or {}
        namespace = meta.get("namespace", "default")
        name = meta.get("name", "")
        pkey = (namespace, name)
        pod_ip = status.get("podIP", "")
        host_ip = status.get("hostIP", "")
        if action == "deleted":
            known = self._pod_ips.pop(pkey, "") or pod_ip
            if known:
                self.daemon.ipcache.delete(known, "k8s")
            self._count("pod")
            return
        # ipcache mapping — skipped for host-networking pods or before
        # an IP is assigned, exactly like updatePodHostIP.  A changed
        # podIP (sandbox restart) drops the stale entry first, or IPAM
        # reuse would leave a shadowing unmanaged mapping behind.
        old_ip = self._pod_ips.get(pkey, "")
        if not spec.get("hostNetwork") and pod_ip and host_ip:
            if old_ip and old_ip != pod_ip:
                self.daemon.ipcache.delete(old_ip, "k8s")
            self.daemon.ipcache.upsert(pod_ip, RESERVED_UNMANAGED,
                                       "k8s", host_ip=host_ip,
                                       metadata=f"pod:{namespace}/{name}")
            self._pod_ips[pkey] = pod_ip
        if action == "modified":
            # label updates re-resolve the pod's endpoint identity;
            # namespace meta labels ride along (reference both paths)
            ep = self.daemon.endpoints.lookup_container(
                f"{namespace}/{name}")
            if ep is not None:
                self.daemon.endpoint_update_labels(
                    ep.id, self._merged_labels(
                        ep, namespace, meta.get("labels") or {}))
        self._count("pod")

    def _pod_identity_labels(self, namespace: str,
                             pod_labels: Dict[str, str]) -> List[str]:
        out = [f"k8s:{k}={v}" for k, v in sorted(pod_labels.items())]
        for k, v in sorted(self._ns_labels.get(namespace, {}).items()):
            out.append(f"k8s:{NS_META_PREFIX}.{k}={v}")
        return out

    def _merged_labels(self, ep, namespace: str,
                       pod_labels: Dict[str, str]) -> List[str]:
        """New full label set for the endpoint: its NON-k8s labels are
        preserved (update_labels replaces the whole set — dropping a
        container:/custom label would flip the identity wrongly), k8s
        pod labels + namespace meta labels are rebuilt."""
        keep = [str(lb) for lb in ep.labels.values()
                if lb.source != SOURCE_K8S]
        return keep + self._pod_identity_labels(namespace, pod_labels)

    # ------------------------------------------------------------ nodes

    def on_node(self, action: str, obj: Dict) -> None:
        """Node events program per-node tunneling + ipcache
        (k8s_watcher.go:2303 addK8sNodeV1 -> updateK8sNodeTunneling)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        status = obj.get("status") or {}
        name = meta.get("name", "")
        if action == "deleted":
            self.daemon.node_manager.node_deleted(
                f"{self.daemon.config.cluster_name}/{name}")
            self._count("node")
            return
        addresses = [NodeAddress(a.get("type", ""), a.get("address", ""))
                     for a in status.get("addresses") or []]
        node = Node(name=name,
                    cluster=self.daemon.config.cluster_name,
                    addresses=addresses,
                    ipv4_alloc_cidr=spec.get("podCIDR") or None)
        self.daemon.node_manager.node_updated(node)
        self._count("node")

    # ------------------------------------------------------- namespaces

    def on_namespace(self, action: str, obj: Dict) -> None:
        """Namespace label changes re-resolve identities of every
        endpoint in the namespace (k8s_watcher.go:2145
        updateK8sV1Namespace — labels carried under the namespace meta
        prefix)."""
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        new_labels = dict(meta.get("labels") or {})
        old_labels = self._ns_labels.get(name, {})
        if action == "deleted":
            self._ns_labels.pop(name, None)
            self._count("namespace")
            return
        self._ns_labels[name] = new_labels
        if new_labels == old_labels:
            self._count("namespace")
            return
        prefix = f"{name}/"
        for ep in self.daemon.endpoints.endpoints():
            cn = ep.container_name or ""
            if not cn.startswith(prefix):
                continue
            pod_labels = {
                lb.key: lb.value for lb in ep.labels.values()
                if lb.source == SOURCE_K8S and
                not lb.key.startswith(NS_META_PREFIX)}
            self.daemon.endpoint_update_labels(
                ep.id, self._merged_labels(ep, name, pod_labels))
        self._count("namespace")

    # ---------------------------------------------------------- ingress

    def on_ingress(self, action: str, obj: Dict) -> None:
        """Single-service ingress -> an external frontend on the host
        address forwarding to the backing service's backends
        (k8s_watcher.go:1376 addIngressV1beta1 + syncExternalLB)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        backend = spec.get("backend") or {}
        svc_name = backend.get("serviceName", "")
        if not svc_name:
            self._count("ingress")
            return  # only single-service ingress is supported
        namespace = meta.get("namespace", "default")
        key = (namespace, meta.get("name", ""))
        try:
            port = int(backend.get("servicePort") or 0)
        except (TypeError, ValueError):
            self._count("ingress")
            return
        if action == "deleted":
            self._ingresses.pop(key, None)
            old_port = self._ingress_ports.pop(key, None)
            if old_port:
                self.daemon.service_delete(self.ingress_host_ip,
                                           old_port)
            self._count("ingress")
            return
        # a changed servicePort must drop the old frontend, or traffic
        # to the stale host port keeps forwarding forever
        old_port = self._ingress_ports.get(key)
        if old_port and old_port != port:
            self.daemon.service_delete(self.ingress_host_ip, old_port)
        self._ingresses[key] = (svc_name, port)
        self._program_ingress(key)
        self._count("ingress")

    def _ingress_target_port(self, namespace: str, svc_name: str,
                             service_port: int) -> Optional[int]:
        """Resolve the backing service's targetPort for the ingress
        servicePort (reference resolves through the service spec).
        None when the service is unknown — the frontend must be torn
        down, not re-programmed with a guessed target port."""
        svc = self._services.get((namespace, svc_name))
        if not svc:
            return None
        for p in svc.get("ports") or []:
            if int(p.get("port", 0)) == service_port:
                try:
                    return int(p.get("targetPort") or service_port)
                except (TypeError, ValueError):
                    return service_port  # named port fallback
        return service_port

    def _program_ingress(self, key: tuple) -> None:
        svc_name, port = self._ingresses[key]
        namespace = key[0]
        target = self._ingress_target_port(namespace, svc_name, port)
        if target is None:
            # backing service gone: tear the frontend down rather than
            # forward to a guessed (wrong) pod port
            old_port = self._ingress_ports.pop(key, None)
            if old_port:
                self.daemon.service_delete(self.ingress_host_ip,
                                           old_port)
            return
        backends = self._endpoints.get((namespace, svc_name), [])
        self.daemon.service_upsert(
            self.ingress_host_ip, port,
            [(ip, target) for ip in backends])
        self._ingress_ports[key] = port

    def _resync_ingresses_for(self, namespace: str,
                              svc_name: str) -> None:
        """Endpoints/service churn re-programs dependent ingress
        frontends (syncExternalLB on endpoint events)."""
        for key, (svc, _port) in list(self._ingresses.items()):
            if key[0] == namespace and svc == svc_name:
                self._program_ingress(key)

    # ------------------------------------------------- async dispatch

    _HANDLERS = {
        "cnp": "on_cnp", "networkpolicy": "on_network_policy",
        "service": "on_service", "endpoints": "on_endpoints",
        "pod": "on_pod", "node": "on_node",
        "namespace": "on_namespace", "ingress": "on_ingress",
    }

    _ACTIONS = {"add": "added", "added": "added",
                "modify": "modified", "modified": "modified",
                "delete": "deleted", "deleted": "deleted"}

    def enqueue_event(self, kind: str, action: str, obj: Dict,
                      retries: int = 0) -> bool:
        """Informer-side entry: apply the event asynchronously, in
        arrival order per resource kind, skipping stale duplicates.

        Reference shape: each resource type gets its own
        serializer.FunctionQueue (daemon/k8s_watcher.go's
        serializer per informer) and events carrying an older-or-equal
        resourceVersion than the last seen one for that object are
        dropped (pkg/versioned's equality/staleness check).  Handler
        APPLICATION is serialized by one re-entrant lock across kinds
        — watcher-local state (_services/_endpoints/_ns_labels/...) is
        shared, so per-kind queues give ordering + a non-blocking
        informer thread, not concurrent mutation.  A handler that
        still fails after `retries` attempts (spaced by a short
        backoff) rolls its resourceVersion record back so the
        informer's resync of the same object is NOT dropped as stale.
        Returns False when the event was dropped as stale.
        """
        action = self._ACTIONS[action]          # KeyError on junk
        handler = getattr(self, self._HANDLERS[kind])
        meta = obj.get("metadata", {})
        okey = (kind, meta.get("namespace", ""), meta.get("name", ""))
        # k8s declares resourceVersions opaque; only decimal ones can
        # be ordered — anything else bypasses dedup instead of killing
        # the informer thread
        rv = meta.get("resourceVersion")
        rv_num = int(rv) if isinstance(rv, str) and rv.isdigit() \
            else None
        with self._lock:
            if self._stopped:
                raise RuntimeError("K8sWatcher is stopped")
            prev = self._resource_versions.get(okey)
            if rv_num is not None and action != "deleted":
                if prev is not None and rv_num <= prev:
                    return False  # stale replay/duplicate
                self._resource_versions[okey] = rv_num
            if action == "deleted":
                self._resource_versions.pop(okey, None)
            fq = self._queues.get(kind)
            if fq is None:
                fq = self._queues[kind] = FunctionQueue(name=kind)

        def rollback_rv():
            # un-record this rv so the apiserver's resync of the
            # identical object is not dropped as stale
            with self._lock:
                if self._resource_versions.get(okey) == rv_num:
                    if prev is None:
                        self._resource_versions.pop(okey, None)
                    else:
                        self._resource_versions[okey] = prev

        def wait(n: int) -> bool:
            if n <= retries:
                time.sleep(min(0.05 * n, 0.5))
                return True
            rollback_rv()  # handler gave up
            return False

        def apply():
            with self._apply_lock:
                handler(action, obj)

        try:
            fq.enqueue(apply, wait)
        except RuntimeError:
            # lost the race with stop(): the event will never apply,
            # so its rv must not poison a later restart's dedup
            rollback_rv()
            raise
        return True

    def wait_idle(self, timeout: float = 10.0) -> bool:
        """Barrier: every enqueued event fully applied."""
        with self._lock:
            queues = list(self._queues.values())
        return all(fq.wait_idle(timeout) for fq in queues)

    def stop(self) -> None:
        with self._lock:
            self._stopped = True
            queues = list(self._queues.values())
            self._queues.clear()
        for fq in queues:
            fq.stop()

    # ---------------------------------------------------------- plumbing

    def _retranslate(self, rules) -> None:
        with self._lock:
            snapshot = dict(self._endpoints)
        for (ns, svc), ips in snapshot.items():
            translate_to_services(rules, svc, ns, ips)

    def _count(self, kind: str = "other") -> None:
        with self._lock:
            self.events_processed += 1
            self.events_by_kind[kind] = \
                self.events_by_kind.get(kind, 0) + 1
