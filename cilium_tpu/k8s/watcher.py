"""k8s event watcher driving the daemon.

Reference: daemon/k8s_watcher.go — informers for CNPs, k8s
NetworkPolicies, Services, Endpoints, Pods and Namespaces feed the
policy repository and the service/endpoint state. Here the watcher is a
sink for an event stream (dicts shaped like k8s watch events); any
source — a test, a file replay, or a real apiserver client — pushes
into it.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from ..labels import LabelArray, Label, SOURCE_K8S
from .policy import (POLICY_LABEL_NAME, POLICY_LABEL_NAMESPACE,
                     parse_cnp, parse_network_policy)
from .translate import endpoints_to_ips, translate_to_services


def _policy_key_labels(name: str, namespace: str) -> LabelArray:
    return LabelArray([
        Label(key=POLICY_LABEL_NAME, value=name, source=SOURCE_K8S),
        Label(key=POLICY_LABEL_NAMESPACE, value=namespace,
              source=SOURCE_K8S)])


class K8sWatcher:
    """Apply k8s object events to a Daemon."""

    def __init__(self, daemon):
        self.daemon = daemon
        self._lock = threading.Lock()
        # (namespace, service) -> backend ips, for ToServices
        self._endpoints: Dict[tuple, List[str]] = {}
        self.events_processed = 0

    # ------------------------------------------------------------ policy

    def on_cnp(self, action: str, obj: Dict) -> None:
        """action: added | modified | deleted
        (k8s_watcher.go addCiliumNetworkPolicyV2 et al.)."""
        meta = obj.get("metadata") or {}
        name = meta.get("name", "")
        namespace = meta.get("namespace", "default")
        key = _policy_key_labels(name, namespace)
        if action in ("added", "modified"):
            rules = parse_cnp(obj)
            self._retranslate(rules)
            self.daemon.policy_add(rules, replace=True)
        elif action == "deleted":
            self.daemon.policy_delete(key)
        self._count()

    def on_network_policy(self, action: str, obj: Dict) -> None:
        meta = obj.get("metadata") or {}
        key = _policy_key_labels(meta.get("name", ""),
                                 meta.get("namespace", "default"))
        if action in ("added", "modified"):
            rules = parse_network_policy(obj)
            self.daemon.policy_add(rules, replace=True)
        elif action == "deleted":
            self.daemon.policy_delete(key)
        self._count()

    # --------------------------------------------------------- services

    def on_service(self, action: str, obj: Dict) -> None:
        """ClusterIP services program the LB (k8s_watcher.go
        addK8sServiceV1)."""
        meta = obj.get("metadata") or {}
        spec = obj.get("spec") or {}
        vip = spec.get("clusterIP")
        if not vip or vip == "None":
            return
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        if action == "deleted":
            for p in spec.get("ports") or []:
                self.daemon.service_delete(vip, int(p.get("port", 0)))
        else:
            backends = self._endpoints.get(key, [])
            for p in spec.get("ports") or []:
                port = int(p.get("port", 0))
                try:
                    target = int(p.get("targetPort") or port)
                except (TypeError, ValueError):
                    # named targetPort: resolving it needs pod specs;
                    # fall back to the service port (reference resolves
                    # through Endpoints ports)
                    target = port
                self.daemon.service_upsert(
                    vip, port, [(ip, target) for ip in backends])
        self._count()

    def on_endpoints(self, action: str, obj: Dict) -> None:
        """Endpoints drive both LB backends and ToServices translation
        (k8s_watcher.go addK8sEndpointV1 + rule_translate)."""
        meta = obj.get("metadata") or {}
        key = (meta.get("namespace", "default"), meta.get("name", ""))
        ips = [] if action == "deleted" else endpoints_to_ips(obj)
        rules = self.daemon.repo.rules
        with self._lock:
            # translate inside the lock: two events for the same service
            # applied out of order would leave a decommissioned
            # backend's generated CIDR allowed forever (old_ips of the
            # later event would never name it again)
            old_ips = self._endpoints.get(key, [])
            self._endpoints[key] = ips
            touched = translate_to_services(rules, key[1], key[0], ips,
                                            old_backend_ips=old_ips)
            if touched:
                # Heal shared backends: when two services select the
                # same pod IP, removing this service's old CIDRs also
                # removed the sibling's (ownership can't be inferred
                # from IP containment alone).  Re-translating every
                # other known service re-adds anything it still owns —
                # idempotent, since translate replaces-in-place.
                for (ns, svc), sips in self._endpoints.items():
                    if (ns, svc) != key:
                        translate_to_services(rules, svc, ns, sips)
        if touched:
            # the new backend /32s need CIDR identities + ipcache
            # entries before the regenerated policy can match them
            self.daemon.resync_rule_prefixes(rules)
            self.daemon.trigger_policy_updates("k8s-endpoints")
        self._count()

    def _retranslate(self, rules) -> None:
        with self._lock:
            snapshot = dict(self._endpoints)
        for (ns, svc), ips in snapshot.items():
            translate_to_services(rules, svc, ns, ips)

    def _count(self) -> None:
        with self._lock:
            self.events_processed += 1
