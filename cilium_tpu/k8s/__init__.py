"""Kubernetes integration: CRD/NetworkPolicy parsing + translation.

Analog of the reference's ``pkg/k8s``: CiliumNetworkPolicy (CRD) and
k8s NetworkPolicy objects parse into ``policy.api.Rule``s with
namespace scoping injected (pkg/k8s/network_policy.go), and
``ToServices`` rules translate to CIDR sets from Endpoints objects
(pkg/k8s/rule_translate.go). The watcher wires a stream of k8s events
into the daemon (daemon/k8s_watcher.go).
"""

from .policy import (parse_cnp, parse_network_policy,
                     NAMESPACE_LABEL_KEY, POLICY_LABEL_NAME,
                     POLICY_LABEL_NAMESPACE)
from .translate import translate_to_services
from .watcher import K8sWatcher

__all__ = ["parse_cnp", "parse_network_policy", "translate_to_services",
           "K8sWatcher", "NAMESPACE_LABEL_KEY", "POLICY_LABEL_NAME",
           "POLICY_LABEL_NAMESPACE"]
