"""k8s list/watch HTTP client + reflectors (the informer transport).

Reference: daemon/k8s_watcher.go:70-78 builds client-go informers; each
is a Reflector doing LIST (grab the collection + its resourceVersion),
then WATCH from that version (a long-lived chunked stream of typed
events), reconnecting from the last seen version on stream loss and
falling back to a full relist on **410 Gone** (the server compacted the
requested version away).  This module is that machinery over plain
``http.client``, feeding the existing ``K8sWatcher.enqueue_event``
sink — the watcher's ordering/dedup semantics are unchanged; only the
transport is new.

``K8sTransport`` is the EnableK8sWatcher analog: one reflector per
watched resource, all driving one ``K8sWatcher``.
"""

from __future__ import annotations

import http.client
import json
import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple
from urllib.parse import urlparse

# resource path -> the K8sWatcher kind it feeds
WATCHED_RESOURCES = {
    "/apis/cilium.io/v2/ciliumnetworkpolicies": "cnp",
    "/apis/networking.k8s.io/v1/networkpolicies": "networkpolicy",
    "/api/v1/services": "service",
    "/api/v1/endpoints": "endpoints",
    "/api/v1/pods": "pod",
    "/api/v1/nodes": "node",
    "/api/v1/namespaces": "namespace",
    "/apis/networking.k8s.io/v1/ingresses": "ingress",
}


class GoneError(Exception):
    """410: the requested resourceVersion was compacted away."""


from ..utils.netio import teardown_http_conn as _teardown_conn  # noqa: E402
from ..utils.resilience import (CircuitBreaker,  # noqa: E402
                                WATCH_RELISTS)


class K8sClient:
    """Minimal apiserver client: list + streaming watch."""

    def __init__(self, base_url: str, timeout: float = 10.0):
        u = urlparse(base_url)
        self.host = u.hostname or "127.0.0.1"
        self.port = u.port or (443 if u.scheme == "https" else 80)
        self.timeout = timeout

    def _connect(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.host, self.port,
                                          timeout=self.timeout)

    def list(self, path: str) -> Tuple[List[Dict], str]:
        """Returns (items, collection resourceVersion)."""
        conn = self._connect()
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            body = resp.read()
            if resp.status != 200:
                raise OSError(f"list {path}: HTTP {resp.status}")
            doc = json.loads(body)
            return (doc.get("items", []),
                    (doc.get("metadata") or {}).get("resourceVersion",
                                                    "0"))
        finally:
            conn.close()

    def watch(self, path: str, resource_version: str,
              register=None) -> Iterator[Tuple[str, Dict]]:
        """Yields (event type, object) from a chunked watch stream
        starting after ``resource_version``.  Raises GoneError on the
        in-stream 410 Status event; plain stream loss just ends the
        iterator (the reflector re-watches from its last version).

        The watch read has NO timeout: a healthy cluster can be silent
        for minutes.  ``register(conn)`` hands the live connection to
        the caller so its stop path can close it from outside and
        unblock the read (client-go's context-cancelled watch)."""
        conn = self._connect()
        # connect EAGERLY: HTTPConnection only opens its socket at
        # request time, so a caller registering the conn for
        # stop-time teardown would otherwise see sock=None and its
        # kill would be a silent no-op (stuck reflector thread)
        conn.connect()
        if register is not None:
            register(conn)
        try:
            conn.request(
                "GET",
                f"{path}?watch=true&resourceVersion={resource_version}")
            resp = conn.getresponse()
            if resp.status == 410:
                raise GoneError(path)
            if resp.status != 200:
                raise OSError(f"watch {path}: HTTP {resp.status}")
            conn.sock.settimeout(None)
            for raw in resp:
                line = raw.strip()
                if not line:
                    continue
                try:
                    event = json.loads(line)
                except ValueError:
                    raise OSError(f"watch {path}: bad frame")
                etype = event.get("type", "")
                obj = event.get("object", {})
                if etype == "ERROR":
                    if obj.get("code") == 410:
                        raise GoneError(path)
                    raise OSError(f"watch {path}: {obj}")
                yield etype, obj
        finally:
            # the stream may still be live (generator abandoned
            # mid-iteration) — see _teardown_conn for why plain
            # close() would block here
            _teardown_conn(conn)


class Reflector:
    """LIST+WATCH one resource into a K8sWatcher (client-go Reflector
    + DeltaFIFO Replace semantics)."""

    def __init__(self, client: K8sClient, path: str, kind: str,
                 watcher, backoff_base: float = 0.05,
                 backoff_max: float = 2.0,
                 breaker: Optional[CircuitBreaker] = None):
        self.client = client
        self.path = path
        self.kind = kind
        self.watcher = watcher
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        # a flapping apiserver degrades to the breaker's bounded probe
        # cadence instead of a reconnect hot loop
        self.breaker = breaker or CircuitBreaker(
            f"k8s-watch-{kind}", failure_threshold=3,
            reset_timeout=max(backoff_base * 4, 0.1),
            max_reset=max(backoff_max, 5.0))
        self._stop = threading.Event()
        self._conn_lock = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"reflector-{kind}")
        # object key -> last seen object (for relist deletion diffing,
        # the DeletedFinalStateUnknown analog)
        self._known: Dict[Tuple[str, str], Dict] = {}
        self.relists = 0
        self.rewatches = 0
        self.synced = threading.Event()

    # ------------------------------------------------------------ loop

    def start(self) -> "Reflector":
        self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        with self._conn_lock:
            if self._conn is not None:
                _teardown_conn(self._conn)
        self._thread.join(timeout=timeout)

    def _register_conn(self, conn) -> None:
        with self._conn_lock:
            self._conn = conn
        if self._stop.is_set():
            _teardown_conn(conn)

    def _key(self, obj: Dict) -> Tuple[str, str]:
        meta = obj.get("metadata", {})
        return (meta.get("namespace", ""), meta.get("name", ""))

    def _feed(self, action: str, obj: Dict) -> None:
        try:
            self.watcher.enqueue_event(self.kind, action, obj)
        except RuntimeError:
            # watcher stopped: the reflector is shutting down too
            self._stop.set()

    def _relist(self) -> str:
        items, rv = self.client.list(self.path)
        self.relists += 1
        WATCH_RELISTS.inc(labels={"transport": "k8s"})
        fresh = {self._key(o): o for o in items}
        # Replace semantics: everything current is an upsert (the
        # watcher's resourceVersion dedup drops no-ops), everything
        # we knew that vanished while we weren't watching is a delete
        for key, obj in fresh.items():
            self._feed("modified" if key in self._known else "added",
                       obj)
        for key, obj in list(self._known.items()):
            if key not in fresh:
                self._feed("deleted", obj)
        self._known = fresh
        self.synced.set()
        return rv

    def _run(self) -> None:
        failures = 0
        rv: Optional[str] = None
        while not self._stop.is_set():
            if not self.breaker.allow():
                # open: one probe per bounded interval, nothing else
                self._stop.wait(max(self.breaker.retry_in(), 0.02))
                continue
            try:
                if rv is None:
                    rv = self._relist()
                    self.breaker.record_success()
                self.rewatches += 1
                for etype, obj in self.client.watch(
                        self.path, rv, register=self._register_conn):
                    if self._stop.is_set():
                        break
                    self.breaker.record_success()
                    action = etype.lower()
                    if action not in ("added", "modified", "deleted"):
                        continue  # e.g. BOOKMARK
                    key = self._key(obj)
                    if action == "deleted":
                        self._known.pop(key, None)
                    else:
                        self._known[key] = obj
                    self._feed(action, obj)
                    new_rv = obj.get("metadata", {}) \
                        .get("resourceVersion")
                    if new_rv is not None:
                        rv = new_rv
                    failures = 0
                # clean stream end: re-watch from the last version
            except GoneError:
                # compacted: full relist is the ONLY correct recovery
                # (not a transport failure — the breaker stays closed)
                rv = None
            except AttributeError:
                # http.client nulls resp.fp when stop() closes the
                # connection under a blocked reader; ONLY during stop
                # is that a dead stream — otherwise it's a real bug
                if not self._stop.is_set():
                    raise
            except (OSError, http.client.HTTPException):
                # HTTPException covers NotConnected from a conn the
                # stop path tore down (auto_open cleared) and
                # IncompleteRead from a stream cut mid-chunk
                self.breaker.record_failure()
                failures += 1
                self._stop.wait(min(self.backoff_base * (2 ** failures),
                                    self.backoff_max))
        # loop exits on stop()


class K8sTransport:
    """All eight reflectors against one apiserver, feeding one
    K8sWatcher (daemon/k8s_watcher.go EnableK8sWatcher analog)."""

    def __init__(self, watcher, base_url: str,
                 resources: Optional[Dict[str, str]] = None):
        self.client = K8sClient(base_url)
        self.reflectors = [
            Reflector(self.client, path, kind, watcher)
            for path, kind in (resources or WATCHED_RESOURCES).items()]

    def start(self) -> "K8sTransport":
        for r in self.reflectors:
            r.start()
        return self

    def wait_synced(self, timeout: float = 10.0) -> bool:
        deadline = time.time() + timeout
        for r in self.reflectors:
            if not r.synced.wait(max(0.0, deadline - time.time())):
                return False
        return True

    def stop(self) -> None:
        for r in self.reflectors:
            r._stop.set()
        for r in self.reflectors:
            r.stop()
