"""ToServices -> ToCIDRSet translation from Endpoints objects.

Reference: pkg/k8s/rule_translate.go — an egress rule naming a k8s
service resolves to the service's backend IPs as generated CIDR rules;
Endpoints add/delete events re-translate affected rules
(Repository.TranslateRules, repository.go:674).
"""

from __future__ import annotations

import ipaddress
from typing import Dict, Iterable, List, Optional, Sequence

from ..policy.api import CIDRRule, Rule


def _parse_ips(ips) -> List:
    out = []
    for ip in ips:
        try:
            out.append(ipaddress.ip_address(ip))
        except ValueError:
            continue
    return out


def _covers_any(cidr: str, parsed_ips) -> bool:
    try:
        net = ipaddress.ip_network(cidr, strict=False)
    except ValueError:
        return False
    return any(ip in net for ip in parsed_ips)


def endpoints_to_ips(endpoints_obj: Dict) -> List[str]:
    """k8s Endpoints object -> backend IPs (subsets[].addresses[].ip)."""
    ips = []
    for subset in endpoints_obj.get("subsets") or []:
        for addr in subset.get("addresses") or []:
            ip = addr.get("ip")
            if ip:
                ips.append(ip)
    return ips


def translate_to_services(rules: Sequence[Rule], service_name: str,
                          namespace: str,
                          backend_ips: Iterable[str],
                          old_backend_ips: Optional[Iterable[str]] = None
                          ) -> int:
    """Rewrite every egress ToServices reference to (service, ns) into
    generated ToCIDRSet entries. Returns rules touched.

    Reference: rule_translate.go RuleTranslator.Translate — only
    generated entries *belonging to this service* are replaced
    (deleteToCidrFromEndpoint removes generated CIDRs containing the
    service's endpoint IPs).  A rule can carry ToServices for several
    services; wiping every generated entry on one service's Endpoints
    event would transiently deny the other services' traffic.
    """
    backend_ips = list(backend_ips)
    # entries to drop: this service's previous backends plus its new
    # ones (replace-in-place when an IP is unchanged); parsed once so
    # the per-entry containment check is O(entries x ips) comparisons,
    # not string parses
    remove_ips = _parse_ips(set(old_backend_ips or []) | set(backend_ips))
    touched = 0
    for rule in rules:
        changed = False
        for eg in rule.egress:
            hit = any(
                s.k8s_service is not None and
                s.k8s_service.service_name == service_name and
                (s.k8s_service.namespace or "default") == namespace
                for s in eg.to_services)
            if not hit:
                continue
            keep = [c for c in eg.to_cidr_set
                    if not (c.generated and _covers_any(c.cidr,
                                                        remove_ips))]
            gen = [CIDRRule(cidr=f"{ip}/32" if ":" not in ip
                            else f"{ip}/128", generated=True)
                   for ip in backend_ips]
            eg.to_cidr_set = keep + gen
            changed = True
        if changed:
            touched += 1
    return touched
