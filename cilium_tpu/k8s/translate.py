"""ToServices -> ToCIDRSet translation from Endpoints objects.

Reference: pkg/k8s/rule_translate.go — an egress rule naming a k8s
service resolves to the service's backend IPs as generated CIDR rules;
Endpoints add/delete events re-translate affected rules
(Repository.TranslateRules, repository.go:674).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Sequence

from ..policy.api import CIDRRule, Rule


def endpoints_to_ips(endpoints_obj: Dict) -> List[str]:
    """k8s Endpoints object -> backend IPs (subsets[].addresses[].ip)."""
    ips = []
    for subset in endpoints_obj.get("subsets") or []:
        for addr in subset.get("addresses") or []:
            ip = addr.get("ip")
            if ip:
                ips.append(ip)
    return ips


def translate_to_services(rules: Sequence[Rule], service_name: str,
                          namespace: str,
                          backend_ips: Iterable[str]) -> int:
    """Rewrite every egress ToServices reference to (service, ns) into
    generated ToCIDRSet entries. Returns rules touched.

    Reference: rule_translate.go RuleTranslator.Translate — existing
    generated entries for the service are replaced (delete-then-add on
    Endpoints change).
    """
    touched = 0
    for rule in rules:
        changed = False
        for eg in rule.egress:
            hit = any(
                s.k8s_service is not None and
                s.k8s_service.service_name == service_name and
                (s.k8s_service.namespace or "default") == namespace
                for s in eg.to_services)
            if not hit:
                continue
            keep = [c for c in eg.to_cidr_set if not c.generated]
            gen = [CIDRRule(cidr=f"{ip}/32" if ":" not in ip
                            else f"{ip}/128", generated=True)
                   for ip in backend_ips]
            eg.to_cidr_set = keep + gen
            changed = True
        if changed:
            touched += 1
    return touched
