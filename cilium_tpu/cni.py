"""CNI plugin: container runtime -> agent endpoint lifecycle.

Reference: plugins/cilium-cni/cilium-cni.go — kubelet invokes the
plugin with CNI_COMMAND=ADD/DEL and a JSON config on stdin; the plugin
allocates addressing and drives the agent's REST endpoint API, then
prints a CNI result object. Exposed as ``cilium-tpu cni`` so the same
binary serves both roles (like the reference's single distribution).
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

from .cli import Client
from .endpoint.ids import CNI_ID_BASE, stable_endpoint_id

CNI_VERSION = "0.3.1"


def _endpoint_id_for(container_id: str) -> int:
    """Stable endpoint id derived from the container id (the reference
    derives it from the interface; any stable mapping works)."""
    return stable_endpoint_id(container_id, CNI_ID_BASE)


def cni_add(client: Client, container_id: str, netns: str = "",
            ifname: str = "eth0",
            config: Optional[Dict] = None) -> Dict:
    """CNI ADD: create the endpoint, return the CNI result."""
    config = config or {}
    ep_id = _endpoint_id_for(container_id)
    labels = [f"container:id={container_id}"]
    for k, v in (config.get("labels") or {}).items():
        labels.append(f"k8s:{k}={v}")
    ipv4 = config.get("ip", "")
    try:
        ep = client.put(f"/endpoint/{ep_id}", {
            "ipv4": ipv4, "container-name": container_id[:12],
            "labels": labels})
    except SystemExit as e:
        # runtimes retry ADD; an existing endpoint is success
        # (idempotency per the CNI spec) — return its addressing
        if getattr(e, "status", None) != 409:
            raise
        ep = client.get(f"/endpoint/{ep_id}")
    result = {
        "cniVersion": CNI_VERSION,
        "interfaces": [{"name": ifname, "sandbox": netns}],
        "ips": [{"version": "4",
                 "address": f"{ep['addressing']['ipv4']}/32"}]
        if ep["addressing"]["ipv4"] else [],
    }
    return result


def cni_del(client: Client, container_id: str) -> bool:
    ep_id = _endpoint_id_for(container_id)
    try:
        client.delete(f"/endpoint/{ep_id}")
        return True
    except SystemExit as e:
        # 404 = already gone: CNI DEL must be idempotent.  Any other
        # failure (unreachable agent, 5xx) must propagate — reporting
        # success would stop the runtime's retries and leak the
        # endpoint, its IP, and its identity refcount in the agent
        if getattr(e, "status", None) == 404:
            return False
        raise


def main(argv=None) -> int:
    """Entry for CNI invocation (env-var driven, per the CNI spec)."""
    command = os.environ.get("CNI_COMMAND", "")
    container_id = os.environ.get("CNI_CONTAINERID", "")
    netns = os.environ.get("CNI_NETNS", "")
    ifname = os.environ.get("CNI_IFNAME", "eth0")
    api = os.environ.get("CILIUM_TPU_API", "http://127.0.0.1:9234")
    client = Client(api)
    try:
        config = json.load(sys.stdin) if not sys.stdin.isatty() else {}
    except ValueError:
        config = {}
    if command == "ADD":
        print(json.dumps(cni_add(client, container_id, netns, ifname,
                                 config)))
        return 0
    if command == "DEL":
        try:
            cni_del(client, container_id)
        except SystemExit as e:
            # CNI error result (spec 1.0 "error" object, code 7 =
            # generic failure): non-zero exit makes the runtime retry
            print(json.dumps({"code": 7, "msg": str(e)}))
            return 1
        return 0
    if command == "VERSION":
        print(json.dumps({"cniVersion": CNI_VERSION,
                          "supportedVersions": [CNI_VERSION]}))
        return 0
    print(json.dumps({"code": 4,
                      "msg": f"unsupported CNI_COMMAND {command!r}"}))
    return 1


if __name__ == "__main__":
    sys.exit(main())
