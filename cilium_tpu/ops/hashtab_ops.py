"""Batched open-addressing hash lookup (device side).

Replaces the reference's per-packet in-kernel BPF map lookups
(bpf/lib/policy.h:61-96 — up to 3 hash lookups/packet) with one batched
gather-based probe: for a batch of B queries each probing K slots, the
lookup is K gathers over an [E*S] flat table — pure VPU work that XLA
fuses, no host round-trips.

Implementation notes for this TPU platform:
  * all arithmetic is int32 (uint32 is bit-identical for mul/add/xor under
    two's complement; logical shifts via lax.shift_right_logical) — the
    host builder (compiler.hashtab.hash_mix) matches bit-for-bit;
  * NO axis-1 advanced-indexing selects (x[iota, argmax]): they lower to a
    catastrophically slow gather here. Keys are unique per table, so at
    most one probe slot matches and masked sums replace first-hit selects.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

# int32 bit-patterns of the uint32 mixing constants.
_C1 = int(np.array(0x9E3779B1, np.uint32).view(np.int32))
_C2 = int(np.array(0x85EBCA6B, np.uint32).view(np.int32))
_C3 = int(np.array(0xC2B2AE35, np.uint32).view(np.int32))


def hash_mix_jnp(a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    """int32 mix — bit-identical to compiler.hashtab.hash_mix (uint32)."""
    a = a.astype(jnp.int32)
    b = b.astype(jnp.int32)
    h = a * _C1
    h = h ^ lax.shift_right_logical(h, 15)
    h = h + b * _C2
    h = h ^ lax.shift_right_logical(h, 13)
    h = h * _C3
    h = h ^ lax.shift_right_logical(h, 16)
    return h


def batched_lookup(key_a: jnp.ndarray, key_b: jnp.ndarray,
                   value: jnp.ndarray,
                   q_a: jnp.ndarray, q_b: jnp.ndarray,
                   max_probe: int,
                   row: Optional[jnp.ndarray] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Probe stacked tables for a batch of queries.

    key_a/key_b/value: [S] or [E, S] int32 table words (key_b==0: empty).
    q_a/q_b: [B] int32 query words. row: [B] table row index when tables
    are stacked (required iff tables are 2-D).

    Returns (found [B] bool, value [B] int32, flat_slot [B] int32) where
    flat_slot indexes the flattened [E*S] table (for counter scatter).
    """
    stacked = key_a.ndim == 2
    slots = key_a.shape[-1]
    mask = jnp.int32(slots - 1)
    flat_a = key_a.reshape(-1)
    flat_b = key_b.reshape(-1)
    flat_v = value.reshape(-1)

    h = hash_mix_jnp(q_a, q_b)
    base = h & mask
    # [B, K] probe slots — K is a compile-time constant from the builder.
    probes = (base[:, None] +
              jnp.arange(max_probe, dtype=jnp.int32)[None, :]) & mask
    if stacked:
        flat_idx = row.astype(jnp.int32)[:, None] * jnp.int32(slots) + probes
    else:
        flat_idx = probes

    got_a = flat_a[flat_idx]          # [B, K]
    got_b = flat_b[flat_idx]
    got_v = flat_v[flat_idx]
    hit = (got_a == q_a[:, None]) & (got_b == q_b[:, None]) & (got_b != 0)

    any_hit = jnp.any(hit, axis=1)
    # Keys are unique per table => at most one probe hits; masked sums
    # select it without slow axis-1 index selects.
    val = jnp.sum(jnp.where(hit, got_v, jnp.int32(0)), axis=1)
    slot = jnp.sum(jnp.where(hit, flat_idx, jnp.int32(0)), axis=1)
    return any_hit, val, slot
