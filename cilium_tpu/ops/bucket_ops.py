"""Batched two-choice bucket lookup + the at-scale verdict engine.

Device twin of compiler/bucket_tables.py: a lookup is 2 row-gathers of
W contiguous slots + 2W lane compares per stage, *independent of table
size* — the constant-probe replacement for the linear-probe chain that
grows to ~48 at BASELINE config 2 scale (10k endpoints x 1k rules).

Verdict semantics are identical to bpf/lib/policy.h:46
__policy_can_access (exact -> L3-only -> L4-wildcard -> drop) and to
datapath/verdict.py's linear-probe engine; parity is test-enforced
against the scalar oracle.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..compiler.bucket_tables import BucketTables
from .hashtab_ops import hash_mix_jnp

VERDICT_DROP = -1
VERDICT_DROP_FRAG = -2
VERDICT_ALLOW = 0

_SALT = int(np.array(0xA5A5A5A5, np.uint32).view(np.int32))


def second_hash_jnp(ka: jnp.ndarray, kb: jnp.ndarray) -> jnp.ndarray:
    """Lockstep with compiler.bucket_tables.second_hash."""
    return hash_mix_jnp(kb ^ jnp.int32(_SALT), ka)


def bucket_pair_jnp(ka, kb, nb_mask: jnp.ndarray):
    b1 = hash_mix_jnp(ka, kb) & nb_mask
    b2 = second_hash_jnp(ka, kb) & nb_mask
    b2 = jnp.where(b2 == b1, (b1 + 1) & nb_mask, b2)
    return b1, b2


def bucket_lookup(key_a: jnp.ndarray, key_b: jnp.ndarray,
                  value: jnp.ndarray, nb: int,
                  q_a: jnp.ndarray, q_b: jnp.ndarray,
                  row: jnp.ndarray
                  ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """[E*NB, W] tables, [B] queries -> (found, value, flat_slot).

    flat_slot indexes the flattened [E*NB*W] table (counter scatter).
    """
    nb_mask = jnp.int32(nb - 1)
    width = key_a.shape[-1]
    b1, b2 = bucket_pair_jnp(q_a, q_b, nb_mask)
    r1 = row.astype(jnp.int32) * jnp.int32(nb) + b1
    r2 = row.astype(jnp.int32) * jnp.int32(nb) + b2
    # two row-gathers per table word: [B, W] each
    cand_a = jnp.concatenate([key_a[r1], key_a[r2]], axis=1)  # [B, 2W]
    cand_b = jnp.concatenate([key_b[r1], key_b[r2]], axis=1)
    cand_v = jnp.concatenate([value[r1], value[r2]], axis=1)
    hit = (cand_a == q_a[:, None]) & (cand_b == q_b[:, None]) & \
        (cand_b != 0)
    any_hit = jnp.any(hit, axis=1)
    # keys unique per endpoint => at most one hit: masked sums select
    val = jnp.sum(jnp.where(hit, cand_v, jnp.int32(0)), axis=1)
    lane = jnp.arange(2 * width, dtype=jnp.int32)[None, :]
    base = jnp.where(lane < width, r1[:, None], r2[:, None])
    flat = base * jnp.int32(width) + jnp.where(
        lane < width, lane, lane - jnp.int32(width))
    slot = jnp.sum(jnp.where(hit, flat, jnp.int32(0)), axis=1)
    return any_hit, val, slot


def _pack_meta_vec(dport, proto, direction):
    return ((dport & 0xFFFF) << 16) | ((proto & 0xFF) << 8) | \
        ((direction & 1) << 1) | 1


class BucketCounters(NamedTuple):
    packets: jnp.ndarray  # [E*NB*W] uint32
    bytes: jnp.ndarray


def bucket_verdict_step(key_id, key_meta, value, counters: BucketCounters,
                        pkt_ep, pkt_ident, pkt_dport, pkt_proto, pkt_dir,
                        pkt_len, pkt_frag, nb: int):
    """3-stage verdict over bucketed tables (jit/shard_map friendly).

    Same contract as datapath.verdict.verdict_step, constant 6 gathers
    total (2 per stage)."""
    frag = pkt_frag.astype(bool)
    meta_exact = _pack_meta_vec(pkt_dport, pkt_proto, pkt_dir)
    meta_l3 = _pack_meta_vec(jnp.zeros_like(pkt_dport),
                             jnp.zeros_like(pkt_proto), pkt_dir)
    zero_id = jnp.zeros_like(pkt_ident)
    f1, v1, s1 = bucket_lookup(key_id, key_meta, value, nb,
                               pkt_ident, meta_exact, pkt_ep)
    f2, _v2, s2 = bucket_lookup(key_id, key_meta, value, nb,
                                pkt_ident, meta_l3, pkt_ep)
    f3, v3, s3 = bucket_lookup(key_id, key_meta, value, nb,
                               zero_id, meta_exact, pkt_ep)
    f1 = f1 & ~frag
    f3 = f3 & ~frag
    verdict = jnp.where(
        f1, v1,
        jnp.where(f2, jnp.int32(VERDICT_ALLOW),
                  jnp.where(f3, v3,
                            jnp.where(frag, jnp.int32(VERDICT_DROP_FRAG),
                                      jnp.int32(VERDICT_DROP)))))
    hit = f1 | f2 | f3
    hit_slot = jnp.where(f1, s1, jnp.where(f2, s2, s3))
    inc_p = hit.astype(jnp.uint32)
    inc_b = jnp.where(hit, pkt_len.astype(jnp.uint32), jnp.uint32(0))
    return verdict, BucketCounters(
        packets=counters.packets.at[hit_slot].add(inc_p),
        bytes=counters.bytes.at[hit_slot].add(inc_b))


class BucketVerdictEngine:
    """Device-resident bucketed verdict tables + per-entry counters.

    The at-scale twin of datapath.verdict.VerdictEngine — constant
    probe cost regardless of endpoint/rule count, so it carries
    BASELINE config 2 (10k x 1k) and beyond.
    """

    def __init__(self, tables: BucketTables, device=None):
        self.revision = tables.revision
        self.nb = tables.buckets_per_ep
        self.width = tables.width
        self.num_endpoints = tables.num_endpoints
        put = (lambda x: jax.device_put(x, device)) if device \
            else jnp.asarray
        self.key_id = put(tables.key_a)
        self.key_meta = put(tables.key_b)
        self.value = put(tables.value)
        n = tables.key_a.size
        self.counters = BucketCounters(packets=put(np.zeros(n, np.uint32)),
                                       bytes=put(np.zeros(n, np.uint32)))
        self._step = jax.jit(functools.partial(bucket_verdict_step,
                                               nb=self.nb),
                             donate_argnums=(3,))

    def nbytes(self) -> int:
        return int(self.key_id.nbytes + self.key_meta.nbytes +
                   self.value.nbytes + self.counters.packets.nbytes +
                   self.counters.bytes.nbytes)

    def __call__(self, pkt_ep, pkt_ident, pkt_dport, pkt_proto, pkt_dir,
                 pkt_len, pkt_frag=None):
        def arr(x):
            # don't bounce already-device-resident inputs through host
            if isinstance(x, jax.Array):
                return x.astype(jnp.int32) if x.dtype != jnp.int32 else x
            return jnp.asarray(np.asarray(x, np.int32))
        b = pkt_ep.shape[0] if hasattr(pkt_ep, "shape") \
            else len(pkt_ep)
        frag = arr(pkt_frag if pkt_frag is not None else np.zeros(b))
        verdict, self.counters = self._step(
            self.key_id, self.key_meta, self.value, self.counters,
            arr(pkt_ep), arr(pkt_ident), arr(pkt_dport), arr(pkt_proto),
            arr(pkt_dir), arr(pkt_len), frag)
        return verdict
