"""Batched DFA evaluation: byte-stream scan over stacked transition tables.

The reference semantics: advance [B, R] DFA states over [B, L] payload
bytes with one gather per byte position (``lax.scan`` over the length
axis). State is carried in/out, so long payloads stream through in
chunks with the state vector as the carry — the blockwise/"ring"
treatment of the sequence dimension (SURVEY.md §2.8: streaming L7
byte-stream parsing is this domain's long-sequence axis).

``dfa_match``/``dfa_scan`` here are the ORACLE tier: int32 tables, one
dependent gather per byte, simple enough to be obviously correct — the
parity anchor for every other walker (tests pin the scalar C++ walker,
the sharded scan, and all ``ops/dfa_engine`` strategies to it).  The
production L7 hot loop runs on ``ops/dfa_engine.DFAEngine``, which
quantizes the tables, collapses the byte alphabet into equivalence
classes, and walks k bytes per dependent step; this module keeps the
host-encode helpers (``encode_strings``, ``bucket_cols``,
``bucket_rows``) both tiers share.

Padding convention: byte -1 marks end-of-input; states freeze there.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def dfa_scan(table: jnp.ndarray, states: jnp.ndarray,
             data: jnp.ndarray) -> jnp.ndarray:
    """Advance DFA states over byte columns.

    table: [S, 256] int32; states: [B, R] int32 (current states);
    data: [B, L] int32 bytes in [0,255], or -1 for padding.
    Returns final states [B, R].
    """
    flat = table.reshape(-1)          # [S*256]
    b, r = states.shape

    def step(st, col):
        # col: [B]; st: [B, R]
        valid = col >= 0
        idx = st * jnp.int32(256) + jnp.where(valid, col, 0)[:, None]
        nxt = flat[idx]               # [B, R] — 2-D gather (fast path)
        return jnp.where(valid[:, None], nxt, st), None

    final, _ = lax.scan(step, states, data.T)  # scan over L
    return final


@jax.jit
def dfa_match(table: jnp.ndarray, accept: jnp.ndarray, starts: jnp.ndarray,
              data: jnp.ndarray) -> jnp.ndarray:
    """One-shot anchored match of every regex against every row.

    data: [B, L] padded bytes. Returns accept mask [B, R].

    Jitted: an eager call re-traces the whole scan per batch (measured
    ~100ms/call of pure dispatch at batch 2k); under jit the program
    is compiled once per (B, L, R) shape and cached.
    """
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :], (b, starts.shape[0]))
    final = dfa_scan(table, states.astype(jnp.int32), data)
    ok = accept[final]
    # Rows poisoned as overlong (-2 fill from encode_strings) never match.
    overlong = jnp.any(data == -2, axis=1)
    return ok & ~overlong[:, None]


def encode_strings(strings, length: int) -> "np.ndarray":
    """Host helper: pad byte strings to an [B, L] int32 block (-1 =
    padding; overlong rows poisoned with -2 so nothing matches).

    Vectorized: one concat + one masked scatter instead of a per-row
    frombuffer loop (the loop dominated the L7 check at batch 2k)."""
    import numpy as np
    n = len(strings)
    raw = [s.encode() if isinstance(s, str) else bytes(s)
           for s in strings]
    clipped = [b[:length] for b in raw]
    lens = np.fromiter((len(b) for b in clipped), np.int64, count=n)
    out = np.full((n, length), -1, np.int32)
    if n:
        concat = np.frombuffer(b"".join(clipped), np.uint8)
        mask = np.arange(length)[None, :] < lens[:, None]
        out[mask] = concat
        overlong = np.fromiter((len(b) > length for b in raw),
                               bool, count=n)
        out[overlong] = -2
    return out


def device_dfa_tables(compiled) -> Tuple[jnp.ndarray, jnp.ndarray,
                                         jnp.ndarray]:
    """(table, accept, starts) uploaded once — the shared helper every
    engine caches at construction instead of re-uploading per check."""
    return (jnp.asarray(compiled.table), jnp.asarray(compiled.accept),
            jnp.asarray(compiled.starts))


def bucket_cols(data: "np.ndarray", min_cols: int = 16) -> "np.ndarray":
    """Trim a [B, L] block to the power-of-two column count covering the
    longest real row.

    The DFA scan is sequential in L, so a 40-byte request padded to a
    512-byte block pays 512 scan steps; trimming to 64 pays 64.  The
    cap `L` stays the semantic overlong limit (rows poisoned with -2 by
    encode_strings keep their poison in any column slice).  Power-of-two
    widths bound the jit program cache exactly like bucket_rows."""
    import numpy as np
    from ..utils.bucketing import bucket_size
    b, full = data.shape
    if b == 0 or full <= min_cols:
        return data
    used = np.nonzero((data >= 0).any(axis=0))[0]
    eff = int(used[-1]) + 1 if used.size else 1
    cols = bucket_size(eff, min_cols)
    if cols >= full:
        return data
    return np.ascontiguousarray(data[:, :cols])


def bucket_rows(data: "np.ndarray", min_rows: int = 16) -> "np.ndarray":
    """Pad a [B, L] block to the next power-of-two row count.

    dfa_match is jitted, so every distinct batch size is a separate
    XLA compile; live proxies see arbitrary batch sizes (1, 2, 17...)
    and would pay a fresh compile each — bucketing bounds the program
    cache to O(log B_max) entries.  Pad rows are -1 (pure padding:
    states freeze at start, and callers slice the result back)."""
    import numpy as np
    from ..utils.bucketing import bucket_size
    b = data.shape[0]
    rows = bucket_size(b, min_rows)
    if rows == b:
        return data
    out = np.full((rows, data.shape[1]), -1, data.dtype)
    out[:b] = data
    return out
