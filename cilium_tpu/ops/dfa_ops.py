"""Batched DFA evaluation: byte-stream scan over stacked transition tables.

The L7 hot loop: advance [B, R] DFA states over [B, L] payload bytes with
one gather per byte position (``lax.scan`` over the length axis). State
is carried in/out, so long payloads stream through in chunks with the
state vector as the carry — the blockwise/"ring" treatment of the
sequence dimension (SURVEY.md §2.8: streaming L7 byte-stream parsing is
this domain's long-sequence axis).

Padding convention: byte -1 marks end-of-input; states freeze there.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def dfa_scan(table: jnp.ndarray, states: jnp.ndarray,
             data: jnp.ndarray) -> jnp.ndarray:
    """Advance DFA states over byte columns.

    table: [S, 256] int32; states: [B, R] int32 (current states);
    data: [B, L] int32 bytes in [0,255], or -1 for padding.
    Returns final states [B, R].
    """
    flat = table.reshape(-1)          # [S*256]
    b, r = states.shape

    def step(st, col):
        # col: [B]; st: [B, R]
        valid = col >= 0
        idx = st * jnp.int32(256) + jnp.where(valid, col, 0)[:, None]
        nxt = flat[idx]               # [B, R] — 2-D gather (fast path)
        return jnp.where(valid[:, None], nxt, st), None

    final, _ = lax.scan(step, states, data.T)  # scan over L
    return final


def dfa_match(table: jnp.ndarray, accept: jnp.ndarray, starts: jnp.ndarray,
              data: jnp.ndarray) -> jnp.ndarray:
    """One-shot anchored match of every regex against every row.

    data: [B, L] padded bytes. Returns accept mask [B, R].
    """
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :], (b, starts.shape[0]))
    final = dfa_scan(table, states.astype(jnp.int32), data)
    ok = accept[final]
    # Rows poisoned as overlong (-2 fill from encode_strings) never match.
    overlong = jnp.any(data == -2, axis=1)
    return ok & ~overlong[:, None]


def encode_strings(strings, length: int) -> "np.ndarray":
    """Host helper: pad/truncate byte strings to an [B, L] int32 block."""
    import numpy as np
    out = np.full((len(strings), length), -1, np.int32)
    for i, s in enumerate(strings):
        bs = s.encode() if isinstance(s, str) else bytes(s)
        n = min(len(bs), length)
        out[i, :n] = np.frombuffer(bs[:n], np.uint8)
        if len(bs) > length:
            out[i, :] = -2  # overlong: poison so nothing matches
    return out
