"""Device kernels (JAX/Pallas): batched hash lookup, LPM, DFA evaluation.

Everything here is shape-static, jit-safe, and scalar-loop-free: lookups
are gathers, probes are statically bounded by the compiler's recorded
``max_probe``, control flow is `where`/`scan` only.
"""

from .hashtab_ops import batched_lookup, hash_mix_jnp
from .lpm_ops import lpm_lookup
