"""Fused, quantized, depth-reduced DFA engines for the L7 hot loop.

``dfa_ops.dfa_match`` walks payloads one byte per dependent step over an
int32 table — O(L) sequential gathers, the bottleneck that kept
http-regex below its baseline on every recorded run.  This module
rebuilds that path around three composable optimizations, selected per
(table size, payload length, batch) at engine construction:

1. **Quantization** — transition tables are stored and gathered at the
   narrowest dtype the state count allows (int8 for S<=127, int16 for
   S<=32767) on accelerators, so the whole table set stays VMEM-
   resident instead of spilling to HBM.  On CPU the tables stay int32:
   XLA's CPU gathers widen narrow loads and measure slower, and the
   packed tables fit L2/L3 either way (selection is per-backend and
   reported, so artifacts stay attributable).

2. **Depth reduction** — the byte alphabet is collapsed into
   equivalence classes first (compiler/regexc.byte_equivalence_classes;
   policy rule sets typically produce 10-30 classes), then k
   consecutive per-class transition functions are precomposed into one
   stride table [S, (C+1)^k] at construction, so the walk takes
   ceil(L/k) dependent gathers instead of L.  When the table is too
   large to precompose, the same reduction runs on device per batch
   (dfa_parallel.dfa_scan_compose: k-1 parallel compose rounds, then an
   L/k walk), and ``lax.associative_scan`` (dfa_parallel) is the
   long-payload endpoint with O(log L) depth.

3. **Split/fused dispatch** — the class map + stride packing is cheap
   vectorized integer work, so it runs EITHER fused into the device
   program (``match``: one jitted program per (B, L) shape — the
   one-call path) OR on the host (``encode`` -> ``match_encoded``), the
   form the pipelined proxy uses: host-packing batch N+1 overlaps the
   device walk of batch N, and the device program shrinks to the
   ceil(L/k) carry walk alone.  The streaming ``scan`` variant donates
   the state carry so steady-state chunk loops allocate nothing new.

Every strategy and both dispatch forms are bit-identical to the
``dfa_match`` oracle (tests/test_dfa_engine.py), including the
padding-freeze and overlong semantics: negative bytes (-1 padding, -2
poison) map to an identity class, which composes as the identity
function, and the -2 row poison is masked at accept time exactly like
the oracle.
"""

from __future__ import annotations

import functools
import time
from dataclasses import dataclass
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .dfa_parallel import dfa_match_compose, dfa_match_parallel, \
    dfa_parallel_scan, dfa_scan_compose

# Host-precomposed stride tables must stay resident in fast memory:
# VMEM (16MB/core) bounds the accelerator budget; CPU tables only need
# to stay inside L2/L3, so the budget is looser there.
STRIDE_BUDGET_ACCEL = 4 << 20
STRIDE_BUDGET_CPU = 16 << 20
# Packed-column bound: (C+1)^k columns; 2^16 keeps S * cols * state
# index arithmetic comfortably inside int32.
MAX_PACKED_COLS = 1 << 16
MAX_STRIDE = 8
# [B, L, S] transition-function materialization bound for the on-device
# strategies (compose/assoc).
DEVICE_F_BUDGET = 256 << 20
# Payload lengths below this never leave the stride path: the depth is
# already tiny and per-batch precompute cannot pay for itself.
SHORT_PAYLOAD = 64


def quantize_dtype(num_states: int) -> np.dtype:
    """Narrowest signed dtype that can index ``num_states`` states."""
    if num_states <= (1 << 7) - 1:
        return np.dtype(np.int8)
    if num_states <= (1 << 15) - 1:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


@dataclass
class PackedBatch:
    """Host-encoded input for ``match_encoded``.

    For the stride strategy ``idx`` is the [B, G] packed class-group
    index block (G = ceil(L/k)); otherwise it is the raw [B, L] byte
    block and the device program does its own mapping.  ``overlong``
    is the -2 poison row mask, precomputed so the device never re-scans
    the bytes."""

    idx: np.ndarray
    overlong: np.ndarray
    rows: int
    packed: bool


@functools.partial(jax.jit, static_argnums=(0, 1))
def _stride_scan(k: int, c1: int, flat_tab, class_map, states, data):
    """Fused form: class map + packing + ceil(L/k) dependent gathers.

    flat_tab: [S * c1**k] stride table; class_map: [258] int32 (byte+2
    -> class, both negative bytes mapped to the identity class c1-1);
    states: [B, R] int32; data: [B, L] int32 bytes.
    """
    b, l = data.shape
    cls = class_map[data + jnp.int32(2)]            # [B, L]
    pad = (-l) % k
    if pad:
        cls = jnp.concatenate(
            [cls, jnp.full((b, pad), c1 - 1, jnp.int32)], axis=1)
    g = cls.reshape(b, -1, k)
    idx = g[:, :, 0]
    for j in range(1, k):                           # earlier byte = high digit
        idx = idx * jnp.int32(c1) + g[:, :, j]      # [B, G]
    return _packed_walk(c1 ** k, flat_tab, states, idx)


def _packed_walk(w: int, flat_tab, states, idx):
    """The dependent-gather carry walk shared by both dispatch forms."""
    def step(st, col):                              # col: [B]; st: [B, R]
        nxt = flat_tab[st * jnp.int32(w) + col[:, None]]
        return nxt.astype(jnp.int32), None

    final, _ = lax.scan(step, states, idx.T)
    return final


_stride_scan_donated = jax.jit(
    _stride_scan.__wrapped__, static_argnums=(0, 1), donate_argnums=(4,))


@functools.partial(jax.jit, static_argnums=(0, 1))
def _stride_match(k: int, c1: int, flat_tab, class_map, accept, starts,
                  data):
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    final = _stride_scan.__wrapped__(k, c1, flat_tab, class_map, states,
                                     data)
    ok = accept[final]
    overlong = jnp.any(data == -2, axis=1)
    return ok & ~overlong[:, None]


@functools.partial(jax.jit, static_argnums=(0,))
def _packed_match(w: int, flat_tab, accept, starts, idx, overlong):
    """Split form: the device program is the carry walk alone — the
    class map/packing already happened on the host (PackedBatch)."""
    b = idx.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    final = _packed_walk(w, flat_tab, states, idx)
    return accept[final] & ~overlong[:, None]


_assoc_match = jax.jit(dfa_match_parallel)
_assoc_scan = jax.jit(dfa_parallel_scan)


class DFAEngine:
    """One compiled regex set, matched by the best strategy for its
    (table size, payload length, batch) point.

    Strategies:
      - ``stride``  — host-precomposed k-class stride table; the
                      default whenever the packed table fits budget
                      (k=1 degenerates to a class-compressed serial
                      walk).
      - ``compose`` — device-side k-group composition then an L/k walk;
                      for tables too big to precompose but payloads
                      long enough that depth dominates.
      - ``assoc``   — ``lax.associative_scan``, O(log L) depth; the
                      long-payload endpoint on accelerators.
    """

    def __init__(self, compiled, max_len: int, batch_hint: int = 2048,
                 prefer: Optional[str] = None,
                 stride_budget: Optional[int] = None,
                 dtype: Optional[np.dtype] = None,
                 on_accel: Optional[bool] = None):
        self.compiled = compiled
        self.max_len = int(max_len)
        self.batch_hint = int(batch_hint)
        s = int(compiled.num_states)
        if on_accel is None:
            try:
                on_accel = jax.default_backend() != "cpu"
            except Exception:  # noqa: BLE001 — backend probe best-effort
                on_accel = False
        self.on_accel = bool(on_accel)
        # quantize for VMEM residency on accelerators; int32 on CPU
        # (narrow gathers measure slower there and cache still fits)
        self._dtype = np.dtype(dtype) if dtype is not None else (
            quantize_dtype(s) if self.on_accel else np.dtype(np.int32))
        if np.iinfo(self._dtype).max < s - 1:
            raise ValueError(f"dtype {self._dtype} cannot hold {s} states")
        itemsize = self._dtype.itemsize
        if stride_budget is None:
            stride_budget = STRIDE_BUDGET_ACCEL if self.on_accel \
                else STRIDE_BUDGET_CPU
        class_of, class_tab = compiled.byte_classes()
        self.num_classes = int(class_tab.shape[1])
        self._c1 = self.num_classes + 1             # + identity class

        # largest stride whose precomposed table stays in budget
        k = 1
        while (k < MAX_STRIDE and self._c1 ** (k + 1) <= MAX_PACKED_COLS
               and s * self._c1 ** (k + 1) * itemsize <= stride_budget):
            k += 1
        device_f_bytes = self.batch_hint * self.max_len * s * itemsize
        if prefer is not None:
            if prefer not in ("stride", "compose", "assoc"):
                raise ValueError(f"unknown DFA strategy {prefer!r}")
            strategy = prefer
        elif (self.on_accel and self.max_len >= 256
              and (self.max_len + k - 1) // k > 64
              and device_f_bytes <= DEVICE_F_BUDGET):
            # stride can't get the depth down on-accel: go log-depth
            strategy = "assoc"
        elif (k == 1 and self.max_len >= SHORT_PAYLOAD
              and device_f_bytes <= DEVICE_F_BUDGET):
            # class alphabet too rich to precompose: reduce depth on
            # device instead
            strategy = "compose"
        else:
            strategy = "stride"
        self.strategy = strategy
        self.k = k if strategy == "stride" else \
            (4 if strategy == "compose" else 1)

        self._accept = jnp.asarray(compiled.accept)
        self._starts = jnp.asarray(compiled.starts)
        self._flat = None
        self._map = None
        self._map_np = None
        self._table_q = None
        if strategy == "stride":
            tab_c = np.concatenate(
                [class_tab, np.arange(s, dtype=np.int32)[:, None]],
                axis=1)                             # [S, C+1]
            t = tab_c
            for _ in range(self.k - 1):
                # T'[s, i*C1 + c] = tab_c[T[s, i], c]: one more byte of
                # lookahead folded into every column
                t = tab_c[t].reshape(s, -1)
            self._packed_bytes = int(t.size * itemsize)
            self._flat = jnp.asarray(
                np.ascontiguousarray(t.astype(self._dtype)).reshape(-1))
            map258 = np.full(258, self.num_classes, np.int32)
            map258[2:] = class_of                   # byte b at index b+2
            self._map_np = map258
            self._map = jnp.asarray(map258)
        else:
            self._packed_bytes = int(s * 256 * itemsize)
            self._table_q = jnp.asarray(compiled.table.astype(self._dtype))

    # ----------------------------------------------------- host encode

    def encode(self, data: np.ndarray) -> PackedBatch:
        """Host stage of the split dispatch: class-map and stride-pack a
        [B, L] byte block (vectorized numpy), so the device program is
        the carry walk alone.  In a pipelined caller this overlaps the
        previous batch's device walk.  Non-stride strategies pass the
        bytes through (their mapping is part of the device program)."""
        data = np.asarray(data)
        overlong = (data == -2).any(axis=1)
        if self.strategy != "stride":
            return PackedBatch(idx=data, overlong=overlong,
                               rows=data.shape[0], packed=False)
        b, l = data.shape
        cls = self._map_np[data + 2]
        pad = (-l) % self.k
        if pad:
            cls = np.concatenate(
                [cls, np.full((b, pad), self.num_classes, np.int32)],
                axis=1)
        g = cls.reshape(b, -1, self.k)
        idx = g[:, :, 0].astype(np.int32)
        for j in range(1, self.k):
            idx = idx * self._c1 + g[:, :, j]
        return PackedBatch(idx=idx, overlong=overlong, rows=b,
                           packed=True)

    # ------------------------------------------------------------ match

    def match(self, data) -> jnp.ndarray:
        """Anchored match, [B, R] bool on device — the dfa_match
        contract (padding freeze, -2 poison), no synchronization.
        Accepts a raw byte block or a :class:`PackedBatch`."""
        if isinstance(data, PackedBatch):
            return self.match_encoded(data)
        data = jnp.asarray(data)
        # jit-cache telemetry (observability/jitstats): the dispatch
        # slice is timed and the first call per (engine, strategy,
        # geometry) is classified as a compile
        from ..observability.jitstats import jit_telemetry
        t0 = time.perf_counter() if jit_telemetry.enabled else 0.0
        if self.strategy == "stride":
            out = _stride_match(self.k, self._c1, self._flat,
                                self._map, self._accept, self._starts,
                                data)
        elif self.strategy == "compose":
            out = dfa_match_compose(self._table_q, self._accept,
                                    self._starts, data, self.k)
        else:
            out = _assoc_match(self._table_q, self._accept,
                               self._starts, data)
        if jit_telemetry.enabled:
            jit_telemetry.record(
                f"dfa.match-{self.strategy}", id(self),
                tuple(data.shape), time.perf_counter() - t0)
        return out

    def match_encoded(self, packed: PackedBatch) -> jnp.ndarray:
        """Device half of the split dispatch (see :meth:`encode`)."""
        if not packed.packed:
            data = jnp.asarray(packed.idx)
            if self.strategy == "compose":
                return dfa_match_compose(self._table_q, self._accept,
                                         self._starts, data, self.k)
            if self.strategy == "assoc":
                return _assoc_match(self._table_q, self._accept,
                                    self._starts, data)
            return _stride_match(self.k, self._c1, self._flat, self._map,
                                 self._accept, self._starts, data)
        return _packed_match(self._c1 ** self.k, self._flat,
                             self._accept, self._starts,
                             jnp.asarray(packed.idx),
                             jnp.asarray(packed.overlong))

    def scan(self, states, data, donate: bool = False) -> jnp.ndarray:
        """Streaming chunk scan: advance [B, R] carried states over a
        [B, L] chunk (dfa_scan contract).  With ``donate=True`` the
        carry buffer is donated to the jitted program, so a steady-state
        chunk loop reuses one buffer instead of allocating per chunk."""
        data = jnp.asarray(data)
        states = jnp.asarray(states, dtype=jnp.int32)
        if self.strategy == "stride":
            fn = _stride_scan_donated if donate else _stride_scan
            return fn(self.k, self._c1, self._flat, self._map, states,
                      data)
        if self.strategy == "compose":
            return dfa_scan_compose(self._table_q, states, data, self.k)
        return _assoc_scan(self._table_q, states, data).astype(jnp.int32)

    # ------------------------------------------------------------ report

    def depth(self, length: Optional[int] = None) -> int:
        """Dependent-step count for a payload of ``length`` bytes."""
        ln = self.max_len if length is None else int(length)
        if self.strategy == "assoc":
            return max(1, int(np.ceil(np.log2(max(ln, 2)))))
        return (ln + self.k - 1) // self.k

    def describe(self) -> dict:
        """Engine-selection report for bench extras / status surfaces."""
        dt = self._dtype.name
        return {"strategy": self.strategy, "k": self.k, "dtype": dt,
                "states": int(self.compiled.num_states),
                "classes": self.num_classes,
                "depth_at_max_len": self.depth(),
                "byte_table_bytes": int(self.compiled.table.nbytes),
                "resident_bytes": self._packed_bytes,
                "on_accel": self.on_accel,
                "tag": f"{self.strategy}{self.k}-{dt}-C{self.num_classes}"}
