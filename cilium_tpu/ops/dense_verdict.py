"""Dense broadcast-compare verdict engine (+ Pallas TPU kernel).

The hash-probe engine (ops/hashtab_ops) implements the reference's map
semantics with K dependent gathers per stage — fine on CPU, but random
gathers are the one access pattern TPUs dislike. This module is the
TPU-first alternative: policy entries live as flat arrays [N] (one row
per real entry, not per hash slot), and a batch classifies by
broadcast-comparing packet keys against all entries — a [B, N] int32
compare on the VPU with per-stage priority selection, no gathers, no
data-dependent control flow. Per-entry packet/byte counters fall out as
column reductions of the effective-match matrix (the per-entry counter
layout of bpf/lib/policy.h:67, for free).

Semantics are identical to the 3-stage fallback of
bpf/lib/policy.h:46 __policy_can_access; parity with the hash engine
and the scalar oracle is enforced by tests.

The Pallas kernel runs a 2-D grid (packet blocks x entry tiles): the
entry axis streams through VMEM in TILE_N tiles while per-packet stage
accumulators stay VMEM-resident across the inner tile loop, so there is
no entry-count cap.  On CPU it runs in interpret mode.  Note the
compare is still O(B*N): at very large N (millions of entries) the
constant-probe bucket engine (ops/bucket_ops.py) is the right tool —
dense wins on small-to-mid rule sets where gathers dominate.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.policy_tables import pack_key, pack_meta
from ..policy.mapstate import PolicyMapState

try:
    from jax.experimental import pallas as pl
    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    pl = None
    HAS_PALLAS = False

VERDICT_DROP = -1

# Entry axis padded to the TPU lane width.
LANE = 128
# Per-grid-step entry tile: [block_b, TILE_N] compare matrices must fit
# VMEM (~16 MB/core); 256x2048 int32 = 2 MB per live matrix.  The entry
# axis itself is unbounded — the kernel walks it in tiles (2-D grid),
# carrying per-packet stage accumulators in VMEM-resident output blocks.
TILE_N = 2048


class DenseTables(NamedTuple):
    """Flat policy entries across all endpoints, padded to LANE."""

    ep: jnp.ndarray      # [N] int32, -1 on padding rows
    key_a: jnp.ndarray   # [N] int32 identity word
    key_b: jnp.ndarray   # [N] int32 packed meta word
    value: jnp.ndarray   # [N] int32 proxy port


def compile_dense(map_states: Sequence[PolicyMapState]) -> DenseTables:
    """Stack every endpoint's entries into flat arrays.

    One row per real entry — the dense engine needs no hash slots, so
    its footprint is exactly sum(len(state)) rows (vs E*S slots)."""
    eps: List[int] = []
    kas: List[int] = []
    kbs: List[int] = []
    vals: List[int] = []
    for ep_idx, state in enumerate(map_states):
        for k, v in sorted(state.items(),
                           key=lambda kv: pack_key(kv[0])):
            ka, kb = pack_key(k)
            eps.append(ep_idx)
            kas.append(ka)
            kbs.append(kb)
            vals.append(v.proxy_port)
    n = len(eps)
    pad = (-n) % LANE
    if n == 0:
        pad = LANE
    eps += [-1] * pad
    kas += [0] * pad
    kbs += [0] * pad
    vals += [0] * pad
    as_i32 = lambda xs: jnp.asarray(
        np.array(xs, np.uint32).view(np.int32))
    return DenseTables(ep=jnp.asarray(np.array(eps, np.int32)),
                       key_a=as_i32(kas), key_b=as_i32(kbs),
                       value=jnp.asarray(np.array(vals, np.int32)))


# key_b packing: single lockstep definition (works elementwise on jnp
# arrays — pure bit ops)
_meta = pack_meta


def _classify_block(ep, ka, kb, val, pep, pid, pme, pml, plen):
    """Shared core: [B] packets vs [N] entries -> verdict + counter
    deltas. Pure jnp — used verbatim by the XLA path and inside the
    Pallas kernel (where the arrays are VMEM-resident)."""
    same_ep = pep[:, None] == ep[None, :]
    ident_eq = pid[:, None] == ka[None, :]
    m1 = same_ep & ident_eq & (pme[:, None] == kb[None, :])
    m2 = same_ep & ident_eq & (pml[:, None] == kb[None, :])
    m3 = same_ep & (ka[None, :] == 0) & (pme[:, None] == kb[None, :])
    i1 = m1.astype(jnp.int32)
    i3 = m3.astype(jnp.int32)
    hit1 = i1.sum(axis=1) > 0
    hit2 = m2.astype(jnp.int32).sum(axis=1) > 0
    hit3 = i3.sum(axis=1) > 0
    # unique keys per endpoint => at most one match per stage: sum works
    val1 = (i1 * val[None, :]).sum(axis=1)
    val3 = (i3 * val[None, :]).sum(axis=1)
    verdict = jnp.where(
        hit1, val1,
        jnp.where(hit2, jnp.int32(0),
                  jnp.where(hit3, val3, jnp.int32(VERDICT_DROP))))
    # effective match: the stage that decided each packet
    m_eff = m1 | (m2 & ~hit1[:, None]) | (m3 & ~(hit1 | hit2)[:, None])
    ieff = m_eff.astype(jnp.int32)
    d_packets = ieff.sum(axis=0)
    d_bytes = (ieff * plen[:, None]).sum(axis=0)
    return verdict, d_packets, d_bytes


def dense_verdict_step(tables: DenseTables, counters_packets: jnp.ndarray,
                       counters_bytes: jnp.ndarray, pkt_ep: jnp.ndarray,
                       pkt_ident: jnp.ndarray, pkt_dport: jnp.ndarray,
                       pkt_proto: jnp.ndarray, pkt_dir: jnp.ndarray,
                       pkt_len: jnp.ndarray):
    """Pure-jnp dense engine (XLA fuses the whole thing).

    Returns (verdict [B], counters_packets' [N], counters_bytes' [N]).
    """
    meta_exact = _meta(pkt_dport, pkt_proto, pkt_dir)
    meta_l3 = _meta(jnp.zeros_like(pkt_dport), jnp.zeros_like(pkt_proto),
                    pkt_dir)
    verdict, d_pk, d_by = _classify_block(
        tables.ep, tables.key_a, tables.key_b, tables.value,
        pkt_ep, pkt_ident, meta_exact, meta_l3, pkt_len)
    return (verdict, counters_packets + d_pk.astype(jnp.uint32),
            counters_bytes + d_by.astype(jnp.uint32))


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _dense_tiled_kernel(ep_ref, ka_ref, kb_ref, val_ref, pep_ref, pid_ref,
                        pme_ref, pml_ref, h1_ref, v1_ref, i1_ref, h2_ref,
                        i2_ref, h3_ref, v3_ref, i3_ref, *, tile_n: int):
    """Grid step (i: packet block, j: entry tile; j fastest).

    Accumulates per-packet stage partials across entry tiles in the
    eight output blocks, which map to the same (0, i) block for every j
    — they stay VMEM-resident and survive across the inner j loop.
    Unique keys per endpoint mean at most ONE entry matches per stage
    across ALL tiles, so sums both accumulate and select.  Entry
    indices are stored +1 so 0 means "no match" (entry 0 is real).
    """
    j = pl.program_id(1)
    ep = ep_ref[0, :]
    ka = ka_ref[0, :]
    kb = kb_ref[0, :]
    val = val_ref[0, :]
    pep = pep_ref[0, :]
    pid = pid_ref[0, :]
    pme = pme_ref[0, :]
    pml = pml_ref[0, :]

    same_ep = pep[:, None] == ep[None, :]
    ident_eq = pid[:, None] == ka[None, :]
    m1 = same_ep & ident_eq & (pme[:, None] == kb[None, :])
    m2 = same_ep & ident_eq & (pml[:, None] == kb[None, :])
    m3 = same_ep & (ka[None, :] == 0) & (pme[:, None] == kb[None, :])
    i1 = m1.astype(jnp.int32)
    i2 = m2.astype(jnp.int32)
    i3 = m3.astype(jnp.int32)
    # global entry index of this tile's columns, +1 (0 = no match)
    gidx = (j * tile_n +
            jax.lax.broadcasted_iota(jnp.int32, m1.shape, 1) + 1)

    d_h1 = i1.sum(axis=1)
    d_v1 = (i1 * val[None, :]).sum(axis=1)
    d_i1 = (i1 * gidx).sum(axis=1)
    d_h2 = i2.sum(axis=1)
    d_i2 = (i2 * gidx).sum(axis=1)
    d_h3 = i3.sum(axis=1)
    d_v3 = (i3 * val[None, :]).sum(axis=1)
    d_i3 = (i3 * gidx).sum(axis=1)

    @pl.when(j == 0)
    def _zero():
        for ref in (h1_ref, v1_ref, i1_ref, h2_ref, i2_ref, h3_ref,
                    v3_ref, i3_ref):
            ref[0, :] = jnp.zeros_like(d_h1)

    h1_ref[0, :] += d_h1
    v1_ref[0, :] += d_v1
    i1_ref[0, :] += d_i1
    h2_ref[0, :] += d_h2
    i2_ref[0, :] += d_i2
    h3_ref[0, :] += d_h3
    v3_ref[0, :] += d_v3
    i3_ref[0, :] += d_i3


def dense_verdict_pallas(tables: DenseTables, pkt_ep, pkt_ident,
                         pkt_dport, pkt_proto, pkt_dir, pkt_len,
                         block_b: int = 256, tile_n: int = TILE_N,
                         interpret: Optional[bool] = None):
    """Pallas dense engine, entry axis tiled through VMEM.

    Returns (verdict [B], counter deltas (packets [N], bytes [N])).
    No entry-count cap: the grid walks ceil(N / tile_n) tiles per
    packet block.  Requires B % block_b == 0.
    """
    if not HAS_PALLAS:
        raise RuntimeError("pallas unavailable")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = tables.ep.shape[0]
    b = pkt_ep.shape[0]
    block_b = min(block_b, b)
    if b % block_b:
        raise ValueError(f"batch {b} not divisible by block {block_b}")
    tile_n = min(tile_n, max(LANE, n))
    pad = (-n) % tile_n
    ep_t, ka_t, kb_t, val_t = tables
    if pad:
        ep_t = jnp.concatenate(
            [ep_t, jnp.full(pad, -1, jnp.int32)])  # never matches
        zeros = jnp.zeros(pad, jnp.int32)
        ka_t = jnp.concatenate([ka_t, zeros])
        kb_t = jnp.concatenate([kb_t, zeros])
        val_t = jnp.concatenate([val_t, zeros])
    n_pad = n + pad
    n_tiles = n_pad // tile_n

    meta_exact = _meta(pkt_dport, pkt_proto, pkt_dir)
    meta_l3 = _meta(jnp.zeros_like(pkt_dport), jnp.zeros_like(pkt_proto),
                    pkt_dir)
    row = lambda x: x.reshape(1, -1)
    entry_spec = lambda: pl.BlockSpec((1, tile_n), lambda i, j: (0, j))
    pkt_spec = lambda: pl.BlockSpec((1, block_b), lambda i, j: (0, i))
    acc_spec = lambda: pl.BlockSpec((1, block_b), lambda i, j: (0, i))
    acc_shape = lambda: jax.ShapeDtypeStruct((1, b), jnp.int32)

    (h1, v1, i1, h2, i2, h3, v3, i3) = pl.pallas_call(
        functools.partial(_dense_tiled_kernel, tile_n=tile_n),
        grid=(b // block_b, n_tiles),
        in_specs=[entry_spec(), entry_spec(), entry_spec(), entry_spec(),
                  pkt_spec(), pkt_spec(), pkt_spec(), pkt_spec()],
        out_specs=[acc_spec() for _ in range(8)],
        out_shape=[acc_shape() for _ in range(8)],
        interpret=interpret,
    )(row(ep_t), row(ka_t), row(kb_t), row(val_t), row(pkt_ep),
      row(pkt_ident), row(meta_exact), row(meta_l3))
    h1, v1, i1, h2, i2, h3, v3, i3 = (x[0] for x in
                                      (h1, v1, i1, h2, i2, h3, v3, i3))
    hit1 = h1 > 0
    hit2 = h2 > 0
    hit3 = h3 > 0
    verdict = jnp.where(
        hit1, v1,
        jnp.where(hit2, jnp.int32(0),
                  jnp.where(hit3, v3, jnp.int32(VERDICT_DROP))))
    # counter scatter outside the kernel: each decided packet
    # increments its deciding entry (same m_eff semantics as the jnp
    # path); misses scatter weight 0 into entry 0
    win = jnp.where(hit1, i1, jnp.where(hit2, i2,
                                        jnp.where(hit3, i3, 0)))
    decided = win > 0
    idx = jnp.maximum(win - 1, 0)
    inc = decided.astype(jnp.int32)
    d_pk = jnp.zeros(n, jnp.int32).at[idx].add(inc)
    d_by = jnp.zeros(n, jnp.int32).at[idx].add(
        inc * pkt_len.astype(jnp.int32))
    return verdict, d_pk, d_by


# ---------------------------------------------------------------------------
# Dense LPM + fused raw-path step (gather-free flagship pipeline)
# ---------------------------------------------------------------------------

class DenseLPM(NamedTuple):
    """Flat LPM entries: addr-under-mask compare, longest-prefix wins."""

    net: jnp.ndarray    # [P] int32 network address (pre-masked)
    mask: jnp.ndarray   # [P] int32 netmask
    plen: jnp.ndarray   # [P] int32 prefix length + 1 (0 = padding row)
    value: jnp.ndarray  # [P] int32 identity


def compile_dense_lpm(prefixes) -> DenseLPM:
    """{cidr: identity} -> DenseLPM (pads to LANE)."""
    import ipaddress
    rows = []
    for cidr, ident in sorted(prefixes.items()):
        net = ipaddress.ip_network(cidr, strict=False)
        mask = int(net.netmask)
        rows.append((int(net.network_address) & mask, mask,
                     net.prefixlen + 1, ident))
    pad = (-len(rows)) % LANE
    if not rows:
        pad = LANE
    rows += [(0, 0xFFFFFFFF, 0, 0)] * pad  # plen 0 rows never win
    arr = np.array(rows, np.uint64)
    u = lambda col: jnp.asarray(arr[:, col].astype(np.uint32)
                                .view(np.int32))
    return DenseLPM(net=u(0), mask=u(1), plen=u(2), value=u(3))


def dense_lpm_lookup(lpm: DenseLPM, addr: jnp.ndarray):
    """[B] addr -> (found [B] bool, value [B] int32): longest matching
    prefix wins, as one [B, P] masked compare + two reductions."""
    match = (addr[:, None] & lpm.mask[None, :]) == lpm.net[None, :]
    score = jnp.where(match, lpm.plen[None, :], 0)
    best = score.max(axis=1)
    # exactly one prefix of a given length can contain an address,
    # so a masked sum selects the winner's value
    sel = match & (score == best[:, None]) & (best[:, None] > 0)
    value = (sel.astype(jnp.int32) * lpm.value[None, :]).sum(axis=1)
    return best > 0, value


# Identity assigned on ipcache miss (reference: world).
WORLD_IDENTITY = 2


def dense_datapath_step(tables: DenseTables, lpm: DenseLPM,
                        counters_packets, counters_bytes, pkt_ep,
                        pkt_src_addr, pkt_dport, pkt_proto, pkt_dir,
                        pkt_len):
    """Gather-free flagship step: dense ipcache LPM -> dense 3-stage
    verdict -> per-entry counters. Same contract as
    datapath.pipeline.datapath_step."""
    found, ident = dense_lpm_lookup(lpm, pkt_src_addr)
    identity = jnp.where(found, ident, jnp.int32(WORLD_IDENTITY))
    verdict, counters_packets, counters_bytes = dense_verdict_step(
        tables, counters_packets, counters_bytes, pkt_ep, identity,
        pkt_dport, pkt_proto, pkt_dir, pkt_len)
    return verdict, identity, counters_packets, counters_bytes


class DenseVerdictEngine:
    """Host wrapper: compile states, run batches, keep counters."""

    def __init__(self, map_states: Sequence[PolicyMapState],
                 use_pallas: bool = False, block_b: int = 256):
        self.tables = compile_dense(map_states)
        n = self.tables.ep.shape[0]
        # the tiled kernel has no entry cap (entry axis walks VMEM in
        # TILE_N tiles), so pallas is available at any N
        self.use_pallas = use_pallas and HAS_PALLAS
        self.block_b = block_b
        self.counters_packets = jnp.zeros(n, jnp.uint32)
        self.counters_bytes = jnp.zeros(n, jnp.uint32)
        self._jit_step = jax.jit(dense_verdict_step, donate_argnums=(1, 2))
        self._jit_pallas = jax.jit(functools.partial(
            dense_verdict_pallas, block_b=block_b))

    def __call__(self, pkt_ep, pkt_ident, pkt_dport, pkt_proto, pkt_dir,
                 pkt_len):
        arr = lambda x: jnp.asarray(np.asarray(x, np.int32))
        args = (arr(pkt_ep), arr(pkt_ident), arr(pkt_dport),
                arr(pkt_proto), arr(pkt_dir), arr(pkt_len))
        if self.use_pallas and args[0].shape[0] % self.block_b == 0:
            verdict, dpk, dby = self._jit_pallas(self.tables, *args)
            self.counters_packets = self.counters_packets + \
                dpk.astype(jnp.uint32)
            self.counters_bytes = self.counters_bytes + \
                dby.astype(jnp.uint32)
            return verdict
        verdict, self.counters_packets, self.counters_bytes = \
            self._jit_step(self.tables, self.counters_packets,
                           self.counters_bytes, *args)
        return verdict
