"""Sequence-parallel DFA evaluation: composition scan over bytes.

``dfa_scan`` (ops/dfa_ops) walks the payload serially — O(L) dependent
steps. This module is the long-sequence treatment (the ring-attention /
context-parallel analog for this domain, SURVEY.md §2.8): a DFA step on
byte ``c`` is a function f_c: state -> state, i.e. a vector
``table[:, c]`` of shape [S]; matching a payload is the composition
f_{c_L} ∘ … ∘ f_{c_1}. Function composition is associative, so:

- ``dfa_parallel_scan``: ``jax.lax.associative_scan`` over the byte
  axis — O(log L) depth, every position's composition computed in
  parallel on-device (the scan work is [L, S] gathers: VPU-friendly).
- ``dfa_scan_sharded``: ``shard_map`` over a mesh axis with the
  sequence dimension sharded — each device composes its local chunk,
  then a log-width ``lax.ppermute`` exclusive-prefix exchange composes
  chunk boundaries over ICI, exactly the blockwise/ring pattern used
  for ring attention, with transition functions instead of KV blocks.

Padding bytes (-1) compose as the identity function, so ragged payloads
need no special casing.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def transition_functions(table: jnp.ndarray,
                         data: jnp.ndarray) -> jnp.ndarray:
    """Bytes -> per-position transition vectors.

    table: [S, 256]; data: [..., L] int32 bytes (-1 == padding).
    Returns [..., L, S] where out[..., i, s] = next state from s on
    byte i (identity for padding)."""
    s = table.shape[0]
    ident = jnp.arange(s, dtype=table.dtype)
    safe = jnp.where(data >= 0, data, 0)
    f = table.T[safe]                      # [..., L, S]
    return jnp.where((data >= 0)[..., None], f, ident)


def compose(g: jnp.ndarray, f: jnp.ndarray) -> jnp.ndarray:
    """(g ∘ f)[..., s] = g[..., f[..., s]] — 'apply f first, then g'.

    Both [..., S]; batched gather along the last axis."""
    return jnp.take_along_axis(g, f, axis=-1)


def dfa_parallel_scan(table: jnp.ndarray, states: jnp.ndarray,
                      data: jnp.ndarray) -> jnp.ndarray:
    """Sequence-parallel equivalent of dfa_ops.dfa_scan.

    table: [S, 256]; states: [B, R]; data: [B, L].
    Returns final states [B, R]."""
    f = transition_functions(table, data)          # [B, L, S]
    # scan composes left-to-right: out[i] = f_i ∘ … ∘ f_0
    total = lax.associative_scan(
        lambda a, b: compose(b, a), f, axis=1)[:, -1]   # [B, S]
    return jnp.take_along_axis(total, states, axis=-1)


def dfa_match_parallel(table: jnp.ndarray, accept: jnp.ndarray,
                       starts: jnp.ndarray,
                       data: jnp.ndarray) -> jnp.ndarray:
    """Anchored match of every regex against every row (parallel scan).

    Same contract as dfa_ops.dfa_match."""
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    final = dfa_parallel_scan(table, states, data)
    ok = accept[final]
    overlong = jnp.any(data == -2, axis=1)
    return ok & ~overlong[:, None]


# ---------------------------------------------------------------------------
# k-stride compose: depth reduction without a host-precomposed table
# ---------------------------------------------------------------------------

def dfa_scan_compose(table: jnp.ndarray, states: jnp.ndarray,
                     data: jnp.ndarray, k: int) -> jnp.ndarray:
    """Serial-equivalent scan in ceil(L/k) dependent steps.

    The per-byte transition functions are materialized ([B, L, S]) and
    composed in groups of ``k`` on device — k-1 parallel ``compose``
    rounds with no sequential dependency — then the carry walks the
    L/k group functions.  The middle ground between ``dfa_scan``
    (depth L, no precompute) and ``dfa_parallel_scan`` (depth log L,
    full O(L·S) scan work): used when the table is too large to stride-
    precompose on the host (ops/dfa_engine) but payloads are long
    enough that depth dominates.

    table: [S, 256]; states: [B, R] int32; data: [B, L]. Returns final
    states [B, R] (bit-identical to dfa_scan; padding composes as
    identity)."""
    b, l = data.shape
    pad = (-l) % k
    if pad:
        data = jnp.concatenate(
            [data, jnp.full((b, pad), -1, data.dtype)], axis=1)
    f = transition_functions(table, data)          # [B, L', S]
    f = f.reshape(b, -1, k, f.shape[-1])
    g = f[:, :, 0]
    for j in range(1, k):                          # apply position j after
        g = compose(f[:, :, j], g)                 # the earlier ones
    def step(st, gcol):                            # gcol: [B, S]
        # cast keeps the carry dtype stable when the table is quantized
        nxt = jnp.take_along_axis(gcol, st, axis=-1)
        return nxt.astype(states.dtype), None
    final, _ = lax.scan(step, states, jnp.swapaxes(g, 0, 1))
    return final


@functools.partial(jax.jit, static_argnums=(4,))
def dfa_match_compose(table: jnp.ndarray, accept: jnp.ndarray,
                      starts: jnp.ndarray, data: jnp.ndarray,
                      k: int) -> jnp.ndarray:
    """Anchored match via the k-stride compose scan (dfa_match
    contract, including the -2 overlong poison)."""
    b = data.shape[0]
    states = jnp.broadcast_to(starts[None, :],
                              (b, starts.shape[0])).astype(jnp.int32)
    final = dfa_scan_compose(table, states, data, k)
    ok = accept[final]
    overlong = jnp.any(data == -2, axis=1)
    return ok & ~overlong[:, None]


# ---------------------------------------------------------------------------
# Multi-chip: sequence axis sharded over the mesh
# ---------------------------------------------------------------------------

def dfa_scan_sharded(table: jnp.ndarray, states: jnp.ndarray,
                     data: jnp.ndarray, mesh: Mesh,
                     seq_axis: str) -> jnp.ndarray:
    """Final DFA states with the SEQUENCE dimension sharded over
    ``seq_axis`` — context parallelism for payloads too long for one
    chip.

    Each device composes its local [L/N] chunk into one transition
    vector (log-depth associative scan), then an exclusive-prefix
    composition across devices runs as log2(N) ``lax.ppermute`` hops
    over ICI; finally every device applies (prefix ∘ local) and the
    last shard holds the answer, which is returned replicated.

    table/states replicated; data [B, L] with L divisible by the axis
    size. Returns final states [B, R] (replicated)."""
    n = mesh.shape[seq_axis]
    s = table.shape[0]

    def local(table_l, states_l, data_l):
        f = transition_functions(table_l, data_l)   # [B, L/N, S]
        chunk = lax.associative_scan(
            lambda a, b: compose(b, a), f, axis=1)[:, -1]  # [B, S]

        # Hillis-Steele inclusive prefix composition across devices:
        # after round hop, acc_i = f_i ∘ … ∘ f_{max(0, i-2*hop+1)}; at
        # the end acc_i = f_i ∘ … ∘ f_0 (log2(N) ppermute hops on ICI)
        idx = lax.axis_index(seq_axis)
        ident = jnp.broadcast_to(jnp.arange(s, dtype=chunk.dtype),
                                 chunk.shape)
        acc = chunk
        hop = 1
        while hop < n:
            shifted = lax.ppermute(
                acc, seq_axis,
                [(i, i + hop) for i in range(n - hop)])
            # devices with nothing to their left compose with identity
            shifted = jnp.where(idx >= hop, shifted, ident)
            acc = compose(acc, shifted)  # earlier chunks apply first
            hop <<= 1

        # the last shard's inclusive prefix is the whole sequence;
        # replicate it via a masked psum
        is_last = (idx == n - 1).astype(acc.dtype)
        total = lax.psum(acc * is_last, seq_axis)
        return jnp.take_along_axis(total, states_l, axis=-1)

    return shard_map(
        local, mesh=mesh,
        in_specs=(P(), P(), P(None, seq_axis)),
        out_specs=P(),
    )(table, states, data)
