"""Batched longest-prefix-match lookup (device side).

The reference's LPM trie walk (bpf/lib/maps.h ipcache, bpf_xdp.c:97
check_v4) becomes: for each of P distinct prefix lengths (descending), a
masked exact-match probe; the first (=longest) hit wins. P ≤ 40
(MaxCIDRPrefixLengths) keeps the [B, P, K] gather volume bounded.

First-hit selection along P uses a cumsum mask (hit & cumsum(hit)==1)
instead of argmax + take_along_axis — axis-indexed selects are slow on
this platform (see hashtab_ops).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from .hashtab_ops import hash_mix_jnp

# Plain Python int: a module-level jnp scalar would be captured as a
# device-array constant in every jit and costs a host sync per call on
# this platform (measured ~200x slowdown).
LPM_MISS = -1


def lpm_lookup(masks: jnp.ndarray, key_a: jnp.ndarray, key_b: jnp.ndarray,
               value: jnp.ndarray, prefix_lens: jnp.ndarray,
               addrs: jnp.ndarray, max_probe: int
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """LPM over stacked per-length tables.

    masks: [P] int32; key_a/key_b/value: [P, S] int32; prefix_lens: [P]
    (descending); addrs: [B] int32 (uint32 addresses bit-cast).
    Returns (found [B] bool, value [B] int32 — LPM_MISS on miss).
    """
    p, slots = key_a.shape
    if p == 0:
        b = addrs.shape[0]
        return jnp.zeros(b, bool), jnp.full(b, LPM_MISS, jnp.int32)
    mask_slots = jnp.int32(slots - 1)

    masked = addrs.astype(jnp.int32)[:, None] & masks.astype(jnp.int32)[None, :]
    qb = ((prefix_lens.astype(jnp.int32) << 1) | 1)[None, :]       # [1, P]
    qb = jnp.broadcast_to(qb, masked.shape)                        # [B, P]

    h = hash_mix_jnp(masked, qb)
    base = h & mask_slots                                          # [B, P]
    probes = (base[:, :, None] +
              jnp.arange(max_probe, dtype=jnp.int32)[None, None, :]) \
        & mask_slots                                               # [B,P,K]
    row_off = (jnp.arange(p, dtype=jnp.int32) * jnp.int32(slots))[None, :, None]
    flat_idx = row_off + probes

    flat_a, flat_b = key_a.reshape(-1), key_b.reshape(-1)
    flat_v = value.reshape(-1)
    # Gather with a 2-D index array: 3-D advanced indexing lowers to a
    # pathologically slow gather on this platform (measured ~10^4 x).
    b = addrs.shape[0]
    idx2 = flat_idx.reshape(b, p * max_probe)
    got_a = flat_a[idx2].reshape(b, p, max_probe)
    got_b = flat_b[idx2].reshape(b, p, max_probe)
    got_v = flat_v[idx2].reshape(b, p, max_probe)
    hit = (got_a == masked[:, :, None]) & (got_b == qb[:, :, None]) & \
        (got_b != 0)

    # Within one prefix-length table keys are unique: masked sum over K.
    hit_per_len = jnp.any(hit, axis=2)                             # [B, P]
    val_per_len = jnp.sum(jnp.where(hit, got_v, jnp.int32(0)), axis=2)
    # Longest match = first hit in descending-length order.
    first_mask = hit_per_len & (jnp.cumsum(hit_per_len.astype(jnp.int32),
                                           axis=1) == 1)
    any_hit = jnp.any(hit_per_len, axis=1)
    val = jnp.sum(jnp.where(first_mask, val_per_len, jnp.int32(0)), axis=1)
    return any_hit, jnp.where(any_hit, val, jnp.int32(LPM_MISS))


def _hash6_jnp(w0, w1, w2, w3, occ):
    """Device twin of compiler.lpm._hash6 — keep in lockstep."""
    return hash_mix_jnp(hash_mix_jnp(w0, w1),
                        hash_mix_jnp(w2 ^ occ, w3))


def lpm6_lookup(masks: jnp.ndarray, k0: jnp.ndarray, k1: jnp.ndarray,
                k2: jnp.ndarray, k3: jnp.ndarray, kb: jnp.ndarray,
                value: jnp.ndarray, prefix_lens: jnp.ndarray,
                addrs: jnp.ndarray, max_probe: int
                ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """IPv6 LPM over stacked per-length tables (full 128-bit compare).

    masks: [P, 4]; k0..k3/kb/value: [P, S]; prefix_lens: [P]
    (descending); addrs: [B, 4] int32 big-endian words.
    Returns (found [B] bool, value [B] int32 — LPM_MISS on miss).
    """
    p, slots = kb.shape
    b = addrs.shape[0]
    if p == 0:
        return jnp.zeros(b, bool), jnp.full(b, LPM_MISS, jnp.int32)
    mask_slots = jnp.int32(slots - 1)

    # [B, P] masked words
    a = addrs.astype(jnp.int32)
    m = masks.astype(jnp.int32)
    w = [a[:, None, i] & m[None, :, i] for i in range(4)]
    occ = ((prefix_lens.astype(jnp.int32) << 1) | 1)[None, :]      # [1, P]
    occ = jnp.broadcast_to(occ, w[0].shape)

    h = _hash6_jnp(w[0], w[1], w[2], w[3], occ)
    base = h & mask_slots                                          # [B, P]
    probes = (base[:, :, None] +
              jnp.arange(max_probe, dtype=jnp.int32)[None, None, :]) \
        & mask_slots
    row_off = (jnp.arange(p, dtype=jnp.int32) * jnp.int32(slots))[None, :, None]
    idx2 = (row_off + probes).reshape(b, p * max_probe)

    def gather(t):
        return t.reshape(-1)[idx2].reshape(b, p, max_probe)

    hit = (gather(k0) == w[0][:, :, None]) & \
        (gather(k1) == w[1][:, :, None]) & \
        (gather(k2) == w[2][:, :, None]) & \
        (gather(k3) == w[3][:, :, None])
    got_b = gather(kb)
    got_v = gather(value)
    hit = hit & (got_b == occ[:, :, None]) & (got_b != 0)

    hit_per_len = jnp.any(hit, axis=2)
    val_per_len = jnp.sum(jnp.where(hit, got_v, jnp.int32(0)), axis=2)
    first_mask = hit_per_len & (jnp.cumsum(hit_per_len.astype(jnp.int32),
                                           axis=1) == 1)
    any_hit = jnp.any(hit_per_len, axis=1)
    val = jnp.sum(jnp.where(first_mask, val_per_len, jnp.int32(0)), axis=1)
    return any_hit, jnp.where(any_hit, val, jnp.int32(LPM_MISS))
