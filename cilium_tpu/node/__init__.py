"""Node discovery and inter-node datapath programming.

Analog of the reference's ``pkg/node``: each agent registers its Node in
the kvstore shared store (``cilium/state/nodes/v1``), watches peers, and
programs per-remote-node forwarding state (the tunnel-endpoint table the
datapath's encap step consumes — pkg/maps/tunnel analog).
"""

from .node import Node, NodeAddress
from .manager import NodeManager
from .registry import NODES_PATH, NodeRegistry

__all__ = ["Node", "NodeAddress", "NodeManager", "NodeRegistry",
           "NODES_PATH"]
