"""Node registry over the kvstore shared store.

Reference: pkg/node/store.go — nodes register at
``cilium/state/nodes/v1/<cluster>/<name>`` (lease-backed) and watch the
prefix for peers joining/leaving.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional

from ..kvstore.backend import BackendOperations
from ..kvstore.store import SharedStore
from .node import Node

NODES_PATH = "cilium/state/nodes/v1"


class NodeRegistry:
    """Publish the local node + track the cluster's node set."""

    def __init__(self, backend: BackendOperations,
                 on_node_update: Optional[Callable[[Node], None]] = None,
                 on_node_delete: Optional[Callable[[str], None]] = None):
        self._on_update = on_node_update
        self._on_delete = on_node_delete
        self._mu = threading.Lock()
        self._nodes: Dict[str, Node] = {}
        self._store = SharedStore(backend, NODES_PATH,
                                  on_update=self._store_update,
                                  on_delete=self._store_delete)

    def _store_update(self, name: str, value: dict) -> None:
        try:
            node = Node.from_model(value)
        except (KeyError, ValueError):
            return
        with self._mu:
            self._nodes[node.full_name] = node
        if self._on_update:
            self._on_update(node)

    def _store_delete(self, name: str) -> None:
        with self._mu:
            self._nodes.pop(name, None)
        if self._on_delete:
            self._on_delete(name)

    def register_local(self, node: Node) -> None:
        """Publish (lease-backed: the entry dies with this agent's
        session — the failure-detection path)."""
        self._store.update_local(node.full_name, node.to_model())

    def unregister_local(self, node: Node) -> None:
        self._store.delete_local(node.full_name)

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._store.wait_synced(timeout)

    def nodes(self) -> List[Node]:
        with self._mu:
            return sorted(self._nodes.values(), key=lambda n: n.full_name)

    def get(self, full_name: str) -> Optional[Node]:
        with self._mu:
            return self._nodes.get(full_name)

    def __len__(self):
        with self._mu:
            return len(self._nodes)

    def close(self) -> None:
        self._store.close()
