"""The Node model.

Reference: pkg/node/node.go — Node{Name, Cluster, IPAddresses,
IPv4AllocCIDR, IPv6AllocCIDR, ClusterID} plus helpers; serialized into
the kvstore store (pkg/node/store.go).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ADDR_INTERNAL_IP = "InternalIP"
ADDR_EXTERNAL_IP = "ExternalIP"
ADDR_CILIUM_INTERNAL_IP = "CiliumInternalIP"


@dataclass(frozen=True)
class NodeAddress:
    type: str
    ip: str


@dataclass
class Node:
    """One cluster node and its pod-CIDR allocation."""

    name: str
    cluster: str = "default"
    cluster_id: int = 0
    addresses: List[NodeAddress] = field(default_factory=list)
    ipv4_alloc_cidr: Optional[str] = None  # pod CIDR served by this node
    ipv6_alloc_cidr: Optional[str] = None
    # observer endpoint this node's Hubble serves /flows on (base URL);
    # peers' relays federate through it (hubble-relay peer service)
    hubble_address: Optional[str] = None

    @property
    def full_name(self) -> str:
        return f"{self.cluster}/{self.name}"

    def get_node_ip(self, ipv6: bool = False) -> Optional[str]:
        """Preferred reachable address (reference: node.GetNodeIP —
        internal beats external)."""
        want_version = 6 if ipv6 else 4
        best = None
        for pref in (ADDR_CILIUM_INTERNAL_IP, ADDR_INTERNAL_IP,
                     ADDR_EXTERNAL_IP):
            for a in self.addresses:
                try:
                    if ipaddress.ip_address(a.ip).version != want_version:
                        continue
                except ValueError:
                    continue
                if a.type == pref:
                    return a.ip
                best = best or a.ip
        return best

    def to_model(self) -> Dict:
        out = {
            "Name": self.name,
            "Cluster": self.cluster,
            "ClusterID": self.cluster_id,
            "IPAddresses": [{"Type": a.type, "IP": a.ip}
                            for a in self.addresses],
            "IPv4AllocCIDR": self.ipv4_alloc_cidr,
            "IPv6AllocCIDR": self.ipv6_alloc_cidr,
        }
        if self.hubble_address:
            out["HubbleAddress"] = self.hubble_address
        return out

    @classmethod
    def from_model(cls, d: Dict) -> "Node":
        return cls(name=d["Name"], cluster=d.get("Cluster", "default"),
                   cluster_id=int(d.get("ClusterID", 0)),
                   addresses=[NodeAddress(type=a["Type"], ip=a["IP"])
                              for a in d.get("IPAddresses", [])],
                   ipv4_alloc_cidr=d.get("IPv4AllocCIDR"),
                   ipv6_alloc_cidr=d.get("IPv6AllocCIDR"),
                   hubble_address=d.get("HubbleAddress"))
