"""Per-remote-node datapath programming.

Reference: pkg/node/manager.go:94-195 — for every peer node the agent
programs (a) the tunnel map entry pod-CIDR -> node IP (tunnel mode;
pkg/maps/tunnel) or a direct route, and (b) an ipcache entry marking the
node's pod CIDR as remote. Here the "tunnel map" is a host dict the
encap stage consumes, and the pod-CIDR ipcache upserts flow through the
normal listener into the device LPM.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from ..identity import RESERVED_WORLD
from ..ipcache.ipcache import SOURCE_KVSTORE, IPCache
from .node import Node

ROUTE_TUNNEL = "tunnel"
ROUTE_DIRECT = "direct"


class NodeManager:
    """Realize node add/update/delete into forwarding state."""

    def __init__(self, local_node: str, ipcache: Optional[IPCache] = None,
                 mode: str = ROUTE_TUNNEL, datapath=None):
        self.local_node = local_node
        self.mode = mode
        self.ipcache = ipcache
        # datapath.load_tunnel realizes tunnel_map changes as the
        # device-resident tunnel LPM the encap stage consumes
        # (pkg/maps/tunnel SetTunnelEndpoint -> cilium_tunnel_map)
        self.datapath = datapath
        self._mu = threading.Lock()
        self._nodes: Dict[str, Node] = {}
        # pod CIDR prefix -> tunnel endpoint IP (pkg/maps/tunnel analog)
        self.tunnel_map: Dict[str, str] = {}
        # direct routes: pod CIDR -> nexthop node IP
        self.routes: Dict[str, str] = {}

    def _program_tunnel(self) -> None:
        """Push the current tunnel map into the datapath (device LPM:
        pod CIDR -> tunnel endpoint node IP as u32).  Snapshot and
        apply under one lock hold: concurrent node events (registry
        watch thread + clustermesh) applying snapshots out of order
        would leave stale tunnel state programmed."""
        if self.datapath is None:
            return
        from ..compiler.lpm import ipv4_to_u32
        with self._mu:
            prefixes = {cidr: int(ipv4_to_u32(ip))
                        for cidr, ip in self.tunnel_map.items()}
            self.datapath.load_tunnel(prefixes)

    def node_updated(self, node: Node) -> None:
        """Reference: manager.go NodeUpdated — program or refresh the
        per-node state (idempotent)."""
        if node.full_name == self.local_node:
            return
        node_ip = node.get_node_ip()
        with self._mu:
            old = self._nodes.get(node.full_name)
            if old is not None and old.ipv4_alloc_cidr and \
                    old.ipv4_alloc_cidr != node.ipv4_alloc_cidr:
                self._remove_cidr_locked(old.ipv4_alloc_cidr)
            self._nodes[node.full_name] = node
            if node.ipv4_alloc_cidr and node_ip:
                if self.mode == ROUTE_TUNNEL:
                    self.tunnel_map[node.ipv4_alloc_cidr] = node_ip
                else:
                    self.routes[node.ipv4_alloc_cidr] = node_ip
        if self.ipcache is not None and node.ipv4_alloc_cidr and node_ip:
            # remote pod CIDR resolves to world until a more specific
            # endpoint entry arrives via the ip-identity watch
            self.ipcache.upsert(node.ipv4_alloc_cidr, RESERVED_WORLD,
                                SOURCE_KVSTORE, host_ip=node_ip,
                                metadata=f"node:{node.full_name}")
        self._program_tunnel()

    def node_deleted(self, full_name: str) -> None:
        """Reference: manager.go NodeDeleted — tear down routes/tunnel."""
        with self._mu:
            node = self._nodes.pop(full_name, None)
            if node is None:
                return
            if node.ipv4_alloc_cidr:
                self._remove_cidr_locked(node.ipv4_alloc_cidr)
        if self.ipcache is not None and node.ipv4_alloc_cidr:
            self.ipcache.delete(node.ipv4_alloc_cidr, SOURCE_KVSTORE)
        self._program_tunnel()

    def _remove_cidr_locked(self, cidr: str) -> None:
        self.tunnel_map.pop(cidr, None)
        self.routes.pop(cidr, None)

    def nodes(self) -> list:
        """Known peer nodes (manager view, for `cilium node list` when
        no kvstore registry is attached)."""
        with self._mu:
            return sorted(self._nodes.values(),
                          key=lambda n: n.full_name)

    def tunnel_endpoint_for(self, pod_cidr: str) -> Optional[str]:
        with self._mu:
            return self.tunnel_map.get(pod_cidr)

    def __len__(self):
        with self._mu:
            return len(self._nodes)
