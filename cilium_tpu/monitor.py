"""Monitor: datapath event aggregation + subscriber fan-out.

Reference: monitor/ + pkg/monitor — BPF programs emit DropNotify/
TraceNotify into a perf ring; cilium-node-monitor consumes it and fans
out to subscribers over unix sockets (monitor/main.go:81-119), with
decoders in pkg/monitor/datapath_{drop,trace}.go. Here the batched
datapath returns one event code per packet; the hub aggregates counts
(metricsmap analog), keeps a bounded sample ring, and fans decoded
samples out to in-process subscribers (the CLI's ``monitor`` command).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .datapath.events import (DROP_NAMES, TIER_L7_FAST_ALLOW,
                              TIER_L7_FAST_DENY, TIER_NAMES,
                              TRACE_NAMES, format_denied_key)
from .utils.metrics import (DROP_COUNT, FORWARD_COUNT,
                            L7_FAST_VERDICTS, POLICY_RULE_DROPS,
                            POLICY_VERDICT_TIERS, THREAT_SCORES,
                            THREAT_VERDICTS)

# label-cardinality guard: at most this many DISTINCT denied keys are
# admitted into the per-rule drop counter per ingested batch (the
# biggest offenders win; the rest still count under drop_count_total)
MAX_RULE_KEYS_PER_BATCH = 32


@dataclass(frozen=True)
class MonitorEvent:
    """One decoded sample.

    kind "" = datapath DropNotify/TraceNotify analog (code/endpoint/
    packet fields populated); kind "agent" = AgentNotify analog
    (pkg/monitor/agent events: policy updates, endpoint lifecycle);
    kind "l7" = LogRecordNotify analog (proxy access-log records in
    the monitor stream) — the same three families `cilium monitor`
    prints in the reference."""

    timestamp: float
    code: int            # trace point (>=0) or drop reason (<0)
    endpoint: int
    identity: int
    dport: int
    proto: int
    length: int
    kind: str = ""       # "" | "agent" | "l7"
    note: str = ""
    # hub-assigned monotonic sequence number (perf-ring cursor analog):
    # pollers resume from ?since=<seq> instead of deduping replays
    seq: int = 0
    # verdict provenance (0/"" when provenance is disabled): the
    # decision-tier code (events.TIER_*) and the compiled rule key
    # that decided — the matched policymap entry, or for drops the
    # denied query key (events.format_denied_key)
    tier: int = 0
    matched_rule: str = ""

    @property
    def is_drop(self) -> bool:
        return self.kind == "" and self.code < 0

    def describe(self) -> str:
        if self.kind == "agent":
            return f"AGENT {self.note}"
        if self.kind == "l7":
            return f"L7 {self.note}"
        name = DROP_NAMES.get(self.code) or TRACE_NAMES.get(self.code) or \
            f"code {self.code}"
        kind = "DROP" if self.is_drop else "TRACE"
        prov = ""
        if self.tier:
            prov = f" tier={TIER_NAMES.get(self.tier, self.tier)}"
            if self.matched_rule:
                prov += f" rule={self.matched_rule}"
        return (f"{kind} ep={self.endpoint} identity={self.identity} "
                f"dport={self.dport} proto={self.proto} "
                f"len={self.length}: {name}{prov}")


class MonitorHub:
    """Aggregate + sample + fan out datapath events."""

    def __init__(self, ring_capacity: int = 4096,
                 samples_per_batch: int = 16):
        self.ring_capacity = ring_capacity
        self.samples_per_batch = samples_per_batch
        self._lock = threading.Lock()
        self._ring: List[MonitorEvent] = []
        self._counts: Dict[int, int] = {}
        self._bytes: Dict[int, int] = {}
        self._subscribers: List[Callable[[MonitorEvent], None]] = []
        self.lost = 0  # samples not ringed (perf-ring lost-events analog)
        # AgentNotify / LogRecordNotify counters, keyed by event name
        self._notify_counts: Dict[str, int] = {}
        # monotonic event cursor; 0 is the "from the beginning" sentinel
        self._next_seq = 1
        # provenance: cumulative drops per denied/matched rule key
        # (the "top-dropped rules" surface; fed only when the caller
        # passes tiers/match_slots from an enable_provenance engine)
        self._rule_drops: Dict[str, int] = {}

    # ------------------------------------------------------------ ingest

    def ingest_batch(self, event_codes, endpoints, identities, dports,
                     protos, lengths, tiers=None, match_slots=None,
                     rule_of=None, l7_proto_of=None,
                     threat_out=None) -> None:
        """Aggregate one datapath batch (all args array-like [B]).

        ``tiers``/``match_slots`` are the engine's per-packet
        provenance outputs (Datapath.last_provenance) and ``rule_of``
        its slot->string decoder (Datapath.provenance_rule_of): when
        present, samples carry the decision tier + decided rule,
        verdicts count by tier, and drops aggregate per denied key.
        ``l7_proto_of`` (Datapath.l7_fast_protocol_of) maps a match
        slot to its fast program's protocol tag so rows decided by the
        on-device L7 fast-verdict stage feed
        ``l7_fast_verdicts_total{protocol,outcome}``.

        ``threat_out`` is the engine's packed per-packet threat lane
        (Datapath.last_threat: score | band<<8 | fired): feeds
        ``threat_verdicts_total{outcome}`` and the score histogram."""
        codes = np.asarray(event_codes)
        eps = np.asarray(endpoints)
        ids = np.asarray(identities)
        dps = np.asarray(dports)
        prs = np.asarray(protos)
        lns = np.asarray(lengths)
        trs = None if tiers is None else np.asarray(tiers)
        slots = None if match_slots is None else np.asarray(match_slots)
        now = time.time()

        uniq, cnt = np.unique(codes, return_counts=True)
        drop_bytes: Dict[int, int] = {}
        for code, n in zip(uniq.tolist(), cnt.tolist()):
            drop_bytes[code] = int(lns[codes == code].sum())
            if code < 0:
                DROP_COUNT.inc(n, labels={
                    "reason": DROP_NAMES.get(code, str(code))})
            else:
                FORWARD_COUNT.inc(n)

        if trs is not None:
            for tier, n in zip(*map(np.ndarray.tolist,
                                    np.unique(trs, return_counts=True))):
                POLICY_VERDICT_TIERS.inc(n, labels={
                    "tier": TIER_NAMES.get(tier, str(tier))})
            self._count_l7_fast(trs, slots, l7_proto_of)
        if threat_out is not None:
            self._count_threat(np.asarray(threat_out))
        rule_drops = self._aggregate_rule_drops(codes, ids, dps, prs,
                                                slots, rule_of) \
            if trs is not None else {}

        def _rule(i: int) -> str:
            if trs is None:
                return ""
            if slots is not None and int(slots[i]) >= 0 and \
                    rule_of is not None:
                return rule_of(int(slots[i]))
            if int(codes[i]) < 0:
                return format_denied_key(int(ids[i]), int(dps[i]),
                                         int(prs[i]))
            return ""

        # bounded sampling: first K drops + first K traces per batch
        samples: List[MonitorEvent] = []
        for want_drop in (True, False):
            mask = codes < 0 if want_drop else codes >= 0
            idx = np.flatnonzero(mask)[:self.samples_per_batch]
            for i in idx.tolist():
                samples.append(MonitorEvent(
                    timestamp=now, code=int(codes[i]), endpoint=int(eps[i]),
                    identity=int(ids[i]), dport=int(dps[i]),
                    proto=int(prs[i]), length=int(lns[i]),
                    tier=0 if trs is None else int(trs[i]),
                    matched_rule=_rule(i)))
        with self._lock:
            for code, n in zip(uniq.tolist(), cnt.tolist()):
                self._counts[code] = self._counts.get(code, 0) + int(n)
                self._bytes[code] = self._bytes.get(code, 0) + \
                    drop_bytes[code]
            for rule, n in rule_drops.items():
                self._rule_drops[rule] = \
                    self._rule_drops.get(rule, 0) + n
            # stamp the monotonic cursor under the lock (the seq order
            # IS the ring order — pollers resume from it)
            from dataclasses import replace as _replace
            samples = [_replace(ev, seq=self._next_seq + i)
                       for i, ev in enumerate(samples)]
            self._next_seq += len(samples)
            self._ring.extend(samples)
            if len(self._ring) > self.ring_capacity:
                self._ring = self._ring[-self.ring_capacity:]
            self.lost += max(0, int(codes.shape[0]) - len(samples))
            subs = list(self._subscribers)
        for fn in subs:
            for ev in samples:
                fn(ev)

    @staticmethod
    def _count_l7_fast(trs, slots, l7_proto_of) -> None:
        """Count rows the on-device L7 fast-verdict stage decided into
        l7_fast_verdicts_total{protocol,outcome}.  Protocol resolves
        per distinct match slot (one decode covers the whole group) —
        the fast tiers always carry the decided redirect entry's
        slot."""
        for tier, outcome in ((TIER_L7_FAST_ALLOW, "allow"),
                              (TIER_L7_FAST_DENY, "deny")):
            mask = trs == tier
            total = int(mask.sum())
            if not total:
                continue
            if slots is None or l7_proto_of is None:
                L7_FAST_VERDICTS.inc(total, labels={
                    "protocol": "unknown", "outcome": outcome})
                continue
            uniq, cnt = np.unique(slots[mask], return_counts=True)
            for slot, n in zip(uniq.tolist(), cnt.tolist()):
                proto = l7_proto_of(int(slot)) or "unknown"
                L7_FAST_VERDICTS.inc(int(n), labels={
                    "protocol": proto, "outcome": outcome})

    @staticmethod
    def _count_threat(out: np.ndarray) -> None:
        """Decode one batch's packed threat lane into outcome counts
        + the score histogram (grouped by distinct score so a big
        batch costs at most 256 histogram touches)."""
        from .threat.stage import unpack_threat_out
        score, band, fired = unpack_threat_out(out)
        outcome = np.where(
            fired & (band == 3), 3,
            np.where(fired & (band == 1), 1,
                     np.where(fired & (band == 2), 2, 0)))
        names = {0: "scored", 1: "rate-limited", 2: "redirected",
                 3: "dropped"}
        for code, n in zip(*map(np.ndarray.tolist,
                                np.unique(outcome,
                                          return_counts=True))):
            THREAT_VERDICTS.inc(n, labels={"outcome": names[code]})
        for val, n in zip(*map(np.ndarray.tolist,
                               np.unique(score, return_counts=True))):
            THREAT_SCORES.observe_many(float(val), n)

    @staticmethod
    def _aggregate_rule_drops(codes, ids, dps, prs, slots,
                              rule_of) -> Dict[str, int]:
        """Per-rule-key drop totals for one batch: dropped rows group
        by (identity, dport, proto) — for provenance tiers a drop
        means NO compiled entry matched, so the denied query key IS
        the attribution operators need ("who is being denied what").
        Capped at MAX_RULE_KEYS_PER_BATCH distinct keys (biggest
        first) so one scan can't explode metric cardinality."""
        drop_idx = np.flatnonzero(codes < 0)
        if drop_idx.size == 0:
            return {}
        keyed = np.stack([ids[drop_idx].astype(np.int64),
                          dps[drop_idx].astype(np.int64),
                          prs[drop_idx].astype(np.int64)], axis=1)
        uniq, cnt = np.unique(keyed, axis=0, return_counts=True)
        order = np.argsort(cnt)[::-1][:MAX_RULE_KEYS_PER_BATCH]
        out: Dict[str, int] = {}
        for j in order.tolist():
            rule = format_denied_key(int(uniq[j, 0]), int(uniq[j, 1]),
                                     int(uniq[j, 2]))
            out[rule] = int(cnt[j])
            POLICY_RULE_DROPS.inc(int(cnt[j]), labels={"rule": rule})
        return out

    def top_dropped_rules(self, n: int = 10) -> List[Dict]:
        """The denied rule keys dropping the most packets (cumulative
        since start/reset), largest first."""
        with self._lock:
            items = sorted(self._rule_drops.items(),
                           key=lambda kv: -kv[1])[:n]
        return [{"rule": rule, "packets": count}
                for rule, count in items]

    def _push(self, ev: MonitorEvent, counter: str) -> None:
        from dataclasses import replace as _replace
        with self._lock:
            self._notify_counts[counter] = \
                self._notify_counts.get(counter, 0) + 1
            ev = _replace(ev, seq=self._next_seq)
            self._next_seq += 1
            self._ring.append(ev)
            if len(self._ring) > self.ring_capacity:
                self._ring = self._ring[-self.ring_capacity:]
            subs = list(self._subscribers)
        for fn in subs:
            fn(ev)

    def notify_agent(self, event: str, note: str = "") -> None:
        """AgentNotify analog (pkg/monitor agent events: policy
        updated/deleted, endpoint lifecycle, agent start)."""
        self._push(MonitorEvent(
            timestamp=time.time(), code=0, endpoint=0, identity=0,
            dport=0, proto=0, length=0, kind="agent",
            note=f"{event} {note}".strip()), f"agent:{event}")

    def notify_l7(self, entry) -> None:
        """LogRecordNotify analog: a proxy access-log record enters
        the monitor stream (pkg/proxy/logger -> monitor)."""
        info = " ".join(f"{k}={v}" for k, v in
                        sorted((entry.info or {}).items()))
        self._push(MonitorEvent(
            timestamp=entry.timestamp, code=0, endpoint=0,
            identity=entry.src_identity, dport=0, proto=0, length=0,
            kind="l7",
            note=f"{entry.l7_protocol} {entry.verdict} "
                 f"src={entry.src_identity} dst={entry.dst_identity} "
                 f"{info}".strip()),
            f"l7:{entry.l7_protocol}:{entry.verdict}")

    # --------------------------------------------------------- consumers

    def subscribe(self, fn: Callable[[MonitorEvent], None]) -> Callable:
        """Register a subscriber; returns an unsubscribe closure
        (monitor/main.go fan-out analog)."""
        with self._lock:
            self._subscribers.append(fn)

        def unsubscribe():
            with self._lock:
                if fn in self._subscribers:
                    self._subscribers.remove(fn)
        return unsubscribe

    def tail(self, n: int = 100, drops_only: bool = False,
             kind: Optional[str] = None,
             since: int = 0) -> List[MonitorEvent]:
        """Matching samples.  Without ``since``: the last ``n`` (the
        "show me recent events" view).  With ``since``: the OLDEST
        ``n`` with seq > since — forward paging, so a follower that
        fell behind a burst drains it page by page instead of having
        the middle silently capped away (nothing is lost unless it
        fell off the ring, which ``last_seq`` vs the first returned
        seq reveals)."""
        with self._lock:
            ring = list(self._ring)
        if since:
            ring = [e for e in ring if e.seq > since]
        if drops_only:
            ring = [e for e in ring if e.is_drop]
        if kind is not None:
            ring = [e for e in ring if e.kind == kind]
        return ring[:n] if since else ring[-n:]

    @property
    def last_seq(self) -> int:
        with self._lock:
            return self._next_seq - 1

    def stats(self) -> Dict[str, Dict]:
        """metricsmap-style dump: per-code packet/byte totals, plus
        agent/l7 notification counts."""
        with self._lock:
            out = {}
            for code, n in sorted(self._counts.items()):
                name = DROP_NAMES.get(code) or TRACE_NAMES.get(code) or \
                    str(code)
                out[name] = {"code": code, "packets": n,
                             "bytes": self._bytes.get(code, 0)}
            for name, n in sorted(self._notify_counts.items()):
                out[name] = {"events": n}
            return out

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self._counts = {}
            self._bytes = {}
            self._notify_counts = {}
            self._rule_drops = {}
            self.lost = 0


# ---------------------------------------------------------------------------
# Cross-process fan-out (monitor/main.go:81-119)
# ---------------------------------------------------------------------------
#
# The reference's cilium-node-monitor serves decoded events to N
# subscriber processes over a unix socket; slow subscribers get a lossy
# bounded queue, not backpressure into the datapath.  Here the hub is
# served over TCP with the kvstore framing: one writer thread + bounded
# queue per subscriber, overflow counted and dropped.

def _monitor_event_dict(ev: MonitorEvent) -> Dict:
    return {"seq": ev.seq, "timestamp": ev.timestamp, "code": ev.code,
            "endpoint": ev.endpoint, "identity": ev.identity,
            "dport": ev.dport, "proto": ev.proto, "length": ev.length,
            "kind": ev.kind, "note": ev.note, "tier": ev.tier,
            "matched_rule": ev.matched_rule,
            "message": ev.describe()}


class MonitorServer:
    """Serve a MonitorHub's event stream to subscriber processes."""

    def __init__(self, hub: MonitorHub, host: str = "127.0.0.1",
                 port: int = 0, queue_depth: int = 1024):
        import socketserver
        from .kvstore.server import recv_frame, send_frame
        self.hub = hub
        self.queue_depth = queue_depth
        outer = self

        class _Conn(socketserver.BaseRequestHandler):
            def setup(self):
                import queue as _q
                self.q: "_q.Queue" = _q.Queue(maxsize=outer.queue_depth)
                self.dropped = 0
                self.unsub = None

            def handle(self):
                import queue as _q
                # replay the ring, then follow live events
                req = recv_frame(self.request)
                if not req or req.get("op") != "follow":
                    return
                n = int(req.get("replay", 0))
                drops_only = bool(req.get("drops", False))

                def on_event(ev: MonitorEvent) -> None:
                    if drops_only and not ev.is_drop:
                        return
                    try:
                        self.q.put_nowait(ev)
                    except _q.Full:
                        self.dropped += 1  # lossy, never backpressures

                # subscribe BEFORE snapshotting the ring: events
                # ingested while the replay is on the wire land in the
                # queue instead of vanishing in the gap; the queue is
                # then deduped against what the replay already sent
                # (ring and queue share the same event objects)
                self.unsub = outer.hub.subscribe(on_event)
                # filter-before-truncate: replay=N means the last N
                # *matching* samples (hub.tail owns that semantics)
                replay = outer.hub.tail(n, drops_only=drops_only) \
                    if n else []
                replayed_ids = {id(ev) for ev in replay}
                for ev in replay:
                    try:
                        send_frame(self.request,
                                   _monitor_event_dict(ev))
                    except OSError:
                        return
                last_send = time.time()
                while not outer._stop.is_set():
                    try:
                        ev = self.q.get(timeout=0.5)
                    except _q.Empty:
                        # idle ping: the only way to notice a client
                        # that vanished while no events flow — without
                        # it the handler thread + hub subscription
                        # leak forever
                        if time.time() - last_send > 2.0:
                            try:
                                send_frame(self.request, {"ping": 1})
                                last_send = time.time()
                            except OSError:
                                return
                        continue
                    if id(ev) in replayed_ids:
                        replayed_ids.discard(id(ev))
                        continue  # already sent in the replay
                    try:
                        send_frame(self.request,
                                   _monitor_event_dict(ev))
                        last_send = time.time()
                    except OSError:
                        return

            def finish(self):
                if self.unsub is not None:
                    self.unsub()

        class _TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._stop = threading.Event()
        self._tcp = _TCP((host, port), _Conn)
        self.host, self.port = self._tcp.server_address
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True,
                                        name="monitor-server")

    def start(self) -> "MonitorServer":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()  # handler loops drain within their poll tick
        self._tcp.shutdown()
        self._tcp.server_close()


def monitor_follow(port: int, host: str = "127.0.0.1",
                   replay: int = 0, drops_only: bool = False):
    """Generator of event dicts from a MonitorServer — the subscriber
    half (cilium monitor following from a separate process)."""
    import socket as _socket
    from .kvstore.server import recv_frame, send_frame
    sock = _socket.create_connection((host, port), timeout=10)
    # clear the connect timeout: a quiet stream must block, not
    # silently end after 10 idle seconds (recv timeout would surface
    # as OSError -> recv_frame None -> clean-close ambiguity)
    sock.settimeout(None)
    try:
        send_frame(sock, {"op": "follow", "replay": replay,
                          "drops": drops_only})
        while True:
            msg = recv_frame(sock)
            if msg is None:
                return
            if "ping" in msg:
                continue  # server liveness probe, not an event
            yield msg
    finally:
        sock.close()
