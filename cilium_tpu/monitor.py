"""Monitor: datapath event aggregation + subscriber fan-out.

Reference: monitor/ + pkg/monitor — BPF programs emit DropNotify/
TraceNotify into a perf ring; cilium-node-monitor consumes it and fans
out to subscribers over unix sockets (monitor/main.go:81-119), with
decoders in pkg/monitor/datapath_{drop,trace}.go. Here the batched
datapath returns one event code per packet; the hub aggregates counts
(metricsmap analog), keeps a bounded sample ring, and fans decoded
samples out to in-process subscribers (the CLI's ``monitor`` command).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import numpy as np

from .datapath.events import DROP_NAMES, TRACE_NAMES
from .utils.metrics import DROP_COUNT, FORWARD_COUNT


@dataclass(frozen=True)
class MonitorEvent:
    """One decoded sample (DropNotify/TraceNotify analog)."""

    timestamp: float
    code: int            # trace point (>=0) or drop reason (<0)
    endpoint: int
    identity: int
    dport: int
    proto: int
    length: int

    @property
    def is_drop(self) -> bool:
        return self.code < 0

    def describe(self) -> str:
        name = DROP_NAMES.get(self.code) or TRACE_NAMES.get(self.code) or \
            f"code {self.code}"
        kind = "DROP" if self.is_drop else "TRACE"
        return (f"{kind} ep={self.endpoint} identity={self.identity} "
                f"dport={self.dport} proto={self.proto} "
                f"len={self.length}: {name}")


class MonitorHub:
    """Aggregate + sample + fan out datapath events."""

    def __init__(self, ring_capacity: int = 4096,
                 samples_per_batch: int = 16):
        self.ring_capacity = ring_capacity
        self.samples_per_batch = samples_per_batch
        self._lock = threading.Lock()
        self._ring: List[MonitorEvent] = []
        self._counts: Dict[int, int] = {}
        self._bytes: Dict[int, int] = {}
        self._subscribers: List[Callable[[MonitorEvent], None]] = []
        self.lost = 0  # samples not ringed (perf-ring lost-events analog)

    # ------------------------------------------------------------ ingest

    def ingest_batch(self, event_codes, endpoints, identities, dports,
                     protos, lengths) -> None:
        """Aggregate one datapath batch (all args array-like [B])."""
        codes = np.asarray(event_codes)
        eps = np.asarray(endpoints)
        ids = np.asarray(identities)
        dps = np.asarray(dports)
        prs = np.asarray(protos)
        lns = np.asarray(lengths)
        now = time.time()

        uniq, cnt = np.unique(codes, return_counts=True)
        drop_bytes: Dict[int, int] = {}
        for code, n in zip(uniq.tolist(), cnt.tolist()):
            drop_bytes[code] = int(lns[codes == code].sum())
            if code < 0:
                DROP_COUNT.inc(n, labels={
                    "reason": DROP_NAMES.get(code, str(code))})
            else:
                FORWARD_COUNT.inc(n)

        # bounded sampling: first K drops + first K traces per batch
        samples: List[MonitorEvent] = []
        for want_drop in (True, False):
            mask = codes < 0 if want_drop else codes >= 0
            idx = np.flatnonzero(mask)[:self.samples_per_batch]
            for i in idx.tolist():
                samples.append(MonitorEvent(
                    timestamp=now, code=int(codes[i]), endpoint=int(eps[i]),
                    identity=int(ids[i]), dport=int(dps[i]),
                    proto=int(prs[i]), length=int(lns[i])))
        with self._lock:
            for code, n in zip(uniq.tolist(), cnt.tolist()):
                self._counts[code] = self._counts.get(code, 0) + int(n)
                self._bytes[code] = self._bytes.get(code, 0) + \
                    drop_bytes[code]
            self._ring.extend(samples)
            if len(self._ring) > self.ring_capacity:
                self._ring = self._ring[-self.ring_capacity:]
            self.lost += max(0, int(codes.shape[0]) - len(samples))
            subs = list(self._subscribers)
        for fn in subs:
            for ev in samples:
                fn(ev)

    # --------------------------------------------------------- consumers

    def subscribe(self, fn: Callable[[MonitorEvent], None]) -> Callable:
        """Register a subscriber; returns an unsubscribe closure
        (monitor/main.go fan-out analog)."""
        with self._lock:
            self._subscribers.append(fn)

        def unsubscribe():
            with self._lock:
                if fn in self._subscribers:
                    self._subscribers.remove(fn)
        return unsubscribe

    def tail(self, n: int = 100,
             drops_only: bool = False) -> List[MonitorEvent]:
        with self._lock:
            ring = list(self._ring)
        if drops_only:
            ring = [e for e in ring if e.is_drop]
        return ring[-n:]

    def stats(self) -> Dict[str, Dict]:
        """metricsmap-style dump: per-code packet/byte totals."""
        with self._lock:
            out = {}
            for code, n in sorted(self._counts.items()):
                name = DROP_NAMES.get(code) or TRACE_NAMES.get(code) or \
                    str(code)
                out[name] = {"code": code, "packets": n,
                             "bytes": self._bytes.get(code, 0)}
            return out

    def reset(self) -> None:
        with self._lock:
            self._ring = []
            self._counts = {}
            self._bytes = {}
            self.lost = 0
