"""Endpoint registry + parallel regeneration build queue.

Reference: pkg/endpointmanager (registry, RegenerateAllEndpoints),
daemon/daemon.go:1133 StartEndpointBuilders (>=4 parallel workers) and
pkg/buildqueue (per-endpoint build serialization with coalescing: a
build requested while one is queued folds into it; a build requested
while one is *running* queues exactly one follow-up).
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional

from ..utils.metrics import (ENDPOINT_COUNT, ENDPOINT_REGENERATION_COUNT,
                             ENDPOINT_REGENERATION_TIME)
from .endpoint import Endpoint, EndpointState

MIN_BUILDERS = 4  # reference: daemon.go:1133 numWorkerThreads floor


class EndpointManager:
    """Registry by id / container name + the build queue."""

    def __init__(self, regenerate_fn: Optional[Callable[[Endpoint], None]]
                 = None, builders: int = MIN_BUILDERS,
                 on_outcome: Optional[Callable[[int, bool], None]] = None):
        self._lock = threading.RLock()
        self._by_id: Dict[int, Endpoint] = {}
        self._by_container: Dict[str, Endpoint] = {}
        self.regenerate_fn = regenerate_fn
        # (endpoint_id, ok) observer — the daemon feeds the monitor's
        # AgentNotify regenerate success/fail events from here
        self.on_outcome = on_outcome
        # build queue state (buildqueue semantics)
        self._queue: "queue.Queue[int]" = queue.Queue()
        self._queued: set = set()     # ids with a pending queue slot
        self._building: set = set()   # ids currently building
        self._rebuild: set = set()    # ids needing a follow-up build
        self._qlock = threading.Lock()
        self._idle = threading.Condition(self._qlock)
        self._stop = threading.Event()
        self._workers = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"ep-builder-{i}")
            for i in range(max(MIN_BUILDERS, builders))]
        for w in self._workers:
            w.start()

    # ---------------------------------------------------------- registry

    def insert(self, ep: Endpoint) -> None:
        with self._lock:
            self._by_id[ep.id] = ep
            if ep.container_name:
                self._by_container[ep.container_name] = ep
            ENDPOINT_COUNT.set(len(self._by_id))

    def remove(self, endpoint_id: int) -> Optional[Endpoint]:
        with self._lock:
            ep = self._by_id.pop(endpoint_id, None)
            if ep is not None and ep.container_name:
                self._by_container.pop(ep.container_name, None)
            ENDPOINT_COUNT.set(len(self._by_id))
            return ep

    def lookup(self, endpoint_id: int) -> Optional[Endpoint]:
        with self._lock:
            return self._by_id.get(endpoint_id)

    def lookup_container(self, name: str) -> Optional[Endpoint]:
        with self._lock:
            return self._by_container.get(name)

    def endpoints(self) -> List[Endpoint]:
        with self._lock:
            return list(self._by_id.values())

    def __len__(self):
        with self._lock:
            return len(self._by_id)

    # ------------------------------------------------------- build queue

    def queue_regeneration(self, endpoint_id: int) -> bool:
        """Enqueue a build for one endpoint. Coalesces: pending builds
        fold, a build during an active build queues one follow-up.
        Returns False if it folded into an existing request."""
        with self._qlock:
            if endpoint_id in self._building:
                self._rebuild.add(endpoint_id)
                return False
            if endpoint_id in self._queued:
                return False
            self._queued.add(endpoint_id)
            self._queue.put(endpoint_id)
            return True

    def regenerate_all(self, reason: str = "") -> int:
        """Reference: endpointmanager RegenerateAllEndpoints (fired by
        TriggerPolicyUpdates). Returns the number of builds enqueued."""
        n = 0
        for ep in self.endpoints():
            ep.set_state(EndpointState.WAITING_TO_REGENERATE,
                         reason or "regenerate-all")
            if self.queue_regeneration(ep.id):
                n += 1
        return n

    def wait_for_quiesce(self, timeout: float = 30.0) -> bool:
        """Block until no builds are queued or running (test barrier)."""
        with self._idle:
            return self._idle.wait_for(
                lambda: not self._queued and not self._building and
                not self._rebuild, timeout=timeout)

    def shutdown(self) -> None:
        self._stop.set()
        for _ in self._workers:
            self._queue.put(-1)
        for w in self._workers:
            w.join(timeout=5)

    def _worker(self) -> None:
        while not self._stop.is_set():
            ep_id = self._queue.get()
            if ep_id < 0:
                return
            with self._qlock:
                self._queued.discard(ep_id)
                self._building.add(ep_id)
            try:
                self._build_one(ep_id)
            except Exception:
                pass  # _build_one accounts failures; keep the worker alive
            finally:
                with self._qlock:
                    self._building.discard(ep_id)
                    if ep_id in self._rebuild:
                        self._rebuild.discard(ep_id)
                        self._queued.add(ep_id)
                        self._queue.put(ep_id)
                    self._idle.notify_all()

    def _build_one(self, ep_id: int) -> None:
        ep = self.lookup(ep_id)
        if ep is None or self.regenerate_fn is None:
            return
        if not ep.set_state(EndpointState.REGENERATING, "build queue"):
            # disconnecting/disconnected endpoints drop the build; any
            # other blocked state is accounted so it can't vanish silently
            if ep.state not in (EndpointState.DISCONNECTING,
                                EndpointState.DISCONNECTED):
                ENDPOINT_REGENERATION_COUNT.inc(
                    labels={"outcome": "skipped-state"})
            return
        ok = False
        import time
        t0 = time.perf_counter()
        try:
            self.regenerate_fn(ep)
            ok = True
        finally:
            ENDPOINT_REGENERATION_COUNT.inc(
                labels={"outcome": "success" if ok else "failure"})
            ENDPOINT_REGENERATION_TIME.observe(time.perf_counter() - t0)
            ep.set_state(EndpointState.READY if ok
                         else EndpointState.NOT_READY, "build done")
            if self.on_outcome is not None:
                try:
                    self.on_outcome(ep_id, ok)
                except Exception:  # noqa: BLE001 — observer must not
                    pass           # poison the build pipeline
