"""Stable numeric endpoint ids from runtime-assigned string keys.

Both container front ends derive the agent endpoint id by hashing the
runtime's identifier (reference: pkg/endpoint/id + the docker driver's
addressing.CiliumIPv6.EndpointID): the CNI plugin from the container
id, the docker libnetwork driver from docker's endpoint UUID.  One
definition here so the mapping cannot drift between them.

The per-caller bases keep typical ids visually distinct but the ranges
overlap (base + [0, 1M)); collisions — across or within front ends —
surface as a 409 from PUT /endpoint/{id}, exactly like a duplicate
create.
"""

from __future__ import annotations

import hashlib

CNI_ID_BASE = 10_000
DOCKER_ID_BASE = 20_000
_SPAN = 1_000_000


def stable_endpoint_id(key: str, base: int) -> int:
    h = hashlib.sha256(key.encode()).digest()
    return base + int.from_bytes(h[:4], "big") % _SPAN
