"""Device-resident stacked policy tables with incremental row updates.

The analog of the reference's per-endpoint pinned BPF policy maps
(pkg/maps/policymap) plus the incremental sync (pkg/endpoint/bpf.go:607
syncPolicyMap): per-endpoint verdict tables live stacked in one [E, S]
device tensor; syncing one endpoint's policy rewrites only that
endpoint's row (three [S] int32 transfers), not the whole stack. Growth
(more endpoints / bigger tables / longer probe chains) falls back to a
full rebuild + swap — the double-buffered "generation" path.
"""

from __future__ import annotations

import threading

from ..utils.lock import RMutex
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.hashtab import HashTable, _next_pow2, build_hash_table
from ..compiler.policy_tables import pack_key
from ..observability.jitstats import jit_telemetry
from ..observability.stages import record_stage
from ..policy.mapstate import PolicyMapState

MIN_SLOTS = 64


def _build_endpoint_table(state: PolicyMapState, slots: Optional[int],
                          max_load: float = 0.5) -> HashTable:
    entries = {pack_key(k): v.proxy_port for k, v in state.items()}
    if slots is None:
        return build_hash_table(entries, min_slots=MIN_SLOTS,
                                max_load=max_load)
    t = build_hash_table(entries, min_slots=slots, max_load=1.0)
    if t.slots != slots:
        raise _NeedsGrow(t.slots)
    return t


class _NeedsGrow(Exception):
    def __init__(self, slots_needed: int):
        self.slots_needed = slots_needed


@jax.jit
def _set_row(arr: jnp.ndarray, row: jnp.ndarray,
             slot: jnp.ndarray) -> jnp.ndarray:
    return arr.at[slot].set(row)


class DeviceTableManager:
    """Owns the stacked device policy tensors and endpoint row slots.

    ``sync_endpoint`` is the hot path: one endpoint's new PolicyMapState
    becomes one row rewrite. The manager keeps a host numpy mirror so a
    full rebuild never round-trips through the device.
    """

    def __init__(self, initial_endpoints: int = 8,
                 initial_slots: int = MIN_SLOTS, max_load: float = 0.5):
        self._lock = RMutex("table-manager")
        self.max_load = max_load
        # hash tables are always pow2-sized; normalize up front so row
        # rebuilds land on exactly self.slots
        initial_slots = _next_pow2(max(initial_slots, 8))
        self.slots = initial_slots
        self.capacity = initial_endpoints
        self.generation = 0           # bumps on every full swap
        self.revision = 0             # policy revision last synced
        self.max_probe = 1
        self._row_probe: Dict[int, int] = {}
        # rows written since the last drain: the engine's packed-buffer
        # write-through (refresh_policy fast path) realizes exactly
        # these as row scatters instead of repacking the whole stack
        self._dirty_slots: set = set()
        self._free: List[int] = list(range(initial_endpoints))
        self._slot_of: Dict[int, int] = {}   # endpoint id -> row
        self._state_of: Dict[int, PolicyMapState] = {}
        # host mirrors
        self._h_key_id = np.zeros((initial_endpoints, initial_slots),
                                  np.int32)
        self._h_key_meta = np.zeros_like(self._h_key_id)
        self._h_value = np.zeros_like(self._h_key_id)
        # device tensors
        self.key_id = jnp.asarray(self._h_key_id)
        self.key_meta = jnp.asarray(self._h_key_meta)
        self.value = jnp.asarray(self._h_value)

    # ------------------------------------------------------------- slots

    def attach(self, endpoint_id: int) -> int:
        """Assign a table row to an endpoint (grows the stack 2x when
        full — the full-swap path)."""
        with self._lock:
            if endpoint_id in self._slot_of:
                return self._slot_of[endpoint_id]
            if not self._free:
                self._grow(capacity=self.capacity * 2)
            slot = self._free.pop(0)
            self._slot_of[endpoint_id] = slot
            self._state_of[endpoint_id] = PolicyMapState()
            return slot

    def detach(self, endpoint_id: int) -> None:
        """Release an endpoint's row and zero it on device."""
        with self._lock:
            slot = self._slot_of.pop(endpoint_id, None)
            if slot is None:
                return
            self._state_of.pop(endpoint_id, None)
            self._row_probe.pop(slot, None)
            self._free.append(slot)
            zero = np.zeros(self.slots, np.int32)
            self._write_row(slot, zero, zero, zero, probe=1)

    def slot_of(self, endpoint_id: int) -> Optional[int]:
        with self._lock:
            return self._slot_of.get(endpoint_id)

    # -------------------------------------------------------------- sync

    def sync_endpoint(self, endpoint_id: int, state: PolicyMapState,
                      revision: int) -> Dict:
        """Realize ``state`` for the endpoint on device.

        Returns sync stats: {"full_swap": bool, "slots": S,
        "entries": N, "generation": G}. Raises KeyError for an
        unattached endpoint.
        """
        import time as _time
        t0 = _time.perf_counter()
        with self._lock:
            slot = self._slot_of[endpoint_id]
            full_swap = False
            try:
                table = _build_endpoint_table(state, self.slots,
                                              self.max_load)
                # guard against load creeping past the bound in-place
                if table.load > self.max_load:
                    raise _NeedsGrow(self.slots * 2)
            except _NeedsGrow as g:
                self._state_of[endpoint_id] = PolicyMapState(state)
                self._grow(slots=max(g.slots_needed, self.slots * 2))
                full_swap = True
                table = None
            if not full_swap:
                self._state_of[endpoint_id] = PolicyMapState(state)
                self._write_row(slot, table.key_a, table.key_b,
                                table.value, probe=table.max_probe)
            self.revision = max(self.revision, revision)
            out = {"full_swap": full_swap, "slots": self.slots,
                   "entries": len(state),
                   "generation": self.generation,
                   "max_probe": self.max_probe}
            nbytes = int(self._h_key_id.nbytes * 3)
        # device-apply telemetry (observability/): the row sync IS the
        # syncPolicyMap hot path, the full swap its slow fallback
        record_stage("device-tables",
                     "full-swap" if full_swap else "row-sync",
                     _time.perf_counter() - t0)
        jit_telemetry.set_device_bytes("policy-tables", nbytes)
        return out

    def _write_row(self, slot: int, key_a: np.ndarray, key_b: np.ndarray,
                   value: np.ndarray, probe: int) -> None:
        self._h_key_id[slot] = key_a
        self._h_key_meta[slot] = key_b
        self._h_value[slot] = value
        self._dirty_slots.add(slot)
        self._row_probe[slot] = probe
        new_probe = max([1] + list(self._row_probe.values()))
        s = jnp.int32(slot)
        self.key_id = _set_row(self.key_id, jnp.asarray(key_a), s)
        self.key_meta = _set_row(self.key_meta, jnp.asarray(key_b), s)
        self.value = _set_row(self.value, jnp.asarray(value), s)
        self.max_probe = new_probe

    def _grow(self, capacity: Optional[int] = None,
              slots: Optional[int] = None) -> None:
        """Full rebuild at a bigger geometry + device swap (the
        double-buffered generation bump)."""
        new_cap = capacity or self.capacity
        new_slots = _next_pow2(slots or self.slots)
        # some endpoint's state may need more slots than requested;
        # find the real bound before touching any manager state
        while True:
            try:
                rebuilt = {
                    ep_id: _build_endpoint_table(self._state_of[ep_id],
                                                 new_slots, max_load=1.0)
                    for ep_id in self._slot_of}
                break
            except _NeedsGrow as g:
                new_slots = _next_pow2(max(g.slots_needed, new_slots * 2))
        h_id = np.zeros((new_cap, new_slots), np.int32)
        h_meta = np.zeros_like(h_id)
        h_val = np.zeros_like(h_id)
        self._row_probe = {}
        for ep_id, slot in self._slot_of.items():
            table = rebuilt[ep_id]
            h_id[slot] = table.key_a
            h_meta[slot] = table.key_b
            h_val[slot] = table.value
            self._row_probe[slot] = table.max_probe
        used = set(self._slot_of.values())
        self._free = [i for i in range(new_cap) if i not in used]
        self.capacity, self.slots = new_cap, new_slots
        self._h_key_id, self._h_key_meta, self._h_value = h_id, h_meta, h_val
        self.key_id = jnp.asarray(h_id)
        self.key_meta = jnp.asarray(h_meta)
        self.value = jnp.asarray(h_val)
        self.max_probe = max([1] + list(self._row_probe.values()))
        self.generation += 1

    # ------------------------------------------------------------- views

    def tensors(self) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        with self._lock:
            return self.key_id, self.key_meta, self.value

    def snapshot(self):
        """Atomic (geometry, tensors) pair under one lock acquisition.

        Consumers that first read geometry and then fetch tensors in a
        second call can interleave with a concurrent sync_endpoint that
        lengthens a probe chain in-place (no generation bump) or a grow
        that reshapes the stack — installing tensors under a step jitted
        for stale geometry.  geometry = (capacity, slots, max_probe,
        generation).
        """
        with self._lock:
            return ((self.capacity, self.slots, self.max_probe,
                     self.generation),
                    (self.key_id, self.key_meta, self.value))

    def drain_dirty(self) -> Dict[int, Tuple[np.ndarray, np.ndarray,
                                             np.ndarray]]:
        """{slot: (key_id row, key_meta row, value row)} for every row
        written since the last drain, from the host mirror (always the
        newest content), clearing the dirty set.  The engine's packed
        write-through consumes this on the refresh_policy fast path;
        rows are idempotent to re-apply, so draining after a full
        rebuild only costs a redundant scatter, never staleness."""
        with self._lock:
            out = {}
            for slot in sorted(self._dirty_slots):
                if slot >= self._h_key_id.shape[0]:
                    continue
                out[slot] = (self._h_key_id[slot].copy(),
                             self._h_key_meta[slot].copy(),
                             self._h_value[slot].copy())
            self._dirty_slots.clear()
            return out

    def host_mirror(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        with self._lock:
            return (self._h_key_id.copy(), self._h_key_meta.copy(),
                    self._h_value.copy())

    def states_by_slot(self) -> Dict[int, PolicyMapState]:
        """{table row slot: PolicyMapState copy} — the host-of-record
        the fail-static oracle (datapath/supervisor.py) enforces while
        the device lane is degraded, and the source the recovery path
        rebuilds device tensors from."""
        with self._lock:
            return {slot: PolicyMapState(self._state_of[ep_id])
                    for ep_id, slot in self._slot_of.items()}

    def stats(self) -> Dict:
        with self._lock:
            return {"capacity": self.capacity, "slots": self.slots,
                    "endpoints": len(self._slot_of),
                    "generation": self.generation,
                    "max_probe": self.max_probe,
                    "revision": self.revision,
                    "nbytes": int(self._h_key_id.nbytes * 3)}
