"""Endpoint lifecycle: state machine, policy regeneration, device sync.

Analog of the reference's ``pkg/endpoint`` + ``pkg/endpointmanager`` +
``pkg/buildqueue``: endpoints move through a validated state machine,
resolve labels to identities, recompute desired policy-map state, and
sync it into the stacked device verdict tables with minimal deltas.
"""

from .endpoint import (Endpoint, EndpointState, RegenerationResult,
                       StateTransitionError)
from .manager import EndpointManager
from .tables import DeviceTableManager

__all__ = [
    "Endpoint", "EndpointState", "RegenerationResult",
    "StateTransitionError", "EndpointManager", "DeviceTableManager",
]
