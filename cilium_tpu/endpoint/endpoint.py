"""The Endpoint: workload attachment point with its own policy state.

Reference: pkg/endpoint/endpoint.go (state machine :237-254,
SetStateLocked transition rules), pkg/endpoint/policy.go
(regeneratePolicy :482, computeDesiredPolicyMapState :254) and
pkg/endpoint/bpf.go (regenerateBPF :467, syncPolicyMap :607,
writeHeaderfile :88 — here a JSON checkpoint instead of a C header).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from .. import identity as idpkg
from ..labels import LabelArray, Labels
from ..policy.l4 import L4Filter, L4Policy
from ..policy.mapstate import (EndpointPolicyConfig, PolicyKey,
                               PolicyMapState, PolicyMapStateEntry,
                               compute_desired_policy_map_state,
                               diff_map_state)
from ..policy.repository import Repository
from ..policy.trace import SearchContext
from ..utils.option import OPTION_ENABLED, IntOptions
from ..utils.spanstat import SpanStat

# New endpoints enforce policy with conntrack on unless overridden
# (reference: endpoints inherit the daemon's option map; see
# DaemonConfig.opts defaults in utils/option.py).
_DEFAULT_ENDPOINT_OPTS = {
    "Policy": OPTION_ENABLED,
    "IngressPolicy": OPTION_ENABLED,
    "EgressPolicy": OPTION_ENABLED,
    "Conntrack": OPTION_ENABLED,
    "ConntrackAccounting": OPTION_ENABLED,
}


class EndpointState:
    """Reference: endpoint.go:237-254 state set."""

    CREATING = "creating"
    WAITING_FOR_IDENTITY = "waiting-for-identity"
    READY = "ready"
    WAITING_TO_REGENERATE = "waiting-to-regenerate"
    REGENERATING = "regenerating"
    RESTORING = "restoring"
    DISCONNECTING = "disconnecting"
    DISCONNECTED = "disconnected"
    NOT_READY = "not-ready"


# Allowed transitions (reference: endpoint.go SetStateLocked's switch;
# disconnecting is reachable from everything, disconnected only from
# disconnecting).
_ALLOWED: Dict[str, Tuple[str, ...]] = {
    EndpointState.CREATING: (
        EndpointState.WAITING_FOR_IDENTITY, EndpointState.READY,
        EndpointState.DISCONNECTING),
    EndpointState.WAITING_FOR_IDENTITY: (
        EndpointState.READY, EndpointState.DISCONNECTING),
    EndpointState.READY: (
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.WAITING_TO_REGENERATE, EndpointState.REGENERATING,
        EndpointState.NOT_READY, EndpointState.DISCONNECTING),
    EndpointState.WAITING_TO_REGENERATE: (
        EndpointState.REGENERATING, EndpointState.DISCONNECTING),
    EndpointState.REGENERATING: (
        EndpointState.READY, EndpointState.NOT_READY,
        EndpointState.WAITING_TO_REGENERATE, EndpointState.DISCONNECTING),
    EndpointState.RESTORING: (
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.WAITING_TO_REGENERATE, EndpointState.REGENERATING,
        EndpointState.READY, EndpointState.DISCONNECTING),
    EndpointState.NOT_READY: (
        EndpointState.WAITING_FOR_IDENTITY,
        EndpointState.WAITING_TO_REGENERATE, EndpointState.READY,
        EndpointState.DISCONNECTING),
    EndpointState.DISCONNECTING: (EndpointState.DISCONNECTED,),
    EndpointState.DISCONNECTED: (),
}


class StateTransitionError(ValueError):
    pass


@dataclass
class RegenerationResult:
    """Outcome of one policy regeneration (spanstat timings included —
    reference logs these per stage, endpoint/policy.go:667-678)."""

    revision: int
    adds: List[Tuple[PolicyKey, PolicyMapStateEntry]]
    deletes: List[PolicyKey]
    redirects_added: List[str]
    redirects_removed: List[str]
    policy_calculation: SpanStat
    table_sync: SpanStat
    total: SpanStat


class Endpoint:
    """One managed endpoint."""

    def __init__(self, endpoint_id: int, ipv4: str = "",
                 container_name: str = "",
                 labels: Optional[Labels] = None,
                 opts: Optional[IntOptions] = None):
        self.id = endpoint_id
        self.ipv4 = ipv4
        self.container_name = container_name
        self.labels = labels or Labels()
        self.opts = opts or IntOptions(defaults=dict(_DEFAULT_ENDPOINT_OPTS))
        self.state = EndpointState.CREATING
        self.status_log: List[Tuple[float, str, str]] = []
        self.identity: Optional[idpkg.Identity] = None
        # realized vs desired policy map state (bpf.go realizedMapState)
        self.realized: PolicyMapState = PolicyMapState()
        self.desired: PolicyMapState = PolicyMapState()
        self.policy_revision = 0          # last fully-applied repo revision
        self.next_policy_revision = 0
        self.l4_policy: Optional[L4Policy] = None
        self.proxy_redirects: Dict[str, int] = {}  # proxy_id -> port
        self.table_slot: Optional[int] = None      # row in device tables
        self._lock = threading.RLock()

    # ------------------------------------------------------------- state

    def set_state(self, new_state: str, reason: str = "") -> bool:
        """Validated transition (endpoint.go SetStateLocked). Returns
        False (no raise) when the move is disallowed, mirroring the
        reference's boolean contract — except unknown states, which are
        programming errors."""
        with self._lock:
            if new_state not in _ALLOWED:
                raise StateTransitionError(f"unknown state {new_state!r}")
            if new_state == self.state:
                return False
            if new_state not in _ALLOWED[self.state]:
                return False
            self.state = new_state
            self.status_log.append((time.time(), new_state, reason))
            if len(self.status_log) > 128:
                self.status_log = self.status_log[-128:]
            return True

    # ---------------------------------------------------------- identity

    def update_labels(self, allocator, labels: Labels) -> bool:
        """Resolve security-relevant labels to an identity; returns True
        if the identity changed (triggering regeneration). Reference:
        endpoint label update path (endpoint.go UpdateLabels ->
        identityLabelsChanged)."""
        with self._lock:
            self.labels = Labels(labels)
            old = self.identity
            if self.state == EndpointState.CREATING:
                self.set_state(EndpointState.WAITING_FOR_IDENTITY,
                               "resolving identity")
            ident, _ = allocator.allocate(labels)
            self.identity = ident
            if self.state == EndpointState.WAITING_FOR_IDENTITY:
                self.set_state(EndpointState.READY, "identity resolved")
            changed = old is None or old.id != ident.id
        if old is not None:
            # drop the previous reference: on a same-labels resolve this
            # cancels the duplicate ref allocate() just took
            allocator.release(old)
        return changed

    @property
    def security_identity(self) -> int:
        with self._lock:
            return self.identity.id if self.identity else 0

    def label_array(self) -> LabelArray:
        with self._lock:
            return self.labels.to_array()

    # ------------------------------------------------------ regeneration

    def policy_config(self, always_allow_localhost: bool = False
                      ) -> EndpointPolicyConfig:
        return EndpointPolicyConfig(
            ingress_enforcement=self.opts.is_enabled("IngressPolicy") and
            self.opts.is_enabled("Policy"),
            egress_enforcement=self.opts.is_enabled("EgressPolicy") and
            self.opts.is_enabled("Policy"),
            always_allow_localhost=always_allow_localhost)

    def regenerate_policy(self, repo: Repository,
                          identity_cache: Dict[int, LabelArray],
                          proxy=None,
                          always_allow_localhost: bool = False
                          ) -> RegenerationResult:
        """Recompute desired policy state and the delta vs realized.

        Reference stack: endpoint/policy.go:482 regeneratePolicy →
        resolveL4Policy → computeDesiredPolicyMapState; redirects via
        proxy.CreateOrUpdateRedirect (bpf.go:356 addNewRedirects /
        :255 removeOldRedirects). The caller applies the delta to the
        device tables, then calls ``apply_regeneration``.
        """
        total = SpanStat().start()
        calc = SpanStat().start()
        with self._lock:
            ep_labels = self.labels.to_array()
            cfg = self.policy_config(always_allow_localhost)
            rev = repo.revision

            ingress_ctx = SearchContext(to_labels=ep_labels)
            egress_ctx = SearchContext(from_labels=ep_labels)
            l4 = L4Policy(
                ingress=repo.resolve_l4_ingress_policy(ingress_ctx),
                egress=repo.resolve_l4_egress_policy(egress_ctx),
                revision=rev)
            self.l4_policy = l4

            # redirects first: desired map entries need the proxy ports
            added_redirects: List[str] = []
            wanted_redirects: Dict[str, int] = {}

            def redirect_port(flt: L4Filter) -> int:
                if proxy is None:
                    return 0
                redir = proxy.create_or_update_redirect(flt, self.id)
                wanted_redirects[redir.id] = redir.proxy_port
                if redir.id not in self.proxy_redirects:
                    added_redirects.append(redir.id)
                return redir.proxy_port

            desired = compute_desired_policy_map_state(
                repo, identity_cache, ep_labels, l4_policy=l4,
                redirect_port_for=redirect_port, config=cfg)
            calc.end()

            removed_redirects = [rid for rid in self.proxy_redirects
                                 if rid not in wanted_redirects]
            if proxy is not None:
                for rid in removed_redirects:
                    proxy.remove_redirect(rid)
            self.proxy_redirects = wanted_redirects

            sync = SpanStat().start()
            adds, deletes = diff_map_state(self.realized, desired)
            sync.end()
            self.desired = desired
            self.next_policy_revision = rev
            total.end()
            return RegenerationResult(
                revision=rev, adds=adds, deletes=deletes,
                redirects_added=added_redirects,
                redirects_removed=removed_redirects,
                policy_calculation=calc, table_sync=sync, total=total)

    def apply_regeneration(self, result: RegenerationResult) -> None:
        """Mark the desired state realized (device sync succeeded)."""
        with self._lock:
            self.realized = PolicyMapState(self.desired)
            self.policy_revision = result.revision

    # -------------------------------------------------------- checkpoint

    def checkpoint(self) -> Dict:
        """Serializable endpoint state (the writeHeaderfile analog:
        everything needed to restore the endpoint after agent restart,
        daemon/state.go)."""
        from ..migrate import CHECKPOINT_VERSION
        with self._lock:
            return {
                "version": CHECKPOINT_VERSION,
                "family": 4,
                "id": self.id,
                "ipv4": self.ipv4,
                "container_name": self.container_name,
                "labels": [str(l) for l in self.labels.to_array()],
                "state": self.state,
                "policy_revision": self.policy_revision,
                "identity": self.security_identity,
                "realized": [
                    {"identity": k.identity, "dest_port": k.dest_port,
                     "nexthdr": k.nexthdr, "direction": k.direction,
                     "proxy_port": v.proxy_port}
                    for k, v in sorted(
                        self.realized.items(),
                        key=lambda kv: (kv[0].identity, kv[0].dest_port,
                                        kv[0].nexthdr, kv[0].direction))],
                "options": self.opts.dump(),
            }

    def write_checkpoint(self, state_dir: str) -> str:
        os.makedirs(state_dir, exist_ok=True)
        path = os.path.join(state_dir, f"ep_{self.id}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.checkpoint(), f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        return path

    @classmethod
    def restore(cls, snapshot: Dict,
                opts: Optional[IntOptions] = None) -> "Endpoint":
        """Rebuild an endpoint from a checkpoint (daemon/state.go
        restoreOldEndpoints). Restored endpoints start in RESTORING and
        need a regeneration to become READY with fresh policy.  Old
        checkpoint versions are migrated forward first
        (cilium-map-migrate analog, migrate.py)."""
        from ..migrate import migrate_snapshot
        snapshot = migrate_snapshot(snapshot)
        ep = cls(endpoint_id=snapshot["id"], ipv4=snapshot.get("ipv4", ""),
                 container_name=snapshot.get("container_name", ""),
                 labels=Labels.from_model(snapshot.get("labels", [])),
                 opts=opts)
        ep.state = EndpointState.RESTORING
        ep.policy_revision = snapshot.get("policy_revision", 0)
        for e in snapshot.get("realized", []):
            ep.realized[PolicyKey(
                identity=e["identity"], dest_port=e["dest_port"],
                nexthdr=e["nexthdr"], direction=e["direction"])] = \
                PolicyMapStateEntry(proxy_port=e.get("proxy_port", 0))
        for name, value in (snapshot.get("options") or {}).items():
            # per-key so one stale option name from an older version
            # can't discard the rest of the checkpointed settings
            try:
                ep.opts.apply_validated({name: value})
            except (KeyError, ValueError):
                pass
        return ep

    def model(self) -> Dict:
        """REST model (api/v1 Endpoint)."""
        with self._lock:
            return {
                "id": self.id,
                "container-name": self.container_name,
                "addressing": {"ipv4": self.ipv4},
                "state": self.state,
                "identity": {
                    "id": self.security_identity,
                    "labels": [str(l) for l in
                               (self.identity.label_array
                                if self.identity else [])]},
                "labels": [str(l) for l in self.labels.to_array()],
                "policy-revision": self.policy_revision,
                "policy-enabled": self.opts.is_enabled("Policy"),
                # device-table row: verdict-service clients address
                # packets by this slot, not the endpoint id
                "table-slot": self.table_slot,
            }
