"""Docker libnetwork remote driver: docker -> agent endpoint lifecycle.

The second container-runtime front end next to the CNI plugin
(reference: plugins/cilium-docker/driver/driver.go + ipam.go).  Docker's
libnetwork calls a remote plugin over HTTP POST with JSON bodies; the
driver answers the NetworkDriver + IpamDriver method set and drives the
agent's REST API:

  Plugin.Activate                 -> {Implements: [NetworkDriver, IpamDriver]}
  NetworkDriver.GetCapabilities   -> local scope (driver.go:240)
  NetworkDriver.Create/DeleteNetwork -> accepted, no state (driver.go:249)
  NetworkDriver.CreateEndpoint    -> PUT /endpoint/{id} (driver.go:283)
  NetworkDriver.Join              -> interface name + static routes +
                                     gateway from daemon addressing
                                     (driver.go:389)
  NetworkDriver.Leave             -> DELETE /endpoint/{id} (driver.go:436)
  IpamDriver.RequestPool          -> CiliumPoolv4/v6 (ipam.go:56)
  IpamDriver.Request/ReleaseAddress -> POST /ipam, DELETE /ipam/{ip}
                                     (ipam.go:102,152)

One inversion vs the reference: it is IPv6-primary (CreateEndpoint
rejects a missing v6 address, driver.go:291); this build is IPv4-first
(the datapath's 32-bit key word), so v4 is required and v6 optional.

The HTTP transport is stdlib http.server on localhost TCP (same choice
as daemon/rest.py; the reference listens on a unix socket that docker
discovers via /run/docker/plugins).  All method logic lives in
LibnetworkDriver.handle() so tests can drive it with plain dicts.
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from .cli import Client
from .endpoint.ids import DOCKER_ID_BASE, stable_endpoint_id

POOL_V4 = "CiliumPoolv4"
POOL_V6 = "CiliumPoolv6"
CONTAINER_IF_PREFIX = "cilium"


class PluginError(RuntimeError):
    """Maps to the libnetwork error response {"Err": msg}."""


def endpoint_id_for(docker_endpoint_id: str) -> int:
    """Stable numeric endpoint id from docker's endpoint UUID (the
    reference derives it from the v6 address's low bits,
    addressing.CiliumIPv6.EndpointID; any stable mapping works)."""
    return stable_endpoint_id(docker_endpoint_id, DOCKER_ID_BASE)


class LibnetworkDriver:
    """The method-set handler, independent of transport."""

    def __init__(self, client: Client, wait_tries: int = 24,
                 wait_base_s: float = 1.0):
        self.client = client
        # the reference waits up to ~24 escalating sleeps for the
        # daemon (driver.go:100); tests pass small values
        conf = None
        for attempt in range(wait_tries):
            try:
                conf = client.get("/config")
                break
            except SystemExit:
                if attempt == wait_tries - 1:
                    raise PluginError("cilium daemon unreachable")
                time.sleep(wait_base_s * attempt)
        self._lock = threading.Lock()
        self.addressing = (conf or {}).get("addressing", {})
        if not self.addressing.get("ipv4", {}).get("ip"):
            raise PluginError("daemon returned no IPv4 addressing")

    # ------------------------------------------------------------ util

    def _update_addressing(self, addressing: Optional[Dict]) -> None:
        """Host addressing can change across a daemon restart; refresh
        from every IPAM response like the reference (ipam.go:126)."""
        if addressing:
            with self._lock:
                self.addressing = addressing

    def _routes(self):
        """Static routes the container needs: the pod CIDR is CONNECTED
        via the cilium interface, everything else goes to the gateway
        (connector.IPv4Routes analog)."""
        with self._lock:
            v4 = self.addressing.get("ipv4", {})
            v6 = self.addressing.get("ipv6", {})
        routes = []
        if v4.get("ip"):
            routes.append({"Destination": f"{v4['ip']}/32",
                           "RouteType": 1, "NextHop": ""})
            routes.append({"Destination": "0.0.0.0/0",
                           "RouteType": 0, "NextHop": v4["ip"]})
        if v6.get("ip"):
            routes.append({"Destination": f"{v6['ip']}/128",
                           "RouteType": 1, "NextHop": ""})
        return routes

    # --------------------------------------------------------- methods

    def handle(self, method: str, body: Dict) -> Dict:
        """Dispatch one libnetwork method; raises PluginError on
        failure (transport encodes it as {"Err": ...})."""
        fn = self._METHODS.get(method)
        if fn is None:
            raise PluginError(f"unknown plugin method {method!r}")
        return fn(self, body or {})

    def _activate(self, body: Dict) -> Dict:
        return {"Implements": ["NetworkDriver", "IpamDriver"]}

    def _capabilities(self, body: Dict) -> Dict:
        return {"Scope": "local"}

    def _create_network(self, body: Dict) -> Dict:
        return {}

    def _delete_network(self, body: Dict) -> Dict:
        return {}

    def _create_endpoint(self, body: Dict) -> Dict:
        eid = body.get("EndpointID", "")
        iface = body.get("Interface") or {}
        ipv4 = (iface.get("Address") or "").split("/")[0]
        if not ipv4:
            raise PluginError("no IPv4 address provided (required)")
        ep_id = endpoint_id_for(eid)
        try:
            self.client.get(f"/endpoint/{ep_id}")
        except SystemExit as e:
            # only a 404 means "free to create"; a 5xx or an
            # unreachable agent must surface, not masquerade as the
            # normal create path
            if getattr(e, "status", None) != 404:
                raise PluginError(f"agent lookup failed: {e}")
        else:
            raise PluginError("endpoint already exists")
        labels = [f"container:docker-endpoint={eid[:12]}"]
        net = body.get("NetworkID", "")
        if net:
            labels.append(f"container:docker-network={net[:12]}")
        try:
            self.client.put(f"/endpoint/{ep_id}", {
                "ipv4": ipv4, "container-name": eid[:12],
                "labels": labels})
        except SystemExit as e:
            raise PluginError(f"endpoint create failed: {e}")
        # MAC resolves at Join time, like the reference (driver.go:350)
        return {"Interface": {"MacAddress": ""}}

    def _delete_endpoint(self, body: Dict) -> Dict:
        # link teardown only in the reference (driver.go:363); the
        # agent endpoint is removed at Leave
        return {}

    def _endpoint_info(self, body: Dict) -> Dict:
        return {"Value": {}}

    def _join(self, body: Dict) -> Dict:
        eid = body.get("EndpointID", "")
        ep_id = endpoint_id_for(eid)
        try:
            self.client.get(f"/endpoint/{ep_id}")
        except SystemExit as e:
            # a transient agent failure must not read as "endpoint
            # gone" — docker would tear down a live container
            if getattr(e, "status", None) == 404:
                raise PluginError(f"endpoint {eid!r} not found")
            raise PluginError(f"agent lookup failed: {e}")
        with self._lock:
            gw6 = self.addressing.get("ipv6", {}).get("ip", "")
        return {
            "InterfaceName": {"SrcName": f"tmp{ep_id}",
                              "DstPrefix": CONTAINER_IF_PREFIX},
            "StaticRoutes": self._routes(),
            "DisableGatewayService": True,
            "GatewayIPv6": gw6,
        }

    def _leave(self, body: Dict) -> Dict:
        ep_id = endpoint_id_for(body.get("EndpointID", ""))
        try:
            self.client.delete(f"/endpoint/{ep_id}")
        except SystemExit as e:
            # 404 = already gone; Leave stays idempotent
            # (driver.go:443).  Anything else would leak the endpoint
            if getattr(e, "status", None) != 404:
                raise PluginError(f"endpoint delete failed: {e}")
        return {}

    def _ipam_capabilities(self, body: Dict) -> Dict:
        return {}

    def _address_spaces(self, body: Dict) -> Dict:
        return {"LocalDefaultAddressSpace": "CiliumLocal",
                "GlobalDefaultAddressSpace": "CiliumGlobal"}

    def _request_pool(self, body: Dict) -> Dict:
        with self._lock:
            v4 = self.addressing.get("ipv4", {})
            v6 = self.addressing.get("ipv6", {})
        if body.get("V6"):
            if not v6.get("ip"):
                raise PluginError("IPv6 not enabled on this daemon")
            return {"PoolID": POOL_V6, "Pool": v6.get("alloc-range", ""),
                    "Data": {"com.docker.network.gateway":
                             f"{v6['ip']}/128"}}
        return {"PoolID": POOL_V4, "Pool": "0.0.0.0/0",
                "Data": {"com.docker.network.gateway": f"{v4['ip']}/32"}}

    def _request_address(self, body: Dict) -> Dict:
        family = "ipv6" if body.get("PoolID") == POOL_V6 else "ipv4"
        try:
            out = self.client.post("/ipam", {"family": family,
                                             "owner": "docker"})
        except SystemExit as e:
            raise PluginError(f"could not allocate IP address: {e}")
        self._update_addressing(out.get("host-addressing"))
        addr = (out.get("address") or {}).get(family)
        if not addr:
            raise PluginError("no IP addressing provided")
        suffix = "/128" if family == "ipv6" else "/32"
        return {"Address": addr + suffix}

    def _release_pool(self, body: Dict) -> Dict:
        return {}

    def _release_address(self, body: Dict) -> Dict:
        try:
            self.client.delete(f"/ipam/{body.get('Address', '')}")
        except SystemExit as e:
            raise PluginError(f"could not release IP address: {e}")
        return {}

    _METHODS = {
        "Plugin.Activate": _activate,
        "NetworkDriver.GetCapabilities": _capabilities,
        "NetworkDriver.CreateNetwork": _create_network,
        "NetworkDriver.DeleteNetwork": _delete_network,
        "NetworkDriver.CreateEndpoint": _create_endpoint,
        "NetworkDriver.DeleteEndpoint": _delete_endpoint,
        "NetworkDriver.EndpointOperInfo": _endpoint_info,
        "NetworkDriver.Join": _join,
        "NetworkDriver.Leave": _leave,
        "IpamDriver.GetCapabilities": _ipam_capabilities,
        "IpamDriver.GetDefaultAddressSpaces": _address_spaces,
        "IpamDriver.RequestPool": _request_pool,
        "IpamDriver.ReleasePool": _release_pool,
        "IpamDriver.RequestAddress": _request_address,
        "IpamDriver.ReleaseAddress": _release_address,
    }


class _PluginHandler(BaseHTTPRequestHandler):
    driver: LibnetworkDriver = None  # set by PluginServer
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):  # quiet
        pass

    def do_POST(self):
        length = int(self.headers.get("Content-Length", 0) or 0)
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except ValueError:
            body = {}
        method = self.path.lstrip("/")
        try:
            out = self.driver.handle(method, body)
            code = 200
        except PluginError as e:
            # libnetwork's error convention: 200 + {"Err": msg} is
            # treated as failure by docker; use it like the reference's
            # sendError-by-body cases
            out, code = {"Err": str(e)}, 400
        payload = json.dumps(out).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(payload)))
        self.end_headers()
        self.wfile.write(payload)


class PluginServer:
    """Localhost TCP transport for the driver (Listen analog)."""

    def __init__(self, driver: LibnetworkDriver, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type("_Bound", (_PluginHandler,), {"driver": driver})
        self._srv = ThreadingHTTPServer((host, port), handler)
        self._srv.daemon_threads = True
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._srv.server_address[1]

    @property
    def base_url(self) -> str:
        host, port = self._srv.server_address[:2]
        return f"http://{host}:{port}"

    def start(self) -> "PluginServer":
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="docker-plugin")
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()


def main(argv=None) -> int:
    """``cilium-tpu docker-plugin`` entry: serve the libnetwork method
    set against a running agent."""
    import argparse
    ap = argparse.ArgumentParser(prog="cilium-tpu docker-plugin")
    ap.add_argument("--api", default="http://127.0.0.1:9234")
    ap.add_argument("--listen-port", type=int, default=9235)
    args = ap.parse_args(argv)
    driver = LibnetworkDriver(Client(args.api))
    srv = PluginServer(driver, port=args.listen_port).start()
    print(f"docker libnetwork plugin ready on {srv.base_url}")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
