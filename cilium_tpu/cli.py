"""The ``cilium-tpu`` CLI.

Mirrors the reference's ``cilium`` command families (cilium/cmd/, 75
commands) against the REST API: policy {get,import,delete,trace,
validate,wait}, endpoint {list,get,config,labels,delete,log,
regenerate,healthz}, identity {list,get}, service {list,update,
delete}, prefilter {list,update,delete}, monitor (--type/--drops/
--socket), status, config, metrics, node, map {list,get}, version,
debuginfo, kvstore {get,set,delete}, cleanup, bugtool,
migrate-state, plus the container front ends (cni, docker-plugin).

Run the agent itself with ``cilium-tpu agent`` (add --verdict-port
to expose the batch verdict service).
"""

from __future__ import annotations

import argparse
import json
import sys
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

DEFAULT_API = "http://127.0.0.1:9234"


class APIError(SystemExit):
    """Typed agent-API failure.  Subclasses SystemExit so bare CLI use
    still exits non-zero with the message on stderr (SystemExit's
    ``code`` stays the message — do NOT store the HTTP status there, or
    an uncaught error would become the process exit status).
    Programmatic callers (docker plugin, CNI) read ``.status`` to tell
    a 404 from a 5xx or from a transport failure (status is None when
    the agent was unreachable)."""

    def __init__(self, message: str, status: Optional[int] = None):
        super().__init__(message)
        self.status = status


class Client:
    """Tiny REST client (pkg/client analog)."""

    def __init__(self, base_url: str = DEFAULT_API):
        self.base_url = base_url.rstrip("/")

    def request(self, method: str, path: str, body=None,
                raw: bool = False, raw_body: Optional[bytes] = None,
                timeout: float = 30):
        data = raw_body if raw_body is not None else \
            (None if body is None else json.dumps(body).encode())
        req = urllib.request.Request(
            self.base_url + path, data=data, method=method,
            headers={"Content-Type": "application/json"} if data else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                payload = resp.read()
        except urllib.error.HTTPError as e:
            payload = e.read()
            try:
                msg = json.loads(payload).get("error", payload.decode())
            except ValueError:
                msg = payload.decode(errors="replace")
            raise APIError(f"API error {e.code}: {msg}", status=e.code)
        except urllib.error.URLError as e:
            raise APIError(
                f"cannot reach agent at {self.base_url}: {e.reason}")
        if raw:
            return payload.decode()
        return json.loads(payload) if payload else None

    def get(self, path, **kw):
        return self.request("GET", path, **kw)

    def put(self, path, body=None):
        return self.request("PUT", path, body)

    def post(self, path, body=None):
        return self.request("POST", path, body)

    def patch(self, path, body=None):
        return self.request("PATCH", path, body)

    def delete(self, path, body=None):
        return self.request("DELETE", path, body)


def _print_json(obj) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True))


def _follow_sleep(interval: float, drained: bool) -> None:
    """Pace a follow-mode poll loop.  A busy emitter must NOT turn
    the follower into a hot spin: when the last poll returned events
    the next one fires sooner, but still floored at a fraction of
    --interval so an always-busy ring costs bounded CPU instead of a
    zero-sleep tight loop against the agent API."""
    time.sleep(interval if drained else max(0.02, interval / 20.0))


# ------------------------------------------------------------- subcommands

def cmd_status(c: Client, args) -> int:
    st = c.get("/healthz")
    if args.json:
        _print_json(st)
        return 0
    kv = st["kvstore"]
    print(f"KVStore:       {kv['state']} ({kv['backend']})")
    if kv.get("mode") and kv["mode"] != "ok":
        # the control plane is down: the agent is pinning
        # last-known-good state and journaling mutations for replay
        print(f"KVStore:       {kv['mode'].upper()}: pinned "
              f"last-known-good (staleness "
              f"{kv.get('staleness-seconds', 0)}s, journal "
              f"{kv.get('journal-depth', 0)} queued, breaker "
              f"{kv.get('breaker')}, "
              f"{kv.get('local-identities', 0)} local identities)")
    elif kv.get("staleness-seconds", 0) > 0:
        print(f"KVStore:       STALE: {kv['staleness-seconds']}s since "
              f"last successful op "
              f"({kv.get('consecutive-failures', 0)} consecutive "
              f"failures, breaker {kv.get('breaker')})")
    print(f"Policy:        revision {st['policy']['revision']}, "
          f"{st['policy']['rules']} rules")
    eps = st["endpoints"]
    states = " ".join(f"{k}={v}" for k, v in
                      sorted(eps.get("by-state", {}).items()))
    print(f"Endpoints:     {eps['total']} ({states})")
    print(f"Identities:    {st['identities']}")
    print(f"IPCache:       {st['ipcache']} entries")
    print(f"Nodes:         {st['nodes']} peers")
    print(f"Proxy:         {st['proxy']['redirects']} redirects")
    for cm in st.get("clustermesh", []):
        ready = "ready" if cm["ready"] else "connecting"
        print(f"ClusterMesh:   {cm['name']} (id {cm['cluster-id']}): "
              f"{ready}, {cm['num-nodes']} nodes")
    bad = [ctl for ctl in st.get("controllers", [])
           if ctl["consecutive-failure-count"] > 0]
    print(f"Controllers:   {len(st.get('controllers', []))} "
          f"({len(bad)} failing)")
    ch = st.get("controller-health") or {}
    if ch.get("failing"):
        # the loud top-level signal: a reconcile loop is wedged
        print(f"Controllers:   {ch['status']}")
        for f in ch["failing"]:
            print(f"Controllers:     {f['name']}: "
                  f"{f['consecutive-failures']}x — {f['last-error']}")
    tr = st.get("transports")
    if tr:
        open_breakers = [n for n, s in tr.get("breakers", {}).items()
                         if s != "closed"]
        print(f"Transports:    {tr['retries']} retries, "
              f"{tr['verify-on-retry']} verified, "
              f"{tr['watch-relists']} relists, "
              f"{len(open_breakers)} breakers open")
    dp_state = st.get("dataplane") or {}
    geom = dp_state.get("geometry")
    if geom:
        print(f"Dataplane:     sharded (dp={geom['dp']}, "
              f"ep={geom['ep']}, {geom['devices']} devices)")
    if dp_state.get("mode", "ok") != "ok":
        # the loudest line status can carry: the device lane is down
        # and traffic is being served fail-static from the host oracle
        print(f"Dataplane:     {dp_state.get('status')}")
    mp = st.get("map-pressure") or {}
    for warning in mp.get("warnings", []):
        print(f"MapPressure:   WARNING {warning}")
    da = (st.get("provenance") or {}).get("drift-audit") or {}
    if da.get("status") == "FAILING":
        print(f"DriftAudit:    FAILING — {da.get('divergences', '?')} "
              f"divergence(s) between compiled tables and the host "
              f"policy oracle (see /debuginfo provenance)")
    if getattr(args, "verbose", False):
        # self-telemetry detail (the status --verbose surface):
        # per-map fill, compile/jit-cache accounting, tracer health,
        # recent policy-propagation delays
        for name, m in sorted(mp.get("maps", {}).items()):
            if m.get("pressure") is not None:
                print(f"Map:           {name:14s} "
                      f"{m['occupied']}/{m['capacity']} "
                      f"({m['pressure'] * 100:.1f}%)")
            else:
                print(f"Map:           {name:14s} "
                      f"{m['occupied']} entries")
        # sharded dataplane: per-shard occupancy of the bounded
        # tables (CT/policy/flows) — the shard-local view the warn
        # threshold is applied to
        for shard, rep in sorted((mp.get("shards") or {}).items()):
            for name, m in sorted((rep.get("maps") or {}).items()):
                if m.get("pressure") is not None:
                    print(f"Map[s{shard}]:       {name:14s} "
                          f"{m['occupied']}/{m['capacity']} "
                          f"({m['pressure'] * 100:.1f}%)")
        tel = st.get("telemetry") or {}
        jit = tel.get("jit") or {}
        if jit:
            compiles = sum((jit.get("compiles") or {}).values())
            secs = sum((jit.get("compile-seconds") or {}).values())
            print(f"JIT:           {compiles} compiles "
                  f"({secs:.2f}s), cache "
                  f"{jit.get('cache-hits', 0)} hits / "
                  f"{jit.get('cache-misses', 0)} misses, "
                  f"{jit.get('device-bytes-total', 0) / 1e6:.1f}MB "
                  f"device tables")
        tracing = tel.get("tracing") or {}
        if tracing:
            state = "on" if tracing.get("enabled") else "off"
            print(f"Tracing:       {state}, "
                  f"{tracing.get('buffered', 0)}/"
                  f"{tracing.get('capacity', 0)} spans buffered")
        for rec in tel.get("propagation") or []:
            delay = rec.get("first-verdict-delay-s")
            state = f"{delay * 1000:.1f}ms to first verdict" \
                if delay is not None else "awaiting first verdict"
            print(f"PolicyRev:     r{rec['revision']} "
                  f"({rec['rules']} rules): {state}")
        prov = st.get("provenance") or {}
        if da and da.get("status") != "FAILING":
            print(f"DriftAudit:    {da.get('status')} "
                  f"({da.get('checked', 0)} tuples, "
                  f"{da.get('sc-checked', 0)} label cross-checks)")
        for rec in prov.get("top-dropped-rules") or []:
            print(f"TopDropped:    {rec['rule']} "
                  f"({rec['packets']} packets)")
        # serving SLO tier: the cilium-tpu-top-style one-shot snapshot
        # (per-lane latency percentiles, deadline-budget burn, queue
        # flight sample) — observability/slo.py
        slo = st.get("slo") or {}
        lanes = slo.get("lanes") or {}
        if lanes:
            print(f"SLO:           objective "
                  f"{slo.get('objective-ms', 0)}ms, error budget "
                  f"{slo.get('error-budget', 0)}")
            print(f"SLO:           {'LANE':<14} {'SHARD':>5} "
                  f"{'REQS':>9} {'P50us':>9} {'P99us':>9} "
                  f"{'BREACH':>7} {'BURN':>7} {'QUEUE':>7} "
                  f"{'INFL':>5}")
            for name, row in sorted(lanes.items()):
                q = row.get("queue") or {}
                shard = "-" if row.get("shard") is None \
                    else str(row["shard"])
                print(f"SLO:           {name:<14} {shard:>5} "
                      f"{row['requests']:>9} {row['p50-us']:>9.1f} "
                      f"{row['p99-us']:>9.1f} {row['breaches']:>7} "
                      f"{row['burn-rate']:>7.2f} "
                      f"{q.get('pending', 0):>7} "
                      f"{q.get('inflight', 0):>5}")
        fr = st.get("flight-recorder") or {}
        if fr.get("ringed"):
            print(f"FlightRec:     {fr['ringed']} event(s) buffered "
                  f"(seq {fr['seq']}, {fr.get('evicted', 0)} "
                  f"evicted) — `cilium-tpu events` replays the "
                  f"timeline")
    return 0


def cmd_policy(c: Client, args) -> int:
    if args.policy_cmd == "get":
        _print_json(c.get("/policy"))
    elif args.policy_cmd == "import":
        text = sys.stdin.read() if args.file == "-" else \
            open(args.file).read()
        # validate client-side first for a friendly error
        from .policy.jsonio import rules_from_json
        rules_from_json(text)
        out = c.request("PUT", "/policy", raw_body=text.encode())
        print(f"Revision: {out['revision']}")
    elif args.policy_cmd == "delete":
        path = "/policy"
        if args.labels:
            from urllib.parse import urlencode
            path += "?" + urlencode([("labels", l) for l in args.labels])
        out = c.delete(path)
        print(f"Revision: {out['revision']} ({out['deleted']} deleted)")
    elif args.policy_cmd == "trace":
        if args.replay:
            # provenance replay: through the REAL compiled device
            # tables, not the host label simulation
            if args.endpoint is None:
                print("policy trace --replay requires --endpoint",
                      file=sys.stderr)
                return 2
            if args.identity is None and not args.src:
                print("policy trace --replay requires --identity or "
                      "--src labels", file=sys.stderr)
                return 2
            body = {"endpoint": args.endpoint,
                    "dport": int((args.dport or ["0"])[0]),
                    "proto": args.proto,
                    "direction": args.direction}
            if args.identity is not None:
                body["identity"] = args.identity
            else:
                body["labels"] = args.src
            out = c.post("/policy/trace", body)
            for line in out["explanation"]:
                print(line)
            verdict = out["device"]["verdict"]
            print(f"Final verdict: "
                  f"{'DENIED' if verdict < 0 else 'ALLOWED'}"
                  + (f" (proxy {verdict})" if verdict > 0 else ""))
            if out["drift"]:
                print("DRIFT: device tables diverge from the host "
                      "oracle — compiler bug", file=sys.stderr)
                return 2
            return 0 if verdict >= 0 else 1
        if not args.src or not args.dst:
            print("policy trace requires --src and --dst "
                  "(or --replay)", file=sys.stderr)
            return 2
        out = c.post("/policy/resolve", {
            "from": args.src, "to": args.dst,
            "dports": [int(p) for p in args.dport or []],
            "verbose": args.verbose})
        print(out["trace"])
        print(f"Final verdict: {out['verdict'].upper()}")
        return 0 if out["verdict"] == "allowed" else 1
    elif args.policy_cmd == "validate":
        # cilium policy validate: parse + sanitize locally, no import
        from .policy.jsonio import rules_from_json
        text = sys.stdin.read() if args.file == "-" else \
            open(args.file).read()
        rules = rules_from_json(text)
        for r in rules:
            r.sanitize()
        print(f"Valid: {len(rules)} rule(s)")
    elif args.policy_cmd == "wait":
        # cilium policy wait: block until every endpoint realized the
        # revision (policy_wait.go)
        # the transport deadline must outlive the server-side wait
        out = c.request("POST", "/policy/wait",
                        {"revision": args.revision,
                         "timeout": args.timeout},
                        timeout=args.timeout + 10)
        state = "realized" if out["realized"] else "TIMED OUT"
        print(f"Revision {out['revision']}: {state}")
        return 0 if out["realized"] else 1
    return 0


def cmd_node(c: Client, args) -> int:
    nodes = c.get("/node")
    if args.json:
        _print_json(nodes)
        return 0
    for n in nodes:
        addrs = ",".join(a.get("IP", "") for a in
                         (n.get("IPAddresses") or []))
        print(f"{n.get('Name','?'):30s} {addrs:20s} "
              f"{n.get('IPv4AllocCIDR') or '-'}")
    return 0


def cmd_map(c: Client, args) -> int:
    """cilium map list / cilium bpf <map> list analogs: device-table
    inventory and entry dumps."""
    if args.map_cmd == "list":
        _print_json(c.get("/map"))
    elif args.map_cmd == "get":
        _print_json(c.get(f"/map/{args.name}?n={args.n}"))
    return 0


def cmd_version(c: Client, args) -> int:
    from . import __version__ as v
    print(f"Client: cilium-tpu {v}")
    try:
        st = c.get("/healthz")
        feats = st.get("features", {})
        print(f"Daemon: cilium-tpu {st.get('version', 'unknown')} "
              f"(backend {feats.get('backend', '?')}, "
              f"uptime {st.get('uptime-seconds', 0):.0f}s)")
    except Exception as e:  # noqa: BLE001 — client-only mode
        print(f"Daemon: unreachable ({e})")
    return 0


def cmd_endpoint(c: Client, args) -> int:
    if args.endpoint_cmd == "list":
        eps = c.get("/endpoint")
        fmt = "{:<8} {:<12} {:<16} {:<10} {:<24} {}"
        print(fmt.format("ID", "STATE", "IPv4", "IDENTITY",
                         "CONTAINER", "LABELS"))
        for ep in eps:
            print(fmt.format(
                ep["id"], ep["state"], ep["addressing"]["ipv4"] or "-",
                ep["identity"]["id"], ep["container-name"] or "-",
                ",".join(ep["labels"])))
    elif args.endpoint_cmd == "get":
        _print_json(c.get(f"/endpoint/{args.id}"))
    elif args.endpoint_cmd == "delete":
        c.delete(f"/endpoint/{args.id}")
        print(f"Endpoint {args.id} deleted")
    elif args.endpoint_cmd == "config":
        changes = {}
        for kv in args.options or []:
            k, _, v = kv.partition("=")
            changes[k] = v
        if not changes:
            ep = c.get(f"/endpoint/{args.id}")
            _print_json(ep)
        else:
            out = c.patch(f"/endpoint/{args.id}/config", changes)
            print(f"Changed {out['changed']} option(s)")
    elif args.endpoint_cmd == "labels":
        out = c.patch(f"/endpoint/{args.id}", {"labels": args.labels})
        print("Labels updated" if out.get("ok") else "No change")
    elif args.endpoint_cmd == "log":
        # cilium endpoint log: the state-transition ring
        for e in c.get(f"/endpoint/{args.id}/log"):
            ts = time.strftime("%H:%M:%S",
                               time.localtime(e["timestamp"]))
            msg = f" ({e['message']})" if e.get("message") else ""
            print(f"{ts}  {e['state']}{msg}")
    elif args.endpoint_cmd == "regenerate":
        out = c.post(f"/endpoint/{args.id}/regenerate")
        print("Regeneration queued" if out.get("queued")
              else "Already queued")
    elif args.endpoint_cmd == "healthz":
        out = c.get(f"/endpoint/{args.id}/healthz")
        _print_json(out)
        return 0 if out.get("healthy") else 1
    return 0


def cmd_identity(c: Client, args) -> int:
    if args.identity_cmd == "list":
        idents = c.get("/identity")
        print(f"{'ID':<12} LABELS")
        for i in idents:
            print(f"{i['id']:<12} {','.join(i['labels'])}")
    elif args.identity_cmd == "get":
        _print_json(c.get(f"/identity/{args.id}"))
    return 0


def cmd_service(c: Client, args) -> int:
    if args.service_cmd == "list":
        svcs = c.get("/service")
        print(f"{'FRONTEND':<24} BACKENDS")
        for s in svcs:
            front = f"{s['vip']}:{s['port']}"
            backs = ", ".join(f"{b['ip']}:{b['port']}"
                              for b in s["backends"])
            print(f"{front:<24} {backs}")
    elif args.service_cmd == "update":
        backends = []
        for b in args.backends:
            ip, _, port = b.rpartition(":")
            backends.append({"ip": ip, "port": int(port)})
        vip, _, port = args.frontend.rpartition(":")
        c.put("/service", {"vip": vip, "port": int(port),
                           "backends": backends})
        print("Service updated")
    elif args.service_cmd == "delete":
        vip, _, port = args.frontend.rpartition(":")
        c.delete("/service", {"vip": vip, "port": int(port)})
        print("Service deleted")
    return 0


def cmd_prefilter(c: Client, args) -> int:
    if args.prefilter_cmd == "list":
        out = c.get("/prefilter")
        print(f"Revision: {out['revision']}")
        for cidr in out["cidrs"]:
            print(cidr)
    elif args.prefilter_cmd == "update":
        out = c.patch("/prefilter", {"cidrs": args.cidrs})
        print(f"Revision: {out['revision']}")
    elif args.prefilter_cmd == "delete":
        out = c.delete("/prefilter", {"cidrs": args.cidrs})
        print(f"Revision: {out['revision']}")
    return 0


def cmd_monitor(c: Client, args) -> int:
    if args.stats:
        _print_json(c.get("/monitor/stats"))
        return 0
    if args.socket:
        # true subscriber stream from a separate process: no polling,
        # no dedupe needed — the server pushes each sample once
        if args.type:
            print("monitor: --type applies to the polling mode only "
                  "(the socket stream is unfiltered)", file=sys.stderr)
            return 2
        from .monitor import monitor_follow
        host, sep, port = args.socket.rpartition(":")
        if not sep or not port.isdigit():
            print(f"monitor: --socket expects host:port, got "
                  f"{args.socket!r}", file=sys.stderr)
            return 2
        for e in monitor_follow(int(port), host=host or "127.0.0.1",
                                replay=args.replay,
                                drops_only=args.drops):
            print(e["message"], flush=True)
        return 0
    # cursor-based polling: the ring hands out monotonic sequence
    # numbers, so the follower resumes from ?since=<seq> — no dedupe
    # set, no silent gap when >n events land between polls (the next
    # poll picks up exactly where the cursor left off)
    cursor = 0
    kind_q = f"&kind={args.type}" if args.type else ""
    try:
        while True:
            events = c.get(
                f"/monitor?n=200&since={cursor}&drops="
                f"{'true' if args.drops else 'false'}{kind_q}")
            for e in events:
                cursor = max(cursor, e.get("seq", 0))
                print(e["message"])
            if not args.follow:
                return 0
            _follow_sleep(args.interval, not events)
    except KeyboardInterrupt:
        return 0


def cmd_hubble(c: Client, args) -> int:
    """``cilium hubble observe`` / ``hubble stats`` — the flow
    observability surface (hubble CLI analog) over /flows."""
    from urllib.parse import urlencode
    if args.hubble_cmd == "stats":
        path = "/flows/stats"
        if getattr(args, "aggregated", False):
            path += "?aggregated=true"
        _print_json(c.get(path))
        return 0

    params = []
    for key in ("verdict", "drop_reason", "tier", "proto",
                "l7_protocol", "l7_method", "l7_path", "node"):
        v = getattr(args, key, None)
        if v:
            params.append((key, v))
    for key in ("identity", "src_identity", "dst_identity", "endpoint",
                "dport", "l7_status", "shard"):
        v = getattr(args, key, None)
        if v is not None:
            params.append((key, str(v)))
    if args.federated:
        params.append(("federated", "true"))
    cursor = args.since

    def fetch():
        qs = list(params) + [("since", str(cursor)), ("n", str(args.n))]
        return c.get("/flows?" + urlencode(qs))

    try:
        while True:
            out = fetch()
            flows = out.get("flows", [])
            for f in flows:
                cursor = max(cursor, f.get("seq", 0))
            if args.json:
                for f in flows:
                    print(json.dumps(f, sort_keys=True))
            else:
                from .hubble.flow import flow_from_dict
                for f in flows:
                    ts = time.strftime(
                        "%H:%M:%S", time.localtime(f.get("timestamp", 0)))
                    node = f.get("node", "")
                    print(f"{ts} [{node}] "
                          f"{flow_from_dict(f).describe()}")
            if args.federated and out.get("partial"):
                degraded = [n["name"] for n in out.get("nodes", [])
                            if n["status"] != "ok"]
                # sharded peers: a degraded dataplane shard is flagged
                # fail-open per shard (its FAIL-STATIC flows are still
                # in the answer, marked as such)
                for n_ in out.get("nodes", []):
                    for s in n_.get("shards") or []:
                        if s.get("status") != "ok":
                            degraded.append(
                                f"{n_['name']}/shard{s['shard']}"
                                f"({s['status']})")
                print(f"(partial result: {', '.join(degraded)} "
                      "unavailable or degraded)", file=sys.stderr)
            if not args.follow:
                return 0
            _follow_sleep(args.interval, not flows)
    except KeyboardInterrupt:
        return 0


def cmd_events(c: Client, args) -> int:
    """``cilium-tpu events`` — replay the incident flight recorder's
    ordered degraded-condition timeline (GET /debug/events), cursor-
    paginated like ``monitor``/``hubble observe``."""
    from urllib.parse import urlencode
    cursor = args.since
    try:
        while True:
            params = [("since", str(cursor)), ("n", str(args.n))]
            if args.type:
                params.append(("type", args.type))
            if args.shard is not None:
                params.append(("shard", str(args.shard)))
            out = c.get("/debug/events?" + urlencode(params))
            events = out.get("events", [])
            for e in events:
                cursor = max(cursor, e.get("seq", 0))
                if args.json:
                    print(json.dumps(e, sort_keys=True))
                    continue
                ts = time.strftime(
                    "%H:%M:%S", time.localtime(e.get("timestamp", 0)))
                where = f"[shard {e['shard']}] " \
                    if e.get("shard") is not None else ""
                attrs = " ".join(
                    f"{k}={v}" for k, v in
                    sorted((e.get("attrs") or {}).items()))
                line = f"#{e['seq']} {ts} {where}{e['type']}"
                if e.get("detail"):
                    line += f": {e['detail']}"
                if attrs:
                    line += f" ({attrs})"
                if e.get("trace-id"):
                    line += f" trace={e['trace-id']}"
                print(line)
            if not args.follow:
                if not events and not args.json:
                    stats = out.get("stats") or {}
                    print(f"(no events after seq {args.since}; "
                          f"{stats.get('ringed', 0)} buffered, "
                          f"{stats.get('evicted', 0)} evicted)")
                return 0
            _follow_sleep(args.interval, not events)
    except KeyboardInterrupt:
        return 0


def cmd_trace(c: Client, args) -> int:
    """``cilium-tpu trace`` — the span-trace surface over
    /debug/traces: recent trace summaries, or one rendered span tree
    by trace id / policy revision."""
    if args.id or args.revision is not None:
        q = f"?id={args.id}" if args.id else \
            f"?revision={args.revision}"
        tree = c.get(f"/debug/traces{q}")
        if args.json:
            _print_json(tree)
            return 0

        def render(node, depth):
            dur = node.get("duration-s") or 0.0
            attrs = " ".join(
                f"{k}={v}" for k, v in
                sorted((node.get("attrs") or {}).items()))
            print(f"{'  ' * depth}{node['name']:<40s} "
                  f"{dur * 1000:10.3f}ms  {attrs}")
            for child in node.get("children", []):
                render(child, depth + 1)

        print(f"Trace {tree['trace-id']}:")
        for root in tree.get("spans", []):
            render(root, 1)
        return 0
    out = c.get(f"/debug/traces?n={args.n}")
    if args.json:
        _print_json(out)
        return 0
    print(f"{'TRACE':<14} {'ROOT':<36} {'SPANS':>5} "
          f"{'DURATION':>12}")
    for t in out.get("traces", []):
        print(f"{t['trace-id']:<14} {t['root']:<36} "
              f"{t['spans']:>5} {t['duration-s'] * 1000:>10.3f}ms")
    ts = out.get("tracer") or {}
    print(f"({'enabled' if ts.get('enabled') else 'disabled'}, "
          f"{ts.get('buffered', 0)}/{ts.get('capacity', 0)} spans "
          f"buffered, {ts.get('dropped', 0)} evicted)")
    return 0


def cmd_threat(c: Client, args) -> int:
    """``cilium-tpu threat`` — the inline threat-scoring plane:
    status (mode/thresholds/model/verdicts), config (thresholds +
    shadow/enforce flips, a live leaf write on the daemon), train
    (fit from the aggregated flow plane + hot-swap push)."""
    if args.threat_cmd == "status":
        out = c.get("/threat")
        if args.json:
            _print_json(out)
            return 0
        mode = out.get("mode", "off")
        print(f"Threat scoring:  {mode}")
        if mode == "off":
            return 0
        if out.get("status"):
            print(f"  {out['status']}")
        model = out.get("model") or {}
        cfg = model.get("config") or {}
        print(f"  model:      gen {cfg.get('generation')}, "
              f"{model.get('features')}x{model.get('hidden')} "
              f"({model.get('resident-bytes')} bytes)")
        print(f"  thresholds: drop>={cfg.get('drop-score')} "
              f"redirect>={cfg.get('redirect-score')} "
              f"ratelimit>={cfg.get('ratelimit-score')} "
              f"(0 = arm off)")
        print(f"  bucket:     rate {cfg.get('rate-per-s')}/s "
              f"burst {cfg.get('burst')}")
        v = out.get("verdicts") or {}
        print("  verdicts:   " + " ".join(
            f"{k}={v.get(k, 0)}" for k in
            ("scored", "rate-limited", "redirected", "dropped")))
        return 0
    if args.threat_cmd == "config":
        changes = {}
        if args.mode:
            changes["mode"] = args.mode
        for field in ("drop_score", "redirect_score",
                      "ratelimit_score", "redirect_port", "burst"):
            val = getattr(args, field)
            if val is not None:
                changes[field] = val
        if args.rate_per_s is not None:
            changes["rate_per_s"] = args.rate_per_s
        if not changes:
            print("nothing to change (see --help)")
            return 1
        _print_json(c.post("/threat/config", changes))
        return 0
    # train
    _print_json(c.post("/threat/train",
                       {"max_flows": args.max_flows}))
    return 0


def cmd_top(c: Client, args) -> int:
    """``cilium-tpu top`` — mesh-wide traffic analytics decoded from
    the device-resident sketches (GET /analytics/top): talkers
    (heavy-hitter identities by bytes/packets/drops), scanners
    (distinct-dport fan-out per identity, scan suspects flagged),
    spreaders (distinct-flow cardinality per identity)."""
    from urllib.parse import urlencode
    qs = urlencode({"view": args.view, "n": str(args.n),
                    "metric": args.metric})
    out = c.get(f"/analytics/top?{qs}")
    if args.json:
        _print_json(out)
        return 0
    entries = out.get("entries", [])
    view = out.get("view", args.view)
    if view == "scanners":
        print(f"{'IDENTITY':<12} {'DPORTS':>8} {'PACKETS':>10}  FLAG")
        for e in entries:
            flag = "SCAN-SUSPECT" if e.get("suspect") else "-"
            print(f"{e['identity']:<12} {e['dports']:>8} "
                  f"{e['packets']:>10}  {flag}")
    elif view == "spreaders":
        print(f"{'IDENTITY':<12} {'FLOWS':>10}")
        for e in entries:
            print(f"{e['identity']:<12} {e['flows']:>10}")
    else:  # talkers
        metric = out.get("metric", args.metric)
        print(f"{'IDENTITY':<12} {metric.upper():>14}")
        for e in entries:
            print(f"{e['identity']:<12} {e['count']:>14}")
    if not entries:
        print("(no traffic decoded in the quiesced epoch)")
    if out.get("partial"):
        bad = sorted(k for k, s in (out.get("shards") or {}).items()
                     if s.get("status") != "ok")
        # fail-open: the remaining shards still answered, but this
        # top-K is missing the degraded shards' traffic — say so
        # loudly instead of presenting a partial decode as the truth
        print(f"(PARTIAL result: analytics shard(s) "
              f"{', '.join(bad)} unreadable — their traffic is "
              f"missing from this view)", file=sys.stderr)
    return 0


def cmd_config(c: Client, args) -> int:
    if not args.options:
        _print_json(c.get("/config"))
        return 0
    changes = {}
    for kv in args.options:
        k, _, v = kv.partition("=")
        changes[k] = v
    out = c.patch("/config", changes)
    print(f"Changed {out['changed']} option(s)")
    return 0


def cmd_metrics(c: Client, args) -> int:
    print(c.get("/metrics", raw=True), end="")
    return 0


def cmd_migrate_state(c: Client, args) -> int:
    """Standalone state migration (bpf/cilium-map-migrate.c analog:
    run around an agent upgrade, before the new agent restores)."""
    from .migrate import CHECKPOINT_VERSION, migrate_state_dir
    migrated, current, skipped = migrate_state_dir(
        args.state_dir, keep_backup=not args.no_backup)
    print(f"migrated {migrated} checkpoint(s) to "
          f"v{CHECKPOINT_VERSION}; {current} already current")
    if skipped:
        print(f"SKIPPED {len(skipped)} unmigratable checkpoint(s): "
              f"{', '.join(skipped)}", file=sys.stderr)
        return 1
    return 0


def cmd_bugtool(c: Client, args) -> int:
    from .bugtool import collect_remote
    path = collect_remote(c, args.output or None)
    print(f"Archive written: {path}")
    return 0


def cmd_cni(c: Client, args) -> int:
    import os
    from . import cni
    os.environ.setdefault("CILIUM_TPU_API", c.base_url)
    os.environ["CNI_COMMAND"] = args.cni_cmd.upper()
    if args.container_id:
        os.environ["CNI_CONTAINERID"] = args.container_id
    return cni.main()


def cmd_debuginfo(c: Client, args) -> int:
    """cilium debuginfo (cilium/cmd/debuginfo.go): one aggregate
    snapshot of agent state."""
    _print_json(c.get("/debuginfo"))
    return 0


def cmd_kvstore(c: Client, args) -> int:
    """cilium kvstore get/set/delete (cilium/cmd/kvstore_*.go),
    routed through the agent's kvstore connection."""
    from urllib.parse import quote
    key = quote(args.key, safe="/")  # spaces/?/# must not split the URL
    if args.kvstore_cmd == "get":
        suffix = "?prefix=true" if args.recursive else ""
        _print_json(c.get(f"/kvstore/{key}{suffix}"))
    elif args.kvstore_cmd == "set":
        _print_json(c.put(f"/kvstore/{key}", {"value": args.value}))
    elif args.kvstore_cmd == "delete":
        suffix = "?prefix=true" if args.recursive else ""
        _print_json(c.request("DELETE", f"/kvstore/{key}{suffix}"))
    return 0


def cmd_cleanup(c: Client, args) -> int:
    """cilium cleanup (cilium/cmd/cleanup.go): remove persisted agent
    state (endpoint checkpoints) from the state directory.  Local
    operation; requires -f like the reference."""
    import os
    import shutil
    if not args.force:
        print("cleanup removes all persisted endpoint state; "
              "re-run with -f/--force to proceed")
        return 1
    state = args.state_dir
    removed = 0
    if os.path.isdir(state):
        for fname in sorted(os.listdir(state)):
            if (fname.startswith("ep_") and fname.endswith(".json")) \
                    or fname == "ct_state.npz":
                os.unlink(os.path.join(state, fname))
                removed += 1
        if args.all:
            shutil.rmtree(state, ignore_errors=True)
    print(f"removed {removed} checkpoint file(s) from {state}")
    return 0


def cmd_docker_plugin(c: Client, args) -> int:
    from . import docker_plugin
    return docker_plugin.main(["--api", c.base_url,
                               "--listen-port", str(args.listen_port)])


def cmd_agent(args) -> int:
    """Run the agent + API server in the foreground."""
    from .daemon import Daemon
    from .daemon.rest import APIServer
    from .kvstore.backend import setup_client
    from .utils.option import DaemonConfig

    cfg = DaemonConfig(cluster_name=args.cluster_name,
                       cluster_id=args.cluster_id,
                       state_dir=args.state_dir,
                       ct_checkpoint_interval_s=getattr(
                           args, "ct_checkpoint_interval", 10.0))
    kv = None
    if args.kvstore and args.kvstore != "none":
        # --kvstore-opt port=2379 lease_ttl=15 ... (daemon/main.go
        # --kvstore-opt analog); numeric values coerce so backend
        # constructors get real ints/floats
        opts = {}
        for item in getattr(args, "kvstore_opt", None) or []:
            k, sep, v = item.partition("=")
            if not sep or not k or not v:
                raise SystemExit(
                    f"--kvstore-opt {item!r}: expected key=value")
            try:
                opts[k] = int(v)
            except ValueError:
                try:
                    opts[k] = float(v)
                except ValueError:
                    opts[k] = v
        try:
            kv = setup_client(args.kvstore, **opts)
        except KeyError:
            raise SystemExit(f"unknown kvstore backend "
                             f"{args.kvstore!r}")
        except TypeError as e:
            raise SystemExit(f"bad --kvstore-opt for "
                             f"{args.kvstore!r}: {e}")
    d = Daemon(config=cfg, kvstore_backend=kv, node_name=args.node_name)
    restored = d.restore_endpoints()
    server = APIServer(d, port=args.api_port).start()
    docker_watcher = None
    if getattr(args, "docker_socket", ""):
        # real dockerd events client (pkg/workloads/docker.go analog)
        from .workloads import (DockerClient, DockerEventWatcher,
                                WorkloadWatcher)
        docker_watcher = DockerEventWatcher(
            DockerClient(args.docker_socket),
            WorkloadWatcher(d, ipam=d.ipam)).start()
    k8s_transport = None
    if getattr(args, "k8s_api_server", ""):
        # real list/watch informers against an apiserver
        # (daemon/k8s_watcher.go EnableK8sWatcher analog)
        from .k8s.client import K8sTransport
        from .k8s.watcher import K8sWatcher
        k8s_transport = K8sTransport(K8sWatcher(d),
                                     args.k8s_api_server).start()
    vsvc = None
    if getattr(args, "verdict_port", 0):
        # the daemon->TPU verdict-service RPC hop: remote ingest
        # points ship header batches here (verdict_service.py)
        from .verdict_service import VerdictService
        secret = None
        if getattr(args, "verdict_secret_file", ""):
            # config errors are startup errors: a missing or empty
            # secret file must stop the agent with a clear message,
            # never degrade into an unauthenticated service
            try:
                with open(args.verdict_secret_file, "rb") as f:
                    secret = f.read().strip()
            except OSError as e:
                raise SystemExit(f"--verdict-secret-file: {e}")
            if not secret:
                raise SystemExit(f"--verdict-secret-file "
                                 f"{args.verdict_secret_file!r} is "
                                 f"empty")
        try:
            vsvc = VerdictService(d.datapath,
                                  host=getattr(args, "verdict_host",
                                               "127.0.0.1"),
                                  port=args.verdict_port,
                                  secret=secret).start()
        except ValueError as e:
            raise SystemExit(f"verdict service config: {e}")
        except (RuntimeError, OSError) as e:
            # native build unavailable (g++ missing raises
            # FileNotFoundError) or the port is taken — the agent
            # still runs, just without the batch RPC surface
            print(f"verdict service disabled: {e}")
    print(f"cilium-tpu agent up: api={server.base_url} "
          f"restored={restored} endpoints" +
          (f" verdict-service=:{vsvc.port}" if vsvc else ""))
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        if docker_watcher is not None:
            docker_watcher.stop()
        if k8s_transport is not None:
            k8s_transport.stop()
        if vsvc is not None:
            vsvc.shutdown()
        server.shutdown()
        d.shutdown()
    return 0


# ------------------------------------------------------------------ parser

def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="cilium-tpu",
        description="TPU-native policy enforcement framework CLI")
    p.add_argument("--api", default=DEFAULT_API,
                   help="agent API base URL")
    sub = p.add_subparsers(dest="cmd", required=True)

    sp = sub.add_parser("status", help="agent health and state")
    sp.add_argument("--json", action="store_true")
    sp.add_argument("-v", "--verbose", action="store_true",
                    help="include map pressure, JIT/compile telemetry "
                         "and policy-propagation delays")

    pol = sub.add_parser("policy", help="policy management")
    pol_sub = pol.add_subparsers(dest="policy_cmd", required=True)
    pol_sub.add_parser("get")
    imp = pol_sub.add_parser("import")
    imp.add_argument("file", help="rules JSON file, or - for stdin")
    dele = pol_sub.add_parser("delete")
    dele.add_argument("--labels", nargs="*", default=[])
    tr = pol_sub.add_parser("trace")
    tr.add_argument("--src", nargs="+", default=[])
    tr.add_argument("--dst", nargs="+", default=[])
    tr.add_argument("--dport", nargs="*")
    tr.add_argument("-v", "--verbose", action="store_true")
    tr.add_argument("--replay", action="store_true",
                    help="replay through the REAL compiled device "
                         "tables (verdict provenance) instead of the "
                         "host label simulation")
    tr.add_argument("--endpoint", type=int, default=None,
                    help="with --replay: local endpoint id")
    tr.add_argument("--identity", type=int, default=None,
                    help="with --replay: peer security identity "
                         "(or resolve --src labels)")
    tr.add_argument("--proto", type=int, default=6,
                    help="with --replay: L4 protocol number")
    tr.add_argument("--direction", default="egress",
                    choices=["ingress", "egress"])
    val = pol_sub.add_parser("validate",
                             help="parse + sanitize locally, no import")
    val.add_argument("file", help="rules JSON file, or - for stdin")
    pw = pol_sub.add_parser("wait",
                            help="block until a revision is realized")
    pw.add_argument("--revision", type=int, default=None)
    pw.add_argument("--timeout", type=float, default=30.0)

    nd = sub.add_parser("node", help="cluster node list")
    nd.add_argument("--json", action="store_true")

    mp = sub.add_parser("map",
                        help="device table inventory + entry dumps "
                             "(bpf map list analogs)")
    mp_sub = mp.add_subparsers(dest="map_cmd", required=True)
    mp_sub.add_parser("list")
    mg = mp_sub.add_parser("get")
    mg.add_argument("name",
                    help="ipcache|ipcache6|ct|ct6|tunnel|lb|lb6|"
                         "prefilter")
    mg.add_argument("-n", type=int, default=4096)

    sub.add_parser("version", help="client + daemon version")

    ep = sub.add_parser("endpoint", help="endpoint management")
    ep_sub = ep.add_subparsers(dest="endpoint_cmd", required=True)
    ep_sub.add_parser("list")
    for name in ("get", "delete", "log", "regenerate", "healthz"):
        e = ep_sub.add_parser(name)
        e.add_argument("id", type=int)
    e = ep_sub.add_parser("config")
    e.add_argument("id", type=int)
    e.add_argument("options", nargs="*", help="Option=value")
    e = ep_sub.add_parser("labels")
    e.add_argument("id", type=int)
    e.add_argument("labels", nargs="+")

    idp = sub.add_parser("identity", help="security identities")
    id_sub = idp.add_subparsers(dest="identity_cmd", required=True)
    id_sub.add_parser("list")
    g = id_sub.add_parser("get")
    g.add_argument("id", type=int)

    svc = sub.add_parser("service", help="service load balancing")
    svc_sub = svc.add_subparsers(dest="service_cmd", required=True)
    svc_sub.add_parser("list")
    up = svc_sub.add_parser("update")
    up.add_argument("--frontend", required=True, help="VIP:port")
    up.add_argument("--backends", nargs="+", required=True,
                    help="ip:port ...")
    de = svc_sub.add_parser("delete")
    de.add_argument("--frontend", required=True)

    pf = sub.add_parser("prefilter", help="XDP-prefilter analog CIDRs")
    pf_sub = pf.add_subparsers(dest="prefilter_cmd", required=True)
    pf_sub.add_parser("list")
    for name in ("update", "delete"):
        u = pf_sub.add_parser(name)
        u.add_argument("cidrs", nargs="+")

    mon = sub.add_parser("monitor", help="datapath event monitor")
    mon.add_argument("--drops", action="store_true")
    mon.add_argument("--type", default="",
                     choices=["", "agent", "l7", "datapath"],
                     help="event family filter (cilium monitor --type)")
    mon.add_argument("--stats", action="store_true")
    mon.add_argument("-f", "--follow", action="store_true")
    mon.add_argument("--interval", type=float, default=1.0)
    mon.add_argument("--socket", default="",
                     help="host:port of the agent's monitor stream "
                          "(cross-process follow, monitor/main.go "
                          "subscriber analog)")
    mon.add_argument("--replay", type=int, default=0,
                     help="with --socket: replay the last N ring "
                          "samples before following")

    hb = sub.add_parser("hubble",
                        help="flow observability (hubble CLI analog)")
    hb_sub = hb.add_subparsers(dest="hubble_cmd", required=True)
    ob = hb_sub.add_parser("observe", help="query/follow flow records")
    ob.add_argument("--verdict", default="",
                    help="FORWARDED | DROPPED | REDIRECTED")
    ob.add_argument("--drop-reason", dest="drop_reason", default="",
                    help="drop reason name or code")
    ob.add_argument("--tier", default="",
                    help="provenance decision tier (prefilter|"
                         "ct-established|l3-allow|l4-rule|l7-redirect"
                         "|deny|lb) or code")
    ob.add_argument("--identity", type=int, default=None,
                    help="match src OR dst identity")
    ob.add_argument("--src-identity", dest="src_identity", type=int,
                    default=None)
    ob.add_argument("--dst-identity", dest="dst_identity", type=int,
                    default=None)
    ob.add_argument("--endpoint", type=int, default=None)
    ob.add_argument("--dport", type=int, default=None)
    ob.add_argument("--proto", default="", help="tcp|udp|icmp|number")
    ob.add_argument("--l7-protocol", dest="l7_protocol", default="",
                    help="http|dns|kafka|parser name")
    ob.add_argument("--l7-method", dest="l7_method", default="")
    ob.add_argument("--l7-path", dest="l7_path", default="",
                    help="path prefix")
    ob.add_argument("--l7-status", dest="l7_status", type=int,
                    default=None, help="HTTP status / DNS rcode")
    ob.add_argument("--node", default="")
    ob.add_argument("--since", type=int, default=0,
                    help="resume from this sequence cursor")
    ob.add_argument("-n", type=int, default=100)
    ob.add_argument("-f", "--follow", action="store_true")
    ob.add_argument("--interval", type=float, default=1.0)
    ob.add_argument("--federated", action="store_true",
                    help="fan out to every relay peer "
                         "(partial results flagged per node AND per "
                         "local dataplane shard)")
    ob.add_argument("--shard", type=int, default=None,
                    help="sharded daemons: only this dataplane "
                         "shard's flows")
    ob.add_argument("--json", action="store_true")
    hs = hb_sub.add_parser("stats",
                           help="observer/aggregation/relay health "
                                "(mesh-wide on sharded daemons)")
    hs.add_argument("--aggregated", action="store_true",
                    help="include the on-device per-flow counters")

    thr = sub.add_parser("threat",
                         help="inline per-packet threat scoring "
                              "(Taurus-style anomaly verdict plane)")
    thr_sub = thr.add_subparsers(dest="threat_cmd", required=True)
    ts = thr_sub.add_parser("status",
                            help="mode, thresholds, model generation, "
                                 "verdict accounting")
    ts.add_argument("--json", action="store_true")
    tc = thr_sub.add_parser(
        "config", help="threshold + shadow/enforce updates (a live "
                       "leaf write on the daemon; mode flips ring "
                       "the flight recorder)")
    tc.add_argument("--mode", choices=("shadow", "enforce"),
                    default="")
    tc.add_argument("--drop-score", dest="drop_score", type=int,
                    default=None, help="score >= this drops (0 = off)")
    tc.add_argument("--redirect-score", dest="redirect_score",
                    type=int, default=None)
    tc.add_argument("--ratelimit-score", dest="ratelimit_score",
                    type=int, default=None)
    tc.add_argument("--redirect-port", dest="redirect_port", type=int,
                    default=None)
    tc.add_argument("--rate-per-s", dest="rate_per_s", type=float,
                    default=None, help="token-bucket refill rate")
    tc.add_argument("--burst", type=int, default=None,
                    help="token-bucket capacity")
    tt = thr_sub.add_parser(
        "train", help="fit from the aggregated flow plane and "
                      "hot-swap the weights (zero repacks)")
    tt.add_argument("--max-flows", dest="max_flows", type=int,
                    default=4096)

    top = sub.add_parser("top",
                         help="device-resident traffic analytics: "
                              "heavy-hitter / scan / cardinality "
                              "views (/analytics/top)")
    top.add_argument("view", nargs="?", default="talkers",
                     choices=["talkers", "scanners", "spreaders"],
                     help="talkers = identities by sketch count, "
                          "scanners = distinct-dport fan-out, "
                          "spreaders = distinct-flow cardinality")
    top.add_argument("-n", type=int, default=10)
    top.add_argument("--metric", default="bytes",
                     choices=["bytes", "packets", "drops"],
                     help="talkers ranking metric")
    top.add_argument("--json", action="store_true")

    cfgp = sub.add_parser("config", help="daemon options")
    cfgp.add_argument("options", nargs="*", help="Option=value")

    sub.add_parser("metrics", help="Prometheus metrics dump")

    ev = sub.add_parser("events",
                        help="incident flight recorder: the ordered "
                             "degraded-condition timeline "
                             "(/debug/events)")
    ev.add_argument("--since", type=int, default=0,
                    help="resume from this sequence cursor")
    ev.add_argument("--type", default="",
                    help="one event type only (e.g. "
                         "dataplane-degraded, kvstore-recovered)")
    ev.add_argument("--shard", type=int, default=None,
                    help="one dataplane shard's events only")
    ev.add_argument("-n", type=int, default=200)
    ev.add_argument("-f", "--follow", action="store_true")
    ev.add_argument("--interval", type=float, default=1.0)
    ev.add_argument("--json", action="store_true")

    trp = sub.add_parser("trace",
                         help="control-plane span traces "
                              "(/debug/traces)")
    trp.add_argument("--id", default="",
                     help="show one trace's span tree")
    trp.add_argument("--revision", type=int, default=None,
                     help="show the span tree of a policy revision's "
                          "propagation")
    trp.add_argument("-n", type=int, default=50,
                     help="trace summaries to list")
    trp.add_argument("--json", action="store_true")

    ms = sub.add_parser("migrate-state",
                        help="upgrade endpoint checkpoints across "
                             "agent versions (cilium-map-migrate "
                             "analog)")
    ms.add_argument("state_dir")
    ms.add_argument("--no-backup", action="store_true")

    bt = sub.add_parser("bugtool", help="archive agent state for a bug report")
    bt.add_argument("-o", "--output", default="")

    cn = sub.add_parser("cni", help="CNI plugin entry (ADD/DEL/VERSION)")
    cn.add_argument("cni_cmd", choices=["add", "del", "version"])
    cn.add_argument("--container-id", default="")

    dp = sub.add_parser("docker-plugin",
                        help="serve the docker libnetwork remote driver")
    dp.add_argument("--listen-port", type=int, default=9235)

    sub.add_parser("debuginfo", help="aggregate agent state snapshot")

    kvp = sub.add_parser("kvstore", help="kvstore access via the agent")
    kv_sub = kvp.add_subparsers(dest="kvstore_cmd", required=True)
    g = kv_sub.add_parser("get")
    g.add_argument("key")
    g.add_argument("--recursive", action="store_true")
    s = kv_sub.add_parser("set")
    s.add_argument("key")
    s.add_argument("value")
    de = kv_sub.add_parser("delete")
    de.add_argument("key")
    de.add_argument("--recursive", action="store_true")

    cl = sub.add_parser("cleanup", help="remove persisted agent state")
    cl.add_argument("-f", "--force", action="store_true")
    cl.add_argument("--all", action="store_true",
                    help="remove the whole state dir")
    cl.add_argument("--state-dir", default="/var/run/cilium_tpu")

    ag = sub.add_parser("agent", help="run the agent")
    ag.add_argument("--api-port", type=int, default=9234)
    ag.add_argument("--verdict-port", type=int, default=0,
                    help="serve the batch verdict service on this "
                         "port (0 = disabled)")
    ag.add_argument("--verdict-host", default="127.0.0.1",
                    help="verdict service bind address; non-loopback "
                         "requires --verdict-secret-file")
    ag.add_argument("--verdict-secret-file", default="",
                    help="file holding the shared secret for verdict-"
                         "service peer authentication (HMAC "
                         "challenge-response)")
    ag.add_argument("--kvstore", default="none",
                    help="none | in-memory | remote | etcd")
    ag.add_argument("--kvstore-opt", action="append", default=[],
                    help="backend option key=value (repeatable), "
                         "e.g. --kvstore-opt port=2379")
    ag.add_argument("--cluster-name", default="default")
    ag.add_argument("--cluster-id", type=int, default=0)
    ag.add_argument("--node-name", default="node-local")
    ag.add_argument("--state-dir", default="")
    ag.add_argument("--ct-checkpoint-interval", type=float, default=10.0,
                    help="seconds between CT snapshots to state-dir "
                         "(0 = only at clean shutdown)")
    ag.add_argument("--k8s-api-server", default="",
                    help="apiserver base URL to list/watch (informer "
                         "transport; empty = no k8s)")
    ag.add_argument("--docker-socket", default="",
                    help="dockerd unix socket to watch container "
                         "events on (empty = no docker runtime)")
    return p


COMMANDS = {
    "status": cmd_status, "policy": cmd_policy, "endpoint": cmd_endpoint,
    "identity": cmd_identity, "service": cmd_service,
    "prefilter": cmd_prefilter, "monitor": cmd_monitor,
    "hubble": cmd_hubble, "threat": cmd_threat, "top": cmd_top,
    "config": cmd_config, "metrics": cmd_metrics,
    "trace": cmd_trace, "events": cmd_events,
    "bugtool": cmd_bugtool, "cni": cmd_cni,
    "docker-plugin": cmd_docker_plugin,
    "debuginfo": cmd_debuginfo, "kvstore": cmd_kvstore,
    "cleanup": cmd_cleanup,
    "migrate-state": cmd_migrate_state,
    "node": cmd_node, "map": cmd_map, "version": cmd_version,
}


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    if args.cmd == "agent":
        return cmd_agent(args)
    return COMMANDS[args.cmd](Client(args.api), args)


if __name__ == "__main__":
    sys.exit(main())
