"""L7 proxy plane: redirect lifecycle, port allocation, engine dispatch.

Reference: pkg/proxy/proxy.go — proxy ports allocated from 10000-20000
(daemon/daemon.go:1326), redirects keyed by ProxyID
``epID:ingress|egress:proto:port`` (pkg/policy/proxyid.go:24), and the
implementation chosen per L7 parser type (proxy.go:154
CreateOrUpdateRedirect: Kafka -> Go proxy, HTTP/other -> Envoy). Here
every redirect owns a compiled batched engine (HTTP DFAs, Kafka ACLs, or
a registered custom parser) plus an access-log stream
(pkg/proxy/logger analog).
"""

from __future__ import annotations

import threading

from .utils.lock import RMutex
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .l7.http import HTTPPolicyEngine, HTTPRequest
from .l7.kafka import KafkaPolicyEngine, KafkaRequest
from .l7.parser import Instance as ParserInstance
from .labels import LabelArray
from .policy.l4 import (L4Filter, PARSER_TYPE_HTTP, PARSER_TYPE_KAFKA,
                        PARSER_TYPE_NONE)

PROXY_PORT_MIN = 10000  # reference: daemon.go:1326
PROXY_PORT_MAX = 20000


def proxy_id(endpoint_id: int, ingress: bool, proto: str, port: int) -> str:
    """Reference: pkg/policy/proxyid.go:24 ProxyID."""
    direction = "ingress" if ingress else "egress"
    return f"{endpoint_id}:{direction}:{proto}:{port}"


@dataclass
class AccessLogEntry:
    """One proxied request record (pkg/proxy/logger AccessLogRecord)."""

    timestamp: float
    proxy_id: str
    l7_protocol: str
    verdict: str           # "forwarded" | "denied"
    src_identity: int
    dst_identity: int
    info: Dict = field(default_factory=dict)


class AccessLog:
    """In-process access-log ring (envoy/accesslog.cc + logger analog)."""

    def __init__(self, capacity: int = 4096):
        self._lock = threading.Lock()
        self._entries: List[AccessLogEntry] = []
        self.capacity = capacity
        self.subscribers: List[Callable[[AccessLogEntry], None]] = []

    def log(self, entry: AccessLogEntry) -> None:
        with self._lock:
            self._entries.append(entry)
            if len(self._entries) > self.capacity:
                self._entries = self._entries[-self.capacity:]
            subs = list(self.subscribers)
        for s in subs:
            s(entry)

    def tail(self, n: int = 100) -> List[AccessLogEntry]:
        with self._lock:
            return self._entries[-n:]


@dataclass
class Redirect:
    """One active redirect (pkg/proxy/proxy.go Redirect)."""

    id: str
    proxy_port: int
    parser_type: str
    endpoint_id: int
    ingress: bool
    to_port: int
    created: float = field(default_factory=time.time)
    # engines per remote-identity rule resolution
    http_engine: Optional[HTTPPolicyEngine] = None
    kafka_engine: Optional[KafkaPolicyEngine] = None
    l7_filter: Optional[L4Filter] = None

    def engines_for(self, remote_labels: Optional[LabelArray]):
        """(Re)build engines from the filter's per-selector rules for a
        given remote identity (l4.go GetRelevantRules)."""
        rules = self.l7_filter.l7_rules_per_ep.get_relevant_rules(
            remote_labels) if self.l7_filter is not None else None
        if self.parser_type == PARSER_TYPE_HTTP:
            return HTTPPolicyEngine(rules.http if rules else [])
        if self.parser_type == PARSER_TYPE_KAFKA:
            return KafkaPolicyEngine(rules.kafka if rules else [])
        return None


class ProxyManager:
    """Redirect registry + port allocator (pkg/proxy/proxy.go:88,154)."""

    def __init__(self, port_min: int = PROXY_PORT_MIN,
                 port_max: int = PROXY_PORT_MAX):
        self._lock = RMutex("proxy-manager")
        self._redirects: Dict[str, Redirect] = {}
        self._ports_in_use: set = set()
        self._next_port = port_min
        self.port_min = port_min
        self.port_max = port_max
        self.access_log = AccessLog()
        # socket data plane (l7/socket_proxy.py), created on demand
        self.dataplane = None
        self.parser_instance = ParserInstance(
            access_logger=lambda d: self.access_log.log(AccessLogEntry(
                timestamp=time.time(), proxy_id=str(d.get("conn_id")),
                l7_protocol=d.get("proto", ""),
                verdict="forwarded" if d.get("verdict") == "pass"
                else "denied",
                src_identity=d.get("src_identity", 0),
                dst_identity=d.get("dst_identity", 0), info=d)))

    def _allocate_port(self) -> int:
        """Reference: proxy.go allocatePort — scan the range."""
        start = self._next_port
        while True:
            p = self._next_port
            self._next_port += 1
            if self._next_port > self.port_max:
                self._next_port = self.port_min
            if p not in self._ports_in_use:
                self._ports_in_use.add(p)
                return p
            if self._next_port == start:
                raise RuntimeError("proxy port range exhausted")

    def create_or_update_redirect(self, flt: L4Filter, endpoint_id: int
                                  ) -> Redirect:
        """Reference: proxy.go:154 CreateOrUpdateRedirect."""
        if flt.l7_parser == PARSER_TYPE_NONE:
            raise ValueError("filter is not a redirect")
        rid = proxy_id(endpoint_id, flt.ingress, flt.protocol, flt.port)
        with self._lock:
            redir = self._redirects.get(rid)
            if redir is None:
                redir = Redirect(id=rid, proxy_port=self._allocate_port(),
                                 parser_type=flt.l7_parser,
                                 endpoint_id=endpoint_id,
                                 ingress=flt.ingress, to_port=flt.port)
                self._redirects[rid] = redir
            redir.parser_type = flt.l7_parser
            redir.l7_filter = flt
        cb = getattr(self, "on_change", None)
        if cb is not None:
            cb()
        return redir

    def remove_redirect(self, rid: str) -> bool:
        with self._lock:
            redir = self._redirects.pop(rid, None)
            if redir is None:
                return False
            self._ports_in_use.discard(redir.proxy_port)
        if self.dataplane is not None:
            try:
                self.dataplane.stop_listener(rid)
            except Exception:  # noqa: BLE001
                pass
        cb = getattr(self, "on_change", None)
        if cb is not None:
            cb()
        return True

    # -- socket data plane ---------------------------------------------------

    def enable_dataplane(self, host: str = "127.0.0.1"):
        """Start the socket-level proxy data plane (lazy import keeps
        asyncio out of pure-policy deployments)."""
        if self.dataplane is None:
            from .l7.socket_proxy import SocketProxy
            self.dataplane = SocketProxy(access_log=self.access_log,
                                         host=host)
        return self.dataplane

    def activate_redirect(self, redir: Redirect,
                          orig_dst: Callable,
                          remote_labels: Optional[Callable] = None,
                          identities: Optional[Callable] = None) -> int:
        """Bind the redirect's proxy port on the data plane.

        orig_dst(peer_addr) -> (host, port): the proxymap analog
        resolving the flow's original destination.
        remote_labels(peer_addr) -> LabelArray: peer identity labels for
        per-selector rule resolution (l4.go GetRelevantRules).
        Returns the bound port (== redir.proxy_port).
        """
        from .l7.socket_proxy import ListenerContext
        dataplane = self.enable_dataplane()
        labels_of = remote_labels or (lambda addr: None)

        def l7_rules(addr):
            if redir.l7_filter is None:
                return []
            rules = redir.l7_filter.l7_rules_per_ep.get_relevant_rules(
                labels_of(addr))
            return list(rules.l7) if rules and rules.l7 else []

        ctx = ListenerContext(
            redirect_id=redir.id,
            parser_type=redir.parser_type,
            orig_dst=orig_dst,
            l7_rules=l7_rules,
            identities=identities or (lambda addr: (0, 0)),
            http_engine_for=lambda addr: redir.engines_for(
                labels_of(addr)) if redir.parser_type ==
            PARSER_TYPE_HTTP else None,
            kafka_engine_for=lambda addr: redir.engines_for(
                labels_of(addr)) if redir.parser_type ==
            PARSER_TYPE_KAFKA else None)
        return dataplane.start_listener(redir.proxy_port, ctx)

    def shutdown_dataplane(self) -> None:
        if self.dataplane is not None:
            self.dataplane.shutdown()
            self.dataplane = None

    def get(self, rid: str) -> Optional[Redirect]:
        with self._lock:
            return self._redirects.get(rid)

    def redirects(self) -> List[Redirect]:
        with self._lock:
            return list(self._redirects.values())

    def __len__(self):
        with self._lock:
            return len(self._redirects)

    # -- request-time checks (the proxy data path) --------------------------

    def check_http(self, redir: Redirect, remote_labels: LabelArray,
                   requests: Sequence[HTTPRequest]):
        engine = redir.engines_for(remote_labels)
        verdicts = engine.check(requests)
        for req, ok in zip(requests, verdicts):
            self.access_log.log(AccessLogEntry(
                timestamp=time.time(), proxy_id=redir.id, l7_protocol="http",
                verdict="forwarded" if ok else "denied",
                src_identity=0, dst_identity=0,
                info={"method": req.method, "path": req.path,
                      "host": req.host}))
        return verdicts

    def check_kafka(self, redir: Redirect, remote_labels: LabelArray,
                    requests: Sequence[KafkaRequest]):
        engine = redir.engines_for(remote_labels)
        verdicts = engine.check(requests)
        for req, ok in zip(requests, verdicts):
            self.access_log.log(AccessLogEntry(
                timestamp=time.time(), proxy_id=redir.id,
                l7_protocol="kafka",
                verdict="forwarded" if ok else "denied",
                src_identity=0, dst_identity=0,
                info={"api_key": req.api_key, "topics": req.topics,
                      "client_id": req.client_id}))
        return verdicts
