"""The fused per-packet threat-scoring stage (Taurus-style, jnp).

Runs INSIDE both jitted family pipelines behind the static
``with_threat`` gate (datapath/pipeline.py), right after the final
verdict precedence: every packet gets a 0..255 anomaly score from

  * the in-pipeline Hubble flow-table probe (per-flow packet/byte
    counters + last-seen, read from the same device table the flow
    tail updates),
  * the claim-window aggregates kept in the shard-local ThreatState
    buffer (per-identity new-flow rate + dport-span port-scan signal),
  * the packet's own tuple features (SYN-without-established, dport,
    proto, length, WORLD peer, fragment),

then maps the score through the policy-controlled config to a verdict
arm: drop (VERDICT_DROP_THREAT), redirect-to-proxy, or token-bucket
rate-limit (probabilistic drop keyed on score once the identity's
bucket runs dry).  In shadow mode (cfg enforce=0) the verdict is
provably untouched — the arms are computed for observability only and
the token buckets are never consumed — so scoring can run against
production traffic with bit-exact pre-threat verdicts.

Cost shape: the state buffer is BUCKET-major ([T+1, 6] int32 — one
row per identity bucket, fields as columns) so the whole per-packet
state read is ONE [B, 6] row gather (pre) plus one (post), and the
updates collapse to six scatters (window reset as one [B, 4] row-span
write, counter add, dport min/max, token refill-span write, token
debit) — scatter cost is per-index, the flow-table lesson.  Feature
log-buckets come from the float32 exponent (exact for the clamped
int range, so no 16-compare chains).

Determinism contract: every scatter is either same-value-per-bucket
(set), commutative (add), or order-free (min/max), so the numpy
oracle (``oracle.py``) reproduces the device output bit-exactly —
the parity tests in tests/test_threat.py hold that line.  All
arithmetic is int32; no value can overflow (the model quantization
bounds in ``model.py`` size the products).
"""

from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from ..ops.hashtab_ops import hash_mix_jnp
from .model import (CFG_BURST, CFG_DROP, CFG_ENFORCE, CFG_RATE_Q8,
                    CFG_RATELIMIT, CFG_REDIRECT, CFG_REDIRECT_PORT,
                    SCORE_MAX, WEIGHT_Q)

# ThreatState column layout ([T+1, COLS] int32; row T is the no-op
# sentinel that absorbs masked scatters, the CT/flow convention)
COL_TOKENS = 0      # token-bucket fill, Q8.8 (may run negative: debt)
COL_TB_TS = 1       # last refill timestamp
COL_WIN_TS = 2      # claim-window start timestamp
COL_WIN_NEW = 3     # new flows observed in the window
COL_DPORT_MIN = 4   # smallest dport in the window (65535 on reset)
COL_DPORT_MAX = 5   # largest dport in the window
STATE_COLS = 6

# identity -> bucket salt (any fixed odd-ish constant works; the
# oracle shares it)
BUCKET_SALT = 0x7EA7

# threat_out lane encoding: score | band << 8 | fired << 10
ARM_NONE, ARM_RATELIMIT, ARM_REDIRECT, ARM_DROP = 0, 1, 2, 3
OUT_ARM_SHIFT = 8
OUT_FIRED_BIT = 1 << 10

# log_bucket clamps its input here: float32 is exact far beyond it,
# and the 0..16 bucket range saturates at 2^15 anyway
LOG_CLAMP = 1 << 22


class ThreatState(NamedTuple):
    """The shard-local mutable threat-plane buffer: ONE [T+1, 6] int32
    dispatch leaf (token buckets + claim-window aggregates), owned per
    engine like the CT pack — each mesh shard keeps its own copy on
    its own column (specs.THREAT_STATE_SPECS)."""

    state: jnp.ndarray


def make_threat_state(buckets: int) -> ThreatState:
    assert buckets & (buckets - 1) == 0, "buckets must be a power of 2"
    return ThreatState(
        state=jnp.zeros((buckets + 1, STATE_COLS), jnp.int32))


def log_bucket(x: jnp.ndarray) -> jnp.ndarray:
    """Integer floor-log2 bucket (0 for x<=0, else min(16,
    floor(log2 x)+1)) via the float32 exponent — exact for the
    clamped range on every backend, mirrored by the oracle."""
    xc = jnp.clip(x.astype(jnp.int32), 0, LOG_CLAMP)
    _m, e = jnp.frexp(xc.astype(jnp.float32))
    return jnp.minimum(jnp.where(xc > 0, e, 0), 16).astype(jnp.int32)


def _flow_probe(flows, src_id, dst_id, dport, proto, *,
                flow_slots: int, flow_probe: int):
    """Probe the device flow table for each packet's flow under the
    allowed-traffic key (event TRACE_TO_LXC) — the same exact-compare
    window walk the aggregation kernel runs, read-only over the
    PRE-update state.  Returns (found, packets, bytes, last_seen)."""
    from ..hubble.aggregation import (_LS, _probe_idx, _window_lookup,
                                      pack_flow_meta)
    meta = pack_flow_meta(dport.astype(jnp.int32),
                          proto.astype(jnp.int32),
                          jnp.zeros_like(dport))       # TRACE_TO_LXC
    k0 = src_id.astype(jnp.int32)
    k1 = dst_id.astype(jnp.int32)
    q = jnp.stack([k0, k1, meta], axis=1)
    idx = _probe_idx(k0, k1, meta, flow_slots, flow_probe)
    _got, _hit, found, slot = _window_lookup(flows.keys[:, :3], idx, q)
    slot = jnp.where(found, slot, jnp.int32(flow_slots))  # sentinel
    cnt = flows.counters[slot].astype(jnp.int32)          # [B, 2]
    last = flows.keys[slot, _LS]
    zero = jnp.zeros_like(slot)
    return (found, jnp.where(found, cnt[:, 0], zero),
            jnp.where(found, cnt[:, 1], zero),
            jnp.where(found, last, zero))


def threat_stage(tables, threat: ThreatState, flows, verdict, *,
                 identity, dport, proto, tcp_flags, length,
                 is_fragment, established, saddr_w, daddr_w, sport,
                 flow_src, flow_dst, now, window_s: int,
                 flow_slots: int = 0, flow_probe: int = 0,
                 stripe: int = 4, exempt=None):
    """One fused scoring pass.  ``tables`` carries the tm_* model
    leaves; ``flows`` is the (pre-update) FlowState or None; all
    per-packet args are [B] int32 (v6 passes fold6'd address words).
    ``flow_src``/``flow_dst`` are the oriented flow-key identities the
    aggregation tail uses (pipeline._flow_identities), so the probe
    hits exactly the entries the flow plane maintains.

    ``stripe`` (static) stripes the window-aggregate UPDATE: each
    batch scatters contributions from one rotating contiguous
    1/stripe block of its rows (the flow table's ls_stripe
    precedent), so the aggregate is a consistent 1-in-stripe sample
    of the traffic — feature READS stay per-packet for every row, and
    the scoring weights absorb the sampling factor.  stripe=1 is the
    every-row configuration.  Deterministic either way: the phase
    derives from ``now``, so the oracle mirrors it exactly.

    Returns (verdict', threat', threat_out [B],
    thr_drop [B] bool, thr_redir [B] bool, rl_drop [B] bool) —
    the three fired masks feed the provenance tier override."""
    from jax import lax as _lax

    from ..datapath.verdict import VERDICT_DROP_THREAT

    state = threat.state
    t = state.shape[0] - 1
    b = identity.shape[0]
    cfg = tables.tm_cfg
    now_i = jnp.int32(now)
    sentinel = jnp.int32(t)

    # -- claim-window aggregates (per-identity buckets) -----------------
    bucket = hash_mix_jnp(identity, jnp.full((b,), BUCKET_SALT,
                                             jnp.int32)) & jnp.int32(t - 1)
    st_n = max(1, min(stripe, b))
    width = b // st_n if b % st_n == 0 else b

    def _sl(x):
        if width == b:
            return x
        phase = jnp.remainder(now_i, jnp.int32(st_n))
        return _lax.dynamic_slice_in_dim(x, phase * width, width)

    bucket_s = _sl(bucket)
    win_ts = state[bucket_s, COL_WIN_TS]
    expired = (now_i - win_ts) >= jnp.int32(window_s)
    tgt_exp = jnp.where(expired, bucket_s, sentinel)
    reset_vals = jnp.broadcast_to(
        jnp.array([0, 0, 65535, 0], jnp.int32)
        .at[0].set(now_i)[None, :], (width, 4))
    state = state.at[tgt_exp, COL_WIN_TS:].set(reset_vals)
    new_flow_s = _sl(~established)
    dport_s = _sl(dport)
    state = state.at[jnp.where(new_flow_s, bucket_s, sentinel),
                     COL_WIN_NEW].add(1)
    state = state.at[bucket_s, COL_DPORT_MIN].min(dport_s)
    state = state.at[bucket_s, COL_DPORT_MAX].max(dport_s)
    post = state[bucket]                                  # [B, 6]
    win_new = post[:, COL_WIN_NEW]
    spread = jnp.maximum(post[:, COL_DPORT_MAX] -
                         post[:, COL_DPORT_MIN], 0)

    # -- flow-table probe (per-flow history) ----------------------------
    if flows is not None and flow_slots > 0:
        found, fl_pkts, fl_bytes, fl_last = _flow_probe(
            flows, flow_src, flow_dst, dport, proto,
            flow_slots=flow_slots, flow_probe=flow_probe)
    else:
        found = jnp.zeros(b, bool)
        fl_pkts = fl_bytes = fl_last = jnp.zeros(b, jnp.int32)

    # -- feature lanes (model.FEATURES order, each 0..255) --------------
    full = jnp.full((b,), SCORE_MAX, jnp.int32)
    zero = jnp.zeros(b, jnp.int32)
    syn = (tcp_flags & jnp.int32(0x02)) != 0
    is_tcp = proto == jnp.int32(6)
    recency = jnp.where(found,
                        jnp.clip(now_i - fl_last, 0, SCORE_MAX), full)
    feats = jnp.stack([
        15 * log_bucket(fl_pkts),
        15 * log_bucket(fl_bytes),
        recency,
        jnp.where(syn & is_tcp & ~established, full, zero),
        jnp.where(established, full, zero),
        15 * log_bucket(win_new),
        15 * log_bucket(spread),
        jnp.minimum(dport >> 8, SCORE_MAX),
        jnp.where(proto == jnp.int32(17), full, zero),
        15 * log_bucket(length),
        jnp.where(identity == jnp.int32(2), full, zero),  # WORLD
        jnp.where(is_fragment != 0, full, zero),
    ], axis=1)                                            # [B, F]

    # -- the quantized scorer (MXU-shaped: two small contractions) ------
    z1 = jnp.sum(feats[:, :, None] * tables.tm_w1[None, :, :],
                 axis=1) >> WEIGHT_Q
    h = jnp.clip(z1 + tables.tm_b1[None, :], 0, SCORE_MAX)
    z2 = jnp.sum(h * tables.tm_w2[None, :], axis=1) >> WEIGHT_Q
    score = jnp.clip(z2 + tables.tm_b2[0], 0, SCORE_MAX)

    # -- verdict arms + token bucket, behind a runtime gate -------------
    # The whole enforcement half (arm classification, the tuple-hash
    # uniform, the token bucket and the verdict override) runs under a
    # lax.cond on "any arm threshold armed": in score-only mode (every
    # threshold 0 — the shadow default) it is SKIPPED at runtime, so
    # pure scoring pays for the scorer alone.  Semantics are identical
    # either way: with all thresholds 0 the armed branch computes
    # all-False masks and writes nothing (the numpy oracle mirrors the
    # unconditional math).
    from jax import lax

    enforce = cfg[CFG_ENFORCE] != 0
    eligible = verdict >= 0          # never overrides an existing drop
    if exempt is not None:
        # rows another stage answered terminally (the v6 local ICMPv6
        # responder) are scored but never overridden
        eligible = eligible & ~exempt
    any_arm = (cfg[CFG_DROP] > 0) | (cfg[CFG_REDIRECT] > 0) | \
        (cfg[CFG_RATELIMIT] > 0)

    def _armed(state):
        drop_arm = eligible & (cfg[CFG_DROP] > 0) & \
            (score >= cfg[CFG_DROP])
        redir_arm = eligible & ~drop_arm & (cfg[CFG_REDIRECT] > 0) & \
            (score >= cfg[CFG_REDIRECT])
        rl_arm = eligible & ~drop_arm & ~redir_arm & \
            (cfg[CFG_RATELIMIT] > 0) & (score >= cfg[CFG_RATELIMIT])
        # token bucket (rate-limit arm, enforce only; batch-granular:
        # same-batch rows of one bucket share the pre-batch token
        # view, consumption lands as one accumulated debit)
        want = rl_arm & enforce
        # cols 0/1 are untouched by the window scatters, so the
        # post-window gather IS the pre-batch token view
        dt = jnp.clip(now_i - post[:, COL_TB_TS], 0, 3600)
        refilled = jnp.minimum(
            cfg[CFG_BURST] << WEIGHT_Q,
            post[:, COL_TOKENS] + cfg[CFG_RATE_Q8] * dt)
        has_token = refilled >= jnp.int32(1 << WEIGHT_Q)
        # probabilistic drop keyed on score once the bucket is dry:
        # the per-packet uniform derives from the tuple + timestamp
        # hash (the host oracle mirrors the exact mix)
        word = ((sport & jnp.int32(0xFFFF)) << 16) | \
            (dport & jnp.int32(0xFFFF))
        prand = hash_mix_jnp(
            hash_mix_jnp(saddr_w, daddr_w),
            hash_mix_jnp(word, jnp.full((b,), 0, jnp.int32)
                         + now_i)) & jnp.int32(0xFF)
        denom = jnp.maximum(jnp.int32(256) - cfg[CFG_RATELIMIT], 1)
        p = jnp.clip((score - cfg[CFG_RATELIMIT] + 1) * 255 // denom,
                     0, 255)
        rl_drop = want & ~has_token & (prand < p)
        tgt_want = jnp.where(want, bucket, sentinel)
        state = state.at[tgt_want, COL_TOKENS:COL_WIN_TS].set(
            jnp.stack([refilled, jnp.broadcast_to(now_i, (b,))],
                      axis=1))
        consumed = want & has_token
        state = state.at[jnp.where(consumed, bucket, sentinel),
                         COL_TOKENS].add(jnp.int32(-(1 << WEIGHT_Q)))
        state = state.at[sentinel].set(
            jnp.zeros(STATE_COLS, jnp.int32))
        # final verdict override (enforce only; shadow is bit-exact)
        thr_drop = (drop_arm & enforce) | rl_drop
        thr_redir = redir_arm & enforce & (verdict == 0)
        v = jnp.where(
            thr_drop, jnp.int32(VERDICT_DROP_THREAT),
            jnp.where(thr_redir, cfg[CFG_REDIRECT_PORT], verdict))
        band = jnp.where(
            drop_arm, jnp.int32(ARM_DROP),
            jnp.where(redir_arm, jnp.int32(ARM_REDIRECT),
                      jnp.where(rl_arm, jnp.int32(ARM_RATELIMIT),
                                jnp.int32(ARM_NONE))))
        return v, state, band, thr_drop, thr_redir, rl_drop

    def _score_only(state):
        state = state.at[sentinel].set(
            jnp.zeros(STATE_COLS, jnp.int32))
        false = jnp.zeros(b, bool)
        return (verdict, state, jnp.zeros(b, jnp.int32), false,
                false, false)

    verdict, state, band, thr_drop, thr_redir, rl_drop = lax.cond(
        any_arm, _armed, _score_only, state)

    fired = thr_drop | thr_redir
    threat_out = score | (band << OUT_ARM_SHIFT) | \
        jnp.where(fired, jnp.int32(OUT_FIRED_BIT), jnp.int32(0))
    return (verdict, ThreatState(state=state), threat_out,
            thr_drop, thr_redir, rl_drop)


def unpack_threat_out(out) -> Tuple:
    """Decode the packed [B] threat_out lane -> (score, band, fired)
    numpy arrays (host-side; monitor/daemon consumers)."""
    import numpy as _np
    arr = _np.array(out, _np.int32)
    score = arr & 0xFF
    band = (arr >> OUT_ARM_SHIFT) & 0x3
    fired = (arr & OUT_FIRED_BIT) != 0
    return score, band, fired
