"""Host-side threat-model training from federated Hubble flow drains.

PR 13's federated drain streams the COMPLETE per-shard device flow
plane host-side (hubble/federation.ShardedObserver.drain); this module
closes the loop: aggregate flow records -> per-flow feature rows in
the SAME feature space the fused stage scores (model.FEATURES order)
-> a logistic scorer fit with plain numpy gradient descent (no new
deps) -> quantized int32 weights that hot-swap through the engine's
delta-apply leaf writes with zero repacks and no serving pause.

Labels: by default a flow is anomalous when its aggregated event code
is a drop (the dataplane already said no — the model learns to
predict policy/prefilter denials from traffic shape, the classic
DDoS-detector bootstrap) ; callers with better ground truth pass
``labels`` explicitly.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .model import (NUM_FEATURES, SCORE_MAX, ThreatConfig, ThreatModel,
                    linear_model)
from .oracle import log_bucket_np


def features_from_flow(flow: Dict, now: Optional[int] = None
                       ) -> np.ndarray:
    """One aggregated flow record (FlowTable.snapshot() /
    FlowRecord-shaped dict) -> the [NUM_FEATURES] int feature row.

    Flow records carry the per-flow half of the feature space
    (packets, bytes, recency, dport, proto); the per-packet-only
    lanes (SYN state, CT establishment, window aggregates) train at
    their neutral midpoint so their weights stay driven by the
    hand-seeded prior until per-packet ground truth exists."""
    pkts = int(flow.get("packets", 0))
    byts = int(flow.get("bytes", 0))
    dport = int(flow.get("dport", 0))
    proto = int(flow.get("proto", 0))
    last = int(flow.get("last-seen", 0))
    now = int(now) if now is not None else last
    f = np.zeros(NUM_FEATURES, np.int32)
    f[0] = 15 * int(log_bucket_np(np.array([pkts]))[0])
    f[1] = 15 * int(log_bucket_np(np.array([byts]))[0])
    f[2] = min(max(now - last, 0), SCORE_MAX)
    f[3] = 0                              # syn-no-established
    f[4] = SCORE_MAX if pkts > 1 else 0   # multi-packet ~ established
    f[5] = 0                              # window lanes: per-packet only
    f[6] = 0
    f[7] = min(dport >> 8, SCORE_MAX)
    f[8] = SCORE_MAX if proto == 17 else 0
    f[9] = 15 * int(log_bucket_np(
        np.array([byts // max(pkts, 1)]))[0])
    f[10] = SCORE_MAX if flow.get("src-identity") == 2 or \
        flow.get("dst-identity") == 2 else 0
    f[11] = 0
    return f


def label_from_flow(flow: Dict) -> int:
    """Default label: the flow aggregated under a drop event code."""
    return 1 if int(flow.get("event", 0)) < 0 else 0


class ThreatTrainer:
    """Logistic scorer fit in plain numpy (optax-lite: full-batch
    gradient descent with momentum), emitting a quantized linear
    ThreatModel whose integer forward pass spans the 0..255 score
    range."""

    def __init__(self, lr: float = 0.5, epochs: int = 300,
                 momentum: float = 0.9, l2: float = 1e-3):
        self.lr = lr
        self.epochs = epochs
        self.momentum = momentum
        self.l2 = l2
        self.last_report: Dict = {}

    def fit(self, flows: Sequence[Dict],
            labels: Optional[Sequence[int]] = None,
            now: Optional[int] = None,
            config: Optional[ThreatConfig] = None) -> ThreatModel:
        """Fit over aggregated flow records; returns the quantized
        model (generation carried from ``config``)."""
        flows = list(flows)
        if not flows:
            raise ValueError("no flows to train on")
        x = np.stack([features_from_flow(f, now) for f in flows]) \
            .astype(np.float64) / SCORE_MAX
        y = np.array([label_from_flow(f) for f in flows], np.float64) \
            if labels is None else np.array(labels, np.float64)
        # class-balanced weighting: anomalous flows are usually the
        # small-packet minority — letting high-volume allowed flows
        # dominate the loss would train the scorer to say "normal"
        pos = max(float((y > 0.5).sum()), 1.0)
        neg = max(float((y <= 0.5).sum()), 1.0)
        sample_w = np.where(y > 0.5, 0.5 / pos, 0.5 / neg)
        w = np.zeros(NUM_FEATURES)
        bias = 0.0
        vw = np.zeros_like(w)
        vb = 0.0
        for _ in range(self.epochs):
            z = x @ w + bias
            pred = 1.0 / (1.0 + np.exp(-np.clip(z, -30, 30)))
            err = (pred - y) * sample_w
            gw = x.T @ err + self.l2 * w
            gb = float(err.sum())
            vw = self.momentum * vw - self.lr * gw
            vb = self.momentum * vb - self.lr * gb
            w += vw
            bias += vb
        z = x @ w + bias
        pred = 1.0 / (1.0 + np.exp(-np.clip(z, -30, 30)))
        acc = float(((pred > 0.5) == (y > 0.5)).mean())
        # Quantize: the stage computes ((f_int @ w_q) >> 8) + b_q with
        # f_int = f * 255, so w_q = w * 256 / 255 * GAIN maps the
        # logit onto the integer lane; GAIN spreads z in [-4, 4] over
        # the 0..255 score range around midpoint 128.
        gain = 32.0
        w_q = w * 256.0 / SCORE_MAX * gain
        b_q = bias * gain + 128.0
        model = linear_model(w_q, bias=b_q,
                             config=config or ThreatConfig())
        self.last_report = {
            "flows": len(flows),
            "positives": int((y > 0.5).sum()),
            "train-accuracy": round(acc, 4),
            "weights-l2": round(float(np.sqrt((w ** 2).sum())), 4),
            "generation": model.config.generation,
        }
        return model
