"""Numpy twin of the fused threat-scoring stage — the bit-exact parity
reference tests/test_threat.py replays device batches against.

Mirrors ``stage.threat_stage`` operation for operation, INCLUDING its
batched-scatter semantics: window resets are same-value sets, counter
adds accumulate (np.add.at), dport span uses order-free min/max
scatters, and the token bucket is batch-granular (every same-batch row
of a bucket sees the same pre-batch token view; consumption lands as
one accumulated debit).  All arithmetic is int32/uint32 wrap — the
same dtypes the compiled program runs.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..compiler.hashtab import hash_mix
from .model import (CFG_BURST, CFG_DROP, CFG_ENFORCE, CFG_RATE_Q8,
                    CFG_RATELIMIT, CFG_REDIRECT, CFG_REDIRECT_PORT,
                    SCORE_MAX, WEIGHT_Q, ThreatModel)
from .stage import (ARM_DROP, ARM_NONE, ARM_RATELIMIT, ARM_REDIRECT,
                    BUCKET_SALT, COL_DPORT_MAX, COL_DPORT_MIN,
                    COL_TB_TS, COL_TOKENS, COL_WIN_NEW, COL_WIN_TS,
                    LOG_CLAMP, OUT_ARM_SHIFT, OUT_FIRED_BIT)


def log_bucket_np(x: np.ndarray) -> np.ndarray:
    """stage.log_bucket twin: float32 exponent of the clamped value —
    exact over the clamped range, so numpy/XLA agree bit-for-bit."""
    xc = np.clip(np.array(x, np.int64), 0, LOG_CLAMP)
    _m, e = np.frexp(xc.astype(np.float32))
    return np.minimum(np.where(xc > 0, e, 0), 16).astype(np.int32)


def flow_snapshot_index(snapshot) -> Dict[Tuple[int, int, int, int, int],
                                          Tuple[int, int, int]]:
    """FlowTable.snapshot() rows -> {(src, dst, dport, proto, event):
    (packets, bytes, last-seen)} for the oracle's probe lookups."""
    return {(f["src-identity"], f["dst-identity"], f["dport"],
             f["proto"], f["event"]):
            (f["packets"], f["bytes"], f["last-seen"]) for f in snapshot}


def _i32(x):
    return np.array(x, np.int64).astype(np.uint32).astype(np.int64)


def oracle_threat_step(state: np.ndarray, model: ThreatModel, verdict,
                       *, identity, dport, proto, tcp_flags, length,
                       is_fragment, established, saddr_w, daddr_w,
                       sport, flow_src, flow_dst, now: int,
                       window_s: int,
                       flow_index: Optional[Dict] = None,
                       stripe: int = 4, exempt=None):
    """One oracle pass over [B] int arrays.  ``state`` is the host
    mirror of the ThreatState buffer ([T+1, STATE_COLS] int32,
    mutated in place); ``flow_index`` is flow_snapshot_index() over
    the PRE-step device flow table (None = flows disabled).

    Returns (verdict' [B], threat_out [B], scores [B], band [B],
    thr_drop [B], thr_redir [B], rl_drop [B])."""
    from ..datapath.verdict import VERDICT_DROP_THREAT

    t = state.shape[0] - 1
    identity = np.array(identity, np.int64)
    dport = np.array(dport, np.int64)
    proto = np.array(proto, np.int64)
    sport = np.array(sport, np.int64)
    length = np.array(length, np.int64)
    verdict = np.array(verdict, np.int32).copy()
    established = np.array(established, bool)
    b = identity.shape[0]
    cfg = model.config.encode()
    now = int(now)

    bucket = (hash_mix(np.uint32(identity & 0xFFFFFFFF),
                       np.full(b, BUCKET_SALT, np.uint32))
              & np.uint32(t - 1)).astype(np.int64)

    # window: striped update slice (stage semantics: one rotating
    # contiguous 1/stripe block contributes per batch), reset expired
    # buckets (same-value sets), accumulate
    st_n = max(1, min(int(stripe), b))
    width = b // st_n if b % st_n == 0 else b
    if width == b:
        sl = slice(0, b)
    else:
        phase = now % st_n
        sl = slice(phase * width, phase * width + width)
    bucket_s = bucket[sl]
    win_ts = state[bucket_s, COL_WIN_TS].astype(np.int64)
    expired = (now - win_ts) >= window_s
    eb = bucket_s[expired]
    state[eb, COL_WIN_TS] = now
    state[eb, COL_WIN_NEW] = 0
    state[eb, COL_DPORT_MIN] = 65535
    state[eb, COL_DPORT_MAX] = 0
    new_flow_s = ~established[sl]
    np.add.at(state[:, COL_WIN_NEW], bucket_s[new_flow_s], 1)
    np.minimum.at(state[:, COL_DPORT_MIN], bucket_s,
                  dport[sl].astype(np.int32))
    np.maximum.at(state[:, COL_DPORT_MAX], bucket_s,
                  dport[sl].astype(np.int32))
    post = state[bucket].astype(np.int64)
    win_new = post[:, COL_WIN_NEW]
    spread = np.maximum(post[:, COL_DPORT_MAX] -
                        post[:, COL_DPORT_MIN], 0)

    # flow probe (allowed-traffic key: event TRACE_TO_LXC == 0)
    found = np.zeros(b, bool)
    fl_pkts = np.zeros(b, np.int64)
    fl_bytes = np.zeros(b, np.int64)
    fl_last = np.zeros(b, np.int64)
    if flow_index is not None:
        fsrc = np.array(flow_src, np.int64)
        fdst = np.array(flow_dst, np.int64)
        for i in range(b):
            key = (int(fsrc[i]), int(fdst[i]), int(dport[i]) & 0xFFFF,
                   int(proto[i]) & 0xFF, 0)
            got = flow_index.get(key)
            if got is not None:
                found[i] = True
                # device reads the uint32 counters as int32 bits
                fl_pkts[i] = np.int32(np.uint32(got[0]))
                fl_bytes[i] = np.int32(np.uint32(got[1]))
                fl_last[i] = got[2]

    syn = (np.array(tcp_flags, np.int64) & 0x02) != 0
    is_tcp = proto == 6
    full = np.full(b, SCORE_MAX, np.int32)
    zero = np.zeros(b, np.int32)
    recency = np.where(found, np.clip(now - fl_last, 0, SCORE_MAX),
                       SCORE_MAX)
    feats = np.stack([
        15 * log_bucket_np(fl_pkts),
        15 * log_bucket_np(fl_bytes),
        recency.astype(np.int32),
        np.where(syn & is_tcp & ~established, full, zero),
        np.where(established, full, zero),
        15 * log_bucket_np(win_new),
        15 * log_bucket_np(spread),
        np.minimum(dport >> 8, SCORE_MAX).astype(np.int32),
        np.where(proto == 17, full, zero),
        15 * log_bucket_np(length),
        np.where(identity == 2, full, zero),
        np.where(np.array(is_fragment, np.int64) != 0, full, zero),
    ], axis=1)
    score = model.score(feats)

    enforce = bool(cfg[CFG_ENFORCE])
    eligible = verdict >= 0
    if exempt is not None:
        eligible = eligible & ~np.array(exempt, bool)
    drop_arm = eligible & (cfg[CFG_DROP] > 0) & (score >= cfg[CFG_DROP])
    redir_arm = eligible & ~drop_arm & (cfg[CFG_REDIRECT] > 0) & \
        (score >= cfg[CFG_REDIRECT])
    rl_arm = eligible & ~drop_arm & ~redir_arm & \
        (cfg[CFG_RATELIMIT] > 0) & (score >= cfg[CFG_RATELIMIT])

    want = rl_arm & enforce
    # token cols are untouched by the window scatters: the post-window
    # gather IS the pre-batch token view (stage.py reads the same)
    dt = np.clip(now - post[:, COL_TB_TS], 0, 3600)
    refilled = np.minimum(int(cfg[CFG_BURST]) << WEIGHT_Q,
                          post[:, COL_TOKENS]
                          + int(cfg[CFG_RATE_Q8]) * dt)
    has_token = refilled >= (1 << WEIGHT_Q)
    with np.errstate(over="ignore"):
        word = np.uint32((sport & 0xFFFF) << 16) | np.uint32(dport
                                                             & 0xFFFF)
        prand = (hash_mix(hash_mix(np.uint32(_i32(saddr_w)),
                                   np.uint32(_i32(daddr_w))),
                          hash_mix(word, np.full(b, np.uint32(
                              np.int64(now) & 0xFFFFFFFF))))
                 & np.uint32(0xFF)).astype(np.int64)
    denom = max(256 - int(cfg[CFG_RATELIMIT]), 1)
    p = np.clip((score.astype(np.int64) - int(cfg[CFG_RATELIMIT]) + 1)
                * 255 // denom, 0, 255)
    rl_drop = want & ~has_token & (prand < p)
    wb = bucket[want]
    state[wb, COL_TOKENS] = refilled[want].astype(np.int32)
    state[wb, COL_TB_TS] = now
    consumed = want & has_token
    np.add.at(state[:, COL_TOKENS], bucket[consumed],
              -(1 << WEIGHT_Q))

    thr_drop = (drop_arm & enforce) | rl_drop
    thr_redir = redir_arm & enforce & (verdict == 0)
    verdict = np.where(
        thr_drop, np.int32(VERDICT_DROP_THREAT),
        np.where(thr_redir, np.int32(cfg[CFG_REDIRECT_PORT]), verdict))

    band = np.where(drop_arm, ARM_DROP,
                    np.where(redir_arm, ARM_REDIRECT,
                             np.where(rl_arm, ARM_RATELIMIT, ARM_NONE))
                    ).astype(np.int32)
    fired = thr_drop | thr_redir
    threat_out = (score | (band << OUT_ARM_SHIFT) |
                  np.where(fired, OUT_FIRED_BIT, 0)).astype(np.int32)
    return (verdict.astype(np.int32), threat_out, score, band,
            thr_drop, thr_redir, rl_drop)
