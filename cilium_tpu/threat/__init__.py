"""Inline per-packet ML threat scoring (Taurus-style anomaly plane).

Per PAPERS.md "Taurus: A Data Plane Architecture for Per-Packet ML",
the dataplane itself scores every packet for anomaly/DDoS behavior
instead of shipping everything to a host-side detector.  This package
is that verdict plane:

- ``model.py``   — the small quantized scorer (int32 fixed-point
  2-layer net) + the policy-controlled threshold/mode config, packed
  into device table leaves that hot-swap through the delta-apply path.
- ``stage.py``   — the fused jnp scoring stage both jitted family
  pipelines run behind the static ``with_threat`` gate, plus the
  shard-local token-bucket/window state buffer.
- ``oracle.py``  — the numpy twin of the stage (bit-exact parity
  reference; tests/test_threat.py holds the line).
- ``trainer.py`` — host-side fitting from federated Hubble flow drains
  (plain numpy gradient descent, no new deps).
"""

from .model import (CFG_BURST, CFG_DROP, CFG_ENFORCE, CFG_GENERATION,
                    CFG_RATE_Q8, CFG_RATELIMIT, CFG_REDIRECT,
                    CFG_REDIRECT_PORT, FEATURES, NUM_FEATURES,
                    SCORE_MAX, ThreatConfig, ThreatModel, default_model)
from .stage import (ThreatState, make_threat_state, threat_stage,
                    unpack_threat_out)
from .trainer import ThreatTrainer

__all__ = [
    "CFG_BURST", "CFG_DROP", "CFG_ENFORCE", "CFG_GENERATION",
    "CFG_RATE_Q8", "CFG_RATELIMIT", "CFG_REDIRECT",
    "CFG_REDIRECT_PORT", "FEATURES", "NUM_FEATURES", "SCORE_MAX",
    "ThreatConfig", "ThreatModel", "ThreatState", "ThreatTrainer",
    "default_model", "make_threat_state", "threat_stage",
    "unpack_threat_out",
]
