"""The threat scorer: a small quantized model + its verdict config.

Everything here is integer fixed-point by design:

- the fused pipeline stage (``stage.py``) and the numpy oracle
  (``oracle.py``) must agree BIT-exactly across backends, which rules
  out float accumulation order games — all scoring math is int32 with
  Q8.8 weights and an explicit ``>> 8`` requantize between layers;
- per "TaNG: TSS-assisted Neural Networks on GPUs" the win of a small
  dense scorer over the gather-heavy classify path is matrix-unit
  shaped work — a [B, F] @ [F, H] int32 contraction is exactly the
  kind of op the MXU (or any vector unit) eats, unlike hash probes.

Score range is 0..SCORE_MAX (255).  Features are 0..255 int32 lanes
(``stage.py`` FEATURES order); weights are int32 clamped to +/-32767
(Q8.8: value 256 == 1.0).  The forward pass:

    h = clip(((f @ w1) >> 8) + b1, 0, 255)      # [B, H]
    s = clip(((h @ w2) >> 8) + b2, 0, 255)      # [B]

A linear model is the H=1 special case with w2=[256] (identity pass-
through), which is what the trainer emits by default.

The model rides the packed dispatch as its own ``threat-model`` buffer
group (parallel/specs.PACKED_GROUP_SPECS, the l7-dfa precedent): five
int32 leaves — w1, b1, w2, b2 and the [8] config vector — so a weight
push or a threshold/mode flip is a region write into the live group
buffer (engine ``apply_threat_weights`` / ``set_threat_config``),
never a repack and never a re-jit.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Dict, Optional, Tuple

import numpy as np

SCORE_MAX = 255
WEIGHT_Q = 8                  # Q8.8 fixed point: 256 == 1.0
WEIGHT_MAX = 32767            # weights clamp to int16 range ("quantized")

# Feature lanes of the fused stage, in order.  Each is an int32 in
# [0, 255]; log-bucketed lanes use 15 * floor-log2-ish buckets (see
# stage.log_bucket) so their exactness survives any backend.
FEATURES = (
    "flow-packets-log",    # Hubble flow-table probe: per-flow packets
    "flow-bytes-log",      # per-flow bytes
    "flow-recency",        # seconds since the flow's last-seen (255 =
    #                        no flow entry / flows disabled)
    "syn-no-established",  # TCP SYN on a not-established flow
    "established",         # CT fast-path hit
    "newflow-rate-log",    # per-identity new flows in the claim window
    "port-spread-log",     # per-identity dport span in the window
    #                        (port-entropy-style scan signal)
    "dport-high",          # dport >> 8 (ephemeral/port-walk signal)
    "is-udp",
    "pkt-len-log",
    "is-world",            # peer identity resolved to WORLD
    "is-fragment",
)
NUM_FEATURES = len(FEATURES)

# tm_cfg vector layout ([8] int32): the policy-controlled verdict
# knobs, traced as VALUES (not statics) so a shadow<->enforce flip or
# a threshold change is a leaf write, never a re-jit.
CFG_ENFORCE = 0        # 0 = shadow (score-only), 1 = enforce
CFG_DROP = 1           # score >= this -> drop arm (0 disables)
CFG_REDIRECT = 2       # score >= this -> redirect arm (0 disables)
CFG_RATELIMIT = 3      # score >= this -> rate-limit arm (0 disables)
CFG_REDIRECT_PORT = 4  # the proxy port the redirect arm answers
CFG_RATE_Q8 = 5        # token-bucket refill (tokens/sec, Q8.8)
CFG_BURST = 6          # token-bucket capacity (whole tokens)
CFG_GENERATION = 7     # model generation (bumped per weight push)

CFG_LEN = 8


@dataclass(frozen=True)
class ThreatConfig:
    """Policy-controlled thresholds + mode.  Default: shadow (score-
    only) with every enforcement arm disabled — a pushed model can
    never deny traffic the policy allows until an operator opts in."""

    mode: str = "shadow"          # "shadow" | "enforce"
    drop_score: int = 0
    redirect_score: int = 0
    ratelimit_score: int = 0
    redirect_port: int = 0
    rate_per_s: float = 256.0
    burst: int = 1024
    generation: int = 1

    def encode(self) -> np.ndarray:
        cfg = np.zeros(CFG_LEN, np.int32)
        cfg[CFG_ENFORCE] = 1 if self.mode == "enforce" else 0
        cfg[CFG_DROP] = int(self.drop_score)
        cfg[CFG_REDIRECT] = int(self.redirect_score)
        cfg[CFG_RATELIMIT] = int(self.ratelimit_score)
        cfg[CFG_REDIRECT_PORT] = int(self.redirect_port)
        cfg[CFG_RATE_Q8] = min(1 << 16,
                               max(0, int(round(self.rate_per_s * 256))))
        cfg[CFG_BURST] = min(1 << 20, max(1, int(self.burst)))
        cfg[CFG_GENERATION] = int(self.generation)
        return cfg

    @classmethod
    def decode(cls, cfg) -> "ThreatConfig":
        c = [int(x) for x in cfg]
        return cls(mode="enforce" if c[CFG_ENFORCE] else "shadow",
                   drop_score=c[CFG_DROP], redirect_score=c[CFG_REDIRECT],
                   ratelimit_score=c[CFG_RATELIMIT],
                   redirect_port=c[CFG_REDIRECT_PORT],
                   rate_per_s=c[CFG_RATE_Q8] / 256.0,
                   burst=c[CFG_BURST], generation=c[CFG_GENERATION])

    def describe(self) -> Dict:
        return {"mode": self.mode, "drop-score": self.drop_score,
                "redirect-score": self.redirect_score,
                "ratelimit-score": self.ratelimit_score,
                "redirect-port": self.redirect_port,
                "rate-per-s": self.rate_per_s, "burst": self.burst,
                "generation": self.generation}


def _quant(w, lo=-WEIGHT_MAX, hi=WEIGHT_MAX) -> np.ndarray:
    return np.clip(np.rint(np.array(w, np.float64)), lo, hi) \
        .astype(np.int32)


@dataclass
class ThreatModel:
    """One quantized scorer generation + its verdict config.

    ``w1`` [F, H], ``b1`` [H], ``w2`` [H], ``b2`` scalar — all int32
    Q8.8.  ``tables()`` emits the five device leaves; a same-geometry
    replacement hot-swaps through the engine's leaf write-through."""

    w1: np.ndarray
    b1: np.ndarray
    w2: np.ndarray
    b2: int = 0
    config: ThreatConfig = field(default_factory=ThreatConfig)

    def __post_init__(self):
        self.w1 = _quant(self.w1).reshape(NUM_FEATURES, -1)
        self.b1 = _quant(self.b1, -(1 << 20), 1 << 20).reshape(-1)
        self.w2 = _quant(self.w2).reshape(-1)
        self.b2 = int(np.clip(self.b2, -(1 << 20), 1 << 20))
        if self.w1.shape[1] != self.b1.shape[0] or \
                self.b1.shape[0] != self.w2.shape[0]:
            raise ValueError("inconsistent threat-model geometry: "
                             f"w1 {self.w1.shape} b1 {self.b1.shape} "
                             f"w2 {self.w2.shape}")

    @property
    def hidden(self) -> int:
        return int(self.w1.shape[1])

    @property
    def geometry(self) -> Tuple[int, int]:
        return (NUM_FEATURES, self.hidden)

    def tables(self) -> Dict[str, np.ndarray]:
        """The five int32 device leaves of the ``threat-model`` group."""
        return {"tm_w1": self.w1, "tm_b1": self.b1, "tm_w2": self.w2,
                "tm_b2": np.array([self.b2], np.int32),
                "tm_cfg": self.config.encode()}

    def score(self, features: np.ndarray) -> np.ndarray:
        """The exact integer forward pass over [B, F] feature rows —
        the host twin of the fused stage's scorer (oracle.py builds
        its parity expectation from this)."""
        f = np.array(features, np.int32).reshape(-1, NUM_FEATURES)
        z1 = ((f.astype(np.int64) @ self.w1.astype(np.int64)) >> WEIGHT_Q
              ).astype(np.int32) + self.b1
        h = np.clip(z1, 0, SCORE_MAX)
        z2 = ((h.astype(np.int64) @ self.w2.astype(np.int64)) >> WEIGHT_Q
              ).astype(np.int32) + np.int32(self.b2)
        return np.clip(z2, 0, SCORE_MAX).astype(np.int32)

    def with_config(self, config: ThreatConfig) -> "ThreatModel":
        return replace(self, config=config)

    def nbytes(self) -> int:
        return int(self.w1.nbytes + self.b1.nbytes + self.w2.nbytes
                   + 4 + CFG_LEN * 4)

    def describe(self) -> Dict:
        return {"features": NUM_FEATURES, "hidden": self.hidden,
                "resident-bytes": self.nbytes(),
                "config": self.config.describe()}


def linear_model(weights, bias: float = 0.0,
                 config: Optional[ThreatConfig] = None) -> ThreatModel:
    """A linear scorer as the H=1 special case: layer 2 is the Q8.8
    identity (w2 = [256], b2 = 0), so score == layer-1 output."""
    w = np.array(weights, np.float64).reshape(NUM_FEATURES, 1)
    return ThreatModel(w1=w, b1=np.array([bias]), w2=np.array([256]),
                       b2=0, config=config or ThreatConfig())


def default_model(config: Optional[ThreatConfig] = None) -> ThreatModel:
    """The hand-tuned bootstrap scorer shipped before any training:
    weights anomaly-shaped signals (SYN floods, new-flow storms, port
    scans, WORLD-sourced traffic) so shadow mode is useful on day one.
    A trained model replaces it through the same hot-swap path."""
    w = np.zeros(NUM_FEATURES, np.float64)
    by = {name: i for i, name in enumerate(FEATURES)}
    w[by["syn-no-established"]] = 140    # Q8.8: ~0.55 per 255-lane
    w[by["newflow-rate-log"]] = 120
    w[by["port-spread-log"]] = 110
    w[by["is-world"]] = 40
    w[by["flow-recency"]] = 20
    w[by["established"]] = -120          # long-lived flows score low
    w[by["flow-packets-log"]] = -30
    return linear_model(w, bias=0.0, config=config)
