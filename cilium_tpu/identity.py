"""Security identities: numeric IDs, reserved ranges, the identity cache.

Semantics follow the reference's ``pkg/identity`` (numericidentity.go,
identity.go, allocator.go): a security identity is a ``uint32`` derived from
a set of security-relevant labels; IDs < 256 are reserved, dynamic IDs live
in [256, 65535] with cluster bits shifted above bit 16.

Distributed allocation (the kvstore master/slave-key protocol) lives in
``cilium_tpu.kvstore.allocator``; this module is the pure model plus a
local in-process allocator used by tests and single-node operation.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from . import labels as lbl
from .labels import Label, LabelArray, Labels

# Reference: pkg/identity/numericidentity.go:27-39
MINIMAL_NUMERIC_IDENTITY = 256
USER_RESERVED_NUMERIC_IDENTITY = 128
INVALID_IDENTITY = 0

# Reference: pkg/identity/allocator.go:79-80 — dynamic ID space.
MAX_NUMERIC_IDENTITY = 65535

# Cluster ID is encoded above bit 16 (reference: identity/allocator.go:93).
CLUSTER_ID_SHIFT = 16

# Node-local ephemeral identity scope (reference: identity.
# IdentityScopeLocal — CIDR identities carry bit 24).  Identities
# allocated here never leave the node: the kvstore-outage fallback
# allocates endpoint identities from this range while the cluster
# allocator is unreachable, and they are promoted to cluster-scope IDs
# on reconnect (kvstore/identity_allocator.FallbackIdentityAllocator).
LOCAL_SCOPE_IDENTITY_BASE = 1 << 24


def is_local_scope_identity(numeric_id: int) -> bool:
    """True for node-local ephemeral identities (never published to
    the cluster; promoted to cluster scope on kvstore reconnect)."""
    return numeric_id >= LOCAL_SCOPE_IDENTITY_BASE

# Reserved numeric identities (reference: numericidentity.go:42-104).
IDENTITY_UNKNOWN = 0
RESERVED_HOST = 1
RESERVED_WORLD = 2
RESERVED_UNMANAGED = 3
RESERVED_HEALTH = 4
RESERVED_INIT = 5

# Well-known cluster components (reference: numericidentity.go:63-78).
RESERVED_ETCD_OPERATOR = 100
RESERVED_CILIUM_KVSTORE = 101
RESERVED_KUBE_DNS = 102
RESERVED_EKS_KUBE_DNS = 103
RESERVED_CORE_DNS = 104

RESERVED_IDENTITY_NAMES: Dict[int, str] = {
    IDENTITY_UNKNOWN: lbl.ID_NAME_UNKNOWN,
    RESERVED_HOST: lbl.ID_NAME_HOST,
    RESERVED_WORLD: lbl.ID_NAME_WORLD,
    RESERVED_UNMANAGED: lbl.ID_NAME_UNMANAGED,
    RESERVED_HEALTH: lbl.ID_NAME_HEALTH,
    RESERVED_INIT: lbl.ID_NAME_INIT,
}

RESERVED_IDENTITIES: Dict[str, int] = {
    v: k for k, v in RESERVED_IDENTITY_NAMES.items() if k != IDENTITY_UNKNOWN
}


def get_reserved_id(name: str) -> int:
    """Name -> reserved numeric identity (0 == unknown)."""
    return RESERVED_IDENTITIES.get(name, IDENTITY_UNKNOWN)


def is_reserved_identity(numeric_id: int) -> bool:
    """IDs below the unmanaged boundary are reserved infrastructure IDs
    (reference: bpf/lib/policy.h identity_is_reserved uses < UNMANAGED_ID;
    the full reserved block is < MinimalNumericIdentity)."""
    return 0 < numeric_id < MINIMAL_NUMERIC_IDENTITY


@dataclass(frozen=True)
class Identity:
    """A security identity: numeric ID + the labels it stands for.

    Reference: pkg/identity/identity.go:27.
    """

    id: int
    labels: Labels

    @property
    def label_array(self) -> LabelArray:
        return self.labels.to_array()

    @property
    def labels_sha256(self) -> str:
        return self.labels.sha256_sum()

    def __hash__(self):
        return hash(self.id)

    def __eq__(self, other):
        return isinstance(other, Identity) and self.id == other.id


def _reserved_identity_cache() -> Dict[int, Identity]:
    cache: Dict[int, Identity] = {}
    for num, name in RESERVED_IDENTITY_NAMES.items():
        if num == IDENTITY_UNKNOWN:
            continue
        labels = Labels.from_labels([lbl.reserved_label(name)])
        cache[num] = Identity(id=num, labels=labels)
    return cache


RESERVED_IDENTITY_CACHE = _reserved_identity_cache()


def look_up_reserved_identity(numeric_id: int) -> Optional[Identity]:
    return RESERVED_IDENTITY_CACHE.get(numeric_id)


def look_up_reserved_identity_by_labels(labels: Labels) -> Optional[Identity]:
    """Single reserved label -> reserved identity (reference:
    identity/identity.go LookupReservedIdentity path)."""
    if len(labels) != 1:
        return None
    (only,) = labels.values()
    if only.source != lbl.SOURCE_RESERVED:
        return None
    rid = get_reserved_id(only.key)
    if rid == IDENTITY_UNKNOWN:
        return None
    return RESERVED_IDENTITY_CACHE[rid]


class IdentityCache(Dict[int, LabelArray]):
    """Snapshot map numeric-ID -> LabelArray used during policy resolution.

    Reference: pkg/identity/cache.go (GetIdentityCache) — policy
    recomputation iterates this cache to materialize per-identity verdicts.
    """

    @classmethod
    def snapshot(cls, allocator) -> "IdentityCache":
        """Works with any allocator exposing ``snapshot_identities()``."""
        cache = cls()
        for num, ident in RESERVED_IDENTITY_CACHE.items():
            cache[num] = ident.label_array
        for ident in allocator.snapshot_identities():
            cache[ident.id] = ident.label_array
        return cache


class LocalIdentityAllocator:
    """In-process identity allocator with refcounting.

    Mirrors the allocation contract of the reference's kvstore-backed
    allocator (pkg/identity/allocator.go:124 AllocateIdentity /
    :161 Release) without the distribution: same labels -> same ID,
    refcounted release, IDs from [256, 65535], cluster bits shifted in.
    The kvstore-backed distributed allocator (cilium_tpu.kvstore.allocator)
    plugs in behind the same interface.
    """

    def __init__(self, cluster_id: int = 0,
                 on_change: Optional[Callable[[str, Identity], None]] = None):
        self.cluster_id = cluster_id
        self._lock = threading.RLock()
        self._by_sha: Dict[str, Identity] = {}
        self._by_id: Dict[int, Identity] = {}
        self._refcount: Dict[int, int] = {}
        self._next = MINIMAL_NUMERIC_IDENTITY
        self._on_change = on_change  # ("add"|"delete", identity)

    def _pick_free_id(self) -> int:
        """Returns a full numeric ID (cluster bits included) not in use."""
        start = self._next
        while True:
            cand = self._next
            self._next += 1
            if self._next > MAX_NUMERIC_IDENTITY:
                self._next = MINIMAL_NUMERIC_IDENTITY
            numeric = (self.cluster_id << CLUSTER_ID_SHIFT) | cand
            if numeric not in self._by_id:
                return numeric
            if self._next == start:
                raise RuntimeError("identity space exhausted")

    def allocate(self, labels: Labels) -> Tuple[Identity, bool]:
        """Return (identity, is_new). Reserved labels short-circuit."""
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved, False
        sha = labels.sha256_sum()
        with self._lock:
            existing = self._by_sha.get(sha)
            if existing is not None:
                self._refcount[existing.id] += 1
                return existing, False
            numeric = self._pick_free_id()
            ident = Identity(id=numeric, labels=Labels(labels))
            self._by_sha[sha] = ident
            self._by_id[numeric] = ident
            self._refcount[numeric] = 1
        if self._on_change:
            self._on_change("add", ident)
        return ident, True

    def release(self, ident: Identity) -> bool:
        """Decrement refcount; free on zero. Returns True if freed."""
        if is_reserved_identity(ident.id):
            return False
        freed = False
        with self._lock:
            if ident.id not in self._refcount:
                return False
            self._refcount[ident.id] -= 1
            if self._refcount[ident.id] <= 0:
                del self._refcount[ident.id]
                del self._by_id[ident.id]
                self._by_sha.pop(ident.labels.sha256_sum(), None)
                freed = True
        if freed and self._on_change:
            self._on_change("delete", ident)
        return freed

    def snapshot_identities(self) -> List[Identity]:
        """Point-in-time list of live dynamic identities (the allocator
        interface consumed by IdentityCache.snapshot)."""
        with self._lock:
            return list(self._by_id.values())

    def lookup_by_id(self, numeric_id: int) -> Optional[Identity]:
        reserved = look_up_reserved_identity(numeric_id)
        if reserved is not None:
            return reserved
        with self._lock:
            return self._by_id.get(numeric_id)

    def lookup_by_labels(self, labels: Labels) -> Optional[Identity]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved
        with self._lock:
            return self._by_sha.get(labels.sha256_sum())

    def __len__(self):
        with self._lock:
            return len(self._by_id)
