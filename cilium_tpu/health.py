"""Active path probing across nodes.

Reference: cilium-health + pkg/health — a prober walks the known node
set, issues ICMP + HTTP probes per node (pkg/health/server/prober.go:
139,229), and keeps per-path status with last-seen timestamps; results
surface in ``cilium-health status`` and the agent status. Here the
probe transport is pluggable (an in-process reachability function by
default; a real deployment plugs sockets), the scheduling/state model
is the same.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from .utils.controller import ControllerManager, ControllerParams

PROBE_ICMP = "icmp"
PROBE_HTTP = "http"


@dataclass
class PathStatus:
    """One node's probe results (healthModels.PathStatus analog)."""

    node: str
    ip: str
    icmp_ok: Optional[bool] = None
    http_ok: Optional[bool] = None
    last_probed: float = 0.0
    latency_s: Dict[str, float] = field(default_factory=dict)
    failures: int = 0

    @property
    def healthy(self) -> bool:
        return bool(self.icmp_ok) and self.http_ok is not False


def make_icmp6_probe(resolve_datapath, src_ip6: str):
    """ICMPv6 probe riding the NDP/echo responder stage (pipeline
    stage 1.5; bpf/lib/icmp6.h): the echo request classifies through
    the datapath of the node that OWNS the probed address — a
    responder only answers for its own router_ip6, so the resolver
    models the wire hop cilium-health's real echo takes.

    ``resolve_datapath``: ``ip -> Datapath`` callable, or a plain dict
    (unknown address = unreachable).  The reachability signal is
    end-to-end: the target's step must answer ICMP6_ECHO_REPLY, and
    the TARGET's own reply synthesis
    (Datapath.icmp6_echo_reply_bytes, built from the router address
    the target has programmed — not from this prober's arguments)
    must parse back addressed from the probed ip to the prober.
    Non-ICMP kinds and v4 addresses answer (True, 0.0) so a caller
    can layer this over another probe_fn."""
    import numpy as np

    from .compiler.lpm import ipv6_to_words
    from .datapath.engine import make_full_batch6
    from .datapath.events import ICMP6_ECHO_REPLY
    from .datapath.icmp6 import parse_icmp6

    if hasattr(resolve_datapath, "get"):
        mapping = resolve_datapath
        resolve_datapath = mapping.get

    def probe(kind: str, ip: str):
        if kind != PROBE_ICMP or ":" not in ip:
            return True, 0.0
        dp = resolve_datapath(ip)
        if dp is None:
            return False, 0.0
        t0 = time.time()
        batch = make_full_batch6(
            endpoint=[0], saddr=[src_ip6], daddr=[ip],
            sport=[0], dport=[0], direction=[1], proto=[58],
            icmp_type=[128])
        _v, event, _i, _n = dp.process6(batch)
        if int(np.asarray(event)[0]) != ICMP6_ECHO_REPLY:
            return False, time.time() - t0
        # consume the TARGET's synthesized reply like the wire
        # delivered it: its source must be the address we probed
        # (derived from the target's router state, not our inputs)
        try:
            reply = parse_icmp6(dp.icmp6_echo_reply_bytes(src_ip6))
        except (RuntimeError, AssertionError):
            return False, time.time() - t0
        ok = reply["type"] == 129 and reply["checksum_ok"] and \
            reply["src_words"] == list(ipv6_to_words(ip)) and \
            reply["dst_words"] == list(ipv6_to_words(src_ip6))
        return ok, time.time() - t0

    return probe


class HealthProber:
    """Periodic prober over the node set.

    ``nodes_fn`` returns [(node_name, ip)]; ``probe_fn(kind, ip)``
    returns (ok, latency_seconds).
    """

    def __init__(self, nodes_fn: Callable[[], List],
                 probe_fn: Optional[Callable[[str, str], tuple]] = None,
                 interval: float = 10.0,
                 controllers: Optional[ControllerManager] = None):
        self.nodes_fn = nodes_fn
        self.probe_fn = probe_fn or (lambda kind, ip: (True, 0.0))
        self._lock = threading.Lock()
        self._status: Dict[str, PathStatus] = {}
        self._controllers = controllers or ControllerManager()
        self._owns_controllers = controllers is None
        self._controllers.update_controller(
            "health-prober", ControllerParams(do_func=self.probe_once,
                                              run_interval=interval))

    def probe_once(self) -> None:
        """One sweep over all known nodes (prober.go runProbe)."""
        now = time.time()
        seen = set()
        for entry in self.nodes_fn():
            name, ip = entry if isinstance(entry, tuple) else \
                (entry.full_name, entry.get_node_ip())
            if not ip:
                continue
            seen.add(name)
            st = self._get(name, ip)
            for kind in (PROBE_ICMP, PROBE_HTTP):
                try:
                    ok, lat = self.probe_fn(kind, ip)
                except Exception:
                    ok, lat = False, 0.0
                if kind == PROBE_ICMP:
                    st.icmp_ok = ok
                else:
                    st.http_ok = ok
                st.latency_s[kind] = lat
                if not ok:
                    st.failures += 1
            st.last_probed = now
        with self._lock:
            for name in list(self._status):
                if name not in seen:
                    del self._status[name]  # node left the cluster

    def _get(self, name: str, ip: str) -> PathStatus:
        with self._lock:
            st = self._status.get(name)
            if st is None or st.ip != ip:
                st = PathStatus(node=name, ip=ip)
                self._status[name] = st
            return st

    def status(self) -> Dict[str, Dict]:
        """healthModels-shaped dump for REST/CLI."""
        with self._lock:
            return {
                name: {
                    "ip": st.ip,
                    "icmp": st.icmp_ok,
                    "http": st.http_ok,
                    "healthy": st.healthy,
                    "failures": st.failures,
                    "latency-seconds": dict(st.latency_s),
                    "last-probed": st.last_probed,
                } for name, st in sorted(self._status.items())}

    def unhealthy_nodes(self) -> List[str]:
        with self._lock:
            return [n for n, st in self._status.items() if not st.healthy]

    def shutdown(self) -> None:
        if self._owns_controllers:
            self._controllers.remove_all()
        else:
            self._controllers.remove_controller("health-prober")


# ---------------------------------------------------------------------------
# Real-socket transport (cilium-health's probe endpoints)
# ---------------------------------------------------------------------------
#
# The reference runs cilium-health as a per-node responder; the prober
# issues ICMP echo + an HTTP GET against it (prober.go:139,229).  The
# TCP analogs: the "icmp" probe is a bare connect (reachability), the
# "http" probe is a ping/pong round trip through the responder.

class HealthResponder:
    """Per-node probe endpoint (cilium-health listener analog)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        import socketserver

        class _Handler(socketserver.BaseRequestHandler):
            def handle(self):
                # read to the newline delimiter: TCP has no message
                # boundaries, a segmented "ping\n" must still pong
                try:
                    buf = b""
                    while b"\n" not in buf and len(buf) < 64:
                        chunk = self.request.recv(64)
                        if not chunk:
                            return
                        buf += chunk
                    if buf.startswith(b"ping"):
                        self.request.sendall(b"pong\n")
                except OSError:
                    pass

        class _TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = _TCP((host, port), _Handler)
        self.host, self.port = self._tcp.server_address
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True,
                                        name="health-responder")

    def start(self) -> "HealthResponder":
        self._thread.start()
        return self

    def shutdown(self) -> None:
        self._tcp.shutdown()
        self._tcp.server_close()


def make_tcp_probe(port_of: Callable[[str], int],
                   timeout: float = 2.0):
    """A probe_fn over real sockets.  ``port_of(ip)`` maps a node IP
    to its health responder port (the reference derives it from the
    health endpoint's address)."""
    import socket as _socket

    def probe(kind: str, ip: str):
        port = port_of(ip)
        t0 = time.time()
        try:
            with _socket.create_connection((ip, port),
                                           timeout=timeout) as s:
                if kind == PROBE_HTTP:
                    s.settimeout(timeout)
                    s.sendall(b"ping\n")
                    buf = b""
                    while b"\n" not in buf and len(buf) < 16:
                        chunk = s.recv(16)
                        if not chunk:
                            break
                        buf += chunk
                    if not buf.startswith(b"pong"):
                        return False, time.time() - t0
                return True, time.time() - t0
        except OSError:
            return False, time.time() - t0

    return probe
