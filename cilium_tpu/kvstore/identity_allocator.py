"""Kvstore-backed (distributed) security-identity allocator.

Binds the generic master/slave-key allocator to the identity model:
same labels -> same numeric ID on every node of the cluster, refcounted
via per-node lease-protected slave keys, reclaimed by GC.

Reference: pkg/identity/allocator.go:73 (InitIdentityAllocator),
:124 (AllocateIdentity), :161 (Release); kvstore path
``cilium/state/identities/v1`` (allocator.go:57); cluster-ID bits shifted
above bit 16 (allocator.go:93).
"""

from __future__ import annotations

import base64
from typing import Callable, List, Optional, Tuple

from ..identity import (CLUSTER_ID_SHIFT, MAX_NUMERIC_IDENTITY,
                        MINIMAL_NUMERIC_IDENTITY, Identity,
                        is_reserved_identity, look_up_reserved_identity,
                        look_up_reserved_identity_by_labels)
from ..labels import Labels, parse_label
from .allocator import Allocator
from .backend import BackendOperations

IDENTITY_PREFIX = "cilium/state/identities/v1"


def encode_labels(labels: Labels) -> str:
    """Labels -> allocator key. Base64url keeps '/' (CIDR labels) out of
    the kvstore path structure."""
    return base64.urlsafe_b64encode(labels.sorted_list()).decode()


def decode_labels(key: str) -> Labels:
    raw = base64.urlsafe_b64decode(key.encode()).decode()
    return Labels.from_labels(
        parse_label(part) for part in raw.split(";") if part)


class DistributedIdentityAllocator:
    """Drop-in for LocalIdentityAllocator backed by the shared kvstore."""

    def __init__(self, backend: BackendOperations, node: str,
                 cluster_id: int = 0,
                 on_change: Optional[Callable[[str, Identity], None]] = None,
                 prefix: str = IDENTITY_PREFIX,
                 seed: Optional[int] = None):
        self.cluster_id = cluster_id
        self._on_change = on_change
        self._alloc = Allocator(backend, prefix, node,
                                MINIMAL_NUMERIC_IDENTITY,
                                MAX_NUMERIC_IDENTITY,
                                on_event=self._event, seed=seed)

    def _numeric(self, local_id: int) -> int:
        return (self.cluster_id << CLUSTER_ID_SHIFT) | local_id

    def _event(self, typ: str, local_id: int, key: str) -> None:
        if self._on_change is None:
            return
        try:
            labels = decode_labels(key)
        except ValueError:
            return
        self._on_change("add" if typ in ("add", "modify") else "delete",
                        Identity(id=self._numeric(local_id), labels=labels))

    # -- LocalIdentityAllocator-compatible interface -----------------------
    def allocate(self, labels: Labels) -> Tuple[Identity, bool]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved, False
        local_id, is_new = self._alloc.allocate(encode_labels(labels))
        return Identity(id=self._numeric(local_id),
                        labels=Labels(labels)), is_new

    def release(self, ident: Identity) -> bool:
        if is_reserved_identity(ident.id):
            return False
        return self._alloc.release(encode_labels(ident.labels))

    def snapshot_identities(self) -> List[Identity]:
        out = []
        for local_id, key in self._alloc.snapshot().items():
            try:
                labels = decode_labels(key)
            except ValueError:
                continue
            out.append(Identity(id=self._numeric(local_id), labels=labels))
        return out

    def lookup_by_id(self, numeric_id: int) -> Optional[Identity]:
        reserved = look_up_reserved_identity(numeric_id)
        if reserved is not None:
            return reserved
        local_id = numeric_id & ((1 << CLUSTER_ID_SHIFT) - 1)
        key = self._alloc.get_by_id(local_id)
        if key is None:
            return None
        return Identity(id=numeric_id, labels=decode_labels(key))

    def lookup_by_labels(self, labels: Labels) -> Optional[Identity]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved
        local_id = self._alloc.get(encode_labels(labels))
        if local_id is None:
            return None
        return Identity(id=self._numeric(local_id), labels=Labels(labels))

    def run_gc(self) -> int:
        return self._alloc.run_gc()

    def close(self) -> None:
        self._alloc.close()

    def __len__(self):
        return len(self._alloc.snapshot())
