"""Kvstore-backed (distributed) security-identity allocator.

Binds the generic master/slave-key allocator to the identity model:
same labels -> same numeric ID on every node of the cluster, refcounted
via per-node lease-protected slave keys, reclaimed by GC.

Reference: pkg/identity/allocator.go:73 (InitIdentityAllocator),
:124 (AllocateIdentity), :161 (Release); kvstore path
``cilium/state/identities/v1`` (allocator.go:57); cluster-ID bits shifted
above bit 16 (allocator.go:93).
"""

from __future__ import annotations

import base64
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..identity import (CLUSTER_ID_SHIFT, LOCAL_SCOPE_IDENTITY_BASE,
                        MAX_NUMERIC_IDENTITY, MINIMAL_NUMERIC_IDENTITY,
                        Identity, is_local_scope_identity,
                        is_reserved_identity, look_up_reserved_identity,
                        look_up_reserved_identity_by_labels)
from ..labels import Labels, parse_label
from .allocator import Allocator
from .backend import BackendOperations

IDENTITY_PREFIX = "cilium/state/identities/v1"


def encode_labels(labels: Labels) -> str:
    """Labels -> allocator key. Base64url keeps '/' (CIDR labels) out of
    the kvstore path structure."""
    return base64.urlsafe_b64encode(labels.sorted_list()).decode()


def decode_labels(key: str) -> Labels:
    raw = base64.urlsafe_b64decode(key.encode()).decode()
    return Labels.from_labels(
        parse_label(part) for part in raw.split(";") if part)


class DistributedIdentityAllocator:
    """Drop-in for LocalIdentityAllocator backed by the shared kvstore."""

    def __init__(self, backend: BackendOperations, node: str,
                 cluster_id: int = 0,
                 on_change: Optional[Callable[[str, Identity], None]] = None,
                 prefix: str = IDENTITY_PREFIX,
                 seed: Optional[int] = None):
        self.cluster_id = cluster_id
        self._on_change = on_change
        self._alloc = Allocator(backend, prefix, node,
                                MINIMAL_NUMERIC_IDENTITY,
                                MAX_NUMERIC_IDENTITY,
                                on_event=self._event, seed=seed)

    def _numeric(self, local_id: int) -> int:
        return (self.cluster_id << CLUSTER_ID_SHIFT) | local_id

    def _event(self, typ: str, local_id: int, key: str) -> None:
        if self._on_change is None:
            return
        try:
            labels = decode_labels(key)
        except ValueError:
            return
        self._on_change("add" if typ in ("add", "modify") else "delete",
                        Identity(id=self._numeric(local_id), labels=labels))

    # -- LocalIdentityAllocator-compatible interface -----------------------
    def allocate(self, labels: Labels) -> Tuple[Identity, bool]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved, False
        local_id, is_new = self._alloc.allocate(encode_labels(labels))
        return Identity(id=self._numeric(local_id),
                        labels=Labels(labels)), is_new

    def release(self, ident: Identity) -> bool:
        if is_reserved_identity(ident.id):
            return False
        return self._alloc.release(encode_labels(ident.labels))

    def snapshot_identities(self) -> List[Identity]:
        out = []
        for local_id, key in self._alloc.snapshot().items():
            try:
                labels = decode_labels(key)
            except ValueError:
                continue
            out.append(Identity(id=self._numeric(local_id), labels=labels))
        return out

    def lookup_by_id(self, numeric_id: int) -> Optional[Identity]:
        reserved = look_up_reserved_identity(numeric_id)
        if reserved is not None:
            return reserved
        local_id = numeric_id & ((1 << CLUSTER_ID_SHIFT) - 1)
        key = self._alloc.get_by_id(local_id)
        if key is None:
            return None
        return Identity(id=numeric_id, labels=decode_labels(key))

    def lookup_by_labels(self, labels: Labels) -> Optional[Identity]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved
        local_id = self._alloc.get(encode_labels(labels))
        if local_id is None:
            return None
        return Identity(id=self._numeric(local_id), labels=Labels(labels))

    def adopt_cached(self, labels: Labels) -> Optional[Identity]:
        """Degraded-mode reuse: if the watch cache already binds these
        labels to a cluster ID, adopt it (local ref + journaled slave
        key) without any kvstore round-trip.  None on a cache miss."""
        local_id = self._alloc.adopt_cached(encode_labels(labels))
        if local_id is None:
            return None
        return Identity(id=self._numeric(local_id),
                        labels=Labels(labels))

    def run_gc(self) -> int:
        return self._alloc.run_gc()

    def close(self) -> None:
        self._alloc.close()

    def __len__(self):
        return len(self._alloc.snapshot())


class FallbackIdentityAllocator:
    """Outage-surviving shell around the distributed allocator.

    While the kvstore is healthy every call delegates.  When the
    cluster allocator is unreachable (the outage guard is degraded, or
    an op fails outage-class), ``allocate`` degrades in two steps that
    mirror the reference's local-scope (CIDR) identity semantics:

    1. labels the cluster already bound (visible in the watch cache)
       are **adopted** — same numeric ID as every other node, with the
       slave key journaled for reconnect replay;
    2. genuinely new label sets get a node-local ephemeral identity
       from ``LOCAL_SCOPE_IDENTITY_BASE`` (bit 24 — disjoint from
       every cluster-scope ID), refcounted like any other identity and
       never published.

    On reconnect the daemon promotes local identities to cluster scope
    through the normal allocate path and re-keys only the endpoints
    that actually hold them (kvstore/outage.py is the detector;
    daemon._promote_local_identities is the driver).
    """

    # errors that mean "the control plane is unreachable", not "the
    # caller did something wrong": kvstore transport errors, lock
    # timeouts, the guard's fail-fast degraded error, allocator races
    # that exhausted their kvstore attempts
    OUTAGE_ERRORS = (RuntimeError, OSError)

    def __init__(self, distributed: DistributedIdentityAllocator,
                 guard=None,
                 on_change: Optional[Callable[[str, Identity],
                                              None]] = None):
        self._dist = distributed
        self._guard = guard  # kvstore.outage.OutageGuard (mode oracle)
        self._on_change = on_change
        self._mu = threading.RLock()
        # sha -> [Identity, refcount]
        self._by_sha: Dict[str, list] = {}
        self._by_id: Dict[int, Identity] = {}
        self._next = 0
        self.fallback_allocations = 0
        self.adoptions = 0
        self.promotions = 0

    @property
    def cluster_id(self) -> int:
        return self._dist.cluster_id

    def _degraded(self) -> bool:
        return self._guard is not None and self._guard.mode != "ok"

    # ------------------------------------------------------- allocate

    def allocate(self, labels: Labels) -> Tuple[Identity, bool]:
        reserved = look_up_reserved_identity_by_labels(labels)
        if reserved is not None:
            return reserved, False
        if self._degraded():
            return self._allocate_degraded(labels)
        try:
            return self._dist.allocate(labels)
        except self.OUTAGE_ERRORS:
            if self._guard is None:
                raise
            return self._allocate_degraded(labels)

    def _allocate_degraded(self, labels: Labels) -> Tuple[Identity, bool]:
        # step 1: adopt the cluster's cached binding when one exists
        try:
            adopted = self._dist.adopt_cached(labels)
        except self.OUTAGE_ERRORS:
            adopted = None
        if adopted is not None:
            self.adoptions += 1
            return adopted, False
        # step 2: node-local ephemeral identity
        sha = labels.sha256_sum()
        with self._mu:
            held = self._by_sha.get(sha)
            if held is not None:
                held[1] += 1
                return held[0], False
            self._next += 1
            ident = Identity(id=LOCAL_SCOPE_IDENTITY_BASE + self._next,
                             labels=Labels(labels))
            self._by_sha[sha] = [ident, 1]
            self._by_id[ident.id] = ident
            self.fallback_allocations += 1
        if self._on_change:
            self._on_change("add", ident)
        return ident, True

    def release(self, ident: Identity) -> bool:
        if is_reserved_identity(ident.id):
            return False
        if is_local_scope_identity(ident.id):
            freed = False
            with self._mu:
                held = self._by_sha.get(ident.labels.sha256_sum())
                if held is None or held[0].id != ident.id:
                    return False
                held[1] -= 1
                if held[1] <= 0:
                    del self._by_sha[ident.labels.sha256_sum()]
                    del self._by_id[ident.id]
                    freed = True
            if freed and self._on_change:
                self._on_change("delete", ident)
            return freed
        # cluster-scope: the slave-key delete goes through the guarded
        # backend, which journals it while degraded
        return self._dist.release(ident)

    # ------------------------------------------------------ promotion

    def local_count(self) -> int:
        with self._mu:
            return len(self._by_id)

    def local_identities(self) -> List[Identity]:
        with self._mu:
            return list(self._by_id.values())

    # ------------------------------------------------------- lookups

    def lookup_by_id(self, numeric_id: int) -> Optional[Identity]:
        if is_local_scope_identity(numeric_id):
            with self._mu:
                return self._by_id.get(numeric_id)
        return self._dist.lookup_by_id(numeric_id)

    def lookup_by_labels(self, labels: Labels) -> Optional[Identity]:
        ident = self._dist.lookup_by_labels(labels)
        if ident is not None:
            return ident
        with self._mu:
            held = self._by_sha.get(labels.sha256_sum())
            return held[0] if held is not None else None

    def snapshot_identities(self) -> List[Identity]:
        out = self._dist.snapshot_identities()
        with self._mu:
            out.extend(self._by_id.values())
        return out

    def run_gc(self) -> int:
        return self._dist.run_gc()

    def close(self) -> None:
        self._dist.close()

    def __len__(self):
        with self._mu:
            local = len(self._by_id)
        return len(self._dist) + local
