"""Distributed control-plane key-value store.

TPU-native recast of the reference's ``pkg/kvstore``: a backend
interface (reference: pkg/kvstore/backend.go:86-146) carrying the three
replicated stores (identities, ip->identity, nodes), with:

- an in-process backend for tests/single-node operation (reference:
  pkg/kvstore/dummy.go);
- a TCP server + client pair (server.py / remote.py) with etcd-shaped
  semantics — leases, CreateOnly/CreateIfExists, prefix watches,
  distributed locks — so separate agent processes share one store over
  a real socket (reference: pkg/kvstore/etcd.go);
- the distributed ID-allocation protocol (reference:
  pkg/kvstore/allocator/).

Run a standalone store: ``python -m cilium_tpu.kvstore.serve [port]``.
"""

from .backend import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                      EVENT_MODIFY, BackendOperations, Event, KVLockError,
                      close_client, get_client, register_backend,
                      setup_client, setup_dummy)
from .etcd import EtcdBackend
from .journal import WriteJournal
from .memory import InMemoryBackend
from .mini_etcd import MiniEtcd
from .outage import KVStoreDegradedError, OutageGuard
from .remote import RemoteBackend
from .server import KVStoreServer

__all__ = [
    "BackendOperations", "EtcdBackend", "Event", "InMemoryBackend",
    "KVLockError", "KVStoreDegradedError", "KVStoreServer", "MiniEtcd",
    "OutageGuard", "RemoteBackend", "WriteJournal",
    "EVENT_CREATE", "EVENT_MODIFY", "EVENT_DELETE", "EVENT_LIST_DONE",
    "setup_client", "setup_dummy", "get_client", "close_client",
    "register_backend",
]
