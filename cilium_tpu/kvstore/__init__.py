"""Distributed control-plane key-value store.

TPU-native recast of the reference's ``pkg/kvstore``: a backend interface
(reference: pkg/kvstore/backend.go:86-146) carrying the three replicated
stores (identities, ip->identity, nodes), with an in-process backend for
tests/single-node operation (reference: pkg/kvstore/dummy.go) and the
distributed ID-allocation protocol (reference: pkg/kvstore/allocator/).

An etcd backend slot exists behind the same interface; in this image no
etcd client library is available so distribution across real hosts rides
the in-process backend shared between components (a remote backend is a
drop-in via ``register_backend``).
"""

from .backend import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                      EVENT_MODIFY, BackendOperations, Event, KVLockError,
                      close_client, get_client, register_backend,
                      setup_client, setup_dummy)
from .memory import InMemoryBackend

__all__ = [
    "BackendOperations", "Event", "InMemoryBackend", "KVLockError",
    "EVENT_CREATE", "EVENT_MODIFY", "EVENT_DELETE", "EVENT_LIST_DONE",
    "setup_client", "setup_dummy", "get_client", "close_client",
    "register_backend",
]
