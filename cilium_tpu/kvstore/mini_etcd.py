"""In-repo mini-etcd: an etcd v3 JSON-gateway subset server.

Reference: the production backend of pkg/kvstore is etcd
(pkg/kvstore/etcd.go:1 — leases, keepalives, txn-based CreateOnly,
prefix watches).  This environment has zero egress, so portability of
``BackendOperations`` against a second, *production-shaped* protocol is
proven against this server instead: it speaks the etcd v3 gRPC-gateway
JSON wire (base64 keys/values, the same request/response field names)
for exactly the subset client-side etcd.py uses:

  POST /v3/kv/range         {key, range_end?, limit?}
  POST /v3/kv/put           {key, value, lease?}
  POST /v3/kv/deleterange   {key, range_end?}
  POST /v3/kv/txn           {compare[], success[], failure[]}
  POST /v3/lease/grant      {TTL}
  POST /v3/lease/keepalive  {ID}
  POST /v3/lease/revoke     {ID}
  POST /v3/watch            {create_request:{key, range_end?,
                             start_revision?}} -> chunked JSON stream

Semantics implemented the etcd way: a single global revision counter,
per-key create_revision/mod_revision/version, leases that delete their
attached keys on expiry, watches that replay history from
start_revision and stream live events.
"""

from __future__ import annotations

import base64
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

# bounded watch-replay history; a start_revision older than the window
# answers with compacted=true (etcd's ErrCompacted analog)
HISTORY_LIMIT = 4096


def _b64e(b: bytes) -> str:
    return base64.b64encode(b).decode()


def _b64d(s: str) -> bytes:
    return base64.b64decode(s)


class _KV:
    __slots__ = ("value", "create_rev", "mod_rev", "version", "lease")

    def __init__(self, value: bytes, create_rev: int, mod_rev: int,
                 version: int, lease: int):
        self.value = value
        self.create_rev = create_rev
        self.mod_rev = mod_rev
        self.version = version
        self.lease = lease

    def to_json(self, key: bytes) -> Dict:
        return {"key": _b64e(key), "value": _b64e(self.value),
                "create_revision": str(self.create_rev),
                "mod_revision": str(self.mod_rev),
                "version": str(self.version),
                "lease": str(self.lease)}


class _Lease:
    __slots__ = ("ttl", "deadline", "keys")

    def __init__(self, ttl: float, deadline: float):
        self.ttl = ttl
        self.deadline = deadline
        self.keys: set = set()


class MiniEtcd:
    """Threaded server; start() binds an ephemeral port."""

    def __init__(self, reap_interval: float = 0.2):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._rev = 1                    # etcd starts at revision 1
        self._kv: Dict[bytes, _KV] = {}
        self._leases: Dict[int, _Lease] = {}
        self._next_lease = 1000
        # (rev, "PUT"|"DELETE", key, kv-json-or-None)
        self._history: List[Tuple[int, str, bytes, Optional[Dict]]] = []
        self._oldest_rev = 1
        self._stop = threading.Event()
        httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        httpd.etcd = self
        httpd.daemon_threads = True
        self._httpd = httpd
        self.port = httpd.server_address[1]
        self._threads = [
            threading.Thread(target=httpd.serve_forever, daemon=True,
                             name="mini-etcd"),
            threading.Thread(target=self._reaper, daemon=True,
                             name="mini-etcd-reaper"),
        ]
        self._reap_interval = reap_interval

    def start(self) -> "MiniEtcd":
        for t in self._threads:
            t.start()
        return self

    def shutdown(self) -> None:
        self._stop.set()
        with self._cond:
            self._cond.notify_all()
        self._httpd.shutdown()
        self._httpd.server_close()

    # ------------------------------------------------------ internals

    def _record(self, etype: str, key: bytes,
                kv: Optional[_KV]) -> None:
        """Append one event at the CURRENT revision (callers bump)."""
        self._history.append(
            (self._rev, etype, key,
             kv.to_json(key) if kv is not None else None))
        if len(self._history) > HISTORY_LIMIT:
            drop = len(self._history) - HISTORY_LIMIT
            self._oldest_rev = self._history[drop - 1][0] + 1
            del self._history[:drop]

    def _put_locked(self, key: bytes, value: bytes, lease: int) -> None:
        self._rev += 1
        cur = self._kv.get(key)
        if cur is None:
            kv = _KV(value, self._rev, self._rev, 1, lease)
        else:
            kv = _KV(value, cur.create_rev, self._rev,
                     cur.version + 1, lease)
            if cur.lease and cur.lease != lease and \
                    cur.lease in self._leases:
                self._leases[cur.lease].keys.discard(key)
        self._kv[key] = kv
        if lease and lease in self._leases:
            self._leases[lease].keys.add(key)
        self._record("PUT", key, kv)
        self._cond.notify_all()

    def _delete_locked(self, key: bytes) -> bool:
        cur = self._kv.pop(key, None)
        if cur is None:
            return False
        self._rev += 1
        if cur.lease and cur.lease in self._leases:
            self._leases[cur.lease].keys.discard(key)
        self._record("DELETE", key, None)
        self._cond.notify_all()
        return True

    def _range_keys(self, key: bytes, range_end: bytes) -> List[bytes]:
        if not range_end:
            return [key] if key in self._kv else []
        return sorted(k for k in self._kv
                      if key <= k < range_end)

    def compact(self, revision: Optional[int] = None) -> None:
        """Discard watch-replay history up to ``revision`` (default:
        everything so far) — the etcd Compact analog.  A watch asking
        for an older start_revision gets the compacted error and must
        relist."""
        with self._cond:
            rev = self._rev if revision is None else revision
            self._history = [h for h in self._history if h[0] > rev]
            self._oldest_rev = rev + 1
            self._cond.notify_all()

    def _reap_expired_locked(self, now: float) -> int:
        dead = [lid for lid, l in self._leases.items()
                if l.deadline <= now]
        for lid in dead:
            lease = self._leases.pop(lid)
            for key in sorted(lease.keys):
                self._delete_locked(key)
        return len(dead)

    def _reaper(self) -> None:
        while not self._stop.wait(self._reap_interval):
            with self._cond:
                self._reap_expired_locked(time.monotonic())

    def expire_leases(self) -> int:
        """Chaos hook (utils/faultinject.ControlPlaneFaultInjector):
        expire every live lease NOW and reap its keys — the
        long-outage scenario where clients' keepalives stopped long
        enough ago that the server dropped their session state.
        Returns the number of leases expired."""
        with self._cond:
            for lease in self._leases.values():
                lease.deadline = 0.0
            return self._reap_expired_locked(time.monotonic())

    # ---------------------------------------------------- API handlers

    def handle(self, path: str, body: Dict) -> Dict:
        """Non-streaming endpoints."""
        with self._cond:
            if path == "/v3/kv/range":
                key = _b64d(body.get("key", ""))
                end = _b64d(body.get("range_end", ""))
                keys = self._range_keys(key, end)
                limit = int(body.get("limit", 0))
                if limit:
                    keys = keys[:limit]
                return {"header": {"revision": str(self._rev)},
                        "kvs": [self._kv[k].to_json(k) for k in keys],
                        "count": str(len(keys))}
            if path == "/v3/kv/put":
                lease = int(body.get("lease", 0))
                if lease and lease not in self._leases:
                    return {"error": "lease not found", "code": 5}
                self._put_locked(_b64d(body["key"]),
                                 _b64d(body.get("value", "")), lease)
                return {"header": {"revision": str(self._rev)}}
            if path == "/v3/kv/deleterange":
                key = _b64d(body.get("key", ""))
                end = _b64d(body.get("range_end", ""))
                deleted = 0
                for k in self._range_keys(key, end):
                    if self._delete_locked(k):
                        deleted += 1
                return {"header": {"revision": str(self._rev)},
                        "deleted": str(deleted)}
            if path == "/v3/kv/txn":
                return self._txn_locked(body)
            if path == "/v3/lease/grant":
                ttl = float(body.get("TTL", 5))
                self._next_lease += 1
                lid = self._next_lease
                self._leases[lid] = _Lease(
                    ttl, time.monotonic() + ttl)
                return {"ID": str(lid), "TTL": str(int(ttl))}
            if path == "/v3/lease/keepalive":
                lid = int(body.get("ID", 0))
                lease = self._leases.get(lid)
                if lease is None:
                    return {"result": {"ID": str(lid), "TTL": "0"}}
                lease.deadline = time.monotonic() + lease.ttl
                return {"result": {"ID": str(lid),
                                   "TTL": str(int(lease.ttl))}}
            if path == "/v3/lease/revoke":
                lid = int(body.get("ID", 0))
                lease = self._leases.pop(lid, None)
                if lease is not None:
                    for key in sorted(lease.keys):
                        self._delete_locked(key)
                return {"header": {"revision": str(self._rev)}}
        return {"error": f"unknown path {path}", "code": 3}

    def _txn_locked(self, body: Dict) -> Dict:
        succeeded = all(self._compare(c)
                        for c in body.get("compare", []))
        ops = body.get("success" if succeeded else "failure", [])
        responses = []
        for op in ops:
            if "request_put" in op:
                p = op["request_put"]
                lease = int(p.get("lease", 0))
                if lease and lease not in self._leases:
                    return {"error": "lease not found", "code": 5}
                self._put_locked(_b64d(p["key"]),
                                 _b64d(p.get("value", "")), lease)
                responses.append({"response_put": {}})
            elif "request_delete_range" in op:
                p = op["request_delete_range"]
                for k in self._range_keys(
                        _b64d(p.get("key", "")),
                        _b64d(p.get("range_end", ""))):
                    self._delete_locked(k)
                responses.append({"response_delete_range": {}})
            elif "request_range" in op:
                p = op["request_range"]
                keys = self._range_keys(_b64d(p.get("key", "")),
                                        _b64d(p.get("range_end", "")))
                responses.append({"response_range": {
                    "kvs": [self._kv[k].to_json(k) for k in keys],
                    "count": str(len(keys))}})
        return {"header": {"revision": str(self._rev)},
                "succeeded": succeeded, "responses": responses}

    def _compare(self, c: Dict) -> bool:
        key = _b64d(c.get("key", ""))
        kv = self._kv.get(key)
        target = c.get("target", "VALUE")
        result = c.get("result", "EQUAL")
        if target == "CREATE":
            actual = kv.create_rev if kv is not None else 0
            want = int(c.get("create_revision", 0))
        elif target == "VALUE":
            actual = kv.value if kv is not None else b""
            want = _b64d(c.get("value", ""))
        elif target == "VERSION":
            actual = kv.version if kv is not None else 0
            want = int(c.get("version", 0))
        else:
            return False
        if result == "EQUAL":
            return actual == want
        if result == "GREATER":
            return actual > want
        if result == "LESS":
            return actual < want
        if result == "NOT_EQUAL":
            return actual != want
        return False

    # ----------------------------------------------------- watch plane

    def watch_events(self, key: bytes, range_end: bytes,
                     start_rev: int, stopped) -> "iter":
        """Generator of watch-response dicts (the handler streams
        them).  Yields a compacted error if start_rev fell out of the
        replay window."""
        with self._cond:
            if start_rev and start_rev < self._oldest_rev:
                yield {"result": {"compact_revision":
                                  str(self._oldest_rev)},
                       "error": "required revision has been compacted"}
                return
            # etcd semantics: start_revision=0 means "from current",
            # NOT "replay retained history" — replay only happens for
            # an explicit revision (round-5 ADVICE #1: the old
            # behavior re-emitted up to HISTORY_LIMIT stale events,
            # including DELETEs, diverging from real etcd)
            cursor = self._rev if start_rev == 0 else start_rev - 1
        yield {"result": {"created": True,
                          "header": {"revision": str(self._rev)}}}
        while not stopped():
            with self._cond:
                batch = []
                for rev, etype, k, kvj in self._history:
                    if rev <= cursor:
                        continue
                    in_range = (k == key if not range_end
                                else key <= k < range_end)
                    if not in_range:
                        cursor = max(cursor, rev)
                        continue
                    ev = {"type": etype} if etype == "DELETE" else {}
                    ev["kv"] = kvj if kvj is not None else \
                        {"key": _b64e(k)}
                    batch.append((rev, ev))
                if not batch:
                    self._cond.wait(timeout=0.5)
                    rev_now = self._rev
                    idle = True
                else:
                    idle = False
            if idle:
                # progress notify (etcd WithProgressNotify analog):
                # gives the handler a write on every idle tick, so an
                # abandoned client surfaces as BrokenPipeError instead
                # of a zombie handler thread spinning forever
                yield {"result": {"header": {"revision": str(rev_now)}}}
                continue
            events = [e for _r, e in batch]
            cursor = batch[-1][0]
            yield {"result": {"header": {"revision": str(cursor)},
                              "events": events}}


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"

    def log_message(self, *args):
        pass

    def do_POST(self):  # noqa: N802 — http.server contract
        etcd: MiniEtcd = self.server.etcd
        length = int(self.headers.get("Content-Length", 0))
        try:
            body = json.loads(self.rfile.read(length) or b"{}")
        except ValueError:
            self._json(400, {"error": "bad json"})
            return
        if self.path == "/v3/watch":
            self._stream_watch(etcd, body)
            return
        self._json(200, etcd.handle(self.path, body))

    def _stream_watch(self, etcd: MiniEtcd, body: Dict) -> None:
        req = body.get("create_request", {})
        key = _b64d(req.get("key", ""))
        range_end = _b64d(req.get("range_end", ""))
        start = int(req.get("start_revision", 0))
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        stopped = etcd._stop.is_set
        try:
            for resp in etcd.watch_events(key, range_end, start,
                                          stopped):
                data = (json.dumps(resp) + "\n").encode()
                self.wfile.write(b"%x\r\n" % len(data) + data + b"\r\n")
                self.wfile.flush()
            self.wfile.write(b"0\r\n\r\n")
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        self.close_connection = True

    def _json(self, code: int, obj: Dict) -> None:
        data = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)
