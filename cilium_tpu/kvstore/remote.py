"""TCP kvstore client: BackendOperations over a socket.

The client half of kvstore/server.py — a drop-in backend for the
Daemon, so two agent processes converge identities/ipcache/nodes
through a real network transport (reference: pkg/kvstore/etcd.go's
client role).  A background keepalive thread renews the session lease
at ttl/3; if the process dies the lease lapses server-side and its
lease-backed keys vanish.
"""

from __future__ import annotations

import base64
import socket
import threading
from typing import Dict, Optional

from ..observability.tracer import tracer
from ..utils.metrics import KVSTORE_OPERATIONS
from ..utils.resilience import (TRANSPORT_RETRIES, TRANSPORT_VERIFIES,
                                Deadline)
from .backend import (EVENT_LIST_DONE, BackendOperations, Event,
                      KVLockError, Lock, Watcher, register_backend)
from .server import recv_frame, send_frame

DEFAULT_TTL = 15.0

# Default per-request deadline.  An infinite default wait means a dead
# server dispatch thread (or a dropped response frame) wedges the
# calling daemon forever; ops that legitimately block longer — lock
# acquisition — pass an explicit padded _timeout.
DEFAULT_CALL_TIMEOUT = 30.0

# Ops safe to re-send blindly after a timed-out wait: reads return the
# same answer, set/delete converge to the same state.  Everything else
# (CAS creates, lock ops, watch registration, session hello) either
# double-applies or double-registers on a re-send — those surface the
# timeout and let the caller verify.
_IDEMPOTENT_OPS = frozenset({
    "get", "get_prefix", "list_prefix", "set", "delete",
    "delete_prefix", "renew_lease", "status"})


class RemoteError(RuntimeError):
    pass


class RemoteTimeout(RemoteError):
    """The wait for a response frame expired; the request may still be
    executing server-side (the connection is not known dead)."""


class RemoteBackend(BackendOperations):
    name = "remote"

    def __init__(self, host: str = "127.0.0.1", port: int = 42379,
                 lease_ttl: float = DEFAULT_TTL,
                 connect_timeout: float = 5.0,
                 call_timeout: float = DEFAULT_CALL_TIMEOUT):
        self.host, self.port = host, int(port)
        self.lease_ttl = lease_ttl
        self.call_timeout = call_timeout
        self._sock = socket.create_connection((host, self.port),
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        self._wlock = threading.Lock()
        self._mu = threading.Lock()
        self._next_id = 0
        self._pending: Dict[int, dict] = {}      # id -> {"ev", "resp"}
        self._watchers: Dict[int, Watcher] = {}  # watch_id -> Watcher
        self._closed = threading.Event()
        self._reader = threading.Thread(target=self._read_loop,
                                        daemon=True, name="kv-reader")
        self._reader.start()
        resp = self._call("hello", ttl=lease_ttl)
        self.session = resp["session"]
        self._keepalive = threading.Thread(target=self._keepalive_loop,
                                           daemon=True,
                                           name="kv-keepalive")
        self._keepalive.start()

    # --------------------------------------------------------- plumbing

    def _read_loop(self):
        while not self._closed.is_set():
            try:
                msg = recv_frame(self._sock)
            except (OSError, ValueError):
                msg = None
            if msg is None:
                break
            if "watch_id" in msg:
                with self._mu:
                    watcher = self._watchers.get(int(msg["watch_id"]))
                if watcher is not None:
                    watcher._emit(Event(
                        msg["typ"], msg.get("key", ""),
                        base64.b64decode(msg.get("value_b64", ""))))
                continue
            with self._mu:
                slot = self._pending.get(msg.get("id"))
            if slot is not None:
                slot["resp"] = msg
                slot["ev"].set()
        # connection lost: mark closed FIRST so no new _call can park a
        # slot that nothing will ever complete, then fail everything
        # pending and end watches
        self._closed.set()
        with self._mu:
            pending = list(self._pending.values())
            watchers = list(self._watchers.values())
            self._pending.clear()
            self._watchers.clear()
        for slot in pending:
            slot.setdefault("resp", {"ok": False,
                                     "error": "connection lost"})
            slot["ev"].set()
        for watcher in watchers:
            watcher._queue.put(None)

    def _keepalive_loop(self):
        interval = max(0.2, self.lease_ttl / 3.0)
        while not self._closed.wait(interval):
            try:
                self._call("renew_lease")
                ok = True
            except RemoteError:
                ok = False
            listener = self.keepalive_listener
            if listener is not None:
                try:
                    listener(ok)
                except Exception:  # noqa: BLE001 — observer only
                    pass
            if not ok:
                return

    def _call(self, op: str, _timeout: Optional[float] = None,
              **args) -> dict:
        """One request with a deadline.  Idempotent ops split the
        budget across two attempts: a dropped response frame is
        recovered at half the budget instead of surfacing as a hard
        error at the full one.  Non-idempotent ops get exactly one
        send — their callers verify on RemoteTimeout."""
        if _timeout is None:
            _timeout = self.call_timeout
        # op-kind accounting (cilium_kvstore_operations_total analog)
        # + a child span when inside an active trace (daemon ->
        # kvstore context propagation)
        KVSTORE_OPERATIONS.inc(labels={"backend": "remote", "op": op})
        with tracer.child_span(f"kvstore.{op}"):
            if op not in _IDEMPOTENT_OPS:
                return self._call_once(op, _timeout, args)
            deadline = Deadline(_timeout)
            try:
                return self._call_once(op, max(0.05, _timeout / 2.0),
                                       args)
            except RemoteTimeout:
                if self._closed.is_set():
                    raise
                TRANSPORT_RETRIES.inc(
                    labels={"transport": "remote", "op": op})
                return self._call_once(
                    op, max(0.05, deadline.remaining()), args)

    def _call_once(self, op: str, timeout: float, args: dict) -> dict:
        if self._closed.is_set():
            raise RemoteError("client closed")
        with self._mu:
            self._next_id += 1
            rid = self._next_id
            slot = {"ev": threading.Event()}
            self._pending[rid] = slot
        req = {"id": rid, "op": op}
        req.update(args)
        try:
            send_frame(self._sock, req, self._wlock)
        except OSError as e:
            with self._mu:
                self._pending.pop(rid, None)
            raise RemoteError(f"send failed: {e}") from e
        if not slot["ev"].wait(timeout):
            with self._mu:
                self._pending.pop(rid, None)
            raise RemoteTimeout(f"{op}: timed out")
        with self._mu:
            self._pending.pop(rid, None)
        resp = slot["resp"]
        if not resp.get("ok"):
            if resp.get("kind") == "lock":
                raise KVLockError(resp.get("error", "lock failed"))
            raise RemoteError(resp.get("error", "request failed"))
        return resp

    @staticmethod
    def _b64(value: bytes) -> str:
        return base64.b64encode(value).decode()

    # -------------------------------------------------------- plain ops

    def get(self, key: str) -> Optional[bytes]:
        resp = self._call("get", key=key)
        return None if resp.get("missing") else \
            base64.b64decode(resp["value_b64"])

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        resp = self._call("get_prefix", prefix=prefix)
        return None if resp.get("missing") else \
            base64.b64decode(resp["value_b64"])

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        self._call("set", key=key, value_b64=self._b64(value), lease=lease)

    def delete(self, key: str) -> None:
        self._call("delete", key=key)

    def delete_prefix(self, prefix: str) -> None:
        self._call("delete_prefix", prefix=prefix)

    def create_only(self, key: str, value: bytes,
                    lease: bool = False) -> bool:
        try:
            return self._call("create_only", key=key,
                              value_b64=self._b64(value),
                              lease=lease)["created"]
        except RemoteTimeout:
            # the CAS may have been applied and only the reply lost —
            # verify instead of blindly re-sending (which would report
            # created=False against our own first write)
            if self._closed.is_set():
                raise
            TRANSPORT_VERIFIES.inc(
                labels={"transport": "remote", "op": "create_only"})
            return self.get(key) == value

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        return self._call("create_if_exists", cond_key=cond_key, key=key,
                          value_b64=self._b64(value),
                          lease=lease)["created"]

    # -------------------------------------------------- listing / watch

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        items = self._call("list_prefix", prefix=prefix)["items"]
        return {k: base64.b64decode(v) for k, v in items.items()}

    def _new_watch(self, op: str, prefix: str) -> Watcher:
        watcher = Watcher(prefix, self)
        with self._mu:
            self._next_id += 1
            watch_id = self._next_id
            self._watchers[watch_id] = watcher
        watcher._remote_id = watch_id
        self._call(op, prefix=prefix, watch_id=watch_id)
        return watcher

    def watch(self, prefix: str) -> Watcher:
        return self._new_watch("watch", prefix)

    def list_and_watch(self, prefix: str) -> Watcher:
        return self._new_watch("list_and_watch", prefix)

    def _remove_watcher(self, watcher: Watcher) -> None:
        watch_id = getattr(watcher, "_remote_id", None)
        if watch_id is None:
            return
        with self._mu:
            self._watchers.pop(watch_id, None)
        if not self._closed.is_set():
            try:
                self._call("unwatch", watch_id=watch_id)
            except (RemoteError, KVLockError):
                pass

    # --------------------------------------------------- locks / lease

    def lock_path(self, path: str, timeout: float = 30.0) -> Lock:
        # server enforces the acquisition timeout; our wait is padded
        # so the grant/timeout response normally arrives first.  If our
        # wait still expires (e.g. the frame sat unread behind the
        # server's dispatch bound, so its clock started late), tell the
        # server the wait is abandoned — whichever side the grant raced
        # to releases it, so no lock is stranded on a live connection
        # with no client handle.
        import uuid as _uuid
        ref = _uuid.uuid4().hex
        try:
            resp = self._call("lock", _timeout=timeout + 10.0, path=path,
                              timeout=timeout, lock_ref=ref)
        except RemoteError:
            if not self._closed.is_set():
                try:
                    self._call("abort_lock", _timeout=5.0, lock_ref=ref)
                except (RemoteError, KVLockError):
                    pass
            raise
        return Lock(self, path, resp["lock_id"])

    def _unlock(self, path: str, token: str) -> None:
        try:
            self._call("unlock", lock_id=token)
        except RemoteError:
            pass

    def renew_lease(self) -> None:
        self._call("renew_lease")

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._closed.set()
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()

    def status(self) -> str:
        try:
            return self._call("status", _timeout=2.0)["text"]
        except (RemoteError, KVLockError):
            return "remote: unreachable"


register_backend(RemoteBackend.name, RemoteBackend)
