"""Distributed ID allocation over the kvstore.

Implements the reference's allocator protocol
(pkg/kvstore/allocator/allocator.go:51-135):

- master key ``<prefix>/id/<ID>`` -> key, created atomically (CreateOnly)
  by the first node to claim the ID;
- per-node lease-protected slave key ``<prefix>/value/<key>/<node>`` -> ID,
  marking the node's use of the key (the lease reaps it if the node dies);
- allocate: local-refcount hit, else reuse the ID seen in the watched
  cache (slave key created *conditional on the master still existing*),
  else pick a free ID and CreateOnly the master;
- release: local refcount, on zero delete the slave key;
- GC: delete master keys with no remaining slave keys;
- a watch on ``id/`` feeds every node's cache (and remote clusters').
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Dict, Optional, Tuple

from .backend import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                      EVENT_MODIFY, BackendOperations)

MAX_ALLOCATE_ATTEMPTS = 16


class AllocatorError(RuntimeError):
    pass


class Allocator:
    """Generic distributed key<->ID allocator (keys are opaque strings)."""

    def __init__(self, backend: BackendOperations, prefix: str, node: str,
                 min_id: int, max_id: int,
                 on_event: Optional[Callable[[str, int, str], None]] = None,
                 seed: Optional[int] = None):
        self.backend = backend
        self.prefix = prefix.rstrip("/")
        self.node = node
        self.min_id = min_id
        self.max_id = max_id
        self._rng = random.Random(seed)
        self._mu = threading.RLock()
        # local refcounts: key -> (id, refcount)  (reference: localkeys.go)
        self._local: Dict[str, Tuple[int, int]] = {}
        # watch-fed global cache
        self._id_to_key: Dict[int, str] = {}
        self._key_to_id: Dict[str, int] = {}
        self._on_event = on_event  # (typ, id, key)
        self._synced = threading.Event()
        self._watcher = backend.list_and_watch(self._id_prefix())
        self._thread = threading.Thread(target=self._watch_loop, daemon=True)
        self._thread.start()
        self._synced.wait(5.0)

    # -- key layout --------------------------------------------------------
    def _id_prefix(self) -> str:
        return f"{self.prefix}/id/"

    def _master_key(self, id_: int) -> str:
        return f"{self.prefix}/id/{id_}"

    def _slave_prefix(self, key: str) -> str:
        return f"{self.prefix}/value/{key}/"

    def _slave_key(self, key: str) -> str:
        return self._slave_prefix(key) + self.node

    # -- watch -> cache ----------------------------------------------------
    def _watch_loop(self) -> None:
        for event in self._watcher:
            if event.typ == EVENT_LIST_DONE:
                self._synced.set()
                continue
            try:
                id_ = int(event.key.rsplit("/", 1)[1])
            except ValueError:
                continue
            key = event.value.decode()
            with self._mu:
                if event.typ in (EVENT_CREATE, EVENT_MODIFY):
                    self._id_to_key[id_] = key
                    self._key_to_id[key] = id_
                else:
                    stale = self._id_to_key.pop(id_, None)
                    if stale is not None and \
                            self._key_to_id.get(stale) == id_:
                        del self._key_to_id[stale]
                    key = stale if stale is not None else key
            if self._on_event:
                typ = {EVENT_CREATE: "add", EVENT_MODIFY: "modify",
                       EVENT_DELETE: "delete"}[event.typ]
                self._on_event(typ, id_, key)

    # -- allocation --------------------------------------------------------
    def _select_free_id(self) -> int:
        """Random probe into the ID space avoiding known-used IDs
        (reference: idpool.go draws from a pool; random probing gives the
        same low-collision behavior without materializing the pool)."""
        span = self.max_id - self.min_id + 1
        used = self._id_to_key
        if len(used) >= span:
            raise AllocatorError("ID space exhausted")
        for _ in range(64):
            cand = self.min_id + self._rng.randrange(span)
            if cand not in used:
                return cand
        for cand in range(self.min_id, self.max_id + 1):  # dense fallback
            if cand not in used:
                return cand
        raise AllocatorError("ID space exhausted")

    def _lookup_no_cache(self, key: str) -> Optional[int]:
        """Authoritative key->ID lookup straight from the kvstore (the
        watch cache may lag a concurrent allocation on another node)."""
        for raw in self.backend.list_prefix(self._slave_prefix(key)).values():
            try:
                return int(raw.decode())
            except ValueError:
                continue
        for mkey, raw in self.backend.list_prefix(self._id_prefix()).items():
            if raw.decode() == key:
                try:
                    return int(mkey.rsplit("/", 1)[1])
                except ValueError:
                    continue
        return None

    def allocate(self, key: str) -> Tuple[int, bool]:
        """Return (id, is_new_master). Reference: allocator.go Allocate."""
        with self._mu:
            held = self._local.get(key)
            if held is not None:
                id_, ref = held
                self._local[key] = (id_, ref + 1)
                return id_, False
        # Slow path under a per-key distributed lock (the reference locks
        # the key during first allocation so concurrent nodes converge on
        # one master).
        with self.backend.lock_path(f"{self.prefix}/locks/{key}",
                                    timeout=30.0):
            return self._allocate_locked(key)

    def _allocate_locked(self, key: str) -> Tuple[int, bool]:
        for _ in range(MAX_ALLOCATE_ATTEMPTS):
            # Reuse an ID another node already bound to this key: slave
            # key creation is conditional on the master still existing.
            with self._mu:
                existing = self._key_to_id.get(key)
            if existing is None:
                existing = self._lookup_no_cache(key)
            if existing is not None:
                if self.backend.create_if_exists(
                        self._master_key(existing), self._slave_key(key),
                        str(existing).encode(), lease=True):
                    with self._mu:
                        self._local[key] = (existing, 1)
                        self._id_to_key[existing] = key
                        self._key_to_id[key] = existing
                    return existing, False
                if self.backend.get(self._master_key(existing)) is not None:
                    # master exists but our slave key already did: adopt it
                    with self._mu:
                        self._local[key] = (existing, 1)
                    return existing, False
                with self._mu:  # stale cache entry; retry fresh
                    if self._key_to_id.get(key) == existing:
                        del self._key_to_id[key]
                        self._id_to_key.pop(existing, None)
                continue
            with self._mu:
                cand = self._select_free_id()
            if not self.backend.create_only(self._master_key(cand),
                                            key.encode()):
                continue  # raced with another node; retry
            self.backend.create_only(self._slave_key(key),
                                     str(cand).encode(), lease=True)
            with self._mu:
                self._local[key] = (cand, 1)
                self._id_to_key[cand] = key
                self._key_to_id[key] = cand
            return cand, True
        raise AllocatorError(f"allocation of {key!r} kept racing")

    def adopt_cached(self, key: str) -> Optional[int]:
        """Degraded-mode reuse of a watch-cached binding: take a local
        ref on the ID the cluster already bound to ``key`` without the
        lock/lookup kvstore round-trips (the kvstore is down — the
        cache IS last-known-good truth).  The slave key marking our
        use is created through the backend, which journals it while
        degraded and replays it on reconnect.  Returns the adopted ID,
        or None when the cache has no binding (the caller falls back
        to a node-local ephemeral identity)."""
        with self._mu:
            held = self._local.get(key)
            if held is not None:
                id_, ref = held
                self._local[key] = (id_, ref + 1)
                return id_
            existing = self._key_to_id.get(key)
        if existing is None:
            return None
        try:
            self.backend.create_if_exists(
                self._master_key(existing), self._slave_key(key),
                str(existing).encode(), lease=True)
        except Exception:  # noqa: BLE001 — the local ref is what
            pass           # matters; the journal/reconcile repairs it
        with self._mu:
            self._local[key] = (existing, 1)
        return existing

    def release(self, key: str) -> bool:
        """Drop one local reference; on zero delete our slave key.
        Returns True when the local use count hit zero."""
        with self._mu:
            held = self._local.get(key)
            if held is None:
                return False
            id_, ref = held
            if ref > 1:
                self._local[key] = (id_, ref - 1)
                return False
            del self._local[key]
        self.backend.delete(self._slave_key(key))
        return True

    def run_gc(self) -> int:
        """Reclaim masterless IDs: a master key whose slave-key set is
        empty (all users released or their leases expired) is deleted.
        Reference: allocator.go RunGC. Returns number reclaimed."""
        reclaimed = 0
        for mkey, raw in self.backend.list_prefix(self._id_prefix()).items():
            key = raw.decode()
            if not self.backend.list_prefix(self._slave_prefix(key)):
                with self.backend.lock_path(f"{self.prefix}/locks/{key}",
                                            timeout=5.0):
                    if not self.backend.list_prefix(
                            self._slave_prefix(key)):
                        self.backend.delete(mkey)
                        reclaimed += 1
        return reclaimed

    # -- introspection -----------------------------------------------------
    def get(self, key: str) -> Optional[int]:
        with self._mu:
            return self._key_to_id.get(key)

    def get_by_id(self, id_: int) -> Optional[str]:
        with self._mu:
            return self._id_to_key.get(id_)

    def snapshot(self) -> Dict[int, str]:
        with self._mu:
            return dict(self._id_to_key)

    def close(self) -> None:
        self._watcher.stop()
        self._thread.join(timeout=1.0)
