"""Standalone kvstore server entrypoint.

``python -m cilium_tpu.kvstore.serve [port]`` — the single-binary store
a cluster of agents points at (the etcd role in the reference's
deployment, daemon flag --kvstore; here: Daemon(kvstore_backend=
RemoteBackend(host, port))).
"""

from __future__ import annotations

import signal
import sys
import threading

from .server import DEFAULT_PORT, KVStoreServer


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    port = int(argv[0]) if argv else DEFAULT_PORT
    host = argv[1] if len(argv) > 1 else "0.0.0.0"
    srv = KVStoreServer(host=host, port=port).start()
    print(f"kvstore server listening on {srv.address}", flush=True)
    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    srv.shutdown()
    return 0


if __name__ == "__main__":
    sys.exit(main())
