"""etcd v3 kvstore backend (JSON gateway wire).

Reference: pkg/kvstore/etcd.go:1 — the production backend: a session
lease kept alive by the client, txn-based CreateOnly/CreateIfExists,
prefix ranges, streaming watches, and lease-based locks.  This speaks
the etcd v3 gRPC-gateway JSON protocol (/v3/kv/*, /v3/lease/*,
/v3/watch with base64 keys), so it runs unchanged against a real etcd
gateway or the in-repo mini_etcd.MiniEtcd.

Implements the same ``BackendOperations`` surface as the in-memory and
TCP backends — the whole allocator/ipcache/node stack runs against any
of the three (backend portability is the point: backend.go:86).
"""

from __future__ import annotations

import base64
import http.client
import json
import threading
import time
import uuid
from typing import Dict, Optional

from ..observability.tracer import tracer
from ..utils.metrics import KVSTORE_OPERATIONS
from ..utils.netio import teardown_http_conn
from ..utils.resilience import (SYNTHETIC_EVENTS, TRANSPORT_DEADLINES,
                                TRANSPORT_RETRIES, TRANSPORT_VERIFIES,
                                WATCH_RELISTS, AmbiguousResult, Deadline)
from .backend import (BackendOperations, EVENT_CREATE, EVENT_DELETE,
                      EVENT_LIST_DONE, EVENT_MODIFY, Event, KVLockError,
                      Lock, Watcher, register_backend)


def _b64e(s: "str | bytes") -> str:
    if isinstance(s, str):
        s = s.encode()
    return base64.b64encode(s).decode()


def _b64d(s: str) -> bytes:
    return base64.b64decode(s)


def _prefix_range_end(prefix: bytes) -> bytes:
    """etcd prefix query: range_end = prefix with its last byte
    incremented (clientv3.GetPrefixRangeEnd)."""
    end = bytearray(prefix)
    for i in reversed(range(len(end))):
        if end[i] < 0xFF:
            end[i] += 1
            return bytes(end[:i + 1])
        del end[i]
    return b"\x00"  # prefix of all 0xff: range to the end of keyspace


class EtcdError(RuntimeError):
    pass


class EtcdAmbiguousError(EtcdError, AmbiguousResult):
    """The connection died after the request was delivered: the op may
    or may not have been applied.  Raised only for non-idempotent
    paths (txn CAS) — callers verify by reading the result back."""


# Paths whose effect is NOT idempotent: a lost reply after a delivered
# request leaves the outcome unknown, and a blind re-send of the txn
# CAS would report succeeded=false against the caller's OWN first
# write.  Everything else retries blindly: range/keepalive are pure
# reads, put/deleterange converge to the same state on re-apply, and
# grant/revoke leak at most one TTL-bounded lease.
_NON_IDEMPOTENT_PATHS = frozenset({"/v3/kv/txn"})
_CALL_ATTEMPTS = 3


class EtcdBackend(BackendOperations):
    """BackendOperations over the etcd v3 JSON gateway."""

    name = "etcd"

    def __init__(self, host: str = "127.0.0.1", port: int = 2379,
                 lease_ttl: float = 15.0, timeout: float = 10.0):
        self.host = host
        self.port = port
        self.timeout = timeout
        self.lease_ttl = lease_ttl
        self._watchers: Dict[Watcher, threading.Thread] = {}
        self._watcher_conns: Dict[Watcher, object] = {}
        self._lock = threading.Lock()
        self._conn_mu = threading.Lock()
        self._conn: Optional[http.client.HTTPConnection] = None
        self._closed = threading.Event()
        # session lease (etcd.go: one lease per client, kept alive)
        out = self._call("/v3/lease/grant",
                         {"TTL": str(max(1, int(lease_ttl)))})
        self.lease_id = int(out["ID"])
        self._keepalive = threading.Thread(
            target=self._keepalive_loop, daemon=True,
            name="etcd-keepalive")
        self._keepalive.start()

    # ------------------------------------------------------- transport

    def _call(self, path: str, body: Dict) -> Dict:
        """One request over a persistent keep-alive connection (the
        lock hot path polls; a connect/close per op would churn
        ephemeral ports).  Idempotent paths get bounded
        reconnect-and-retry under a deadline; a non-idempotent path
        (txn CAS) whose connection dies AFTER the request was sent
        surfaces EtcdAmbiguousError instead — the caller must verify
        the outcome, never blind-resend."""
        payload = json.dumps(body).encode()
        idempotent = path not in _NON_IDEMPOTENT_PATHS
        deadline = Deadline(self.timeout)
        # op-kind accounting (cilium_kvstore_operations_total analog)
        # + a child span when the caller is inside an active trace
        # (daemon -> kvstore context propagation)
        op_kind = path[len("/v3/"):].replace("/", "-")
        KVSTORE_OPERATIONS.inc(labels={"backend": "etcd",
                                       "op": op_kind})
        with tracer.child_span(f"etcd.{op_kind}"):
            return self._call_locked(path, payload, idempotent,
                                     deadline)

    def _call_locked(self, path: str, payload: bytes,
                     idempotent: bool, deadline: Deadline) -> Dict:
        attempt = 0
        with self._conn_mu:
            while True:
                sent = False
                if self._conn is None:
                    self._conn = http.client.HTTPConnection(
                        self.host, self.port, timeout=self.timeout)
                try:
                    self._conn.request(
                        "POST", path, body=payload,
                        headers={"Content-Type": "application/json"})
                    sent = True
                    resp = self._conn.getresponse()
                    data = resp.read()
                    status = resp.status
                    break
                except (OSError, http.client.HTTPException) as e:
                    self._conn.close()
                    self._conn = None
                    attempt += 1
                    if sent and not idempotent:
                        raise EtcdAmbiguousError(f"{path}: {e}") from e
                    if attempt >= _CALL_ATTEMPTS or deadline.expired:
                        if deadline.expired:
                            TRANSPORT_DEADLINES.inc(
                                labels={"transport": "etcd"})
                        raise EtcdError(f"{path}: {e}") from e
                    TRANSPORT_RETRIES.inc(
                        labels={"transport": "etcd", "op": path})
                    time.sleep(min(0.02 * (2 ** (attempt - 1)),
                                   deadline.remaining()))
        if status != 200:
            raise EtcdError(f"{path}: HTTP {status}")
        try:
            out = json.loads(data)
        except ValueError as e:
            raise EtcdError(f"{path}: bad response") from e
        if "error" in out:
            raise EtcdError(f"{path}: {out['error']}")
        return out

    def _keepalive_loop(self) -> None:
        interval = max(0.05, self.lease_ttl / 3.0)
        while not self._closed.wait(interval):
            try:
                self._call("/v3/lease/keepalive",
                           {"ID": str(self.lease_id)})
                ok = True  # transient failures: lease survives to ttl
            except EtcdError:
                ok = False
            listener = self.keepalive_listener
            if listener is not None:
                try:
                    listener(ok)
                except Exception:  # noqa: BLE001 — observer only
                    pass

    def _regrant_on_lost_lease(self, fn):
        """Run a lease-attached mutation; if the session lease expired
        server-side (an outage outlived the TTL — the server reaped it
        along with every key it backed), grant a fresh lease and retry
        once.  ``fn`` must re-read ``self.lease_id`` per attempt.  The
        outage reconcile (kvstore/outage.py) re-asserts the reaped
        keys through exactly this path."""
        try:
            return fn()
        except EtcdError as e:
            if "lease not found" not in str(e).lower():
                raise
            out = self._call("/v3/lease/grant",
                             {"TTL": str(max(1, int(self.lease_ttl)))})
            self.lease_id = int(out["ID"])
            return fn()

    # ------------------------------------------------------- plain ops

    def get(self, key: str) -> Optional[bytes]:
        out = self._call("/v3/kv/range", {"key": _b64e(key)})
        kvs = out.get("kvs", [])
        return _b64d(kvs[0]["value"]) if kvs else None

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        p = prefix.encode()
        out = self._call("/v3/kv/range", {
            "key": _b64e(p),
            "range_end": _b64e(_prefix_range_end(p)), "limit": "1"})
        kvs = out.get("kvs", [])
        return _b64d(kvs[0]["value"]) if kvs else None

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        def put():
            body = {"key": _b64e(key), "value": _b64e(value)}
            if lease:
                body["lease"] = str(self.lease_id)
            self._call("/v3/kv/put", body)
        if lease:
            self._regrant_on_lost_lease(put)
        else:
            put()

    def delete(self, key: str) -> None:
        self._call("/v3/kv/deleterange", {"key": _b64e(key)})

    def delete_prefix(self, prefix: str) -> None:
        p = prefix.encode()
        self._call("/v3/kv/deleterange", {
            "key": _b64e(p),
            "range_end": _b64e(_prefix_range_end(p))})

    # ------------------------------------------------------ atomic ops

    def _txn_put_if(self, compare: Dict, key: str, value: bytes,
                    lease: bool) -> bool:
        def txn():
            put = {"key": _b64e(key), "value": _b64e(value)}
            if lease:
                put["lease"] = str(self.lease_id)
            out = self._call("/v3/kv/txn", {
                "compare": [compare],
                "success": [{"request_put": put}]})
            return bool(out.get("succeeded"))
        if lease:
            return self._regrant_on_lost_lease(txn)
        return txn()

    def create_only(self, key: str, value: bytes,
                    lease: bool = False) -> bool:
        # etcd.go CreateOnly: compare create_revision == 0 (absent)
        try:
            return self._txn_put_if(
                {"key": _b64e(key), "target": "CREATE",
                 "result": "EQUAL", "create_revision": "0"},
                key, value, lease)
        except EtcdAmbiguousError:
            # verify-on-retry: value equality is the idempotency test.
            # Callers that need exact ownership (lock_path) write a
            # unique per-request token as the value, so "our value is
            # there" can only mean our create landed.  A failed read
            # here propagates EtcdError: the outcome stays unknown.
            TRANSPORT_VERIFIES.inc(
                labels={"transport": "etcd", "op": "create_only"})
            return self.get(key) == value

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        # compare cond_key's create_revision > 0 (present)
        try:
            return self._txn_put_if(
                {"key": _b64e(cond_key), "target": "CREATE",
                 "result": "GREATER", "create_revision": "0"},
                key, value, lease)
        except EtcdAmbiguousError:
            TRANSPORT_VERIFIES.inc(
                labels={"transport": "etcd", "op": "create_if_exists"})
            return self.get(key) == value

    # ------------------------------------------------ listing/watching

    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        p = prefix.encode()
        out = self._call("/v3/kv/range", {
            "key": _b64e(p),
            "range_end": _b64e(_prefix_range_end(p))})
        return {_b64d(kv["key"]).decode(): _b64d(kv["value"])
                for kv in out.get("kvs", [])}

    def _snapshot(self, prefix: str):
        p = prefix.encode()
        out = self._call("/v3/kv/range", {
            "key": _b64e(p),
            "range_end": _b64e(_prefix_range_end(p))})
        rev = int(out.get("header", {}).get("revision", "0"))
        return out.get("kvs", []), rev

    def _relist_into(self, watcher: Watcher, known: set) -> int:
        """Compaction recovery: relist the prefix, diff against the
        consumer-visible key set, and emit synthetic MODIFY/DELETE
        events (the reflector Replace semantics of k8s/client.py) so a
        consumer can never retain an entry deleted in the blind
        window.  Returns the revision to resume the watch from."""
        kvs, rev = self._snapshot(watcher.prefix)
        WATCH_RELISTS.inc(labels={"transport": "etcd"})
        fresh: Dict[str, bytes] = {}
        for kv in kvs:
            fresh[_b64d(kv["key"]).decode()] = \
                _b64d(kv.get("value", ""))
        for key, value in fresh.items():
            typ = EVENT_MODIFY if key in known else EVENT_CREATE
            watcher._emit(Event(typ, key, value))
            SYNTHETIC_EVENTS.inc(
                labels={"transport": "etcd", "typ": typ})
        for key in sorted(known - fresh.keys()):
            watcher._emit(Event(EVENT_DELETE, key))
            SYNTHETIC_EVENTS.inc(
                labels={"transport": "etcd", "typ": EVENT_DELETE})
        known.clear()
        known.update(fresh)
        return rev + 1

    def _watch_stream(self, watcher: Watcher, start_rev: int,
                      known: set) -> None:
        """Reader thread: one /v3/watch stream, re-established from the
        last delivered revision on stream loss; CREATE vs MODIFY from
        kv.version (1 = first write, etcd semantics).  ``known`` is
        the consumer-visible key set, maintained here so compaction
        recovery can relist-and-diff instead of dropping events."""
        prefix = watcher.prefix.encode()
        cursor: Optional[int] = start_rev  # None => compacted: relist
        while not self._closed.is_set() and \
                not watcher._stopped.is_set():
            if cursor is None:
                try:
                    cursor = self._relist_into(watcher, known)
                except EtcdError:
                    if self._closed.is_set() or \
                            watcher._stopped.is_set():
                        return
                    time.sleep(0.05)
                continue
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout)
            try:
                conn.connect()
                with self._lock:
                    if watcher._stopped.is_set():
                        return
                    self._watcher_conns[watcher] = conn
                payload = json.dumps({"create_request": {
                    "key": _b64e(prefix),
                    "range_end": _b64e(_prefix_range_end(prefix)),
                    "start_revision": str(cursor)}}).encode()
                KVSTORE_OPERATIONS.inc(labels={"backend": "etcd",
                                               "op": "watch"})
                conn.request("POST", "/v3/watch", body=payload,
                             headers={"Content-Type":
                                      "application/json"})
                resp = conn.getresponse()
                if resp.status != 200:
                    raise OSError(f"watch: HTTP {resp.status}")
                conn.sock.settimeout(None)
                for raw in resp:
                    line = raw.strip()
                    if not line:
                        continue
                    msg = json.loads(line)
                    result = msg.get("result", {})
                    if msg.get("error") or "compact_revision" in result:
                        # compacted: the only lossless recovery is a
                        # relist-and-diff against the consumer-visible
                        # set, resuming from the fresh revision
                        cursor = None
                        break
                    events = result.get("events", [])
                    for ev in events:
                        kv = ev.get("kv", {})
                        key = _b64d(kv.get("key", "")).decode()
                        if ev.get("type") == "DELETE":
                            known.discard(key)
                            watcher._emit(Event(EVENT_DELETE, key))
                        else:
                            typ = EVENT_CREATE \
                                if kv.get("version") == "1" \
                                else EVENT_MODIFY
                            known.add(key)
                            watcher._emit(Event(
                                typ, key,
                                _b64d(kv.get("value", ""))))
                    rev = result.get("header", {}).get("revision")
                    if rev is not None and events:
                        cursor = int(rev) + 1
            except AttributeError:
                # http.client nulls resp.fp when the stop path closes
                # the connection under a blocked reader; ONLY then is
                # it a dead stream — otherwise it's a real bug
                if watcher._stopped.is_set() or self._closed.is_set():
                    return
                raise
            except (OSError, ValueError, http.client.HTTPException):
                # HTTPException covers NotConnected from a conn the
                # stop path tore down (auto_open cleared) and
                # IncompleteRead from a stream cut mid-chunk
                if watcher._stopped.is_set() or self._closed.is_set():
                    return
                time.sleep(0.05)
            finally:
                teardown_http_conn(conn)
                with self._lock:
                    self._watcher_conns.pop(watcher, None)

    def _revision(self) -> int:
        """Current store revision (cheap: no kvs transferred)."""
        out = self._call("/v3/kv/range",
                         {"key": _b64e("\x00"), "limit": "1"})
        return int(out.get("header", {}).get("revision", "0"))

    def watch(self, prefix: str) -> Watcher:
        watcher, t = self._make_watcher(prefix, self._revision() + 1,
                                        set())
        t.start()
        return watcher

    def list_and_watch(self, prefix: str) -> Watcher:
        kvs, rev = self._snapshot(prefix)
        # seed the consumer-visible set with the listed keys: they are
        # what compaction recovery must diff deletions against
        known = {_b64d(kv["key"]).decode() for kv in kvs}
        watcher, t = self._make_watcher(prefix, rev + 1, known)
        for kv in kvs:
            watcher._emit(Event(EVENT_CREATE,
                                _b64d(kv["key"]).decode(),
                                _b64d(kv["value"])))
        watcher._emit(Event(EVENT_LIST_DONE))
        # the local thread handle, NOT a dict re-index: a concurrent
        # close() may already have unregistered the watcher
        t.start()
        return watcher

    def _make_watcher(self, prefix: str, start_rev: int, known: set
                      ) -> "tuple[Watcher, threading.Thread]":
        watcher = Watcher(prefix, self)
        t = threading.Thread(target=self._watch_stream,
                             args=(watcher, start_rev, known),
                             daemon=True,
                             name=f"etcd-watch-{prefix}")
        with self._lock:
            self._watchers[watcher] = t
        return watcher, t

    def _remove_watcher(self, watcher: Watcher) -> None:
        with self._lock:
            self._watchers.pop(watcher, None)
            conn = self._watcher_conns.pop(watcher, None)
        if conn is not None:
            teardown_http_conn(conn)

    # ------------------------------------------------------------ locks

    def lock_path(self, path: str, timeout: float = 30.0) -> Lock:
        """Lease-bound lock via atomic create (etcd.go LockPath via
        concurrency.Mutex; same liveness: holder death releases it
        when the lease expires).  The token doubles as the
        idempotency token: if the create txn's reply is lost,
        create_only reads the key back and value==own-token means the
        lock is ours — a reset mid-acquisition can no longer orphan
        the lock until its lease expires."""
        token = uuid.uuid4().hex
        lock_key = f"{path}.lock"
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.create_only(lock_key, token.encode(), lease=True):
                return Lock(self, path, token)
            time.sleep(0.02)
        raise KVLockError(f"lock {path!r}: timeout")

    def _unlock(self, path: str, token: str) -> None:
        # delete only OUR lock (compare value == token), atomically —
        # never a successor's
        body = {
            "compare": [{"key": _b64e(f"{path}.lock"),
                         "target": "VALUE", "result": "EQUAL",
                         "value": _b64e(token)}],
            "success": [{"request_delete_range":
                         {"key": _b64e(f"{path}.lock")}}]}
        try:
            self._call("/v3/kv/txn", body)
        except EtcdAmbiguousError:
            # delete-if-value==token is naturally idempotent: if the
            # first send applied, the re-sent compare fails against an
            # absent key (or a successor's token) and no-ops
            self._call("/v3/kv/txn", body)

    # -------------------------------------------------------- liveness

    def renew_lease(self) -> None:
        self._call("/v3/lease/keepalive", {"ID": str(self.lease_id)})

    def close(self) -> None:
        self._closed.set()
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            w.stop()
        try:
            self._call("/v3/lease/revoke", {"ID": str(self.lease_id)})
        except EtcdError:
            pass

    def status(self) -> str:
        try:
            self._call("/v3/kv/range", {"key": _b64e("\x00")})
            return f"etcd: ok ({self.host}:{self.port}, " \
                   f"lease {self.lease_id})"
        except EtcdError as e:
            return f"etcd: unreachable ({e})"


register_backend("etcd", EtcdBackend)
