"""Bounded write journal for kvstore mutations attempted while degraded.

Reference: the agent's obligation during a control-plane outage is the
inverse of the dataplane's — keep accepting local mutations (endpoint
creates publish ipcache entries, releases delete slave keys) and make
them durable enough to replay once the kvstore returns
(pkg/kvstore/store's local-key re-synchronisation on reconnect).  The
journal records each mutation with a monotonic sequence number,
coalesces per key (a set followed by a delete of the same key replays
as just the delete, in the delete's position), and bounds its depth so
a very long outage degrades to dropped-oldest accounting instead of
unbounded memory — the reconcile pass repairs anything a dropped entry
would have written via the local-key re-assert.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

# journalable mutation kinds (everything else fails fast while degraded)
OP_SET = "set"
OP_DELETE = "delete"
OP_DELETE_PREFIX = "delete_prefix"
OP_CREATE_ONLY = "create_only"
OP_CREATE_IF_EXISTS = "create_if_exists"


@dataclass
class JournalEntry:
    """One journaled mutation, replayed in ``seq`` order."""

    seq: int
    op: str
    key: str
    value: bytes = b""
    lease: bool = False
    cond_key: str = ""           # create_if_exists condition key
    at: float = field(default_factory=time.time)


class WriteJournal:
    """Per-key-coalescing, depth-bounded mutation journal.

    ``record`` appends (coalescing away an older mutation of the same
    key — last-writer-wins keeps the journal depth bounded by the
    distinct touched key set, not the mutation rate); ``snapshot``
    returns the pending entries in sequence order for replay, and
    ``discard`` removes an entry once it has been applied, so a replay
    aborted mid-way by a re-failing backend simply leaves the tail
    queued for the next reconnect.
    """

    def __init__(self, max_entries: int = 8192):
        self.max_entries = max_entries
        self._mu = threading.Lock()
        # coalesce key -> entry; replay order is by entry.seq
        self._entries: Dict[Tuple[str, str], JournalEntry] = {}
        self._seq = 0
        self.appended = 0
        self.coalesced = 0
        self.dropped = 0       # overflow: oldest entries evicted

    # ------------------------------------------------------- recording

    def record(self, op: str, key: str, value: bytes = b"",
               lease: bool = False, cond_key: str = "") -> JournalEntry:
        with self._mu:
            self._seq += 1
            entry = JournalEntry(seq=self._seq, op=op, key=key,
                                 value=value, lease=lease,
                                 cond_key=cond_key)
            # one pending mutation per key: set/delete/create forms
            # coalesce with each other (the LAST one is what the store
            # must end up with)
            ck = (OP_DELETE_PREFIX, key) if op == OP_DELETE_PREFIX \
                else ("k", key)
            if ck in self._entries:
                del self._entries[ck]
                self.coalesced += 1
            if op == OP_DELETE_PREFIX:
                # the prefix delete subsumes every pending mutation of
                # a key under it that was recorded BEFORE it
                doomed = [k for k in self._entries
                          if k[0] == "k" and k[1].startswith(key)]
                for k in doomed:
                    del self._entries[k]
                self.coalesced += len(doomed)
            self._entries[ck] = entry
            self.appended += 1
            while len(self._entries) > self.max_entries:
                oldest = min(self._entries,
                             key=lambda k: self._entries[k].seq)
                del self._entries[oldest]
                self.dropped += 1
            return entry

    # --------------------------------------------------------- replay

    def snapshot(self) -> List[JournalEntry]:
        """Pending entries in replay (sequence) order."""
        with self._mu:
            return sorted(self._entries.values(), key=lambda e: e.seq)

    def discard(self, entry: JournalEntry) -> None:
        """Drop one applied entry (no-op if it was coalesced away by a
        newer mutation while the replay was in flight)."""
        with self._mu:
            for ck, e in list(self._entries.items()):
                if e is entry:
                    del self._entries[ck]
                    return

    def discard_key(self, key: str) -> None:
        """Drop any pending mutation of ``key`` — a successful live
        write supersedes it."""
        with self._mu:
            self._entries.pop(("k", key), None)

    def depth(self) -> int:
        with self._mu:
            return len(self._entries)

    def oldest_age(self) -> Optional[float]:
        with self._mu:
            if not self._entries:
                return None
            return time.time() - min(e.at for e in self._entries.values())

    def stats(self) -> Dict:
        with self._mu:
            return {"depth": len(self._entries),
                    "appended": self.appended,
                    "coalesced": self.coalesced,
                    "dropped": self.dropped}
