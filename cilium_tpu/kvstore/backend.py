"""Backend interface for the control-plane kvstore.

Mirrors the operation set of the reference's ``BackendOperations``
(pkg/kvstore/backend.go:86-146): plain gets/sets, atomic CreateOnly /
CreateIfExists, prefix listing, lease-backed keys that vanish when their
owner dies, prefix watches, and distributed locks.  Values are ``bytes``.
"""

from __future__ import annotations

import queue
import threading
from dataclasses import dataclass
from typing import Dict, Iterator, Optional

# Watch event types (reference: pkg/kvstore/events.go).
EVENT_CREATE = "create"
EVENT_MODIFY = "modify"
EVENT_DELETE = "delete"
EVENT_LIST_DONE = "list-done"  # initial listing finished


@dataclass(frozen=True)
class Event:
    """One watch notification."""

    typ: str
    key: str = ""
    value: bytes = b""


class KVLockError(RuntimeError):
    """Raised when a distributed lock cannot be acquired in time."""


class Watcher:
    """A prefix watch: iterate events until ``stop()``.

    Reference: pkg/kvstore/watcher.go — events are queued so slow
    consumers never block writers.
    """

    def __init__(self, prefix: str, backend: "BackendOperations"):
        self.prefix = prefix
        self._backend = backend
        self._queue: "queue.Queue[Optional[Event]]" = queue.Queue()
        self._stopped = threading.Event()

    def _emit(self, event: Event) -> None:
        if not self._stopped.is_set():
            self._queue.put(event)

    def next_event(self, timeout: Optional[float] = None) -> Optional[Event]:
        """Next event or None on stop/timeout."""
        if self._stopped.is_set() and self._queue.empty():
            return None
        try:
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def __iter__(self) -> Iterator[Event]:
        while True:
            ev = self._queue.get()
            if ev is None:
                return
            yield ev

    def stop(self) -> None:
        self._stopped.set()
        self._backend._remove_watcher(self)
        self._queue.put(None)


class Lock:
    """Handle for a held distributed lock; ``unlock()`` or context-manage."""

    def __init__(self, backend: "BackendOperations", path: str, token: str):
        self._backend = backend
        self.path = path
        self.token = token

    def unlock(self) -> None:
        self._backend._unlock(self.path, self.token)

    def __enter__(self) -> "Lock":
        return self

    def __exit__(self, *exc) -> None:
        self.unlock()


class BackendOperations:
    """Abstract kvstore backend (reference: pkg/kvstore/backend.go:86)."""

    name = "abstract"

    # Optional liveness hook: transports with a background lease
    # keepalive loop (etcd, remote) call ``keepalive_listener(ok)``
    # after each keepalive attempt when set — the outage detector's
    # passive signal (kvstore/outage.py) for a control plane that died
    # with no foreground op in flight.
    keepalive_listener: "Optional[callable]" = None

    # -- plain ops ---------------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        raise NotImplementedError

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        """Value of the first key matching the prefix."""
        raise NotImplementedError

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def delete_prefix(self, prefix: str) -> None:
        raise NotImplementedError

    # -- atomic ops (the allocator protocol depends on these) --------------
    def create_only(self, key: str, value: bytes,
                    lease: bool = False) -> bool:
        """Create iff absent; True on success."""
        raise NotImplementedError

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        """Create ``key`` iff ``cond_key`` exists (atomically)."""
        raise NotImplementedError

    # -- listing / watching ------------------------------------------------
    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        raise NotImplementedError

    def watch(self, prefix: str) -> Watcher:
        """Stream future events under prefix."""
        raise NotImplementedError

    def list_and_watch(self, prefix: str) -> Watcher:
        """EVENT_CREATE for every existing key, EVENT_LIST_DONE, then
        live events (reference: ListAndWatch, backend.go:144)."""
        raise NotImplementedError

    # -- locks / liveness --------------------------------------------------
    def lock_path(self, path: str, timeout: float = 30.0) -> Lock:
        raise NotImplementedError

    def renew_lease(self) -> None:
        """Keepalive for this client's lease (no-op where implicit)."""

    def close(self) -> None:
        pass

    def status(self) -> str:
        return f"{self.name}: ok"

    # hooks used by Watcher/Lock
    def _remove_watcher(self, watcher: Watcher) -> None:
        raise NotImplementedError

    def _unlock(self, path: str, token: str) -> None:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# Module-level client (reference: pkg/kvstore/client.go Get/setup pattern).

_registry: Dict[str, type] = {}
_client: Optional[BackendOperations] = None
_client_lock = threading.Lock()


def register_backend(name: str, cls: type) -> None:
    _registry[name] = cls


def setup_client(backend_name: str, **opts) -> BackendOperations:
    """Select and instantiate the process-global kvstore client."""
    global _client
    with _client_lock:
        if _client is not None:
            _client.close()
        cls = _registry[backend_name]
        _client = cls(**opts)
        return _client


def setup_dummy() -> BackendOperations:
    """In-process backend for tests (reference: dummy.go:18 SetupDummy)."""
    return setup_client("in-memory")


def get_client() -> BackendOperations:
    if _client is None:
        raise RuntimeError("kvstore client not configured; "
                           "call setup_client()/setup_dummy() first")
    return _client


def close_client() -> None:
    global _client
    with _client_lock:
        if _client is not None:
            _client.close()
            _client = None
