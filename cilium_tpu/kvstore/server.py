"""TCP kvstore server — the control plane's real network transport.

Round 1's "distributed" control plane never crossed a process boundary:
every agent shared one in-process MemStore.  This server puts the
MemStore behind a socket with etcd-shaped semantics (reference:
pkg/kvstore/etcd.go — leases, atomic CreateOnly/CreateIfExists, prefix
watches, distributed locks), so separate agent *processes* share one
store and the allocator/ipcache/node protocols run over the wire.

Wire protocol: 4-byte big-endian length + JSON.
  request : {"id": n, "op": "...", ...args}   (values base64)
  response: {"id": n, "ok": bool, ...result}
  event   : {"watch_id": w, "typ": ..., "key": ..., "value_b64": ...}

Sessions are leases: each connection starts one with a TTL; the client
keeps it alive with renew_lease.  A killed client (kill -9) stops
renewing; when the TTL lapses the server reaps the session and its
lease-backed keys vanish — watchers on other connections see the
deletes (allocator.go:88-89 semantics).
"""

from __future__ import annotations

import base64
import json
import queue
import socket
import socketserver
import struct
import threading
import uuid
from typing import Dict, Optional, Tuple

from ..utils.netio import recv_exact as _recv_exact
from .backend import Event, KVLockError, Lock, Watcher
from .memory import InMemoryBackend, MemStore

DEFAULT_PORT = 42379  # etcd's 2379, out of the privileged/common range

# Per-connection in-flight bound for *blocking* ops (lock acquisition).
# Fast ops are dispatched inline on the reader thread, so the reader is
# only ever parked in recv_frame — it sees client EOF promptly and
# finish() releases held locks/watches eagerly.  Lock requests past the
# bound fail fast with a lock error instead of queuing daemon threads.
MAX_INFLIGHT = 64

# Server-side cap on the client-requested lock acquisition timeout, so a
# hostile client can't park dispatch threads forever.
MAX_LOCK_TIMEOUT = 120.0


def send_frame(sock: socket.socket, obj: dict,
               lock: Optional[threading.Lock] = None) -> None:
    data = json.dumps(obj, separators=(",", ":")).encode()
    frame = struct.pack(">I", len(data)) + data
    if lock:
        with lock:
            sock.sendall(frame)
    else:
        sock.sendall(frame)


def recv_frame(sock: socket.socket) -> Optional[dict]:
    hdr = _recv_exact(sock, 4)
    if hdr is None:
        return None
    (length,) = struct.unpack(">I", hdr)
    if length > (64 << 20):
        raise ValueError(f"frame too large: {length}")
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return json.loads(body)


def _b64(value: bytes) -> str:
    return base64.b64encode(value).decode()


def _unb64(s: str) -> bytes:
    return base64.b64decode(s)


class _Conn(socketserver.BaseRequestHandler):
    """One client connection: a session + its watches and locks."""

    def setup(self):
        self.server_obj: "KVStoreServer" = self.server.kv_server
        self.store: MemStore = self.server_obj.store
        # ops delegate to a per-connection InMemoryBackend session, so
        # lease/CAS/lock semantics live in exactly one place
        # (memory.py); this handler only does wire marshaling + watch
        # forwarding
        self.backend: Optional[InMemoryBackend] = None
        # dlock guards watches/locks/finished: dispatch threads insert
        # concurrently with finish() tearing down
        self.dlock = threading.Lock()
        self.finished = False
        # watch_id -> (Watcher, forwarder thread)
        self.watches: Dict[int, Tuple[Watcher, threading.Thread]] = {}
        # lock_id -> Lock handle
        self.locks: Dict[str, Lock] = {}
        # client-supplied lock_ref bookkeeping for abandoned waits:
        # refs with an acquisition still in flight, refs the client
        # aborted before the grant arrived, and ref -> lock_id for
        # aborts that race past the grant.  aborted_refs only ever
        # holds refs still in pending_refs, so it cannot leak.
        self.pending_refs: set = set()
        self.aborted_refs: set = set()
        self.granted_refs: Dict[str, str] = {}
        self._inflight = threading.BoundedSemaphore(MAX_INFLIGHT)
        # Single-writer outgoing queue: responses and watch events never
        # contend on the socket, so a watch forwarder stuck behind a
        # slow consumer cannot stall the reader thread's inline
        # dispatches (keepalives keep flowing).  A consumer that lets
        # the queue fill for SEND_TIMEOUT is evicted (connection
        # closed), like the reference monitor's lossy per-subscriber
        # queues (monitor/main.go send path).
        self.out_q: "queue.Queue[Optional[dict]]" = queue.Queue(
            maxsize=1024)
        self._writer = threading.Thread(target=self._write_loop,
                                        daemon=True, name="kv-writer")
        self._writer.start()

    SEND_TIMEOUT = 5.0

    def _write_loop(self) -> None:
        while True:
            try:
                obj = self.out_q.get(timeout=0.5)
            except queue.Empty:
                if self.finished:
                    return
                continue
            if obj is None:
                return
            try:
                send_frame(self.request, obj)
            except OSError:
                return

    def handle(self):
        self.request.settimeout(None)
        while True:
            try:
                req = recv_frame(self.request)
            except (ValueError, OSError):
                break
            if req is None:
                break
            if req.get("op") == "lock":
                # only lock acquisition may block long; it runs on its
                # own thread so keepalives keep flowing, bounded so a
                # flood fails fast instead of growing a thread per frame
                if self._inflight.acquire(blocking=False):
                    threading.Thread(target=self._dispatch,
                                     args=(req, True),
                                     daemon=True).start()
                else:
                    self._respond({"id": req.get("id"), "ok": False,
                                   "error": "too many pending locks",
                                   "kind": "lock"})
            else:
                # fast ops run inline: the reader thread is otherwise
                # always parked in recv_frame, so EOF -> finish() is
                # prompt even while lock threads wait
                self._dispatch(req, False)

    def _respond(self, resp: dict) -> bool:
        """Enqueue a frame for the writer thread.  A consumer whose
        queue stays full for SEND_TIMEOUT is evicted."""
        try:
            self.out_q.put(resp, timeout=self.SEND_TIMEOUT)
            return True
        except queue.Full:
            try:
                self.request.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            return False

    def _dispatch(self, req: dict, holds_slot: bool) -> None:
        rid = req.get("id")
        try:
            result = self._handle_op(req)
            resp = {"id": rid, "ok": True}
            if result:
                resp.update(result)
        except KVLockError as e:
            resp = {"id": rid, "ok": False, "error": str(e),
                    "kind": "lock"}
        except Exception as e:  # noqa: BLE001 — wire back, don't die
            resp = {"id": rid, "ok": False, "error": repr(e)}
        finally:
            if holds_slot:
                self._inflight.release()
        self._respond(resp)

    # ------------------------------------------------------------- ops

    def _handle_op(self, req: dict) -> Optional[dict]:
        op = req["op"]
        if op == "hello":
            self.backend = InMemoryBackend(
                self.store, lease_ttl=float(req.get("ttl", 15.0)))
            return {"session": self.backend.session}
        be = self.backend
        if be is None:
            raise ValueError("hello required first")
        if op == "renew_lease":
            be.renew_lease()
            return None
        if op == "get":
            v = be.get(req["key"])
            return {"missing": True} if v is None else {"value_b64": _b64(v)}
        if op == "get_prefix":
            v = be.get_prefix(req["prefix"])
            return {"missing": True} if v is None else {"value_b64": _b64(v)}
        if op == "set":
            be.set(req["key"], _unb64(req["value_b64"]),
                   lease=bool(req.get("lease")))
            return None
        if op == "delete":
            be.delete(req["key"])
            return None
        if op == "delete_prefix":
            be.delete_prefix(req["prefix"])
            return None
        if op == "create_only":
            return {"created": be.create_only(
                req["key"], _unb64(req["value_b64"]),
                lease=bool(req.get("lease")))}
        if op == "create_if_exists":
            return {"created": be.create_if_exists(
                req["cond_key"], req["key"], _unb64(req["value_b64"]),
                lease=bool(req.get("lease")))}
        if op == "list_prefix":
            return {"items": {k: _b64(v) for k, v in
                              be.list_prefix(req["prefix"]).items()}}
        if op in ("watch", "list_and_watch"):
            return self._start_watch(req, initial=(op == "list_and_watch"))
        if op == "unwatch":
            self._stop_watch(req["watch_id"])
            return None
        if op == "lock":
            timeout = min(float(req.get("timeout", 30.0)),
                          MAX_LOCK_TIMEOUT)
            lock_ref = req.get("lock_ref")
            if lock_ref is not None:
                with self.dlock:
                    self.pending_refs.add(lock_ref)
            try:
                lock = be.lock_path(req["path"], timeout=timeout)
            except KVLockError:
                with self.dlock:
                    self.pending_refs.discard(lock_ref)
                    self.aborted_refs.discard(lock_ref)
                raise
            lock_id = uuid.uuid4().hex
            with self.dlock:
                self.pending_refs.discard(lock_ref)
                if self.finished:
                    pass  # fall through: connection died while we waited
                elif lock_ref is not None and \
                        lock_ref in self.aborted_refs:
                    # client gave up (its own wait timed out) before the
                    # grant: release instead of stranding a lock the
                    # client has no handle to
                    self.aborted_refs.discard(lock_ref)
                else:
                    self.locks[lock_id] = lock
                    if lock_ref is not None:
                        self.granted_refs[lock_ref] = lock_id
                    return {"lock_id": lock_id}
            lock.unlock()
            raise KVLockError("lock wait abandoned")
        if op == "abort_lock":
            # client-side lock wait timed out; whether the grant already
            # happened decides which side releases
            ref = req["lock_ref"]
            held = None
            with self.dlock:
                lock_id = self.granted_refs.pop(ref, None)
                if lock_id is not None:
                    held = self.locks.pop(lock_id, None)
                elif ref in self.pending_refs:
                    # only mark refs with an acquisition still in
                    # flight; anything else would leak forever
                    self.aborted_refs.add(ref)
            if held:
                held.unlock()
            return None
        if op == "unlock":
            with self.dlock:
                held = self.locks.pop(req["lock_id"], None)
                self.granted_refs = {r: lid for r, lid
                                     in self.granted_refs.items()
                                     if lid != req["lock_id"]}
            if held:
                held.unlock()
            return None
        if op == "status":
            return {"text": be.status().replace("in-memory", "remote", 1)}
        raise ValueError(f"unknown op {op!r}")

    # ----------------------------------------------------------- watches

    def _start_watch(self, req: dict, initial: bool) -> dict:
        watch_id = int(req["watch_id"])
        prefix = req["prefix"]
        watcher = Watcher(prefix, _WatchHost(self.store))
        with self.store.mu:
            if initial:
                self.store.expire_sessions()
                for key in sorted(self.store.data):
                    if key.startswith(prefix):
                        watcher._emit(Event("create", key,
                                            self.store.data[key][0]))
                watcher._emit(Event("list-done"))
            self.store.watchers.append((prefix, watcher))

        def forward():
            for ev in watcher:
                if not self._respond({"watch_id": watch_id,
                                      "typ": ev.typ, "key": ev.key,
                                      "value_b64": _b64(ev.value)}):
                    return

        t = threading.Thread(target=forward, daemon=True)
        t.start()
        with self.dlock:
            if self.finished:
                watcher.stop()
                raise ValueError("connection closed")
            self.watches[watch_id] = (watcher, t)
        return {}

    def _stop_watch(self, watch_id: int) -> None:
        with self.dlock:
            entry = self.watches.pop(int(watch_id), None)
        if entry:
            entry[0].stop()

    def finish(self):
        with self.dlock:
            self.finished = True
            watches = list(self.watches.values())
            self.watches.clear()
            locks = list(self.locks.values())
            self.locks.clear()
            self.granted_refs.clear()
            self.aborted_refs.clear()
            self.pending_refs.clear()
        try:
            self.out_q.put_nowait(None)  # stop the writer
        except queue.Full:
            pass  # writer exits via the finished flag
        for watcher, _t in watches:
            watcher.stop()
        # held locks die with the connection (eager release avoids a
        # stuck allocator waiting a full TTL)
        for lock in locks:
            try:
                lock.unlock()
            except Exception:  # noqa: BLE001
                pass
        # the backend is NOT closed here: its session lives until the
        # TTL lapses, exactly like an etcd lease after the client
        # vanishes (close() would expire the lease immediately)


class _WatchHost:
    """Adapter so server-side Watchers can detach from the MemStore."""

    def __init__(self, store: MemStore):
        self.store = store

    def _remove_watcher(self, watcher: Watcher) -> None:
        with self.store.mu:
            self.store.watchers = [(p, w) for p, w in self.store.watchers
                                   if w is not watcher]


class _ThreadingTCP(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True


class KVStoreServer:
    """The store + listener.  start() binds and serves in background."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 store: Optional[MemStore] = None,
                 expire_interval: float = 0.2):
        self.store = store if store is not None else MemStore()
        self._tcp = _ThreadingTCP((host, port), _Conn)
        self._tcp.kv_server = self
        self.host, self.port = self._tcp.server_address
        self._serve_thread = threading.Thread(
            target=self._tcp.serve_forever, daemon=True, name="kv-server")
        self._expire_interval = expire_interval
        self._stop = threading.Event()
        self._expirer = threading.Thread(target=self._expire_loop,
                                         daemon=True, name="kv-expirer")

    def start(self) -> "KVStoreServer":
        self._serve_thread.start()
        self._expirer.start()
        return self

    def _expire_loop(self):
        # leases must lapse even when no client issues requests —
        # that's the whole point of detecting a kill -9'd agent
        while not self._stop.wait(self._expire_interval):
            with self.store.mu:
                self.store.expire_sessions()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def shutdown(self) -> None:
        self._stop.set()
        self._tcp.shutdown()
        self._tcp.server_close()
