"""Generic shared-store synchronisation over the kvstore.

Reference: pkg/kvstore/store — a JSON-marshalled set of keys under a
common prefix, where every node publishes its own keys (lease-backed) and
watches everyone else's.  Used by the node registry and reusable for any
replicated table.
"""

from __future__ import annotations

import json
import threading
from typing import Callable, Dict, Optional

from .backend import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                      EVENT_MODIFY, BackendOperations)


class SharedStore:
    """A replicated key->dict store under ``prefix``.

    ``update_local`` publishes (lease-backed, so a dead node's keys are
    reaped); remote changes arrive via the watch thread and are surfaced
    through ``on_update``/``on_delete`` callbacks plus a merged snapshot.
    """

    def __init__(self, backend: BackendOperations, prefix: str,
                 on_update: Optional[Callable[[str, dict], None]] = None,
                 on_delete: Optional[Callable[[str], None]] = None):
        self.backend = backend
        self.prefix = prefix.rstrip("/") + "/"
        self._mu = threading.Lock()
        self._local: Dict[str, dict] = {}
        self._remote: Dict[str, dict] = {}
        self._on_update = on_update
        self._on_delete = on_delete
        self._synced = threading.Event()
        self._watcher = backend.list_and_watch(self.prefix)
        self._thread = threading.Thread(target=self._watch_loop,
                                        daemon=True)
        self._thread.start()

    def _name(self, key: str) -> str:
        return key[len(self.prefix):]

    def _watch_loop(self) -> None:
        for event in self._watcher:
            if event.typ == EVENT_LIST_DONE:
                self._synced.set()
                continue
            name = self._name(event.key)
            if event.typ in (EVENT_CREATE, EVENT_MODIFY):
                try:
                    value = json.loads(event.value.decode())
                except ValueError:
                    continue
                with self._mu:
                    self._remote[name] = value
                if self._on_update:
                    self._on_update(name, value)
            elif event.typ == EVENT_DELETE:
                with self._mu:
                    self._remote.pop(name, None)
                if self._on_delete:
                    self._on_delete(name)

    def wait_synced(self, timeout: float = 5.0) -> bool:
        return self._synced.wait(timeout)

    def update_local(self, name: str, value: dict) -> None:
        with self._mu:
            self._local[name] = value
        self.backend.set(self.prefix + name,
                         json.dumps(value, sort_keys=True).encode(),
                         lease=True)

    def delete_local(self, name: str) -> None:
        with self._mu:
            self._local.pop(name, None)
        self.backend.delete(self.prefix + name)

    def snapshot(self) -> Dict[str, dict]:
        """Merged view (remote watch state; includes our own published
        keys once they echo back through the watch)."""
        with self._mu:
            return dict(self._remote)

    def close(self) -> None:
        self._watcher.stop()
        self._thread.join(timeout=1.0)
