"""In-process kvstore backend.

Serves the role of the reference's dummy backend for tests
(pkg/kvstore/dummy.go:18) *and* of an etcd stand-in for single-host
multi-agent simulation: several ``InMemoryBackend`` clients may share one
``MemStore``, each with its own lease session, so lease expiry semantics
(dead node => its lease-backed keys vanish and watchers see deletes —
reference: pkg/kvstore/allocator/allocator.go:88-89) are testable without
a real etcd.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from .backend import (EVENT_CREATE, EVENT_DELETE, EVENT_LIST_DONE,
                      EVENT_MODIFY, BackendOperations, Event, KVLockError,
                      Lock, Watcher, register_backend)

# Reference etcd sessions are 15-minute leases kept alive by the client.
DEFAULT_LEASE_TTL = 900.0


class MemStore:
    """Shared state behind one or more InMemoryBackend clients."""

    def __init__(self):
        self.mu = threading.RLock()
        # key -> (value, owning session id or None)
        self.data: Dict[str, Tuple[bytes, Optional[str]]] = {}
        # session id -> expiry deadline (monotonic seconds)
        self.sessions: Dict[str, float] = {}
        self.watchers: List[Tuple[str, Watcher]] = []
        # lock path -> (token, session id)
        self.locks: Dict[str, Tuple[str, str]] = {}
        self.lock_cv = threading.Condition(self.mu)

    # All methods below assume self.mu is held.

    def _emit(self, event: Event) -> None:
        for prefix, watcher in list(self.watchers):
            if event.key.startswith(prefix):
                watcher._emit(event)

    def _put(self, key: str, value: bytes, session: Optional[str]) -> None:
        typ = EVENT_MODIFY if key in self.data else EVENT_CREATE
        self.data[key] = (value, session)
        self._emit(Event(typ, key, value))

    def _drop(self, key: str) -> None:
        if key in self.data:
            value, _ = self.data.pop(key)
            self._emit(Event(EVENT_DELETE, key, value))

    def expire_sessions(self, now: Optional[float] = None) -> None:
        """Reap dead sessions: their keys and locks evaporate."""
        now = time.monotonic() if now is None else now
        dead = [s for s, dl in self.sessions.items() if dl <= now]
        for session in dead:
            del self.sessions[session]
            for key in [k for k, (_, s) in self.data.items() if s == session]:
                self._drop(key)
            for path in [p for p, (_, s) in self.locks.items()
                         if s == session]:
                del self.locks[path]
        if dead:
            self.lock_cv.notify_all()


class InMemoryBackend(BackendOperations):
    """One client session over a (possibly shared) MemStore."""

    name = "in-memory"

    def __init__(self, store: Optional[MemStore] = None,
                 lease_ttl: float = DEFAULT_LEASE_TTL):
        self.store = store if store is not None else MemStore()
        self.lease_ttl = lease_ttl
        self.session = uuid.uuid4().hex
        with self.store.mu:
            self.store.sessions[self.session] = \
                time.monotonic() + lease_ttl

    def _lease_session(self) -> str:
        """Session id for lease-backed writes, revived if reaped.

        A client stalled past its TTL gets its session (and keys)
        reaped; without revival its later keepalives would silently
        no-op and new lease-backed keys would belong to a session id
        absent from the sessions map — unreapable forever.  Assumes
        store.mu is held.
        """
        if self.session not in self.store.sessions:
            self.store.sessions[self.session] = \
                time.monotonic() + self.lease_ttl
        return self.session

    # -- plain ops ---------------------------------------------------------
    def get(self, key: str) -> Optional[bytes]:
        with self.store.mu:
            self.store.expire_sessions()
            entry = self.store.data.get(key)
            return entry[0] if entry else None

    def get_prefix(self, prefix: str) -> Optional[bytes]:
        with self.store.mu:
            self.store.expire_sessions()
            for key in sorted(self.store.data):
                if key.startswith(prefix):
                    return self.store.data[key][0]
        return None

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        with self.store.mu:
            self.store.expire_sessions()
            self.store._put(key, value,
                            self._lease_session() if lease else None)

    def delete(self, key: str) -> None:
        with self.store.mu:
            self.store.expire_sessions()
            self.store._drop(key)

    def delete_prefix(self, prefix: str) -> None:
        with self.store.mu:
            self.store.expire_sessions()
            for key in [k for k in self.store.data if k.startswith(prefix)]:
                self.store._drop(key)

    # -- atomic ops --------------------------------------------------------
    def create_only(self, key: str, value: bytes,
                    lease: bool = False) -> bool:
        with self.store.mu:
            self.store.expire_sessions()
            if key in self.store.data:
                return False
            self.store._put(key, value,
                            self._lease_session() if lease else None)
            return True

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        with self.store.mu:
            self.store.expire_sessions()
            if cond_key not in self.store.data or key in self.store.data:
                return False
            self.store._put(key, value,
                            self._lease_session() if lease else None)
            return True

    # -- listing / watching ------------------------------------------------
    def list_prefix(self, prefix: str) -> Dict[str, bytes]:
        with self.store.mu:
            self.store.expire_sessions()
            return {k: v for k, (v, _) in self.store.data.items()
                    if k.startswith(prefix)}

    def watch(self, prefix: str) -> Watcher:
        watcher = Watcher(prefix, self)
        with self.store.mu:
            self.store.watchers.append((prefix, watcher))
        return watcher

    def list_and_watch(self, prefix: str) -> Watcher:
        watcher = Watcher(prefix, self)
        with self.store.mu:
            self.store.expire_sessions()
            for key in sorted(self.store.data):
                if key.startswith(prefix):
                    watcher._emit(
                        Event(EVENT_CREATE, key, self.store.data[key][0]))
            watcher._emit(Event(EVENT_LIST_DONE))
            self.store.watchers.append((prefix, watcher))
        return watcher

    def _remove_watcher(self, watcher: Watcher) -> None:
        with self.store.mu:
            self.store.watchers = [(p, w) for p, w in self.store.watchers
                                   if w is not watcher]

    # -- locks / liveness --------------------------------------------------
    def lock_path(self, path: str, timeout: float = 30.0) -> Lock:
        token = uuid.uuid4().hex
        deadline = time.monotonic() + timeout
        with self.store.mu:
            while True:
                self.store.expire_sessions()
                if path not in self.store.locks:
                    self.store.locks[path] = (token, self.session)
                    return Lock(self, path, token)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise KVLockError(f"lock {path!r}: timeout")
                self.store.lock_cv.wait(min(remaining, 0.05))

    def _unlock(self, path: str, token: str) -> None:
        with self.store.mu:
            held = self.store.locks.get(path)
            if held and held[0] == token:
                del self.store.locks[path]
                self.store.lock_cv.notify_all()

    def renew_lease(self) -> None:
        with self.store.mu:
            # revives a reaped session (see _lease_session): a client
            # that stalled past its TTL must regain liveness rather
            # than keep "renewing" a session that no longer exists
            self.store.sessions[self._lease_session()] = \
                time.monotonic() + self.lease_ttl

    def expire_now(self) -> None:
        """Test hook: this client's lease dies immediately (node failure)."""
        with self.store.mu:
            if self.session in self.store.sessions:
                self.store.sessions[self.session] = 0.0
            self.store.expire_sessions()

    def close(self) -> None:
        self.expire_now()

    def status(self) -> str:
        with self.store.mu:
            return (f"{self.name}: {len(self.store.data)} keys, "
                    f"{len(self.store.sessions)} sessions")


register_backend(InMemoryBackend.name, InMemoryBackend)
