"""Control-plane outage survivability: the kvstore outage guard.

Reference: the defining robustness property of the reference agent is
that the *dataplane* keeps enforcing last-known-good policy through
pinned maps while the *control plane* is down (daemon/state.go restore
semantics, pkg/kvstore's reconnect machinery).  This module gives the
kvstore client the same property:

- ``OutageGuard`` wraps any ``BackendOperations`` and classifies every
  operation's outcome into a breaker (utils/resilience.CircuitBreaker).
  Sustained failure — consecutive op failures, failed idle probes, or
  lease-keepalive failures reported by the transport — flips
  ``kvstore_mode`` to **degraded**.
- While degraded (opt-in): watch-fed consumers (allocator caches,
  ipcache, node registry) pin last-known-good state automatically
  (their streams just go quiet); *mutations* are recorded in a bounded
  per-key-coalescing ``WriteJournal`` instead of failing the caller;
  reads and lock/CAS ops fail fast with ``KVStoreDegradedError`` so
  callers (the identity fallback path) can degrade in microseconds
  instead of per-op timeouts.  Local lease-backed keys are tracked in
  a desired-state registry and are NOT dropped: the reconcile pass
  re-asserts any that the server's lease reaper expired during the
  outage (the lease grace window).
- On reconnect (a half-open probe succeeding), mode becomes
  **reconciling**: the journal replays in sequence order
  (rate-limited), then a relist-and-diff over the tracked prefixes
  repairs divergence between the store and the local desired-state
  registry — the outbound twin of the etcd watcher's compaction
  relist (PR 1), which handles the inbound direction on its own.

With ``degrade=False`` the guard is a pure pass-through that only
keeps last-success/failure bookkeeping — the status() staleness fix —
and is behavior-identical to an unwrapped backend.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional

from ..observability.events import (EVENT_KVSTORE_DEGRADED,
                                    EVENT_KVSTORE_RECONCILING,
                                    EVENT_KVSTORE_RECOVERED,
                                    recorder as flight_recorder)
from ..utils.metrics import (KVSTORE_JOURNAL_DEPTH, KVSTORE_MODE,
                             KVSTORE_RECONCILE, KVSTORE_STALENESS)
from ..utils.resilience import CircuitBreaker
from .backend import BackendOperations, Lock, Watcher
from .journal import (OP_CREATE_IF_EXISTS, OP_CREATE_ONLY, OP_DELETE,
                      OP_DELETE_PREFIX, OP_SET, WriteJournal)

MODE_OK = "ok"
MODE_DEGRADED = "degraded"
MODE_RECONCILING = "reconciling"

_MODE_GAUGE = {MODE_OK: 0, MODE_DEGRADED: 1, MODE_RECONCILING: 2}

# cheap read used by idle/half-open probes; never written
PROBE_KEY = "cilium/.outage-probe"


class KVStoreDegradedError(RuntimeError):
    """The kvstore is in degraded mode: the operation cannot be served
    from last-known-good state and was not journaled (reads, locks,
    non-lease CAS creates).  Callers degrade locally — the identity
    path falls back to node-local ephemeral allocation."""


class OutageGuard(BackendOperations):
    """BackendOperations wrapper with outage detection, degraded-mode
    journaling, and reconnect reconciliation."""

    def __init__(self, inner: BackendOperations, degrade: bool = False,
                 failure_threshold: int = 3,
                 probe_interval: float = 0.5, grace_s: float = 60.0,
                 journal_max: int = 8192,
                 replay_ops_per_s: float = 2000.0):
        self.inner = inner
        self.name = inner.name
        self.degrade_enabled = degrade
        self.grace_s = grace_s
        self.probe_interval = probe_interval
        self._replay_sleep = 1.0 / replay_ops_per_s \
            if replay_ops_per_s and replay_ops_per_s > 0 else 0.0
        self._mu = threading.RLock()
        self._mode = MODE_OK
        self._last_ok = time.monotonic()
        self._consecutive_failures = 0
        self._degraded_at: Optional[float] = None
        self._outages = 0
        self._last_reconcile: Optional[Dict] = None
        self.journal = WriteJournal(journal_max)
        # desired state of locally written keys (key -> (value, lease)):
        # lease-backed entries here are what the reconcile re-asserts
        # after a server-side lease expiry during the outage
        self._local_keys: Dict[str, "tuple[bytes, bool]"] = {}
        self._tracked_prefixes: List[str] = []
        self._breaker = CircuitBreaker(
            f"kvstore-{inner.name}",
            failure_threshold=failure_threshold,
            reset_timeout=max(0.05, probe_interval),
            max_reset=max(5.0, probe_interval * 8))
        KVSTORE_MODE.set(0)
        # observe the transport's lease keepalive when it offers the
        # hook (kvstore/etcd.py, kvstore/remote.py): a dying keepalive
        # is an outage signal even when no foreground op is in flight
        if degrade:
            try:
                inner.keepalive_listener = self._keepalive_result
            except AttributeError:
                pass

    # ------------------------------------------------------- detector

    def _keepalive_result(self, ok: bool) -> None:
        if ok:
            self._note_success()
        else:
            self._note_failure()

    def _note_success(self) -> None:
        with self._mu:
            self._last_ok = time.monotonic()
            self._consecutive_failures = 0
            # the breaker always hears about success (a half-open probe
            # carried by a foreground read must close it or it wedges),
            # but MODE only returns to ok through the reconcile path
            self._breaker.record_success()

    def _note_failure(self) -> None:
        with self._mu:
            self._consecutive_failures += 1
            self._breaker.record_failure()
            if self.degrade_enabled and self._mode == MODE_OK and \
                    self._breaker.state != "closed":
                self._set_mode_locked(MODE_DEGRADED)
                self._degraded_at = time.monotonic()
                self._outages += 1
                flight_recorder.record(
                    EVENT_KVSTORE_DEGRADED,
                    detail=f"{self.name}: "
                           f"{self._consecutive_failures} consecutive "
                           f"failures; pinning last-known-good",
                    outage=self._outages,
                    journal_depth=self.journal.depth())

    def _set_mode_locked(self, mode: str) -> None:
        self._mode = mode
        KVSTORE_MODE.set(_MODE_GAUGE[mode])

    @property
    def mode(self) -> str:
        with self._mu:
            return self._mode

    def staleness(self) -> float:
        """Seconds since the last successful operation; 0 while the
        last operation succeeded (the status() contract: a dead
        backend can no longer report 'ok' between calls)."""
        with self._mu:
            if self._consecutive_failures == 0 and \
                    self._mode == MODE_OK:
                return 0.0
            return max(0.0, time.monotonic() - self._last_ok)

    # ----------------------------------------------------- op routing

    def _degraded(self) -> bool:
        with self._mu:
            return self._mode != MODE_OK

    def _read(self, fn: Callable, what: str):
        """Reads: live while ok; while degraded, only the breaker's
        half-open probe slot may try the backend — everyone else fails
        fast (the caches are the degraded read path)."""
        if self._degraded():
            if not self._breaker.allow():
                raise KVStoreDegradedError(
                    f"{self.name}: degraded ({what})")
        try:
            out = fn()
        except Exception:
            self._note_failure()
            raise
        self._note_success()
        return out

    def _mutate(self, op: str, key: str, fn: Callable,
                value: bytes = b"", lease: bool = False,
                cond_key: str = "", journaled_result=None):
        """Mutations: journal while degraded (mode-gated, so replay
        ordering can never interleave with live writes); on a live
        attempt that fails, journal instead of failing the caller —
        the mutation is not lost, it is deferred to the reconcile."""
        if self.degrade_enabled and self._degraded():
            self._journal(op, key, value, lease, cond_key)
            return journaled_result
        try:
            out = fn()
        except Exception:
            self._note_failure()
            if self.degrade_enabled:
                self._journal(op, key, value, lease, cond_key)
                return journaled_result
            raise
        self._note_success()
        # a live write supersedes any pending journaled mutation of the
        # same key (a transient blip may have journaled one without
        # ever flipping the mode)
        self.journal.discard_key(key)
        self._track(op, key, value, lease, result=out)
        return out

    def _journal(self, op, key, value, lease, cond_key) -> None:
        self.journal.record(op, key, value=value, lease=lease,
                            cond_key=cond_key)
        KVSTORE_JOURNAL_DEPTH.set(self.journal.depth())
        self._track(op, key, value, lease, result=True)

    def _track(self, op, key, value, lease, result) -> None:
        """Maintain the desired-state registry of locally written
        keys (what the lease-grace repair re-asserts)."""
        with self._mu:
            if op == OP_SET:
                self._local_keys[key] = (value, lease)
            elif op in (OP_CREATE_ONLY, OP_CREATE_IF_EXISTS):
                if result:
                    self._local_keys[key] = (value, lease)
            elif op == OP_DELETE:
                self._local_keys.pop(key, None)
            elif op == OP_DELETE_PREFIX:
                for k in [k for k in self._local_keys
                          if k.startswith(key)]:
                    del self._local_keys[k]

    # ------------------------------------------------- plain ops

    def get(self, key: str):
        return self._read(lambda: self.inner.get(key), "get")

    def get_prefix(self, prefix: str):
        return self._read(lambda: self.inner.get_prefix(prefix),
                          "get_prefix")

    def list_prefix(self, prefix: str):
        return self._read(lambda: self.inner.list_prefix(prefix),
                          "list_prefix")

    def set(self, key: str, value: bytes, lease: bool = False) -> None:
        return self._mutate(
            OP_SET, key, lambda: self.inner.set(key, value, lease),
            value=value, lease=lease)

    def delete(self, key: str) -> None:
        return self._mutate(OP_DELETE, key,
                            lambda: self.inner.delete(key))

    def delete_prefix(self, prefix: str) -> None:
        return self._mutate(OP_DELETE_PREFIX, prefix,
                            lambda: self.inner.delete_prefix(prefix))

    # ------------------------------------------------- atomic ops

    def create_only(self, key: str, value: bytes,
                    lease: bool = False) -> bool:
        if not lease:
            # a non-lease CAS create (allocator master keys) must not
            # be faked: its boolean answer decides ID ownership.
            # Degraded callers take the local identity fallback instead.
            if self.degrade_enabled and self._degraded():
                raise KVStoreDegradedError(
                    f"{self.name}: degraded (create_only)")
            try:
                out = self.inner.create_only(key, value, lease)
            except Exception:
                self._note_failure()
                raise
            self._note_success()
            return out
        return self._mutate(
            OP_CREATE_ONLY, key,
            lambda: self.inner.create_only(key, value, lease),
            value=value, lease=lease, journaled_result=True)

    def create_if_exists(self, cond_key: str, key: str, value: bytes,
                         lease: bool = False) -> bool:
        if not lease:
            if self.degrade_enabled and self._degraded():
                raise KVStoreDegradedError(
                    f"{self.name}: degraded (create_if_exists)")
            try:
                out = self.inner.create_if_exists(cond_key, key, value,
                                                  lease)
            except Exception:
                self._note_failure()
                raise
            self._note_success()
            return out
        return self._mutate(
            OP_CREATE_IF_EXISTS, key,
            lambda: self.inner.create_if_exists(cond_key, key, value,
                                                lease),
            value=value, lease=lease, cond_key=cond_key,
            journaled_result=True)

    # -------------------------------------------- listing / watching

    def watch(self, prefix: str) -> Watcher:
        return self.inner.watch(prefix)

    def list_and_watch(self, prefix: str) -> Watcher:
        return self.inner.list_and_watch(prefix)

    def _remove_watcher(self, watcher: Watcher) -> None:
        self.inner._remove_watcher(watcher)

    # --------------------------------------------- locks / liveness

    def lock_path(self, path: str, timeout: float = 30.0) -> Lock:
        if self.degrade_enabled and self._degraded():
            raise KVStoreDegradedError(
                f"{self.name}: degraded (lock {path!r})")
        try:
            out = self.inner.lock_path(path, timeout)
        except Exception:
            self._note_failure()
            raise
        self._note_success()
        return out

    def _unlock(self, path: str, token: str) -> None:
        self.inner._unlock(path, token)

    def renew_lease(self) -> None:
        return self._read(lambda: self.inner.renew_lease(),
                          "renew_lease")

    def close(self) -> None:
        self.inner.close()

    def status(self) -> str:
        with self._mu:
            mode, age = self._mode, None
            if self._degraded_at is not None and mode != MODE_OK:
                age = time.monotonic() - self._degraded_at
        if mode != MODE_OK:
            return (f"{self.name}: {mode.upper()} (outage "
                    f"{age:.1f}s, serving last-known-good, "
                    f"{self.journal.depth()} journaled)")
        text = self.inner.status()
        # a dead backend reports 'unreachable' in its status string —
        # feed the detector so staleness/mode reflect it.  (Success is
        # NOT inferred from the text: only real operations and probes
        # reset the staleness clock.)
        if "unreachable" in text:
            self._note_failure()
        return text

    # ------------------------------------------------- tick/reconcile

    def track_prefix(self, prefix: str) -> None:
        """Register a prefix for the reconnect relist-and-diff repair
        (identity slave keys, ipcache entries, node registrations)."""
        with self._mu:
            if prefix not in self._tracked_prefixes:
                self._tracked_prefixes.append(prefix)

    def tick(self) -> Dict:
        """Periodic driver (the daemon's kvstore-outage controller):
        refresh gauges; while ok, probe when idle so an outage is
        detected even with no op flow; while degraded, carry the
        half-open probe and run the reconcile on reconnect.  Returns
        {"reconciled": True, ...} exactly once per recovery."""
        KVSTORE_STALENESS.set(self.staleness())
        KVSTORE_JOURNAL_DEPTH.set(self.journal.depth())
        if not self.degrade_enabled:
            return {}
        with self._mu:
            mode = self._mode
            idle = time.monotonic() - self._last_ok
        if mode == MODE_OK:
            if idle >= self.probe_interval:
                try:
                    self.inner.get(PROBE_KEY)
                    self._note_success()
                except Exception:  # noqa: BLE001 — any failure counts
                    self._note_failure()
            if self.journal.depth():
                # a transient blip journaled mutations without ever
                # flipping the mode: drain them now
                try:
                    self._drain_journal()
                except Exception:  # noqa: BLE001 — stays queued
                    pass
                KVSTORE_JOURNAL_DEPTH.set(self.journal.depth())
            return {}
        # degraded: only the breaker's half-open slot probes
        if not self._breaker.allow():
            return {}
        try:
            self.inner.get(PROBE_KEY)
        except Exception:  # noqa: BLE001
            self._note_failure()
            return {}
        # reconnected: reconcile before announcing ok
        with self._mu:
            self._set_mode_locked(MODE_RECONCILING)
        flight_recorder.record(
            EVENT_KVSTORE_RECONCILING,
            detail=f"{self.name}: reconnect detected; replaying "
                   f"journal + relist repair",
            journal_depth=self.journal.depth())
        ok = self._reconcile()
        if not ok:
            with self._mu:
                self._set_mode_locked(MODE_DEGRADED)
            self._breaker.trip()
            KVSTORE_RECONCILE.inc(labels={"result": "failed"})
            flight_recorder.record(
                EVENT_KVSTORE_DEGRADED,
                detail=f"{self.name}: reconcile failed mid-replay; "
                       f"journal tail stays queued",
                journal_depth=self.journal.depth())
            return {}
        self._breaker.record_success()
        with self._mu:
            self._set_mode_locked(MODE_OK)
            self._consecutive_failures = 0
            self._last_ok = time.monotonic()
            report = self._last_reconcile
        KVSTORE_RECONCILE.inc(labels={"result": "ok"})
        KVSTORE_STALENESS.set(0.0)
        KVSTORE_JOURNAL_DEPTH.set(self.journal.depth())
        flight_recorder.record(
            EVENT_KVSTORE_RECOVERED, detail=self.name,
            replayed=(report or {}).get("replayed", 0),
            repaired=(report or {}).get("repaired", 0),
            outage_s=(report or {}).get("outage-s", 0.0))
        return {"reconciled": True, "report": report}

    def _reconcile(self) -> bool:
        """Journal replay (in sequence order, rate-limited) followed by
        the relist-and-diff repair of locally owned keys over the
        tracked prefixes — divergence (a lease the server reaped
        mid-outage) is repaired with one re-put per key, never a full
        regeneration storm."""
        t0 = time.monotonic()
        with self._mu:
            outage_s = time.monotonic() - self._degraded_at \
                if self._degraded_at is not None else 0.0
            journal_depth = self.journal.depth()
            overflow = self.journal.dropped
        try:
            replayed, conflicts = self._drain_journal()
            # lease-grace repair: relist each tracked prefix once and
            # re-assert any locally owned key the outage cost us
            repaired, checked = self._repair_local_keys()
        except Exception:  # noqa: BLE001 — backend re-failed mid-
            return False   # reconcile; the journal tail stays queued
        self._last_reconcile = {
            "duration-s": round(time.monotonic() - t0, 4),
            "outage-s": round(outage_s, 3),
            "journal-depth": journal_depth,
            "replayed": replayed,
            "conflicts": conflicts,
            "repaired": repaired,
            "local-keys-checked": checked,
            "journal-overflowed": overflow,
            "exceeded-grace": outage_s > self.grace_s,
        }
        return True

    def _drain_journal(self) -> "tuple[int, int]":
        """Replay pending journal entries in sequence order, looping
        until the journal drains (mutations racing in while replaying
        land in later snapshots).  Raises on a backend failure — the
        unapplied tail stays queued for the next attempt."""
        replayed = conflicts = 0
        while True:
            batch = self.journal.snapshot()
            if not batch:
                return replayed, conflicts
            for entry in batch:
                if entry.op == OP_SET:
                    self.inner.set(entry.key, entry.value, entry.lease)
                elif entry.op == OP_DELETE:
                    self.inner.delete(entry.key)
                elif entry.op == OP_DELETE_PREFIX:
                    self.inner.delete_prefix(entry.key)
                elif entry.op == OP_CREATE_ONLY:
                    if not self.inner.create_only(
                            entry.key, entry.value, entry.lease):
                        conflicts += 1
                elif entry.op == OP_CREATE_IF_EXISTS:
                    if not self.inner.create_if_exists(
                            entry.cond_key, entry.key,
                            entry.value, entry.lease):
                        conflicts += 1
                self.journal.discard(entry)
                replayed += 1
                if self._replay_sleep:
                    time.sleep(self._replay_sleep)

    def _repair_local_keys(self) -> "tuple[int, int]":
        with self._mu:
            tracked = list(self._tracked_prefixes)
            desired = dict(self._local_keys)
        repaired = checked = 0
        actual: Dict[str, bytes] = {}
        covered: List[str] = []
        for prefix in tracked:
            actual.update(self.inner.list_prefix(prefix))
            covered.append(prefix)
        for key, (value, lease) in desired.items():
            in_tracked = any(key.startswith(p) for p in covered)
            checked += 1
            current = actual.get(key) if in_tracked \
                else self.inner.get(key)
            if current != value:
                self.inner.set(key, value, lease)
                repaired += 1
            if self._replay_sleep:
                time.sleep(self._replay_sleep)
        return repaired, checked

    # ------------------------------------------------------ reporting

    def report(self) -> Dict:
        """The status() view: mode, staleness, breaker, journal."""
        with self._mu:
            out = {
                "mode": self._mode,
                "degrade-enabled": self.degrade_enabled,
                "staleness-seconds": round(self.staleness(), 3),
                "consecutive-failures": self._consecutive_failures,
                "breaker": self._breaker.state,
                "outages": self._outages,
                "grace-seconds": self.grace_s,
                "local-keys": len(self._local_keys),
                "last-reconcile": self._last_reconcile,
            }
        out.update({"journal": self.journal.stats(),
                    "journal-depth": self.journal.depth()})
        return out
