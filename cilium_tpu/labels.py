"""Label model: sources, parsing, extended keys, sorted arrays, identity hash.

Semantics follow the reference's ``pkg/labels`` (labels.go, array.go,
cidr.go, filter.go): a label is ``(key, value, source)``; its *extended key*
encodes the source as ``source.key`` (with the special wildcard source
``any``); a set of labels has a deterministic sorted string form whose
SHA-256 is the security-identity key.
"""

from __future__ import annotations

import hashlib
import ipaddress
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

PATH_DELIMITER = "."

# Special label names (reference: pkg/labels/labels.go:31-57)
ID_NAME_ALL = "all"
ID_NAME_HOST = "host"
ID_NAME_WORLD = "world"
ID_NAME_CLUSTER = "cluster"
ID_NAME_HEALTH = "health"
ID_NAME_INIT = "init"
ID_NAME_UNMANAGED = "unmanaged"
ID_NAME_UNKNOWN = "unknown"

# Label sources (reference: pkg/labels/labels.go:128-156)
SOURCE_UNSPEC = "unspec"
SOURCE_ANY = "any"
SOURCE_K8S = "k8s"
SOURCE_MESOS = "mesos"
SOURCE_CONTAINER = "container"
SOURCE_RESERVED = "reserved"
SOURCE_CIDR = "cidr"

ANY_PREFIX = SOURCE_ANY + PATH_DELIMITER
RESERVED_PREFIX = SOURCE_RESERVED + PATH_DELIMITER


@dataclass(frozen=True)
class Label:
    """A single label ``source:key=value``.

    Reference: pkg/labels/labels.go (struct Label).
    """

    key: str
    value: str = ""
    source: str = SOURCE_UNSPEC

    def __post_init__(self):
        if self.source == "":
            object.__setattr__(self, "source", SOURCE_UNSPEC)

    @property
    def extended_key(self) -> str:
        """Key with the source encoded; unspec maps to the wildcard source.

        Reference: pkg/labels/labels.go:418 (GetExtendedKey).
        """
        src = self.source
        if src == SOURCE_UNSPEC or src == "":
            src = SOURCE_ANY
        return src + PATH_DELIMITER + self.key

    def is_reserved(self) -> bool:
        return self.source == SOURCE_RESERVED

    def matches_extended_key(self, ext_key: str) -> bool:
        """True if this label is named by ``ext_key`` (``any.`` matches all
        sources)."""
        if ext_key.startswith(ANY_PREFIX):
            return self.key == ext_key[len(ANY_PREFIX):]
        return self.extended_key == ext_key

    def __str__(self) -> str:
        if self.value:
            return f"{self.source}:{self.key}={self.value}"
        return f"{self.source}:{self.key}"

    def sort_key(self) -> Tuple[str, str, str]:
        return (self.source, self.key, self.value)


def parse_label(text: str) -> Label:
    """Parse ``source:key=value`` (source and value optional).

    Reference: pkg/labels/labels.go (ParseLabel). A ``$`` prefix is the
    shorthand for the reserved source (``$host`` == ``reserved:host``).
    """
    source = SOURCE_UNSPEC
    if text.startswith("$"):
        text = RESERVED_PREFIX.replace(".", ":") + text[1:]
    # Split source on the first ':' that appears before any '='.
    eq = text.find("=")
    colon = text.find(":")
    if colon >= 0 and (eq < 0 or colon < eq):
        source, text = text[:colon] or SOURCE_UNSPEC, text[colon + 1:]
    eq = text.find("=")
    if eq < 0:
        key, value = text, ""
    else:
        key, value = text[:eq], text[eq + 1:]
    if source == SOURCE_RESERVED and key == "" and value != "":
        # "reserved:=host" edge: treat value as key
        key, value = value, ""
    return Label(key=key, value=value, source=source)


def parse_select_label(text: str) -> Label:
    """Parse a label used for *selecting* (unspec source becomes ``any``).

    Reference: pkg/labels/labels.go (ParseSelectLabel).
    """
    lbl = parse_label(text)
    if lbl.source == SOURCE_UNSPEC:
        return Label(key=lbl.key, value=lbl.value, source=SOURCE_ANY)
    return lbl


class LabelArray(tuple):
    """An immutable set-like array of labels (reference: pkg/labels/array.go)."""

    def __new__(cls, labels: Iterable[Label] = ()):
        return super().__new__(cls, tuple(labels))

    @classmethod
    def parse(cls, *labels: str) -> "LabelArray":
        return cls(parse_label(s) for s in labels)

    @classmethod
    def parse_select(cls, *labels: str) -> "LabelArray":
        return cls(parse_select_label(s) for s in labels)

    def has(self, ext_key: str) -> bool:
        """True if any label's extended key matches (``any.`` wildcard aware).

        Reference: pkg/labels/array.go:92 (Has).
        """
        return any(l.matches_extended_key(ext_key) for l in self)

    def get(self, ext_key: str) -> str:
        """Value of the label named by ``ext_key`` ('' if absent).

        Reference: pkg/labels/array.go:114 (Get).
        """
        for l in self:
            if l.matches_extended_key(ext_key):
                return l.value
        return ""

    def contains(self, needed: "LabelArray") -> bool:
        """True if every needed label is present (source+key+value equal).

        Reference: pkg/labels/array.go:58 (Contains).
        """
        return all(n in self for n in needed)

    def sorted(self) -> "LabelArray":
        return LabelArray(sorted(self, key=Label.sort_key))

    def get_model(self) -> List[str]:
        return [str(l) for l in self]

    def __repr__(self) -> str:
        return "LabelArray[" + ", ".join(str(l) for l in self) + "]"


class Labels(dict):
    """Mutable map key->Label (reference: pkg/labels/labels.go type Labels)."""

    @classmethod
    def from_model(cls, model: Sequence[str]) -> "Labels":
        lbls = cls()
        for s in model:
            l = parse_label(s)
            lbls[l.key] = l
        return lbls

    @classmethod
    def from_labels(cls, labels: Iterable[Label]) -> "Labels":
        lbls = cls()
        for l in labels:
            lbls[l.key] = l
        return lbls

    def to_array(self) -> LabelArray:
        return LabelArray(sorted(self.values(), key=Label.sort_key))

    def sorted_list(self) -> bytes:
        """Deterministic serialized form used as the identity key.

        Reference: pkg/labels/labels.go (SortedList): sorted by source
        then key, ``source:key=value;`` concatenated.
        """
        parts = []
        for l in sorted(self.values(), key=Label.sort_key):
            parts.append(f"{l.source}:{l.key}={l.value};")
        return "".join(parts).encode()

    def sha256_sum(self) -> str:
        """SHA-256 of the sorted list (reference uses SHA-512/256; a stable
        strong hash is what matters, not the exact algorithm)."""
        return hashlib.sha256(self.sorted_list()).hexdigest()

    def get_model(self) -> List[str]:
        return [str(l) for l in sorted(self.values(), key=Label.sort_key)]

    def equals(self, other: "Labels") -> bool:
        return self.sorted_list() == other.sorted_list()


# --- reserved label helpers -------------------------------------------------

def reserved_label(name: str) -> Label:
    return Label(key=name, value="", source=SOURCE_RESERVED)


LABEL_HOST = reserved_label(ID_NAME_HOST)
LABEL_WORLD = reserved_label(ID_NAME_WORLD)
LABEL_HEALTH = reserved_label(ID_NAME_HEALTH)
LABEL_INIT = reserved_label(ID_NAME_INIT)
LABEL_UNMANAGED = reserved_label(ID_NAME_UNMANAGED)
LABEL_ALL = reserved_label(ID_NAME_ALL)


# --- CIDR labels ------------------------------------------------------------

def _cidr_label_string(net: ipaddress._BaseNetwork) -> str:
    # Label keys may not contain ':' or '/'; encode like the reference
    # (pkg/labels/cidr.go): dots/colons to '-', prefix with 'cidr:'.
    s = str(net.network_address)
    s = s.replace(":", "-").replace(".", "-")
    return f"{s}--{net.prefixlen}" if net.version == 6 else f"{s}-{net.prefixlen}"


def get_cidr_labels(cidr: str) -> LabelArray:
    """Expand a CIDR into one label per covering prefix plus world.

    Reference: pkg/labels/cidr.go (GetCIDRLabels): a /24 yields labels for
    /0../24 so a broader policy CIDR selects the narrower identity.
    """
    net = ipaddress.ip_network(cidr, strict=False)
    out: List[Label] = []
    for plen in range(net.prefixlen + 1):
        covering = ipaddress.ip_network(f"{net.network_address}/{plen}",
                                        strict=False)
        out.append(Label(key=_cidr_label_string(covering), source=SOURCE_CIDR))
    out.append(LABEL_WORLD)
    return LabelArray(out)


def _mask_int(plen: int, version: int) -> int:
    bits = 32 if version == 4 else 128
    if plen == 0:
        return 0
    return ((1 << plen) - 1) << (bits - plen)


def ip_to_cidr_label(ip_str: str) -> Label:
    net = ipaddress.ip_network(ip_str, strict=False)
    return Label(key=_cidr_label_string(net), source=SOURCE_CIDR)
