"""Device mesh construction + canonical shardings for the datapath."""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"   # packet-batch data parallelism (ICI)
EP_AXIS = "ep"   # endpoint-table sharding (model-parallel analog)


def make_mesh(n_devices: Optional[int] = None,
              ep_parallel: int = 1) -> Mesh:
    """A (dp, ep) mesh over the first ``n_devices`` devices.

    ``ep_parallel`` splits devices between batch parallelism and endpoint
    table sharding; default keeps everything on the dp axis.  Asking for
    more devices than the backend exposes is an error, never a silent
    under-provision: a dataplane that believes it spans N fault domains
    but actually spans fewer would mis-scope every per-shard decision.
    """
    avail = jax.devices()
    if n_devices is not None and n_devices > len(avail):
        raise ValueError(
            f"requested {n_devices} devices but only {len(avail)} "
            f"available")
    devs = avail[:n_devices] if n_devices else avail
    n = len(devs)
    if ep_parallel < 1 or n % ep_parallel != 0:
        raise ValueError(f"{n} devices not divisible by ep={ep_parallel}")
    arr = np.array(devs).reshape(n // ep_parallel, ep_parallel)
    return Mesh(arr, axis_names=(DP_AXIS, EP_AXIS))


def ep_submesh(mesh: Mesh, shard: int) -> Mesh:
    """Shard ``shard``'s (dp, 1) column submesh: the devices that hold
    that shard's endpoint-table slice.  Each shard's compiled program
    spans exactly its own column, so a device loss in one column is a
    single-shard fault domain, not a whole-mesh outage."""
    n_ep = mesh.devices.shape[1]
    if not 0 <= shard < n_ep:
        raise ValueError(f"shard {shard} out of range for ep={n_ep}")
    return Mesh(mesh.devices[:, shard:shard + 1],
                axis_names=(DP_AXIS, EP_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[B, ...] tensors: shard the batch across dp, replicate across ep."""
    return NamedSharding(mesh, P(DP_AXIS))


def packed_batch_sharding(mesh: Mesh) -> NamedSharding:
    """[F, B] packed field matrices (pipeline.PACKED_FIELDS rows):
    shard the batch axis (axis 1) across dp."""
    return NamedSharding(mesh, P(None, DP_AXIS))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """[E, S] policy tables: shard the endpoint axis across ep."""
    return NamedSharding(mesh, P(EP_AXIS, None))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree, batch: Optional[int] = None):
    """Place [B]-leading leaves with batch sharding, everything else
    replicated.

    ``batch`` names B explicitly; when omitted it is inferred from the
    first array leaf's leading dimension.  Only leaves whose leading
    dimension equals B (and divides evenly across dp) are sharded —
    scalars, tables and oddly-shaped leaves are replicated onto the
    mesh instead of being sliced along the wrong axis.
    """
    leaves = [x for x in jax.tree.leaves(tree)
              if getattr(x, "ndim", 0) >= 1]
    if batch is None:
        if not leaves:
            return tree
        batch = int(np.shape(leaves[0])[0])
    dp = mesh.devices.shape[0]
    sh = batch_sharding(mesh)
    rep = replicate(mesh)

    def place(x):
        nd = getattr(x, "ndim", 0)
        if nd >= 1 and int(np.shape(x)[0]) == batch and batch % dp == 0:
            return jax.device_put(x, sh)
        return jax.device_put(x, rep)
    return jax.tree.map(place, tree)
