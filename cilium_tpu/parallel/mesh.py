"""Device mesh construction + canonical shardings for the datapath."""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DP_AXIS = "dp"   # packet-batch data parallelism (ICI)
EP_AXIS = "ep"   # endpoint-table sharding (model-parallel analog)


def make_mesh(n_devices: Optional[int] = None,
              ep_parallel: int = 1) -> Mesh:
    """A (dp, ep) mesh over the first ``n_devices`` devices.

    ``ep_parallel`` splits devices between batch parallelism and endpoint
    table sharding; default keeps everything on the dp axis.
    """
    devs = jax.devices()[:n_devices] if n_devices else jax.devices()
    n = len(devs)
    if n % ep_parallel != 0:
        raise ValueError(f"{n} devices not divisible by ep={ep_parallel}")
    arr = np.array(devs).reshape(n // ep_parallel, ep_parallel)
    return Mesh(arr, axis_names=(DP_AXIS, EP_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[B, ...] tensors: shard the batch across dp, replicate across ep."""
    return NamedSharding(mesh, P(DP_AXIS))


def table_sharding(mesh: Mesh) -> NamedSharding:
    """[E, S] policy tables: shard the endpoint axis across ep."""
    return NamedSharding(mesh, P(EP_AXIS, None))


def replicate(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, tree):
    """Place every [B]-leading leaf with batch sharding."""
    sh = batch_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sh), tree)
