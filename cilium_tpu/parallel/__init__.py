"""Mesh/sharding: how the verdict dataplane scales over TPU chips.

The reference scales per-packet work across CPUs/NICs (per-CPU BPF maps,
RSS) and across nodes via kvstore replication. Here the analogs are:
  * ``dp`` mesh axis — the packet batch is sharded across chips (ICI);
  * ``ep`` mesh axis — the stacked per-endpoint policy tables shard
    across chips, one slice + fault domain per shard
    (``sharded.ShardedDatapath``);
  * control-plane replication (kvstore) stays host-side over DCN.

``specs.py`` is the canonical PartitionSpec registry for every device
table leaf (lint-enforced); ``sharded.py`` is the sharded dataplane
with per-shard supervisors and partial-mesh survival.
"""

from .mesh import (DP_AXIS, EP_AXIS, batch_sharding, ep_submesh,
                   make_mesh, packed_batch_sharding, replicate,
                   shard_batch, table_sharding)
from .sharded import (ShardedDatapath, ShardedServingLane,
                      ShardedTableManager, ShardedTicket, global_slot,
                      local_slot, shard_of_slot)
