"""Mesh/sharding helpers: how the datapath scales over TPU chips.

The reference scales per-packet work across CPUs/NICs (per-CPU BPF maps,
RSS) and across nodes via kvstore replication. Here the analogs are:
  * ``dp`` mesh axis — the packet batch is sharded across chips (ICI);
  * ``ep`` mesh axis — stacked per-endpoint policy tables can shard
    across chips when the table set outgrows one chip's HBM;
  * control-plane replication (kvstore) stays host-side over DCN.
"""

from .mesh import (make_mesh, shard_batch, replicate, batch_sharding,
                   table_sharding)
