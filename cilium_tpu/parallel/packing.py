"""The dispatch-floor packing manifest: the table leaf zoo collapsed
into a handful of grouped flat device buffers.

PR 7 measured that flattening and dispatching the ``FullTables``/CT/
flow/counter leaves costs roughly half of the per-batch CPU dispatch
floor — ~40 pytree leaves marshalled host-side on EVERY jitted-step
call, on every backend and on every shard of the mesh.  This module is
the hXDP-style compaction of what crosses the host->device dispatch
boundary: the canonical PartitionSpec registry (``parallel/specs.py``)
already enumerates every table leaf, so it doubles as the packing
manifest — leaves group by (sharding class, dtype) into concatenated
flat buffers, and the per-leaf views are reconstructed *inside* the
jitted program from static offsets (XLA fuses the slicing away; the
compiled math is unchanged, only argument marshalling moves).

Groups:

* ``ep-<dtype>``  — endpoint-axis-sharded leaves (the stacked policy
  tables + per-slot identities): one flat buffer per shard slice.
* ``rep-<dtype>`` — replicated address-keyed leaves (ipcache/LPM, LB,
  prefilter, tunnel): every shard holds a full copy.
* ``ct-state`` / ``counters`` — the donated mutable state packs owned
  by ``datapath/conntrack.py`` and the engine ([8, N+1] and [2, E*S]
  matrices; packed natively, no per-step repack).

Every group name must carry a declared PartitionSpec in
``specs.PACKED_GROUP_SPECS`` — held by ``tests/test_sharding_lint.py``
alongside the jitted-step leaf-count ceiling, so new leaves can't
silently regrow the dispatch floor.
"""

from __future__ import annotations

from typing import Dict, List, NamedTuple, Optional, Tuple

import jax.numpy as jnp

# the engine-owned mutable state packs (not manifest-built, but part
# of the same lint-enforced group namespace)
CT_STATE_GROUP = "ct-state"
COUNTERS_GROUP = "counters"
# the two-leaf Hubble flow pack (hubble/aggregation.py FlowState):
# keys buffer carries the lost/updates accounting row, counters stay
# their own uint32 buffer along the dtype boundary
FLOW_STATE_GROUP = "flow-state"
# the fused L7 fast-verdict DFA table set (l7/fast.py) packs into its
# OWN group instead of riding rep-int32: a no-L7 engine then builds
# the exact pre-fast buffer list, keeping that program byte-identical
# at the pinned leaf ceiling (the per-slot l7_prog classification
# shards with the policy rows and stays in ep-int32)
L7_DFA_GROUP = "l7-dfa"
_L7_DFA_LEAVES = frozenset(
    ("l7_flat", "l7_map", "l7_accept", "l7_starts", "l7_pmask"))
# the inline threat-scoring model (threat/model.py) packs into its OWN
# group for the same reason: a no-threat engine builds the exact
# pre-threat buffer list, and a weight push / threshold flip is a
# region write into this one buffer (engine apply_threat_weights /
# set_threat_config), never a repack
THREAT_MODEL_GROUP = "threat-model"
_THREAT_MODEL_LEAVES = frozenset(
    ("tm_w1", "tm_b1", "tm_w2", "tm_b2", "tm_cfg"))
# the engine-owned mutable threat buffer (threat/stage.ThreatState):
# not manifest-built, same lint-enforced group namespace as ct-state
THREAT_STATE_GROUP = "threat-state"
# the engine-owned traffic-analytics buffer (analytics/stage.
# AnalyticsState): sketches + key tables + cardinality registers as
# one [R, W] int32 leaf — not manifest-built, same lint-enforced
# group namespace as ct-state/threat-state
ANALYTICS_STATE_GROUP = "analytics-state"


class LeafSlot(NamedTuple):
    """One table leaf's view into its group buffer."""

    path: str                 # dotted leaf path (specs.py convention)
    group: str                # owning group buffer name
    offset: int               # flat element offset inside the group
    size: int                 # element count
    shape: Tuple[int, ...]    # static view shape


class GroupSpec(NamedTuple):
    name: str                 # "<class>-<dtype>", e.g. "ep-int32"
    dtype: str
    size: int                 # total flat elements


class PackManifest(NamedTuple):
    """Static packing layout for one table class instance.  Pure
    tuples: hashable and comparable, so geometry changes are detected
    by manifest inequality."""

    cls_name: str
    leaves: Tuple[LeafSlot, ...]
    groups: Tuple[GroupSpec, ...]

    def group_names(self) -> Tuple[str, ...]:
        return tuple(g.name for g in self.groups)

    def leaf_count(self) -> int:
        return len(self.leaves)

    def leaf(self, path: str) -> Optional[LeafSlot]:
        for l in self.leaves:
            if l.path == path:
                return l
        return None


def _classes():
    from ..datapath.pipeline import FullTables, FullTables6
    return {"FullTables": FullTables, "FullTables6": FullTables6}


def _nested_for(cls_name: str) -> Dict[str, type]:
    from ..datapath.lb import LB6Tables, LBTables
    from ..datapath.pipeline import DatapathTables, LPM6Tables
    return {
        "FullTables": {"datapath": DatapathTables, "lb": LBTables},
        "FullTables6": {"ipcache6": LPM6Tables, "pf6": LPM6Tables,
                        "lb6": LB6Tables},
    }.get(cls_name, {})


def _walk(obj, prefix: str = ""):
    """(dotted path, array) for every present (non-None) leaf, in
    field-declaration order — the stable packing order."""
    for f in type(obj)._fields:
        v = getattr(obj, f)
        if v is None:
            continue
        if hasattr(v, "_fields"):
            yield from _walk(v, prefix + f + ".")
        else:
            yield prefix + f, v


def _sharding_class(spec) -> str:
    """ep (endpoint-axis sharded) vs rep (replicated): any mesh axis
    in the declared spec means the leaf's rows belong to one shard."""
    for axis in spec:
        if axis is not None:
            return "ep"
    return "rep"


def build_manifest(tables) -> PackManifest:
    """Packing manifest for one table instance, grouped by (declared
    sharding class, dtype) from the canonical spec registry.  A leaf
    without a registry entry is an error here exactly like it is in
    the sharding lint — new leaves must declare their distribution."""
    from . import specs
    cls_name = type(tables).__name__
    spec_table = specs.registry()[cls_name]
    leaves: List[LeafSlot] = []
    offsets: Dict[str, int] = {}
    dtypes: Dict[str, str] = {}
    for path, arr in _walk(tables):
        spec = spec_table[path]
        dt = str(arr.dtype)
        if path in _L7_DFA_LEAVES:
            group = L7_DFA_GROUP
        elif path in _THREAT_MODEL_LEAVES:
            group = THREAT_MODEL_GROUP
        else:
            group = f"{_sharding_class(spec)}-{dt}"
        off = offsets.get(group, 0)
        size = int(arr.size)
        leaves.append(LeafSlot(path=path, group=group, offset=off,
                               size=size, shape=tuple(arr.shape)))
        offsets[group] = off + size
        dtypes[group] = dt
    groups = tuple(GroupSpec(name=g, dtype=dtypes[g], size=offsets[g])
                   for g in offsets)
    return PackManifest(cls_name=cls_name, leaves=tuple(leaves),
                        groups=groups)


def pack_groups(tables, manifest: PackManifest
                ) -> Tuple[jnp.ndarray, ...]:
    """Concatenate the leaves into their group buffers (device concat;
    control-plane cost, paid once per table generation — never per
    batch).  Returns buffers ordered like ``manifest.groups``."""
    vals = dict(_walk(tables))
    out = []
    for g in manifest.groups:
        parts = [vals[l.path].reshape(-1)
                 for l in manifest.leaves if l.group == g.name]
        out.append(parts[0] if len(parts) == 1
                   else jnp.concatenate(parts))
    return tuple(out)


def unpacker(manifest: PackManifest):
    """Closure rebuilding the table NamedTuple from the group buffers
    INSIDE the jitted program: static slices + reshapes that XLA fuses
    into the consuming gathers — the per-batch flatten cost moves into
    the compiled program where it is free."""
    cls = _classes()[manifest.cls_name]
    nested = _nested_for(manifest.cls_name)
    names = manifest.group_names()

    def unpack(bufs: Tuple[jnp.ndarray, ...]):
        by_group = dict(zip(names, bufs))
        vals = {l.path: by_group[l.group][l.offset:l.offset + l.size]
                .reshape(l.shape) for l in manifest.leaves}
        kwargs = {}
        for f in cls._fields:
            sub_cls = nested.get(f)
            if sub_cls is not None:
                pref = f + "."
                sub = {p[len(pref):]: v for p, v in vals.items()
                       if p.startswith(pref)}
                kwargs[f] = sub_cls(**sub) if sub else None
            else:
                kwargs[f] = vals.get(f)
        return cls(**kwargs)

    return unpack


# ---------------------------------------------------------------------------
# Delta-apply write-through: one endpoint row -> three scatters into
# the packed policy slices, no full repack.
# ---------------------------------------------------------------------------

_POLICY_ROWS = {  # canonical name -> leaf path per table class
    "FullTables": ("datapath.key_id", "datapath.key_meta",
                   "datapath.value"),
    "FullTables6": ("key_id", "key_meta", "value"),
}


def make_policy_row_writer(manifest: PackManifest):
    """(jitted writer, group index) realizing dirty endpoint rows in
    the packed policy slices: ``writer(buf, slots [D], kid [D, S],
    kmeta [D, S], kval [D, S]) -> buf``.  One scatter covers all three
    regions; the single-rule delta stays a row write, never a repack."""
    import jax

    paths = _POLICY_ROWS[manifest.cls_name]
    slots_ = [manifest.leaf(p) for p in paths]
    if any(l is None for l in slots_):
        raise KeyError(f"policy rows missing from {manifest.cls_name} "
                       "manifest")
    group = slots_[0].group
    if any(l.group != group for l in slots_):
        raise ValueError("policy row leaves split across groups")
    gidx = manifest.group_names().index(group)
    offs = tuple(l.offset for l in slots_)
    n_slots = slots_[0].shape[1]

    def write(buf, slots, kid, kmeta, kval):
        col = jnp.arange(n_slots, dtype=jnp.int32)[None, :]
        base = slots[:, None].astype(jnp.int32) * n_slots + col
        idx = jnp.concatenate([(o + base).reshape(-1) for o in offs])
        vals = jnp.concatenate([kid.reshape(-1), kmeta.reshape(-1),
                                kval.reshape(-1)])
        return buf.at[idx].set(vals)

    return jax.jit(write), gidx


def make_l7_prog_row_writer(manifest: PackManifest):
    """Row writer for the per-slot L7 classification table: the
    delta-apply twin of :func:`make_policy_row_writer` for the
    ``l7_prog`` leaf, so an L7 rule change on the refresh fast path
    stays a row scatter.  Returns None when the manifest carries no
    l7_prog leaf (fast verdicts disabled)."""
    import jax

    leaf = manifest.leaf("l7_prog")
    if leaf is None:
        return None
    gidx = manifest.group_names().index(leaf.group)
    off = leaf.offset
    n_slots = leaf.shape[1]

    def write(buf, slots, rows):
        col = jnp.arange(n_slots, dtype=jnp.int32)[None, :]
        idx = off + slots[:, None].astype(jnp.int32) * n_slots + col
        return buf.at[idx.reshape(-1)].set(rows.reshape(-1))

    return jax.jit(write), gidx


def write_leaf(manifest: PackManifest, bufs: Tuple[jnp.ndarray, ...],
               path: str, arr) -> Optional[Tuple[jnp.ndarray, ...]]:
    """Write one whole leaf's region into its group buffer (eager,
    control-plane).  Returns the new buffer tuple, or None when the
    leaf is absent from the manifest or its shape changed — the caller
    must rebuild (geometry change re-packs and re-jits)."""
    leaf = manifest.leaf(path)
    if leaf is None or tuple(arr.shape) != leaf.shape:
        return None
    gidx = manifest.group_names().index(leaf.group)
    out = list(bufs)
    out[gidx] = out[gidx].at[leaf.offset:leaf.offset + leaf.size].set(
        arr.reshape(-1))
    return tuple(out)
