"""The sharded verdict dataplane: the full fused pipeline distributed
across the (dp, ep) device mesh with per-shard fault domains.

Cilium keeps enforcing per-node state when an agent dies; the mesh
analog must keep enforcing per-SHARD state when a device dies.  This
module is that analog, the Taurus per-unit-state-residency argument
applied to the verdict engine:

- **Endpoint-axis sharding.**  The stacked per-endpoint policy tables
  shard across the ``ep`` mesh axis: shard k owns the endpoint slots
  with ``slot % n_shards == k`` (its slice of the logical [E, S]
  stack), realized as that shard's own compiled pipeline resident on
  its (dp, 1) column submesh (``mesh.ep_submesh``).  Packet batches
  shard across ``dp`` inside each column (pjit follows the committed
  shardings the engine placed — ``Datapath.set_mesh_placement``).
  The canonical PartitionSpec of every table leaf lives in
  ``parallel/specs.py`` and is lint-enforced.

- **Shard-local mutable state.**  Conntrack, flow aggregation and
  counters are per shard: a shard's flows belong to its endpoints, so
  CT residency follows table residency and GC sweeps shard-locally
  (``gc`` fans out; per-shard occupancy feeds the shard-labelled
  pressure gauges).

- **Per-shard fault domains.**  Each shard's serving lane runs its own
  ``DeviceSupervisor`` (shard-scoped breaker, watchdog, fault
  accounting — datapath/supervisor.py): when shard k trips, ONLY
  endpoints mapped to shard k serve FAIL-STATIC from that shard's
  ``HostStaticOracle`` (established flows keep their verdicts,
  ``degraded_new_flow_policy`` applies) while every other shard keeps
  serving bit-exact on device — no global pause.  Breaker-gated
  recovery rebuilds and drift-audits only shard k's table slice from
  its host-of-record.

Because each shard's program spans exactly its own column, a lost
device is a single-shard outage by construction — the partial-mesh
survival property the whole-mesh-pjit alternative cannot give (one
program over all devices dies with any of them).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..datapath.engine import Datapath
from ..datapath.events import DROP_POLICY
from ..datapath.pipeline import PACKED_FIELDS
from ..endpoint.tables import DeviceTableManager
from ..observability.pressure import (MAP_ENTRIES, MAP_PRESSURE,
                                      compute_pressure)
from ..policy.mapstate import PolicyMapState
from ..utils.metrics import DATAPLANE_MODE
from .mesh import EP_AXIS, ep_submesh, make_mesh

_MODE_RANK = {"ok": 0, "recovering": 1, "degraded": 2}
_MODE_CODE = {"ok": 0.0, "degraded": 1.0, "recovering": 2.0}


# ---------------------------------------------------------------------------
# Endpoint <-> shard mapping
# ---------------------------------------------------------------------------
#
# Global table slots interleave across shards: global slot g lives on
# shard g % n_shards at local slot g // n_shards.  Interleaving (vs
# contiguous blocks) lets every shard grow independently without
# renumbering anyone else's slots — the same reason consistent-hash
# rings interleave ownership.

def shard_of_slot(global_slot: int, n_shards: int) -> int:
    return int(global_slot) % n_shards


def local_slot(global_slot: int, n_shards: int) -> int:
    return int(global_slot) // n_shards


def global_slot(shard: int, local: int, n_shards: int) -> int:
    return int(local) * n_shards + int(shard)


class ShardedTableManager:
    """Per-shard ``DeviceTableManager``s behind the single-manager
    interface the daemon drives: ``attach``/``sync_endpoint`` touch
    ONLY the owning shard's device slice (one row write on one shard's
    tensors), and a grow on one shard re-jits one shard's program —
    the delta-apply blast radius is one fault domain, not the mesh."""

    def __init__(self, n_shards: int, initial_endpoints: int = 8,
                 initial_slots: int = 64, max_load: float = 0.5):
        if n_shards < 1:
            raise ValueError("n_shards must be >= 1")
        self.n_shards = n_shards
        self.shards = [DeviceTableManager(initial_endpoints,
                                          initial_slots, max_load)
                       for _ in range(n_shards)]

    def shard_of_endpoint(self, endpoint_id: int) -> int:
        """Deterministic endpoint -> shard mapping (stable across
        restarts: re-attached endpoints land on the same shard, so a
        restored CT checkpoint stays shard-consistent)."""
        return int(endpoint_id) % self.n_shards

    def attach(self, endpoint_id: int) -> int:
        k = self.shard_of_endpoint(endpoint_id)
        local = self.shards[k].attach(endpoint_id)
        return global_slot(k, local, self.n_shards)

    def detach(self, endpoint_id: int) -> None:
        self.shards[self.shard_of_endpoint(endpoint_id)].detach(
            endpoint_id)

    def slot_of(self, endpoint_id: int) -> Optional[int]:
        k = self.shard_of_endpoint(endpoint_id)
        local = self.shards[k].slot_of(endpoint_id)
        if local is None:
            return None
        return global_slot(k, local, self.n_shards)

    def sync_endpoint(self, endpoint_id: int, state, revision: int
                      ) -> Dict:
        k = self.shard_of_endpoint(endpoint_id)
        out = self.shards[k].sync_endpoint(endpoint_id, state,
                                           revision)
        return {**out, "shard": k}

    def states_by_slot(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        for k, mgr in enumerate(self.shards):
            for local, st in mgr.states_by_slot().items():
                out[global_slot(k, local, self.n_shards)] = st
        return out

    def stats(self) -> Dict:
        per = [mgr.stats() for mgr in self.shards]
        return {"shards": self.n_shards,
                "endpoints": sum(s["endpoints"] for s in per),
                "capacity": sum(s["capacity"] for s in per),
                "nbytes": sum(s["nbytes"] for s in per),
                "revision": max(s["revision"] for s in per),
                "per-shard": per}


# ---------------------------------------------------------------------------
# Sharded serving lane
# ---------------------------------------------------------------------------

class ShardedTicket:
    """One submission's future across shard lanes: resolves when every
    owning shard's ticket resolves, reassembling per-record results in
    submission order.  A degraded shard's rows carry its fail-static
    answers (no error); a genuinely failed shard's rows carry its
    fail-closed denies and the ticket surfaces that shard's error."""

    def __init__(self, n: int,
                 parts: Sequence[Tuple[np.ndarray, object]]):
        self._n = n
        self._parts = list(parts)
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks: List[Callable] = []
        self._remaining = len(self._parts)
        self.value = None
        self.error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        if not self._parts:
            self._finish()
        else:
            for _idx, ticket in self._parts:
                ticket.add_done_callback(self._part_done)

    def _part_done(self, _ticket) -> None:
        with self._lock:
            self._remaining -= 1
            if self._remaining != 0:
                return
        self._finish()

    def _finish(self) -> None:
        verdict = np.full(self._n, DROP_POLICY, np.int32)
        identity = np.zeros(self._n, np.int32)
        error = None
        for idx, ticket in self._parts:
            if ticket.value is not None:
                verdict[idx] = ticket.value[0]
                identity[idx] = ticket.value[1]
            if error is None and ticket.error is not None:
                error = ticket.error
        self.value = (verdict, identity)
        self.error = error
        with self._lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a bad callback must
                pass           # not poison a shard dispatcher thread

    def add_done_callback(self, cb: Callable) -> None:
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError("sharded ticket not resolved in time")
        return self.value


class ShardedServingLane:
    """The mesh-wide serving facade: splits each submitted SoA record
    chunk by owning shard (``endpoint % n_shards``), rewrites endpoint
    slots to shard-local, and fans the pieces into the per-shard
    continuous micro-batching lanes.  Each piece rides its own shard's
    dispatcher, supervisor and fault domain."""

    def __init__(self, plane: "ShardedDatapath"):
        self.plane = plane
        self.lanes = [sh.serving() for sh in plane.shards]

    def submit_records(self, soa: Dict[str, np.ndarray], n: int,
                       deadline: Optional[float] = None,
                       payload: Optional[np.ndarray] = None
                       ) -> ShardedTicket:
        n = int(n)
        n_shards = self.plane.n_shards
        endpoint = soa["endpoint"][:n]
        owner = endpoint % n_shards
        parts = []
        for k, lane in enumerate(self.lanes):
            idx = np.flatnonzero(owner == k)
            if idx.size == 0:
                continue
            sub = {f: np.ascontiguousarray(soa[f][:n][idx],
                                           dtype=np.int32)
                   for f in PACKED_FIELDS}
            sub["endpoint"] = (sub["endpoint"]
                               // n_shards).astype(np.int32)
            pl = None if payload is None else \
                np.ascontiguousarray(payload[:n][idx])
            parts.append((idx, lane.submit_records(
                sub, int(idx.size), deadline=deadline, payload=pl)))
        return ShardedTicket(n, parts)

    @property
    def supervisors(self) -> List[object]:
        return [lane.supervisor for lane in self.lanes]

    def stats(self) -> Dict:
        return {"lane": "sharded-verdict",
                "shards": {str(k): lane.stats()
                           for k, lane in enumerate(self.lanes)}}

    def close(self, timeout: float = 5.0) -> None:
        for lane in self.lanes:
            lane.close(timeout=timeout)


# ---------------------------------------------------------------------------
# The sharded dataplane
# ---------------------------------------------------------------------------

class ShardedDatapath:
    """N shard engines behind the single-engine surface the daemon
    drives.  Each shard is a full ``Datapath`` (its own CT/flow/counter
    state, its own jitted pipeline) pinned to its column submesh; the
    address-keyed tables (ipcache, prefilter, LB, tunnel) replicate to
    every shard, and the prefilter/LB registries are SHARED host
    objects so one control-plane mutation reaches every shard on the
    reload fan-out."""

    def __init__(self, n_shards: Optional[int] = None, mesh=None,
                 n_devices: Optional[int] = None,
                 ct_slots: int = 1 << 16, ct_probe: int = 8):
        if mesh is None:
            mesh = make_mesh(n_devices, ep_parallel=n_shards or 1)
        self.mesh = mesh
        self.n_shards = int(mesh.shape[EP_AXIS])
        if n_shards is not None and n_shards != self.n_shards:
            raise ValueError(
                f"mesh ep axis {self.n_shards} != n_shards {n_shards}")
        self.shards: List[Datapath] = []
        self.prefilter = None
        self.lb = None
        for k in range(self.n_shards):
            eng = Datapath(ct_slots=ct_slots, ct_probe=ct_probe)
            if k == 0:
                self.prefilter, self.lb = eng.prefilter, eng.lb
            else:
                # shared control-plane registries: one insert, every
                # shard's next reload compiles it
                eng.prefilter = self.prefilter
                eng.lb = self.lb
            eng.configure_supervision(enabled=True, shard=k)
            eng.set_mesh_placement(ep_submesh(mesh, k), shard=k)
            self.shards.append(eng)
        self._serving_lane: Optional[ShardedServingLane] = None
        self._table_mgr: Optional[ShardedTableManager] = None
        self._analytics_breakers: List = []
        self._lock = threading.Lock()

    # ------------------------------------------------------- geometry

    def geometry(self) -> Dict:
        dp, ep = self.mesh.devices.shape
        return {"dp": dp, "ep": ep, "devices": dp * ep,
                "shards": self.n_shards}

    def shard_of_slot(self, slot: int) -> int:
        return shard_of_slot(slot, self.n_shards)

    # ------------------------------------------------- engine surface

    @property
    def telemetry_enabled(self) -> bool:
        return self.shards[0].telemetry_enabled

    @telemetry_enabled.setter
    def telemetry_enabled(self, value: bool) -> None:
        for sh in self.shards:
            sh.telemetry_enabled = value

    @property
    def on_revision_served(self):
        return self.shards[0].on_revision_served

    @on_revision_served.setter
    def on_revision_served(self, fn) -> None:
        # the tracker's revision_served is idempotent per revision, so
        # every shard reports and the first one to serve wins
        for sh in self.shards:
            sh.on_revision_served = fn

    @property
    def revision(self) -> int:
        return max(sh.revision for sh in self.shards)

    @property
    def ct(self):
        """Shard 0's v4 CT table (geometry is uniform across shards);
        per-shard occupancy is in ``map_pressure``/``ct_entries``."""
        return self.shards[0].ct

    @property
    def ct6(self):
        return self.shards[0].ct6

    @property
    def _step(self):
        return self.shards[0]._step

    @property
    def flows(self):
        return self.shards[0].flows

    @property
    def provenance_enabled(self) -> bool:
        return self.shards[0].provenance_enabled

    @property
    def last_provenance(self):
        return self.shards[0].last_provenance

    @property
    def ipcache_prefixes(self) -> Dict[str, int]:
        return self.shards[0].ipcache_prefixes

    @property
    def ipcache_prefixes6(self) -> Dict[str, int]:
        return self.shards[0].ipcache_prefixes6

    @property
    def tunnel_prefixes(self) -> Dict[str, int]:
        return self.shards[0].tunnel_prefixes

    # -------------------------------------------------- table loading

    def load_policy(self, map_states: Sequence,
                    revision: int,
                    ipcache_prefixes: Optional[Dict[str, int]] = None
                    ) -> None:
        """Partition the stacked map states across shards: global slot
        g -> shard ``g % n_shards`` local slot ``g // n_shards``.
        Shards short of states get one empty (deny-all) state so every
        shard compiles a serveable program."""
        states = list(map_states)
        for k, sh in enumerate(self.shards):
            mine = states[k::self.n_shards] or [PolicyMapState()]
            sh.load_policy(mine, revision,
                           ipcache_prefixes=ipcache_prefixes)

    def use_table_manager(self, mgr: ShardedTableManager,
                          ipcache_prefixes: Optional[Dict[str, int]]
                          = None) -> None:
        if mgr.n_shards != self.n_shards:
            raise ValueError(
                f"table manager has {mgr.n_shards} shards, "
                f"dataplane has {self.n_shards}")
        self._table_mgr = mgr
        for k, sh in enumerate(self.shards):
            sh.use_table_manager(mgr.shards[k],
                                 ipcache_prefixes=ipcache_prefixes)

    def refresh_policy(self, revision: Optional[int] = None) -> bool:
        rejitted = False
        for sh in self.shards:
            rejitted = sh.refresh_policy(revision) or rejitted
        return rejitted

    def load_ipcache(self, prefixes: Dict[str, int],
                     prefixes6: Optional[Dict[str, int]] = None
                     ) -> None:
        for sh in self.shards:
            sh.load_ipcache(prefixes, prefixes6)

    def load_ipcache6(self, prefixes6: Dict[str, int]) -> None:
        for sh in self.shards:
            sh.load_ipcache6(prefixes6)

    def load_tunnel(self, prefixes: Dict[str, int]) -> None:
        for sh in self.shards:
            sh.load_tunnel(prefixes)

    def set_endpoint_identity(self, slot: int, identity: int) -> None:
        k = self.shard_of_slot(slot)
        self.shards[k].set_endpoint_identity(
            local_slot(slot, self.n_shards), identity)

    def set_router_ip6(self, ip: str) -> None:
        for sh in self.shards:
            sh.set_router_ip6(ip)

    def icmp6_echo_reply_bytes(self, requester_ip6: str,
                               ident: int = 0, seq: int = 0) -> bytes:
        return self.shards[0].icmp6_echo_reply_bytes(
            requester_ip6, ident=ident, seq=seq)

    def reload_services(self) -> None:
        for sh in self.shards:
            sh.reload_services()

    def reload_prefilter(self) -> None:
        for sh in self.shards:
            sh.reload_prefilter()

    def upsert_service6(self, svc) -> None:
        # each shard keeps its own lb6 registry; identical upsert
        # order means identical rev-NAT index assignment everywhere
        for sh in self.shards:
            sh.upsert_service6(svc)

    def delete_service6(self, vip, port: int, proto: int = 6) -> bool:
        out = False
        for sh in self.shards:
            out = sh.delete_service6(vip, port, proto) or out
        return out

    def lb6_service_list(self):
        return self.shards[0].lb6_service_list()

    # ---------------------------------------------------- flows / prov

    def enable_flow_aggregation(self, slots: int = 1 << 12,
                                max_probe: int = 8,
                                claim_every: int = 4) -> None:
        for sh in self.shards:
            sh.enable_flow_aggregation(slots=slots, max_probe=max_probe,
                                       claim_every=claim_every)

    def disable_flow_aggregation(self) -> None:
        for sh in self.shards:
            sh.disable_flow_aggregation()

    def flow_snapshot(self, max_entries: int = 4096):
        out = []
        for sh in self.shards:
            out.extend(sh.flow_snapshot(max_entries))
        return out[:max_entries]

    def shard_flow_snapshot(self, shard: int,
                            max_entries: int = 4096):
        """ONE shard's device flow table (the federated observer's
        per-shard drain source — hubble/federation.py)."""
        return self.shards[shard].flow_snapshot(max_entries)

    def shard_flow_stats(self, shard: int):
        return self.shards[shard].flow_stats()

    def shard_modes(self) -> Dict[int, str]:
        """{shard: supervisor mode} without creating serving lanes —
        the per-shard fail-open flag source for federated flow
        answers (a degraded shard's flows are FAIL-STATIC records and
        must be flagged as such)."""
        return {k: sh.supervision_status().get("mode", "ok")
                for k, sh in enumerate(self.shards)}

    def flow_stats(self):
        per = [sh.flow_stats() for sh in self.shards]
        if all(p is None for p in per):
            return None
        live = [p for p in per if p is not None]
        agg = {"occupied": sum(p.get("occupied", 0) for p in live),
               "slots": sum(p.get("slots", 0) for p in live),
               "per-shard": {str(k): p for k, p in enumerate(per)}}
        return agg

    def enable_provenance(self) -> None:
        for sh in self.shards:
            sh.enable_provenance()

    def disable_provenance(self) -> None:
        for sh in self.shards:
            sh.disable_provenance()

    def enable_l7_fast(self, programs) -> None:
        """Fan the L7 fast-verdict program set to every shard (the
        fused DFA tables are replicated per shard, like the other
        address/payload-keyed lookups; l7_prog shards with the policy
        rows each shard already owns)."""
        for sh in self.shards:
            sh.enable_l7_fast(programs)

    def disable_l7_fast(self) -> None:
        for sh in self.shards:
            sh.disable_l7_fast()

    def l7_fast_window(self) -> int:
        return self.shards[0].l7_fast_window()

    def l7_fast_report(self):
        return self.shards[0].l7_fast_report()

    # ------------------------------------------- inline threat scoring

    def enable_threat(self, model, buckets: int = 1024,
                      window_s: int = 8, stripe: int = 4) -> None:
        """Fan the threat scorer to every shard: the quantized model
        is replicated (every shard scores against the same weights),
        while each shard owns its OWN ThreatState buffer — token
        buckets and claim windows are shard-local like the CT state,
        so one shard's rate-limit debt never throttles a sibling."""
        for sh in self.shards:
            sh.enable_threat(model, buckets=buckets,
                             window_s=window_s, stripe=stripe)

    def disable_threat(self) -> None:
        for sh in self.shards:
            sh.disable_threat()

    def set_threat_config(self, config) -> None:
        for sh in self.shards:
            sh.set_threat_config(config)

    def apply_threat_weights(self, model) -> bool:
        fast = True
        for sh in self.shards:
            fast = sh.apply_threat_weights(model) and fast
        return fast

    def threat_report(self):
        """Merged report: shard 0's model view + per-shard state."""
        base = self.shards[0].threat_report()
        if base is None:
            return None
        base["shards"] = {str(k): sh.threat_report()
                          for k, sh in enumerate(self.shards)}
        base.pop("shard", None)
        return base

    @property
    def last_threat(self):
        """Concatenated last-batch threat lanes (per-shard engines
        keep their own; diagnostic surface only)."""
        outs = [sh.last_threat for sh in self.shards
                if sh.last_threat is not None]
        if not outs:
            return None
        return np.concatenate([np.array(o) for o in outs])

    # --------------------------------------- device traffic analytics

    def enable_analytics(self, width: int = 1 << 12, depth: int = 2,
                         lanes: int = 4, stripe: int = 16) -> None:
        """Fan the fused traffic-analytics stage to every shard: each
        shard folds its own traffic into its OWN AnalyticsState buffer
        (shard-local, the threat-state precedent).  Mesh-wide answers
        merge the per-shard quiesced sections host-side — sketches add
        elementwise, key tables and cardinality registers max, both
        order-free — so a top-K query never pauses serving."""
        from ..utils.resilience import CircuitBreaker
        with self._lock:
            self._analytics_breakers = [
                CircuitBreaker(f"analytics-drain:shard{k}",
                               failure_threshold=2, reset_timeout=0.5,
                               max_reset=10.0)
                for k in range(self.n_shards)]
        for sh in self.shards:
            sh.enable_analytics(width=width, depth=depth, lanes=lanes,
                                stripe=stripe)

    def disable_analytics(self) -> None:
        for sh in self.shards:
            sh.disable_analytics()

    def swap_analytics_epoch(self) -> Dict[int, int]:
        """Flip every shard's A/B epoch (each swap is a state write
        under that engine's own lock — no global pause).  Returns
        {shard: newly quiesced epoch}."""
        return {k: sh.swap_analytics_epoch()
                for k, sh in enumerate(self.shards)}

    def analytics_sections(self, swap: bool = True) -> Dict:
        """Per-shard quiesced epoch sections behind per-shard
        breakers: an unreadable shard contributes a flagged error and
        the mesh answer degrades to a ``partial`` (fail-open — the
        federated Hubble drain precedent), never a hang.  ``swap``
        flips each readable shard's epoch first, so the sections
        cover traffic since the previous drain cycle."""
        from ..analytics import decode as adec
        eng0 = self.shards[0]
        depth = eng0._analytics_depth
        lanes = eng0._analytics_lanes
        with self._lock:
            breakers = list(self._analytics_breakers)
        sections: List = []
        shards: Dict[str, Dict] = {}
        for k, sh in enumerate(self.shards):
            breaker = breakers[k] if k < len(breakers) else None
            if breaker is not None and not breaker.allow():
                shards[str(k)] = {"status": "breaker-open"}
                continue
            try:
                if swap:
                    epoch = sh.swap_analytics_epoch()
                    snap = sh.analytics_snapshot()
                    section = adec.epoch_section(snap, epoch, depth,
                                                 lanes)
                else:
                    snap = sh.analytics_snapshot()
                    section = adec.quiesced_section(snap, depth,
                                                    lanes)
            except Exception as e:  # noqa: BLE001 — per-shard
                if breaker is not None:
                    breaker.record_failure()   # fail-open, not a hang
                shards[str(k)] = {"status": "error", "error": repr(e)}
                continue
            if breaker is not None:
                breaker.record_success()
            sections.append(section)
            shards[str(k)] = {"status": "ok"}
        partial = any(s["status"] != "ok" for s in shards.values())
        return {"sections": sections, "shards": shards,
                "partial": partial, "depth": depth, "lanes": lanes}

    def analytics_query(self, view: str = "talkers", k: int = 10,
                        metric: str = "bytes",
                        swap: bool = True) -> Dict:
        """ONE mesh-wide top-K answer: merge every readable shard's
        quiesced section, decode the merged section once.  A degraded
        shard shows up as ``partial`` + its flagged status — the
        remaining shards' answer still serves (fail-open)."""
        from ..analytics import decode as adec
        secs = self.analytics_sections(swap=swap)
        if not secs["sections"]:
            return {"view": view, "entries": [], "partial": True,
                    "shards": secs["shards"]}
        merged = adec.merge_sections(secs["sections"], secs["depth"],
                                     secs["lanes"])
        entries = adec.decode_view(merged, view, secs["depth"],
                                   secs["lanes"], k=k, metric=metric)
        return {"view": view, "entries": entries,
                "partial": secs["partial"], "shards": secs["shards"]}

    def analytics_snapshot(self):
        """Shard 0's raw buffer (single-engine API parity; mesh-wide
        consumers use analytics_sections/analytics_query)."""
        return self.shards[0].analytics_snapshot()

    def analytics_report(self):
        """Merged report: shard 0's geometry + per-shard epochs."""
        base = self.shards[0].analytics_report()
        if base is None:
            return None
        base["shards"] = {str(k): sh.analytics_report()
                          for k, sh in enumerate(self.shards)}
        base.pop("shard", None)
        with self._lock:
            breakers = list(self._analytics_breakers)
        if breakers:
            base["open-breakers"] = sum(
                1 for b in breakers if b.state != "closed")
        return base

    # -------------------------------------------------------- serving

    def configure_supervision(self, enabled: bool = True,
                              **knobs) -> None:
        for k, sh in enumerate(self.shards):
            sh.configure_supervision(enabled=enabled, shard=k, **knobs)

    def serving(self) -> ShardedServingLane:
        with self._lock:
            if self._serving_lane is None:
                self._serving_lane = ShardedServingLane(self)
            return self._serving_lane

    def classify_records(self, soa: Dict[str, np.ndarray], n: int,
                         deadline: Optional[float] = None,
                         timeout: float = 120.0):
        """Route one SoA chunk through the per-shard serving lanes and
        wait for the assembled (verdict [n], identity [n]) pair."""
        ticket = self.serving().submit_records(soa, n,
                                               deadline=deadline)
        return ticket.result(timeout=timeout)

    def supervision_status(self) -> Dict:
        shards: Dict[str, Dict] = {}
        worst = "ok"
        degraded: List[int] = []
        supervised = True
        for k, sh in enumerate(self.shards):
            st = sh.supervision_status()
            shards[str(k)] = st
            mode = st.get("mode", "ok")
            if _MODE_RANK[mode] > _MODE_RANK[worst]:
                worst = mode
            if mode != "ok":
                degraded.append(k)
            supervised = supervised and bool(st.get("supervised"))
        DATAPLANE_MODE.set(_MODE_CODE[worst])
        return {"mode": worst, "supervised": supervised,
                "geometry": self.geometry(),
                "degraded-shards": degraded,
                "shards": shards}

    # ------------------------------------------------ replay / states

    def host_policy_states(self) -> Dict[int, object]:
        out: Dict[int, object] = {}
        for k, sh in enumerate(self.shards):
            for local, st in sh.host_policy_states().items():
                out[global_slot(k, local, self.n_shards)] = st
        return out

    def policy_replay(self, endpoints, identities, dports, protos,
                      directions) -> List[Dict]:
        """Replay synthesized headers through the REAL sharded device
        tables: rows route to their owning shard (endpoint slots are
        GLOBAL), replay runs on each shard's live tensors, and the
        results come back in submission order with global slots."""
        eps = np.array(list(endpoints), dtype=np.int64)
        ids = np.array(list(identities), dtype=np.int64)
        dps = np.array(list(dports), dtype=np.int64)
        prs = np.array(list(protos), dtype=np.int64)
        drs = np.array(list(directions), dtype=np.int64)
        out: List[Optional[Dict]] = [None] * eps.shape[0]
        owner = eps % self.n_shards
        for k, sh in enumerate(self.shards):
            idx = np.flatnonzero(owner == k)
            if idx.size == 0:
                continue
            rows = sh.policy_replay(
                (eps[idx] // self.n_shards).tolist(), ids[idx].tolist(),
                dps[idx].tolist(), prs[idx].tolist(),
                drs[idx].tolist())
            for j, row in zip(idx.tolist(), rows):
                row["endpoint-slot"] = int(eps[j])
                row["shard"] = k
                out[j] = row
        return out

    def rule_decoder(self):
        """Shard-aware provenance decoder factory: returns a per-shard
        decoder map {shard: decode} (slots are shard-local flat
        indices; consumers pick the shard the batch routed to)."""
        return {k: sh.rule_decoder()
                for k, sh in enumerate(self.shards)}

    # ----------------------------------------------------- inventory

    def map_inventory(self) -> Dict[str, Dict]:
        per = [sh.map_inventory() for sh in self.shards]
        agg: Dict[str, Dict] = {}
        pol = {"endpoints": 0, "slots": per[0].get("policy", {})
               .get("slots", 0), "attached": 0, "max-probe": 0,
               "entries": 0}
        have_policy = False
        for inv in per:
            p = inv.get("policy")
            if p:
                have_policy = True
                pol["endpoints"] += int(p.get("endpoints", 0))
                pol["attached"] += int(p.get("attached",
                                             p.get("entries", 0)))
                pol["entries"] += int(p.get("entries", 0))
                pol["max-probe"] = max(pol["max-probe"],
                                       int(p.get("max-probe", 0)))
        if have_policy:
            agg["policy"] = pol
        for name in ("ct", "ct6"):
            agg[name] = {
                "slots": sum(int(i[name]["slots"]) for i in per),
                "occupied": sum(int(i[name]["occupied"]) for i in per),
                "max-probe": per[0][name]["max-probe"]}
        # replicated tables: every shard holds the same copy
        for name in ("ipcache", "ipcache6", "tunnel", "lb", "lb6",
                     "prefilter"):
            if name in per[0]:
                agg[name] = dict(per[0][name])
        if "hubble-flows" in per[0]:
            agg["hubble-flows"] = {
                "slots": sum(int(i["hubble-flows"]["slots"])
                             for i in per if "hubble-flows" in i),
                "occupied": sum(int(i["hubble-flows"]["occupied"])
                                for i in per if "hubble-flows" in i)}
        agg["shards"] = {str(k): inv for k, inv in enumerate(per)}
        return agg

    def map_pressure(self, warn_threshold: float = 0.9) -> Dict:
        """Mesh-wide pressure report: per-shard reports with the warn
        threshold applied SHARD-LOCALLY (shard-labelled gauges), plus
        the aggregate view on the unlabelled series."""
        shard_reports: Dict[str, Dict] = {}
        warnings: List[str] = []
        agg: Dict[str, Dict] = {}
        for k, sh in enumerate(self.shards):
            rep = compute_pressure(sh.map_inventory(), warn_threshold,
                                   shard=k)
            shard_reports[str(k)] = rep
            warnings.extend(rep["warnings"])
            for name, m in rep["maps"].items():
                a = agg.setdefault(name, {"occupied": 0, "capacity": 0,
                                          "pressure": None})
                a["occupied"] += int(m["occupied"])
                if m["capacity"] is None:
                    a["capacity"] = None
                elif a["capacity"] is not None:
                    a["capacity"] += int(m["capacity"])
        for name, a in agg.items():
            if a["capacity"]:
                a["pressure"] = round(a["occupied"] / a["capacity"], 6)
                MAP_PRESSURE.set(a["pressure"], labels={"map": name})
            MAP_ENTRIES.set(float(a["occupied"]), labels={"map": name})
        return {"maps": agg, "warnings": warnings,
                "warn-threshold": warn_threshold,
                "shards": shard_reports}

    def map_dump(self, name: str, max_entries: int = 4096):
        if name in ("ct", "ct6", "hubble-flows"):
            out = []
            for sh in self.shards:
                out.extend(sh.map_dump(name, max_entries))
            return out[:max_entries]
        # replicated maps: shard 0's copy IS the mesh's copy
        return self.shards[0].map_dump(name, max_entries)

    def ct_entries(self) -> Tuple[int, int]:
        v4 = v6 = 0
        for sh in self.shards:
            a, b = sh.ct_entries()
            v4 += a
            v6 += b
        return v4, v6

    # ---------------------------------------------------- maintenance

    def gc(self, now: Optional[int] = None) -> int:
        """Shard-aware CT GC: each shard sweeps its own tables on its
        own devices (no cross-shard pause)."""
        return sum(sh.gc(now) for sh in self.shards)

    def pack_stats(self) -> Dict:
        """Packed-dispatch accounting across the mesh: each shard's
        column submesh dispatches its own grouped buffer slices
        (parallel/packing.py), so repacks and delta write-throughs are
        per-shard events with a per-shard blast radius."""
        per = {str(k): sh.pack_stats()
               for k, sh in enumerate(self.shards)}
        return {"full-packs": sum(p["full-packs"] for p in per.values()),
                "row-writes": sum(p["row-writes"] for p in per.values()),
                "leaf-writes": sum(p["leaf-writes"]
                                   for p in per.values()),
                "per-shard": per}

    def flush_telemetry(self) -> None:
        for sh in self.shards:
            sh.flush_telemetry()

    # ------------------------------------------------ CT persistence

    def snapshot_ct(self):
        """(v4, v6) snapshot dicts with shard-prefixed keys — the
        checkpoint stays one flat npz, restore splits it back."""
        v4: Dict[str, np.ndarray] = {
            "shards": np.array([self.n_shards], np.int64)}
        v6: Dict[str, np.ndarray] = {
            "shards": np.array([self.n_shards], np.int64)}
        for k, sh in enumerate(self.shards):
            s4, s6 = sh.snapshot_ct()
            for f, v in s4.items():
                v4[f"s{k}_{f}"] = v
            for f, v in s6.items():
                v6[f"s{k}_{f}"] = v
        return v4, v6

    def restore_ct_snapshots(self, v4, v6) -> int:
        n = int(np.array(v4["shards"]).reshape(-1)[0])
        if n != self.n_shards:
            raise ValueError(
                f"CT snapshot has {n} shards, dataplane has "
                f"{self.n_shards}")
        total = 0
        prepared = []
        for k, sh in enumerate(self.shards):
            sub4 = {f[len(f"s{k}_"):]: v for f, v in v4.items()
                    if f.startswith(f"s{k}_")}
            sub6 = {f[len(f"s{k}_"):]: v for f, v in v6.items()
                    if f.startswith(f"s{k}_")}
            prepared.append((sh, sub4, sub6))
        # validate everything BEFORE assigning anything: a bad shard
        # snapshot is a mesh-wide cold start, never a half-restore
        states = [(sh, sh.ct.prepare_snapshot(sub4),
                   sh.ct6.prepare_snapshot(sub6))
                  for sh, sub4, sub6 in prepared]
        for sh, st4, st6 in states:
            with sh._lock:
                sh.ct.state = st4
                sh.ct6.state = st6
            a, b = sh.ct_entries()
            total += a + b
        return total
