"""Canonical shard-spec registry: every device-table leaf's logical
PartitionSpec over the (dp, ep) mesh.

This is the single source of truth for how the dataplane's device
state distributes across the mesh — the analog of the reference's
per-CPU/per-node map ownership rules.  Policy tables shard their
endpoint axis across ``ep``; the mutable per-shard state (conntrack,
flow aggregation, counters) is shard-LOCAL — logically stacked along
``ep``, physically resident only on its owning shard's (dp, 1) column
submesh — and the address-keyed lookup tables (ipcache, LB, prefilter,
tunnel) are replicated per shard because any shard's packets may
reference any address.

``tests/test_sharding_lint.py`` holds the registry complete: a new
``FullTables``/CT/flow-table leaf without a declared spec here is a
test failure, not a silent default-to-replicated.
"""

from __future__ import annotations

from typing import Dict, List, Type

from jax.sharding import PartitionSpec as P

from .mesh import DP_AXIS, EP_AXIS

# shorthand specs (the logical layout over the FULL (dp, ep) mesh)
EP_ROWS = P(EP_AXIS, None)          # [E, S]: endpoint axis across ep
EP_VEC = P(EP_AXIS)                 # [E]: endpoint axis across ep
SHARD_LOCAL = P(EP_AXIS, None)      # logically [ep, ...]: one copy per
#                                     shard, resident on its column
REPLICATED = P()                    # every shard holds a full copy
BATCH = P(DP_AXIS)                  # [B] packet-batch leaves
PACKED_BATCH = P(None, DP_AXIS)     # [F, B] packed field matrices


# ---------------------------------------------------------------------------
# The registry: {table class name: {leaf path: PartitionSpec}}.
# Nested NamedTuples use dotted paths (FullTables.datapath.key_id ->
# "datapath.key_id").
# ---------------------------------------------------------------------------

DATAPATH_TABLES_SPECS: Dict[str, P] = {
    "key_id": EP_ROWS, "key_meta": EP_ROWS, "value": EP_ROWS,
    "lpm_masks": REPLICATED, "lpm_key_a": REPLICATED,
    "lpm_key_b": REPLICATED, "lpm_value": REPLICATED,
    "lpm_plens": REPLICATED,
}

LB_TABLES_SPECS: Dict[str, P] = {
    "svc_key_a": REPLICATED, "svc_key_b": REPLICATED,
    "svc_value": REPLICATED, "svc_count": REPLICATED,
    "svc_offset": REPLICATED, "svc_revnat": REPLICATED,
    "b_addr": REPLICATED, "b_port": REPLICATED,
    "rev_vip": REPLICATED, "rev_port": REPLICATED,
}

LPM6_TABLES_SPECS: Dict[str, P] = {
    "masks": REPLICATED, "k0": REPLICATED, "k1": REPLICATED,
    "k2": REPLICATED, "k3": REPLICATED, "kb": REPLICATED,
    "value": REPLICATED, "plens": REPLICATED,
}

LB6_TABLES_SPECS: Dict[str, P] = {
    "svc_k0": REPLICATED, "svc_k1": REPLICATED, "svc_k2": REPLICATED,
    "svc_k3": REPLICATED, "svc_kb": REPLICATED,
    "svc_value": REPLICATED, "svc_count": REPLICATED,
    "svc_offset": REPLICATED, "svc_revnat": REPLICATED,
    "b_addr": REPLICATED, "b_port": REPLICATED,
    "rev_vip": REPLICATED, "rev_port": REPLICATED,
}

# On-device L7 fast-verdict tables (l7/fast.py): the per-slot program
# classification shards with the policy rows it annotates; the fused
# DFA table set is replicated — any shard's packets may carry any
# payload (its packed dispatch-buffer group is "l7-dfa" below).
L7_FAST_SPECS: Dict[str, P] = {
    "l7_prog": EP_ROWS,
    "l7_flat": REPLICATED, "l7_map": REPLICATED,
    "l7_accept": REPLICATED, "l7_starts": REPLICATED,
    "l7_pmask": REPLICATED,
}

# Inline threat-scoring model (threat/model.py): the quantized scorer
# weights + threshold/mode config are replicated — every shard scores
# its own packets against the same model (its packed dispatch-buffer
# group is "threat-model" below, so a weight push is a region write).
THREAT_MODEL_SPECS: Dict[str, P] = {
    "tm_w1": REPLICATED, "tm_b1": REPLICATED, "tm_w2": REPLICATED,
    "tm_b2": REPLICATED, "tm_cfg": REPLICATED,
}

FULL_TABLES_SPECS: Dict[str, P] = {
    **{f"datapath.{k}": v for k, v in DATAPATH_TABLES_SPECS.items()},
    **{f"lb.{k}": v for k, v in LB_TABLES_SPECS.items()},
    "pf_masks": REPLICATED, "pf_key_a": REPLICATED,
    "pf_key_b": REPLICATED, "pf_value": REPLICATED,
    "pf_plens": REPLICATED,
    "tun_masks": REPLICATED, "tun_key_a": REPLICATED,
    "tun_key_b": REPLICATED, "tun_value": REPLICATED,
    "tun_plens": REPLICATED,
    "ep_identity": EP_VEC,
    **L7_FAST_SPECS,
    **THREAT_MODEL_SPECS,
}

FULL_TABLES6_SPECS: Dict[str, P] = {
    "key_id": EP_ROWS, "key_meta": EP_ROWS, "value": EP_ROWS,
    **{f"ipcache6.{k}": v for k, v in LPM6_TABLES_SPECS.items()},
    **{f"pf6.{k}": v for k, v in LPM6_TABLES_SPECS.items()},
    **{f"lb6.{k}": v for k, v in LB6_TABLES_SPECS.items()},
    "router_ip6": REPLICATED,
    "ep_identity": EP_VEC,
    **L7_FAST_SPECS,
    **THREAT_MODEL_SPECS,
}

# mutable per-shard state: every leaf lives on its owning shard alone
CT_STATE_SPECS: Dict[str, P] = {
    "k0": SHARD_LOCAL, "k1": SHARD_LOCAL, "k2": SHARD_LOCAL,
    "k3": SHARD_LOCAL, "expires": SHARD_LOCAL, "state": SHARD_LOCAL,
    "rev_nat": SHARD_LOCAL, "proxy_port": SHARD_LOCAL,
}

FLOW_STATE_SPECS: Dict[str, P] = {
    # two-leaf flow pack (hubble/aggregation.py FlowState): the keys
    # buffer carries the accounting row (lost/updates lanes), the
    # uint32 counters stay split along the dtype boundary
    "keys": SHARD_LOCAL, "counters": SHARD_LOCAL,
}

COUNTERS_SPECS: Dict[str, P] = {
    "packets": SHARD_LOCAL, "bytes": SHARD_LOCAL,
}

# the threat plane's mutable buffer (threat/stage.ThreatState): token
# buckets + claim-window aggregates are shard-local like the CT state
# — each shard rate-limits and windows its own endpoints' traffic
THREAT_STATE_SPECS: Dict[str, P] = {
    "state": SHARD_LOCAL,
}

# the traffic-analytics buffer (analytics/stage.AnalyticsState):
# sketches, key tables and cardinality registers are shard-local —
# each shard folds its own traffic, and the mesh-wide answer merges
# shards host-side (add sketches / max registers, decode.py)
ANALYTICS_STATE_SPECS: Dict[str, P] = {
    "state": SHARD_LOCAL,
}

# ---------------------------------------------------------------------------
# Packed dispatch-buffer groups (parallel/packing.py): the grouped flat
# buffers the jitted steps actually take.  Each group's spec is the
# distribution of the CONCATENATED buffer over the mesh — ep-grouped
# slices belong to one shard's column, replicated groups are copied per
# shard, and the mutable state packs are shard-local like the leaves
# they stack.  The sharding lint asserts every group a live engine
# builds is declared here.
# ---------------------------------------------------------------------------

PACKED_GROUP_SPECS: Dict[str, P] = {
    "ep-int32": P(EP_AXIS),        # stacked policy rows + slot
    #                                identities + l7_prog classification
    "rep-int32": P(),              # ipcache/LB/prefilter/tunnel copies
    "l7-dfa": P(),                 # fused L7 fast-verdict DFA table set
    #                                (l7/fast.py; its own group so the
    #                                no-L7 program keeps its exact
    #                                buffer list), replicated per shard
    "ct-state": SHARD_LOCAL,       # [8, N+1] conntrack pack (donated)
    "counters": SHARD_LOCAL,       # [2, E*S] counter pack (donated)
    "flow-state": SHARD_LOCAL,     # 2-leaf flow pack (NOT donated —
    #                                CPU XLA copies donated scatter
    #                                buffers; hubble/aggregation.py)
    "threat-model": P(),           # quantized scorer weights + config
    #                                (threat/model.py; its own group so
    #                                the no-threat program keeps its
    #                                exact buffer list and a weight
    #                                push is a region write, never a
    #                                repack), replicated per shard
    "threat-state": SHARD_LOCAL,   # [6, T+1] token-bucket/window
    #                                buffer (NOT donated, the
    #                                flow-state precedent)
    "analytics-state": SHARD_LOCAL,  # [R, W] sketch/register buffer
    #                                (NOT donated, the flow-state
    #                                precedent; analytics/stage.py)
}


def _table_classes():
    from ..datapath.conntrack import CTState
    from ..datapath.lb import LB6Tables, LBTables
    from ..datapath.pipeline import (DatapathTables, FullTables,
                                     FullTables6, LPM6Tables)
    from ..datapath.verdict import Counters
    from ..analytics.stage import AnalyticsState
    from ..hubble.aggregation import FlowState
    from ..threat.stage import ThreatState
    return {
        DatapathTables: DATAPATH_TABLES_SPECS,
        LBTables: LB_TABLES_SPECS,
        LPM6Tables: LPM6_TABLES_SPECS,
        LB6Tables: LB6_TABLES_SPECS,
        FullTables: FULL_TABLES_SPECS,
        FullTables6: FULL_TABLES6_SPECS,
        CTState: CT_STATE_SPECS,
        FlowState: FLOW_STATE_SPECS,
        Counters: COUNTERS_SPECS,
        ThreatState: THREAT_STATE_SPECS,
        AnalyticsState: ANALYTICS_STATE_SPECS,
    }


def leaf_paths(cls: Type, nested: Dict[str, Type]) -> List[str]:
    """Dotted leaf paths of a NamedTuple table class, recursing into
    fields named in ``nested`` (field name -> NamedTuple class)."""
    out: List[str] = []
    for field in cls._fields:
        sub = nested.get(field)
        if sub is not None:
            out.extend(f"{field}.{p}"
                       for p in leaf_paths(sub, nested))
        else:
            out.append(field)
    return out


def registry() -> Dict[str, Dict[str, P]]:
    """{table class name: specs} for every registered device table."""
    return {cls.__name__: specs
            for cls, specs in _table_classes().items()}


def missing_specs() -> Dict[str, List[str]]:
    """Leaves present on a registered table class but absent from its
    spec table (the sharding lint's subject — must be empty)."""
    from ..datapath.lb import LB6Tables, LBTables
    from ..datapath.pipeline import DatapathTables, LPM6Tables
    nested_by_cls = {
        "FullTables": {"datapath": DatapathTables, "lb": LBTables},
        "FullTables6": {"ipcache6": LPM6Tables, "pf6": LPM6Tables,
                        "lb6": LB6Tables},
    }
    out: Dict[str, List[str]] = {}
    for cls, specs in _table_classes().items():
        nested = nested_by_cls.get(cls.__name__, {})
        missing = [p for p in leaf_paths(cls, nested) if p not in specs]
        if missing:
            out[cls.__name__] = missing
    return out
