"""REST API for the daemon.

Mirrors the reference's OpenAPI surface (api/v1/openapi.yaml) core
paths: /healthz, /config, /debuginfo, /policy, /policy/resolve,
/endpoint, /endpoint/{id} (+ /config /healthz /labels /log
/regenerate), /identity, /identity/{id}, /service, /service/{id},
/prefilter, /ipam (+ /ipam/{ip}), /kvstore/{key}, /map, /map/{name},
plus /metrics (Prometheus text) and /monitor (event tail) — every
path in the reference's api/v1/openapi.yaml. Stdlib http.server —
the reference serves REST over a unix socket; here TCP on localhost
for the CLI.
"""

from __future__ import annotations

import json
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, unquote, urlparse

from ..ipam import IPAMError
from ..labels import LabelArray, parse_label
from ..monitor import _monitor_event_dict
from ..policy.api import PolicyError
from ..policy.jsonio import rules_from_json
from .daemon import Daemon


class _Handler(BaseHTTPRequestHandler):
    daemon: Daemon = None  # set by make_server
    protocol_version = "HTTP/1.1"

    # silence default request logging
    def log_message(self, *args):
        pass

    # ------------------------------------------------------------ helpers

    def _send(self, code: int, body, content_type="application/json"):
        data = body if isinstance(body, bytes) else \
            json.dumps(body, indent=1, sort_keys=True).encode()
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _error(self, code: int, msg: str):
        self._send(code, {"error": msg})

    def _body(self) -> bytes:
        length = int(self.headers.get("Content-Length") or 0)
        return self.rfile.read(length) if length else b""

    def _labels_from_query(self, qs) -> Optional[LabelArray]:
        raw = qs.get("labels", [])
        if not raw:
            return None
        return LabelArray(parse_label(s) for s in raw)

    # ------------------------------------------------------------ routing

    def _route(self, method: str):
        d = self.daemon
        url = urlparse(self.path)
        path = url.path.rstrip("/") or "/"
        qs = parse_qs(url.query)
        try:
            if path == "/healthz" and method == "GET":
                return self._send(200, d.status())
            if path == "/metrics" and method == "GET":
                return self._send(200, d.metrics_text().encode(),
                                  "text/plain; version=0.0.4")
            if path == "/config":
                if method == "GET":
                    return self._send(200, {
                        "daemon": d.config.opts.dump(),
                        "addressing": d.addressing(),
                        "cluster": {"name": d.config.cluster_name,
                                    "id": d.config.cluster_id}})
                if method == "PATCH":
                    changes = json.loads(self._body() or b"{}")
                    return self._send(200,
                                      {"changed": d.config_patch(changes)})
            if path == "/policy":
                if method == "GET":
                    return self._send(
                        200, d.policy_get(self._labels_from_query(qs)))
                if method in ("PUT", "POST"):
                    rules = rules_from_json(self._body())
                    rev = d.policy_add(rules)
                    return self._send(200, {"revision": rev})
                if method == "DELETE":
                    labels = self._labels_from_query(qs) or LabelArray()
                    rev, deleted = d.policy_delete(labels)
                    return self._send(200, {"revision": rev,
                                            "deleted": deleted})
            if path == "/policy/resolve" and method in ("GET", "POST"):
                body = json.loads(self._body() or b"{}")
                frm = LabelArray.parse_select(*body.get("from", []))
                to = LabelArray.parse_select(*body.get("to", []))
                return self._send(200, d.policy_resolve(
                    frm, to, dports=body.get("dports"),
                    verbose=bool(body.get("verbose"))))
            if path == "/policy/trace" and method in ("GET", "POST"):
                # verdict-provenance replay: run the tuple through
                # the REAL compiled device tables and explain the
                # verdict per tier (daemon.policy_trace_replay);
                # query params work for GET, a JSON body for POST
                body = json.loads(self._body() or b"{}")
                for k in ("endpoint", "identity", "dport", "proto",
                          "direction", "labels"):
                    if k not in body and k in qs:
                        body[k] = qs[k] if k == "labels" else qs[k][0]
                if "endpoint" not in body:
                    return self._error(400, "endpoint required")
                try:
                    out = d.policy_trace_replay(
                        int(body["endpoint"]),
                        identity=int(body["identity"])
                        if body.get("identity") is not None else None,
                        labels=body.get("labels"),
                        dport=int(body.get("dport", 0)),
                        proto=int(body.get("proto", 6)),
                        direction=str(body.get("direction", "egress")))
                except KeyError:
                    return self._error(404, "endpoint not found")
                return self._send(200, out)
            if path == "/debug/traces" and method == "GET":
                # span-trace surface (observability/tracer.py):
                # ?id=<trace> or ?revision=<rev> returns one span
                # tree; bare GET lists recent trace summaries plus
                # the propagation-latency report
                tid = qs.get("id", [None])[0]
                rev_q = qs.get("revision", [None])[0]
                out = d.traces(
                    trace_id=tid,
                    revision=int(rev_q) if rev_q is not None else None,
                    limit=int(qs.get("n", ["50"])[0]))
                if out is None:
                    return self._error(404, "trace not found")
                return self._send(200, out)
            if path == "/debug/pipeline" and method == "GET":
                # host-timed stage slices + blocking boundaries
                # (observability/stages.py pipeline_report)
                return self._send(200, d.pipeline_report())
            if path == "/debug/events" and method == "GET":
                # the incident flight recorder (observability/
                # events.py): ordered degraded-condition transitions,
                # cursor-paginated via ?since=<seq> like /monitor
                shard_q = qs.get("shard", [None])[0]
                return self._send(200, d.flight_events(
                    since=int(qs.get("since", ["0"])[0]),
                    limit=int(qs.get("n", ["200"])[0]),
                    event_type=qs.get("type", [None])[0],
                    shard=int(shard_q) if shard_q is not None
                    else None))
            if path == "/threat" and method == "GET":
                # inline threat scoring: mode/thresholds/model/verdict
                # accounting (daemon.threat_status)
                return self._send(200, d.threat_status())
            if path == "/threat/config" and method == "POST":
                # threshold / shadow-enforce updates: a live leaf
                # write, never a re-jit; mode flips ring the incident
                # flight recorder
                changes = json.loads(self._body() or b"{}")
                try:
                    return self._send(200, d.threat_set_config(
                        **{k.replace("-", "_"): v
                           for k, v in changes.items()}))
                except KeyError:
                    return self._error(404, "threat scoring disabled")
                except (TypeError, ValueError) as e:
                    return self._error(400, str(e))
            if path == "/threat/train" and method == "POST":
                # fit from the aggregated flow plane + hot-swap push
                body = json.loads(self._body() or b"{}")
                try:
                    return self._send(200, d.threat_train(
                        max_flows=int(body.get("max_flows", 4096))))
                except KeyError:
                    return self._error(404, "threat scoring disabled")
                except ValueError as e:
                    return self._error(400, str(e))
            if path == "/analytics" and method == "GET":
                # device traffic analytics: geometry + write epoch,
                # last drain outcome, live anomaly sets
                # (daemon.analytics_status)
                return self._send(200, d.analytics_status())
            if path == "/analytics/top" and method == "GET":
                # mesh-wide top-K over the quiesced sketch epoch:
                # ?view=talkers|scanners|spreaders, ?metric=bytes|
                # packets|drops, ?n=<k>.  A degraded shard flags the
                # answer partial (fail-open), never a hang.
                try:
                    return self._send(200, d.analytics_top(
                        view=qs.get("view", ["talkers"])[0],
                        k=int(qs.get("n", ["10"])[0]),
                        metric=qs.get("metric", ["bytes"])[0]))
                except KeyError as e:
                    msg = str(e.args[0]) if e.args else str(e)
                    if "not enabled" in msg:
                        return self._error(404, msg)
                    return self._error(400, msg)
            if path == "/debug/drift-audit" and method == "POST":
                # on-demand drift-audit sweep (the periodic
                # controller's body): replay sampled tuples through
                # the live compiled tables vs the host oracles —
                # restart/chaos journeys use this to prove the
                # restored dataplane is bit-exact RIGHT NOW
                return self._send(200, d.run_drift_audit())
            if path == "/debuginfo" and method == "GET":
                # cilium debuginfo (cilium/cmd/debuginfo.go): one
                # aggregate snapshot for bug reports / support
                return self._send(200, {
                    "status": d.status(),
                    "config": {"daemon": d.config.opts.dump(),
                               "addressing": d.addressing()},
                    "policy": {"revision": d.repo.revision,
                               "rules": d.policy_get(None)},
                    "endpoints": [ep.model()
                                  for ep in d.endpoints.endpoints()],
                    "services": _service_dump(d),
                    "nodes": [n.to_model() for n in
                              (d.node_registry.nodes()
                               if d.node_registry
                               else d.node_manager.nodes())],
                    "ipam": {"v4-allocated": len(d.ipam),
                             "v6-allocated":
                             len(d.ipam6) if d.ipam6 is not None
                             else 0},
                    # flow observability snapshot: recent flows, the
                    # on-device aggregation table, relay peer health
                    "hubble": None if d.hubble is None else {
                        "flows": d.hubble.get_flows(limit=200),
                        "aggregation": d.datapath.flow_stats(),
                        "aggregated-flows":
                        d.datapath.flow_snapshot(512),
                        "relay": d.hubble_relay.node_health()
                        if d.hubble_relay is not None else None},
                    # runtime self-telemetry snapshot: recent traces,
                    # propagation delays, pipeline stages, map
                    # pressure — "what was the agent doing"
                    "observability": {
                        "traces": d.traces(),
                        "pipeline": d.pipeline_report(),
                        "map-pressure": d.datapath.map_pressure(
                            d.config.map_pressure_warn)},
                    # the incident flight recorder: the ordered
                    # degraded-condition timeline + the serving SLO
                    # snapshot — "what happened, in order, and was
                    # the latency objective held"
                    "events": d.flight_events(limit=200),
                    # verdict provenance: drift-audit verdict on the
                    # compiler, the heaviest denied keys, and the
                    # last replay report — "was this verdict right"
                    "provenance": {
                        "enabled": d.datapath.provenance_enabled,
                        "drift-audit": d.drift_report(),
                        "top-dropped-rules":
                        d.monitor.top_dropped_rules(20),
                        "last-replay": d.last_replay_report()},
                })
            m = re.fullmatch(r"/kvstore/(.+)", path)
            if m:
                # cilium kvstore get/set/delete (cilium/cmd/kvstore_*)
                if d.kv is None:
                    return self._error(503, "no kvstore attached")
                key = unquote(m.group(1))
                if method == "GET":
                    if qs.get("prefix", ["0"])[0] in ("1", "true"):
                        vals = d.kv.list_prefix(key)
                        return self._send(200, {
                            k: v.decode("utf-8", "replace")
                            for k, v in vals.items()})
                    val = d.kv.get(key)
                    if val is None:
                        return self._error(404, "key not found")
                    return self._send(
                        200, {key: val.decode("utf-8", "replace")})
                if method == "PUT":
                    body = json.loads(self._body() or b"{}")
                    d.kv.set(key, str(body.get("value", "")).encode())
                    return self._send(200, {"set": key})
                if method == "DELETE":
                    if qs.get("prefix", ["0"])[0] in ("1", "true"):
                        d.kv.delete_prefix(key)
                    else:
                        d.kv.delete(key)
                    return self._send(200, {"deleted": key})
            if path == "/ipam" and method == "POST":
                # daemon/ipam.go AllocateIP analog
                body = json.loads(self._body() or b"{}")
                family = body.get("family", "ipv4")
                if family not in ("ipv4", "ipv6"):
                    return self._error(
                        400, f"unknown address family {family!r}")
                from ..ipam import IPAMError as _IPAMError
                try:
                    out = d.ipam_allocate(family,
                                          owner=body.get("owner", ""))
                except _IPAMError as e:
                    return self._error(502, str(e))
                return self._send(201, out)
            m = re.fullmatch(r"/ipam/([0-9a-fA-F.:]+)", path)
            if m and method == "DELETE":
                if not d.ipam_release(m.group(1)):
                    return self._error(404, "address not allocated")
                return self._send(200, {"released": m.group(1)})
            if path == "/endpoint" and method == "GET":
                return self._send(200, [ep.model()
                                        for ep in d.endpoints.endpoints()])
            m = re.fullmatch(r"/endpoint/(\d+)", path)
            if m:
                ep_id = int(m.group(1))
                if method == "PUT":
                    body = json.loads(self._body() or b"{}")
                    if d.endpoints.lookup(ep_id) is not None:
                        return self._error(409, "endpoint exists")
                    ep = d.endpoint_create(
                        ep_id, ipv4=body.get("ipv4", ""),
                        container_name=body.get("container-name", ""),
                        labels=body.get("labels", []))
                    return self._send(201, ep.model())
                if method == "GET":
                    ep = d.endpoints.lookup(ep_id)
                    if ep is None:
                        return self._error(404, "endpoint not found")
                    return self._send(200, ep.model())
                if method == "DELETE":
                    if not d.endpoint_delete(ep_id):
                        return self._error(404, "endpoint not found")
                    return self._send(200, {"deleted": ep_id})
                if method == "PATCH":
                    body = json.loads(self._body() or b"{}")
                    if "labels" in body:
                        try:
                            changed = d.endpoint_update_labels(
                                ep_id, body["labels"])
                        except KeyError:
                            return self._error(404, "endpoint not found")
                        return self._send(200, {"ok": True,
                                                "changed": changed})
                    return self._error(400, "nothing to patch")
            m = re.fullmatch(r"/endpoint/(\d+)/log", path)
            if m and method == "GET":
                # cilium endpoint log (endpoint_log.go / the status
                # ring of pkg/endpoint endpoint.go:1183)
                ep = d.endpoints.lookup(int(m.group(1)))
                if ep is None:
                    return self._error(404, "endpoint not found")
                return self._send(200, [
                    {"timestamp": ts, "state": st, "message": reason}
                    for ts, st, reason in ep.status_log])
            m = re.fullmatch(r"/endpoint/(\d+)/regenerate", path)
            if m and method == "POST":
                # cilium endpoint regenerate (endpoint_regenerate.go).
                # WAITING_TO_REGENERATE first, like every other trigger
                # path — without it a not-ready endpoint's build is
                # silently skipped by the state machine (the operator's
                # recovery command must actually recover)
                ep_id = int(m.group(1))
                ep = d.endpoints.lookup(ep_id)
                if ep is None:
                    return self._error(404, "endpoint not found")
                from ..endpoint import EndpointState as _ES
                # set_state can lose a race with a concurrent
                # transition (identity resolution finishing, a build
                # completing); retry briefly before concluding the
                # state machine genuinely refuses — a refused move
                # means the queued build would be dropped as
                # skipped-state, which must surface as 409, not as a
                # false queued:true
                moved = False
                for _ in range(3):
                    moved = ep.set_state(_ES.WAITING_TO_REGENERATE,
                                         "api regenerate")
                    if moved or ep.state == _ES.WAITING_TO_REGENERATE:
                        break
                    time.sleep(0.05)
                if not moved and ep.state != _ES.WAITING_TO_REGENERATE:
                    return self._error(
                        409, f"endpoint in state {ep.state!r} "
                             "cannot regenerate")
                queued = d.endpoints.queue_regeneration(ep_id)
                return self._send(200, {"queued": queued})
            m = re.fullmatch(r"/endpoint/(\d+)/healthz", path)
            if m and method == "GET":
                # cilium endpoint healthz (endpoint_healthz.go)
                ep = d.endpoints.lookup(int(m.group(1)))
                if ep is None:
                    return self._error(404, "endpoint not found")
                return self._send(200, {
                    "state": ep.state,
                    "policy-revision": ep.policy_revision,
                    "identity": ep.security_identity,
                    # waiting-to-regenerate is a routine queued-rebuild
                    # window (every policy import passes through it) —
                    # healthy, like the strictly later regenerating
                    "healthy": ep.state in ("ready", "regenerating",
                                            "waiting-to-regenerate")})
            m = re.fullmatch(r"/endpoint/(\d+)/config", path)
            if m and method == "PATCH":
                changes = json.loads(self._body() or b"{}")
                try:
                    n = d.endpoint_config_patch(int(m.group(1)), changes)
                except KeyError:
                    return self._error(404, "endpoint not found")
                return self._send(200, {"changed": n})
            if path == "/identity" and method == "GET":
                labels = qs.get("labels")
                if labels:
                    ident = d.identity_get(labels=labels)
                    if ident is None:
                        return self._error(404, "identity not found")
                    return self._send(200, ident)
                return self._send(200, d.identity_list())
            m = re.fullmatch(r"/identity/(\d+)", path)
            if m and method == "GET":
                ident = d.identity_get(numeric_id=int(m.group(1)))
                if ident is None:
                    return self._error(404, "identity not found")
                return self._send(200, ident)
            if path == "/service":
                if method == "GET":
                    return self._send(200, _service_dump(d))
                if method == "PUT":
                    body = json.loads(self._body() or b"{}")
                    d.service_upsert(
                        body["vip"], int(body["port"]),
                        [(b["ip"], int(b["port"]))
                         for b in body.get("backends", [])],
                        proto=int(body.get("proto", 6)))
                    return self._send(200, {"ok": True})
                if method == "DELETE":
                    body = json.loads(self._body() or b"{}")
                    ok = d.service_delete(body["vip"], int(body["port"]),
                                          proto=int(body.get("proto", 6)))
                    return self._send(200 if ok else 404, {"deleted": ok})
            m = re.fullmatch(r"/service/(\d+)", path)
            if m:
                # GET/DELETE /service/{id} (api/v1 service by id)
                sid = int(m.group(1))
                svc = d.service_find_by_id(sid)
                if method == "GET":
                    if svc is None:
                        return self._error(404, "service not found")
                    return self._send(200, _service_model(svc))
                if method == "DELETE":
                    if not d.service_delete_by_id(sid):
                        return self._error(404, "service not found")
                    return self._send(200, {"deleted": sid})
            m = re.fullmatch(r"/endpoint/(\d+)/labels", path)
            if m:
                # GET/PUT /endpoint/{id}/labels (endpoint_labels.go)
                ep = d.endpoints.lookup(int(m.group(1)))
                if ep is None:
                    return self._error(404, "endpoint not found")
                if method == "GET":
                    return self._send(200, {
                        "labels": [str(l) for l in ep.labels.to_array()],
                        "identity": ep.security_identity})
                if method in ("PUT", "PATCH"):
                    body = json.loads(self._body() or b"{}")
                    changed = d.endpoint_update_labels(
                        ep.id, body.get("labels", []))
                    return self._send(200, {"ok": True,
                                            "changed": changed})
            if path == "/prefilter":
                if method == "GET":
                    cidrs, rev = d.datapath.prefilter.dump()
                    return self._send(200, {"cidrs": cidrs,
                                            "revision": rev})
                if method == "PATCH":
                    body = json.loads(self._body() or b"{}")
                    rev = d.prefilter_update(body.get("cidrs", []))
                    return self._send(200, {"revision": rev})
                if method == "DELETE":
                    body = json.loads(self._body() or b"{}")
                    rev = d.prefilter_delete(body.get("cidrs", []))
                    return self._send(200, {"revision": rev})
            if path == "/monitor" and method == "GET":
                n = int(qs.get("n", ["100"])[0])
                drops = qs.get("drops", ["false"])[0] == "true"
                # agent | l7 | datapath (named sentinel for kind "")
                kind = qs.get("kind", [None])[0]
                if kind == "datapath":
                    kind = ""
                # resume cursor: only events with seq > since (the
                # polling CLI follows without a dedupe set)
                since = int(qs.get("since", ["0"])[0])
                events = d.monitor.tail(n, drops_only=drops, kind=kind,
                                        since=since)
                return self._send(200, [_monitor_event_dict(e)
                                        for e in events])
            if path == "/monitor/stats" and method == "GET":
                return self._send(200, d.monitor.stats())
            if path == "/flows" and method == "GET":
                # Hubble observer surface (observer GetFlows analog):
                # filter grammar in the query string, cursor paging
                # via since=<seq>, federation via federated=true,
                # one dataplane shard via shard=<k> (sharded daemons)
                from ..hubble.filter import FlowFilter
                flt = FlowFilter.from_query(qs)
                n = int(qs.get("n", ["100"])[0])
                if qs.get("federated", ["false"])[0] in ("1", "true"):
                    if d.hubble_relay is None:
                        return self._error(503, "no relay configured")
                    return self._send(200, d.hubble_relay.get_flows(
                        flt, limit=n))
                if d.hubble is None:
                    return self._error(503, "hubble disabled")
                shard_q = qs.get("shard", [None])[0]
                if hasattr(d.hubble, "local_answer"):
                    # sharded: merged shard-attributed flows plus the
                    # per-shard fail-open statuses
                    return self._send(200, d.hubble.local_answer(
                        flt, limit=n,
                        shard=int(shard_q) if shard_q is not None
                        else None))
                if shard_q is not None:
                    return self._error(
                        400, "shard= requires a sharded dataplane "
                             "(dataplane_shards >= 2)")
                return self._send(200, {
                    "flows": d.hubble.get_flows(flt, limit=n),
                    "seq": d.hubble.last_seq,
                    "node": d.hubble.node})
            if path == "/flows/stats" and method == "GET":
                if d.hubble is None:
                    return self._error(503, "hubble disabled")
                out = d.hubble.stats()
                if d.hubble_relay is not None:
                    out["relay"] = d.hubble_relay.node_health()
                agg = qs.get("aggregated", ["false"])[0]
                if agg in ("1", "true"):
                    out["flows"] = d.hubble.aggregate_snapshot()
                return self._send(200, out)
            if path == "/node" and method == "GET":
                # cilium node list (pkg/node)
                return self._send(200, [
                    n.to_model() for n in
                    (d.node_registry.nodes() if d.node_registry
                     else d.node_manager.nodes())])
            if path == "/map" and method == "GET":
                # cilium map list / bpf map show analog
                return self._send(200, d.datapath.map_inventory())
            if path.startswith("/map/") and method == "GET":
                # cilium bpf {ipcache,ct,tunnel,lb,prefilter} list
                name = path[len("/map/"):]
                limit = int(qs.get("n", ["4096"])[0])
                try:
                    return self._send(
                        200, d.datapath.map_dump(name,
                                                 max_entries=limit))
                except KeyError:
                    return self._error(404, f"unknown map {name!r}")
            if path == "/policy/wait" and method == "POST":
                body = json.loads(self._body() or b"{}")
                rev = body.get("revision")
                ok = d.wait_for_policy_revision(
                    rev, timeout=float(body.get("timeout", 30)))
                return self._send(200, {
                    "realized": ok, "revision": d.repo.revision})
            return self._error(404, f"no route for {method} {path}")
        except PolicyError as exc:
            return self._error(400, str(exc))
        except IPAMError as exc:
            return self._error(409, str(exc))
        except (ValueError, KeyError) as exc:
            return self._error(400, f"bad request: {exc}")

    def do_GET(self):
        self._route("GET")

    def do_PUT(self):
        self._route("PUT")

    def do_POST(self):
        self._route("POST")

    def do_DELETE(self):
        self._route("DELETE")

    def do_PATCH(self):
        self._route("PATCH")


def _u32_to_ipv4(v: int) -> str:
    return ".".join(str((v >> s) & 0xFF) for s in (24, 16, 8, 0))


def _words_to_ipv6(words) -> str:
    import ipaddress
    v = 0
    for w in words:
        v = (v << 32) | (int(w) & 0xFFFFFFFF)
    return str(ipaddress.IPv6Address(v))


def _service_model(svc) -> Dict:
    from .daemon import V6_SERVICE_ID_BASE
    v6 = isinstance(svc.vip, tuple)
    addr = _words_to_ipv6 if v6 else _u32_to_ipv4
    sid = svc.rev_nat_index + (V6_SERVICE_ID_BASE if v6 else 0)
    return {"id": sid, "vip": addr(svc.vip),
            "port": svc.port, "proto": svc.proto,
            "backends": [{"ip": addr(b.addr), "port": b.port}
                         for b in svc.backends]}


def _service_dump(d: Daemon):
    # v6 services (lb6 registry) are part of the same audit surface
    return [_service_model(s) for s in d.datapath.lb.services()] + \
        [_service_model(s) for s in d.datapath.lb6_service_list()]


class APIServer:
    """Threaded REST server bound to localhost."""

    def __init__(self, daemon: Daemon, host: str = "127.0.0.1",
                 port: int = 0):
        handler = type("BoundHandler", (_Handler,), {"daemon": daemon})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.host, self.port = self.httpd.server_address
        self._thread = threading.Thread(target=self.httpd.serve_forever,
                                        daemon=True, name="api-server")

    def start(self) -> "APIServer":
        self._thread.start()
        return self

    @property
    def base_url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        self._thread.join(timeout=5)
