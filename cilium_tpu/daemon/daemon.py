"""The Daemon.

Reference: daemon/daemon.go:1090 NewDaemon (bootstrap order), daemon/
policy.go:171 PolicyAdd / :48 TriggerPolicyUpdates, daemon/endpoint.go
(REST endpoint lifecycle), daemon/state.go (restore), daemon/status.go.

TPU shape: the daemon owns one Datapath (device tables + CT state), one
DeviceTableManager-backed regeneration pipeline, and replicates control
state (identities, ipcache, nodes) through the kvstore exactly like the
reference — the "communication backend" is the kvstore plus the device
swap path, not NCCL.
"""

from __future__ import annotations

import ipaddress
import json
import os
import threading
import time

import numpy as np
from typing import Dict, List, Optional, Sequence, Tuple

from .. import identity as idpkg
from ..clustermesh import ClusterMesh
from ..datapath.engine import Datapath
from ..datapath.lb import Backend, Service
from ..endpoint import (DeviceTableManager, Endpoint, EndpointManager,
                        EndpointState)
from ..identity import (Identity, IdentityCache, LocalIdentityAllocator,
                        is_local_scope_identity)
from ..ipcache import (SOURCE_AGENT_LOCAL, SOURCE_GENERATED, IPCache,
                       IPIdentityWatcher, KVStoreIPCacheSyncer,
                       allocate_cidr_identities, release_cidr_identities)
from ..ipcache.kvstore_sync import IP_IDENTITIES_PATH
from ..kvstore import backend as kvbackend
from ..kvstore.identity_allocator import (IDENTITY_PREFIX,
                                          DistributedIdentityAllocator,
                                          FallbackIdentityAllocator)
from ..kvstore.outage import OutageGuard
from ..node.registry import NODES_PATH
from ..ipam import HostScopeIPAM, IPAMError
from ..l7.dns import DNSCache, DNSPoller, inject_to_cidr_set
from ..labels import Labels
from ..monitor import MonitorHub
from ..node import Node, NodeManager, NodeRegistry
from ..observability import (PolicyPropagationTracker, jit_telemetry,
                             pipeline_report, slo_tracker, tracer)
from ..observability.events import recorder as flight_recorder
from ..policy.api import Rule
from ..policy.mapstate import PolicyMapState
from ..policy.repository import Repository
from ..policy.trace import SearchContext, traced_context
from ..proxy import ProxyManager
from ..migrate import MigrationError
from ..utils.lock import RMutex
from ..utils.controller import ControllerManager, ControllerParams
from ..utils.metrics import (ENDPOINT_STATE_COUNT, IDENTITY_COUNT,
                             POLICY_COUNT, POLICY_IMPORT_ERRORS,
                             POLICY_REGENERATION_COUNT, POLICY_REVISION,
                             PROXY_REDIRECTS, registry as metrics_registry)
from ..utils.option import DaemonConfig, parse_option_value
from ..utils import resilience as transport_resilience
from ..utils.trigger import Trigger
from ..compiler.lpm import ipv4_to_u32

# /service/{id} API ids: v6 services offset into a disjoint range
# (each family allocates rev-NAT indices independently)
V6_SERVICE_ID_BASE = 1_000_000


class Daemon:
    """One agent instance."""

    def __init__(self, config: Optional[DaemonConfig] = None,
                 kvstore_backend=None, node_name: str = "node-local",
                 builders: int = 4):
        self.config = config or DaemonConfig()
        self.node_name = node_name
        self.repo = Repository()
        self.ipcache = IPCache()
        self.monitor = MonitorHub()
        self.proxy = ProxyManager(self.config.proxy_port_min,
                                  self.config.proxy_port_max)
        self.controllers = ControllerManager()
        # the verdict dataplane: single-engine by default; with
        # dataplane_shards >= 2 the full fused pipeline shards across
        # the (dp, ep) device mesh — endpoint-axis table slices with
        # per-shard CT/flow state and per-shard fault domains
        # (parallel/sharded.py)
        if self.config.dataplane_shards >= 2:
            from ..parallel.sharded import ShardedDatapath
            self.datapath = ShardedDatapath(
                n_shards=self.config.dataplane_shards,
                ct_slots=self.config.ct_slots)
        else:
            self.datapath = Datapath(ct_slots=self.config.ct_slots)
        # runtime self-telemetry (observability/): span tracing across
        # the control plane, the policy-propagation latency tracker
        # closed by the engine's revision-served hook, and the
        # engine-side stage/jit/verdict accounting — one config switch
        # gates all of it
        tracer.configure(enabled=self.config.enable_tracing,
                         capacity=self.config.trace_capacity)
        self.tracer = tracer
        # serving SLO tier defaults (observability/slo.py): lanes with
        # an admission deadline use it as their objective; everything
        # else is judged against this one
        slo_tracker.configure(
            objective_s=self.config.serving_slo_objective_s,
            error_budget=self.config.serving_slo_error_budget)
        self.propagation = PolicyPropagationTracker(tracer=tracer)
        self.datapath.telemetry_enabled = self.config.enable_tracing
        self.datapath.on_revision_served = \
            self.propagation.revision_served
        # dataplane supervision (datapath/supervisor.py): overload
        # admission control + device-fault circuit breaking with
        # fail-static host fallback on the serving lane; the recovery
        # gate is the FULL drift audit (PR 6) — a rebuilt device table
        # only resumes serving after replaying clean against the host
        # policy oracles
        self.datapath.configure_supervision(
            enabled=self.config.enable_supervision,
            watchdog_s=self.config.supervisor_watchdog_s,
            failure_threshold=self.config.supervisor_failure_threshold,
            reset_s=self.config.supervisor_reset_s,
            new_flow_policy=self.config.degraded_new_flow_policy,
            recovery_gate=self._dataplane_recovery_gate,
            max_pending=self.config.serving_max_pending,
            default_deadline=self.config.serving_deadline_s or None)
        # incremental policy realization: one endpoint's regeneration
        # writes one device-table row (syncPolicyMap analog); the
        # engine re-jits only when the stack's geometry grows.  In
        # sharded mode the row write (and any grow/re-jit) touches
        # ONLY the owning shard's slice.
        if self.config.dataplane_shards >= 2:
            from ..parallel.sharded import ShardedTableManager
            self.table_mgr = ShardedTableManager(
                self.config.dataplane_shards)
        else:
            self.table_mgr = DeviceTableManager()
        self.datapath.use_table_manager(self.table_mgr)
        # host fast path: C++ per-endpoint verdict caches (the eBPF
        # hit-path analog); optional — the TPU path works without it
        try:
            from ..native.fastpath import HostVerdictPath
            self.host_path = HostVerdictPath()
        except (RuntimeError, OSError):
            self.host_path = None
        self.dns_cache = DNSCache()
        self.dns_poller: Optional[DNSPoller] = None
        self.started_at = time.time()

        # daemon-owned host-scope IPAM (daemon/ipam.go handlers): the
        # REST /ipam routes and the docker libnetwork driver allocate
        # from these; the router IP (offset 1) is the node's gateway
        self.ipam = HostScopeIPAM(self.config.ipv4_range)
        self.ipam6 = HostScopeIPAM(self.config.ipv6_range) \
            if self.config.enable_ipv6 else None
        self.host_ipv4 = self.ipam.router_ip()
        # NB: HostScopeIPAM defines __len__, so an empty pool is falsy
        # — identity checks only
        self.host_ipv6 = self.ipam6.router_ip() \
            if self.ipam6 is not None else ""
        if self.host_ipv6:
            # the ICMPv6/NDP responder answers NS/echo for this
            # address (icmp6.h ROUTER_IP; written by datapath init)
            self.datapath.set_router_ip6(self.host_ipv6)

        # L7 access-log records join the monitor stream
        # (LogRecordNotify analog: pkg/proxy/logger -> monitor)
        self.proxy.access_log.subscribers.append(self.monitor.notify_l7)
        self.monitor.notify_agent("agent-start", node_name)

        # Hubble flow observability (hubble/): the observer rings flow
        # records from the sampled datapath events + the structured L7
        # access log; the device aggregation table fuses into the
        # datapath steps; the relay federates /flows across peers
        # discovered through the node registry + clustermesh
        if getattr(self.config, "enable_hubble", True):
            from ..hubble import FlowFilter, FlowObserver, HubbleRelay
            if self.config.hubble_flow_slots > 0:
                self.datapath.enable_flow_aggregation(
                    slots=self.config.hubble_flow_slots,
                    max_probe=self.config.hubble_flow_probe)
            if self.config.dataplane_shards >= 2:
                # the federated cross-shard observer (hubble/
                # federation.py): per-shard flow stores behind one
                # cursor, per-shard device-table drains, and merged
                # shard-attributed answers with fail-open flags
                from ..hubble.federation import ShardedObserver
                self.hubble = ShardedObserver(
                    node=node_name, datapath=self.datapath,
                    capacity=self.config.hubble_ring_capacity)
                if self.config.hubble_drain_interval_s > 0:
                    self.controllers.update_controller(
                        "hubble-shard-drain", ControllerParams(
                            do_func=lambda: self.hubble.drain(),
                            run_interval=self.config
                            .hubble_drain_interval_s))
            else:
                self.hubble = FlowObserver(
                    node=node_name,
                    capacity=self.config.hubble_ring_capacity,
                    datapath=self.datapath)
            self.hubble.attach_monitor(self.monitor)
            self.hubble.attach_access_log(self.proxy.access_log)

            def _local_fetch(query, since, limit):
                flt = FlowFilter.from_query(query)
                if hasattr(self.hubble, "local_answer"):
                    # sharded: the answer carries per-shard fail-open
                    # statuses the relay propagates mesh-wide
                    return self.hubble.local_answer(
                        flt, since=since, limit=limit)
                return {"flows": self.hubble.get_flows(
                    flt, since=since, limit=limit)}

            self.hubble_relay = HubbleRelay(
                local_name=node_name, local_fetch=_local_fetch,
                node_source=self._hubble_peer_urls,
                deadline_s=self.config.hubble_relay_deadline_s)
        else:
            self.hubble = None
            self.hubble_relay = None

        # the node manager must exist before the registry: registry
        # construction synchronously replays pre-existing nodes into
        # _on_node_update, which programs it
        self.node_manager = NodeManager(
            f"{self.config.cluster_name}/{node_name}",
            ipcache=self.ipcache,
            mode="tunnel" if self.config.tunnel != "disabled" else "direct",
            datapath=self.datapath)

        # identity allocation: distributed when a kvstore is attached
        # (daemon.go:1295 InitIdentityAllocator).  The backend is
        # wrapped in the control-plane outage guard (kvstore/outage.py):
        # pass-through bookkeeping by default (the status() staleness
        # fix), full degrade/journal/reconcile machinery when
        # enable_kvstore_survival is on.
        self._kv_guard = None
        # promotion-time identity events must not fan a regeneration
        # storm across every endpoint; see _on_identity_change.  The
        # id-keyed map outlives the time window because the watch echo
        # of a promotion arrives only after the streams re-establish.
        self._suppress_regen_until = 0.0
        self._suppressed_ident_ids: Dict[int, float] = {}
        if kvstore_backend is not None:
            self._kv_guard = OutageGuard(
                kvstore_backend,
                degrade=self.config.enable_kvstore_survival,
                failure_threshold=self.config.kvstore_failure_threshold,
                probe_interval=self.config.kvstore_probe_interval_s,
                grace_s=self.config.kvstore_grace_s,
                journal_max=self.config.kvstore_journal_max,
                replay_ops_per_s=self.config
                .kvstore_reconcile_ops_per_s)
            kvstore_backend = self._kv_guard
        self.kv = kvstore_backend
        if self.kv is not None:
            # remote identity churn must retrigger endpoint policy
            # recompute (pkg/identity identityWatcher ->
            # TriggerPolicyUpdates): a peer node allocating a new
            # identity changes what our selectors match
            allocator = DistributedIdentityAllocator(
                self.kv, node=node_name,
                cluster_id=self.config.cluster_id,
                on_change=self._on_identity_change)
            if self.config.enable_kvstore_survival:
                # outage fallback: adopt cached bindings, else allocate
                # node-local ephemeral identities promoted on reconnect
                allocator = FallbackIdentityAllocator(
                    allocator, guard=self._kv_guard,
                    on_change=self._on_identity_change)
            self.identity_allocator = allocator
            self._ip_syncer = KVStoreIPCacheSyncer(self.kv)
            self.ipcache.add_listener(self._ip_syncer.listener(),
                                      replay=False)
            self._ip_watcher = IPIdentityWatcher(
                self.kv, self.ipcache,
                restart=self.config.enable_kvstore_survival,
                restart_backoff_s=self.config.kvstore_probe_interval_s)
            self._ip_watcher.start()
            self.node_registry = NodeRegistry(
                self.kv,
                on_node_update=self._on_node_update,
                on_node_delete=self._on_node_delete)
            # the reconnect relist-and-diff repairs locally owned keys
            # under exactly the replicated-store prefixes
            self._kv_guard.track_prefix(IDENTITY_PREFIX + "/")
            self._kv_guard.track_prefix(IP_IDENTITIES_PATH + "/")
            self._kv_guard.track_prefix(NODES_PATH + "/")
        else:
            self.identity_allocator = LocalIdentityAllocator(
                cluster_id=self.config.cluster_id)
            self._ip_syncer = None
            self._ip_watcher = None
            self.node_registry = None
        self.clustermesh = ClusterMesh(
            ipcache=self.ipcache,
            on_node_update=self.node_manager.node_updated,
            on_node_delete=self.node_manager.node_deleted)

        # policy-held CIDR identities: prefix -> (Identity, refcount);
        # refs are PER RULE occurrence so partial deletes balance
        self._cidr_idents: Dict[str, Tuple[Identity, int]] = {}
        # rule object -> prefixes it currently holds refs for
        self._rule_prefixes: Dict[int, List[str]] = {}
        self._fqdn_rules: List[Rule] = []
        self._lock = RMutex("daemon")

        # endpoint regeneration pipeline (daemon.go:1133 builders)
        self.endpoints = EndpointManager(
            regenerate_fn=self._regenerate_endpoint, builders=builders,
            on_outcome=lambda ep_id, ok: self.monitor.notify_agent(
                "endpoint-regenerate-success" if ok
                else "endpoint-regenerate-failure", f"id={ep_id}"))
        self._regen_trigger = Trigger(
            lambda reasons: self.endpoints.regenerate_all(
                ",".join(reasons) or "policy-update"),
            min_interval=0.01, name="policy-updates")

        # ipcache churn -> datapath LPM reload, debounced
        self._lpm_trigger = Trigger(
            lambda _r: self.datapath.load_ipcache(
                *self.ipcache.to_lpm_prefix_families()),
            min_interval=0.01, name="ipcache-lpm")
        self.ipcache.add_listener(
            lambda *_a: self._lpm_trigger.trigger("ipcache"), replay=False)

        # verdict provenance (datapath/verdict.py): per-packet
        # matched-rule + decision-tier attribution in the jitted
        # steps, plus the periodic drift audit — the continuous
        # correctness oracle for the policy compiler (replay through
        # the REAL device tables vs the host SearchContext /
        # compute_desired_policy_map_state simulations)
        if self.config.enable_provenance:
            self.datapath.enable_provenance()
        # inline threat scoring (cilium_tpu/threat/): fuse the
        # quantized per-packet anomaly scorer into both family
        # pipelines.  Bootstrap weights are the hand-seeded default
        # model; training (threat_train) hot-swaps better ones through
        # the delta-apply path with zero repacks.
        self._threat_trainer = None
        if self.config.enable_threat:
            from ..threat import ThreatTrainer, default_model
            from ..utils.metrics import THREAT_MODEL_GENERATION
            self._threat_trainer = ThreatTrainer()
            model = default_model(self._threat_config_from_options())
            self.datapath.enable_threat(
                model, buckets=self.config.threat_buckets,
                window_s=self.config.threat_window_s)
            THREAT_MODEL_GENERATION.set(model.config.generation)
        # device-resident traffic analytics (cilium_tpu/analytics/):
        # count-min sketches + cardinality registers fused into both
        # family pipelines; the drain controller swaps the A/B epoch,
        # decodes the quiesced section into the capped top-K byte
        # gauge, and rings heavy-hitter / scan-suspect transitions
        # into the incident flight recorder
        self._analytics_hh_live: set = set()
        self._analytics_scan_live: set = set()
        self._analytics_exported: set = set()
        self._analytics_last: Optional[Dict] = None
        if self.config.enable_analytics:
            self.datapath.enable_analytics(
                width=self.config.analytics_width,
                depth=self.config.analytics_depth,
                lanes=self.config.analytics_lanes,
                stripe=self.config.analytics_stripe)
            if self.config.analytics_drain_interval_s > 0:
                self.controllers.update_controller(
                    "analytics-drain", ControllerParams(
                        do_func=self.analytics_drain,
                        run_interval=self.config
                        .analytics_drain_interval_s))
        self._drift_report: Optional[Dict] = None
        self._last_replay: Optional[Dict] = None
        self._drift_rng = np.random.default_rng(0xC111)
        if self.config.drift_audit_interval_s > 0:
            self.controllers.update_controller(
                "policy-drift-audit", ControllerParams(
                    do_func=self.run_drift_audit,
                    run_interval=self.config.drift_audit_interval_s))

        # periodic CT GC (ctmap.go GC sweep analog)
        self.controllers.update_controller(
            "ct-gc", ControllerParams(
                do_func=lambda: self.datapath.gc(), run_interval=5.0))
        # the control-plane outage driver: probes the kvstore when
        # idle, detects sustained failure, and on reconnect runs the
        # journal replay + relist reconcile followed by local-identity
        # promotion (opt-in; kvstore/outage.py)
        if self._kv_guard is not None and \
                self.config.enable_kvstore_survival:
            self.controllers.update_controller(
                "kvstore-outage", ControllerParams(
                    do_func=self._kvstore_tick,
                    run_interval=self.config.kvstore_probe_interval_s))
        # periodic CT checkpoint: a kill -9'd agent otherwise loses
        # every established flow (shutdown() is the only other writer)
        if self.config.state_dir and \
                self.config.ct_checkpoint_interval_s > 0:
            self.controllers.update_controller(
                "ct-checkpoint", ControllerParams(
                    do_func=self.checkpoint_ct,
                    run_interval=self.config.ct_checkpoint_interval_s))

    # ------------------------------------------------------------ nodes

    def _on_identity_change(self, _typ: str, ident) -> None:
        # may fire during __init__ (watch replay) before the trigger
        # exists; those identities are covered by the first build anyway
        now = time.monotonic()
        if now < getattr(self, "_suppress_regen_until", 0.0):
            # local-identity promotion window: the promotion path
            # queues regeneration for exactly the affected endpoints —
            # the watch echo of our own re-allocations must not fan a
            # full regeneration storm on top of it
            return
        suppressed = getattr(self, "_suppressed_ident_ids", None)
        if suppressed and ident is not None:
            until = suppressed.get(getattr(ident, "id", None))
            if until is not None:
                if now < until:
                    # the watch echo of a promoted identity: streams
                    # re-establish only after reconnect, so this event
                    # lands well past the promotion window — still our
                    # own re-allocation, still not a storm trigger
                    return
                suppressed.pop(ident.id, None)
        trigger = getattr(self, "_regen_trigger", None)
        if trigger is not None:
            trigger.trigger("identity-change")

    # ------------------------------------- control-plane survivability

    def _kvstore_tick(self) -> None:
        """The kvstore-outage controller body: drive the outage
        guard's detector/reconcile state machine, then promote any
        node-local ephemeral identities once the control plane is
        healthy again."""
        guard = self._kv_guard
        event = guard.tick()
        if event.get("reconciled"):
            self.monitor.notify_agent(
                "kvstore-reconnected",
                f"reconcile={event.get('report')}")
        if guard.mode == "ok" and \
                isinstance(self.identity_allocator,
                           FallbackIdentityAllocator) and \
                self.identity_allocator.local_count():
            self._promote_local_identities()

    def _promote_local_identities(self) -> Dict[str, int]:
        """Re-key everything holding a node-local ephemeral identity
        to a cluster-scope one through the (now healthy) distributed
        allocator, regenerating ONLY the affected endpoints: the
        re-keyed ones plus any endpoint whose realized policy map
        references a promoted ID — incremental delta-applies, never a
        full regeneration storm."""
        fb = self.identity_allocator
        mapping: Dict[int, int] = {}   # local id -> cluster id
        # two suppression layers for the watch echo of our own
        # re-allocations: a rolling time window (bumped per promoted
        # identity — a slow kvstore must not outlive it mid-loop) and
        # an id-keyed map (the echo can land only after the watch
        # streams re-establish, well past any fixed window)
        window = max(1.0, 4 * self.config.kvstore_probe_interval_s)
        suppress_for = max(30.0,
                           8 * self.config.kvstore_probe_interval_s)
        self._suppress_regen_until = time.monotonic() + window

        def _register(old_id: int, new_id: int) -> None:
            mapping[old_id] = new_id
            until = time.monotonic() + suppress_for
            self._suppressed_ident_ids[old_id] = until
            self._suppressed_ident_ids[new_id] = until
            self._suppress_regen_until = time.monotonic() + window

        promoted_cidrs = rekeyed = 0
        try:
            # policy-held CIDR identities first (prefix -> identity)
            with self._lock:
                local_cidrs = [
                    (p, ident, n)
                    for p, (ident, n) in self._cidr_idents.items()
                    if is_local_scope_identity(ident.id)]
            for prefix, old, refs in local_cidrs:
                # keep the window alive across each kvstore round-trip
                self._suppress_regen_until = time.monotonic() + window
                new = None
                for _ in range(refs):
                    new, _is_new = fb.allocate(old.labels)
                if new is None or is_local_scope_identity(new.id):
                    continue  # control plane flapped again; next tick
                _register(old.id, new.id)
                with self._lock:
                    self._cidr_idents[prefix] = (new, refs)
                self.ipcache.upsert(prefix, new.id, SOURCE_GENERATED,
                                    metadata="cidr-policy")
                for _ in range(refs):
                    fb.release(old)
                promoted_cidrs += 1
            # endpoint identities: re-resolve labels through the
            # healthy allocator (the normal update path — allocate new,
            # release local, device identity + ipcache in lockstep)
            rekeyed_ids = []
            for ep in self.endpoints.endpoints():
                old_id = ep.security_identity
                if not is_local_scope_identity(old_id):
                    continue
                self._suppress_regen_until = time.monotonic() + window
                changed = ep.update_labels(fb, ep.labels)
                if not changed or \
                        is_local_scope_identity(ep.security_identity):
                    continue
                _register(old_id, ep.security_identity)
                if ep.table_slot is not None:
                    self.datapath.set_endpoint_identity(
                        ep.table_slot, ep.security_identity)
                if ep.ipv4:
                    self.ipcache.upsert(ep.ipv4, ep.security_identity,
                                        SOURCE_AGENT_LOCAL,
                                        metadata=f"endpoint:{ep.id}")
                rekeyed_ids.append(ep.id)
                rekeyed += 1
            # the actually-diverged endpoint set: re-keyed endpoints
            # plus endpoints whose realized maps name a promoted ID
            referencing = []
            if mapping:
                for ep in self.endpoints.endpoints():
                    if ep.id in rekeyed_ids:
                        continue
                    state = PolicyMapState(ep.realized)
                    if any(k.identity in mapping for k in state.keys()):
                        referencing.append(ep.id)
                for eid in rekeyed_ids + referencing:
                    self.endpoints.queue_regeneration(eid)
        finally:
            IDENTITY_COUNT.set(len(self.identity_allocator))
        report = {"promoted": len(mapping), "rekeyed": rekeyed,
                  "cidrs": promoted_cidrs,
                  "regenerated": rekeyed + len(referencing)
                  if mapping else 0}
        if mapping:
            self.monitor.notify_agent(
                "identity-promotion",
                f"promoted={len(mapping)} rekeyed={rekeyed} "
                f"regenerated={report['regenerated']}")
        return report

    def _on_node_update(self, node: Node) -> None:
        self.node_manager.node_updated(node)

    def _on_node_delete(self, full_name: str) -> None:
        self.node_manager.node_deleted(full_name)

    def register_node(self, ipv4: str, pod_cidr: str,
                      hubble_address: str = "") -> Node:
        """Publish this node (pkg/node/store.go:60).  A non-empty
        ``hubble_address`` advertises this agent's /flows observer so
        peers' relays federate through it."""
        from ..node.node import NodeAddress
        node = Node(name=self.node_name,
                    cluster=self.config.cluster_name,
                    cluster_id=self.config.cluster_id,
                    addresses=[NodeAddress(type="InternalIP", ip=ipv4)],
                    ipv4_alloc_cidr=pod_cidr,
                    hubble_address=hubble_address or None)
        if hubble_address and self.hubble_relay is not None:
            # the registry will announce this node under its full
            # name; the relay must not treat that as a remote peer
            self.hubble_relay.local_names.add(node.full_name)
        if self.node_registry is not None:
            self.node_registry.register_local(node)
        return node

    def _hubble_peer_urls(self) -> Dict[str, str]:
        """Relay peer discovery: every node known through the local
        registry or the clustermesh that advertises a Hubble address
        (hubble-relay's peer service, fed from the node store)."""
        out: Dict[str, str] = {}
        registry = getattr(self, "node_registry", None)
        if registry is not None:
            for node in registry.nodes():
                if node.hubble_address:
                    out[node.full_name] = node.hubble_address
        mesh = getattr(self, "clustermesh", None)
        if mesh is not None:
            for node in mesh.peer_nodes():
                if node.hubble_address:
                    out[node.full_name] = node.hubble_address
        return out

    # ----------------------------------------------------------- policy

    def policy_add(self, rules: Sequence[Rule],
                   replace: bool = False) -> int:
        """Import rules (daemon/policy.go:171 PolicyAdd): mark/register
        ToFQDNs rules, allocate CIDR identities + ipcache entries for
        referenced prefixes (one ref per rule occurrence), insert into
        the repo, trigger regeneration.
        """
        t_import = time.perf_counter()
        try:
            for r in rules:
                r.sanitize()
        except Exception:
            POLICY_IMPORT_ERRORS.inc()
            raise
        # FQDN rules: register with the poller; DNS changes re-inject
        # ToCIDRSet and retrigger regeneration (pkg/fqdn/helpers.go:45)
        for r in rules:
            if self._rule_has_fqdn(r):
                with self._lock:
                    self._fqdn_rules.append(r)
                if self.dns_poller is not None:
                    self.dns_poller.register_rule(r)
                inject_to_cidr_set(r, self.dns_cache)

        with self._lock:
            if replace:
                for r in rules:
                    if len(r.labels):
                        self._forget_rules(self.repo.search(r.labels))
                        self.repo.delete_by_labels(r.labels)
            for r in rules:
                prefixes = self._rule_cidr_prefixes(r)
                self._retain_prefixes(prefixes)
                self._rule_prefixes[id(r)] = prefixes
            rev = self.repo.add_list(list(rules))
        POLICY_COUNT.set(len(self.repo))
        POLICY_REVISION.set(rev)
        # policy-propagation tracking: stamp the revision at import;
        # the regeneration pipeline and the engine's revision-served
        # hook fill in compile -> device-apply -> first-verdict, and
        # the delay histogram closes on the last hop
        self.propagation.revision_imported(
            rev, rules=len(rules),
            import_seconds=time.perf_counter() - t_import)
        self.monitor.notify_agent("policy-updated",
                                  f"revision={rev} rules={len(rules)}")
        self.trigger_policy_updates("policy-add")
        return rev

    def policy_delete(self, labels) -> Tuple[int, int]:
        """daemon/policy.go PolicyDelete: drop rules, release their CIDR
        identity refs, deregister their FQDN state."""
        with self._lock:
            doomed = self.repo.search(labels) if len(labels) else \
                self.repo.rules
            rev, deleted = self.repo.delete_by_labels(labels)
            if deleted:
                self._forget_rules(doomed)
        POLICY_COUNT.set(len(self.repo))
        POLICY_REVISION.set(rev)
        if deleted:
            self.monitor.notify_agent(
                "policy-deleted", f"revision={rev} rules={deleted}")
            self.trigger_policy_updates("policy-delete")
        return rev, deleted

    def _forget_rules(self, doomed: Sequence[Rule]) -> None:
        """Release per-rule CIDR refs + FQDN registration (lock held)."""
        doomed_ids = {id(r) for r in doomed}
        for r in doomed:
            self._release_prefixes(
                self._rule_prefixes.pop(id(r), None) or
                self._rule_cidr_prefixes(r))
        self._fqdn_rules = [r for r in self._fqdn_rules
                            if id(r) not in doomed_ids]

    def _resync_rule_prefixes_locked(self, rule: Rule) -> bool:
        """Re-diff one rule's CIDR prefixes against its held refs and
        retain/release the delta (newly referenced IPs need identities
        + ipcache entries or their CIDR labels never match). Returns
        True when anything changed. Lock held."""
        old = self._rule_prefixes.get(id(rule), [])
        new = self._rule_cidr_prefixes(rule)
        if new == old:
            return False
        old_set, new_set = set(old), set(new)
        self._retain_prefixes(sorted(new_set - old_set))
        self._release_prefixes(sorted(old_set - new_set))
        self._rule_prefixes[id(rule)] = new
        return True

    def resync_rule_prefixes(self, rules: Sequence[Rule]) -> int:
        """Public entry for translators that rewrite rules in place
        (k8s ToServices, FQDN): returns rules whose refs changed."""
        n = 0
        with self._lock:
            live = {id(x) for x in self.repo.rules}
            for r in rules:
                if id(r) in self._rule_prefixes or id(r) in live:
                    if self._resync_rule_prefixes_locked(r):
                        n += 1
        return n

    def _retain_prefixes(self, prefixes: Sequence[str]) -> None:
        """One ref per occurrence (lock held)."""
        for p in prefixes:
            if p in self._cidr_idents:
                ident, n = self._cidr_idents[p]
                self._cidr_idents[p] = (ident, n + 1)
            else:
                allocated = allocate_cidr_identities(
                    self.identity_allocator, self.ipcache, [p])
                self._cidr_idents[p] = (allocated[p], 1)

    def _release_prefixes(self, prefixes: Sequence[str]) -> None:
        for p in prefixes:
            ident, n = self._cidr_idents.get(p, (None, 0))
            if ident is None:
                continue
            if n <= 1:
                release_cidr_identities(
                    self.identity_allocator, self.ipcache, {p: ident})
                del self._cidr_idents[p]
            else:
                self._cidr_idents[p] = (ident, n - 1)

    @staticmethod
    def _rule_has_fqdn(rule: Rule) -> bool:
        return any(getattr(eg, "to_fqdns", None) for eg in rule.egress)

    @staticmethod
    def _rule_cidr_prefixes(rule: Rule) -> List[str]:
        """Every CIDR prefix one rule references (incl. FQDN-injected
        to_cidr_set entries)."""
        out: List[str] = []
        for ing in rule.ingress:
            out.extend(c for c in getattr(ing, "from_cidr", []) or [])
            out.extend(c.cidr for c in
                       getattr(ing, "from_cidr_set", []) or [])
        for eg in rule.egress:
            out.extend(c for c in getattr(eg, "to_cidr", []) or [])
            out.extend(c.cidr for c in
                       getattr(eg, "to_cidr_set", []) or [])
        return sorted(set(out))

    def trigger_policy_updates(self, reason: str) -> None:
        """daemon/policy.go:48 TriggerPolicyUpdates."""
        self._regen_trigger.trigger(reason)

    def policy_get(self, labels=None) -> Dict:
        from ..policy.jsonio import rule_to_dict
        rules = self.repo.search(labels) if labels else self.repo.rules
        return {"revision": self.repo.revision,
                "policy": [rule_to_dict(r) for r in rules]}

    def policy_resolve(self, from_labels, to_labels,
                       dports=None, verbose: bool = False) -> Dict:
        """GET /policy/resolve (daemon/policy.go:67): traced verdict."""
        from ..policy.trace import Port
        ports = [Port(port=p, protocol="TCP") if isinstance(p, int) else p
                 for p in (dports or [])]
        ctx = traced_context(from_labels=from_labels, to_labels=to_labels,
                             dports=ports, verbose=verbose)
        verdict = self.repo.allows_ingress(ctx)
        return {"verdict": str(verdict), "trace": ctx.trace_output()}

    # ------------------------------------- verdict provenance surfaces

    def policy_trace_replay(self, endpoint_id: int,
                            identity: Optional[int] = None,
                            labels: Optional[Sequence[str]] = None,
                            dport: int = 0, proto: int = 6,
                            direction: str = "egress") -> Dict:
        """`cilium policy trace --replay` / POST /policy/trace:
        synthesize a header tuple for one local endpoint, run it
        through the REAL compiled device tables, and explain the
        verdict per tier, naming the PolicyKey that matched.  The
        device result is diffed in-line against the host
        compute_desired_policy_map_state oracle (the endpoint's
        realized state), so a compiler bug surfaces as drift right in
        the trace output.  Raises KeyError for an unknown endpoint."""
        from ..compiler.policy_tables import oracle_provenance
        from ..datapath.events import tier_name
        from ..policy.mapstate import EGRESS, INGRESS
        ep = self.endpoints.lookup(endpoint_id)
        if ep is None or ep.table_slot is None:
            raise KeyError(endpoint_id)
        if identity is None:
            if not labels:
                raise ValueError("need identity or labels")
            ident = self.identity_allocator.lookup_by_labels(
                Labels.from_model(list(labels)))
            if ident is None:
                raise ValueError(f"no identity for labels {labels}")
            identity = ident.id
        dirc = EGRESS if str(direction).lower() in ("egress", "1") \
            else INGRESS
        realized = PolicyMapState(ep.realized)
        row = self.datapath.policy_replay(
            [ep.table_slot], [identity], [dport], [proto], [dirc])[0]
        o_verdict, o_tier, o_key = oracle_provenance(
            realized, identity, dport, proto, dirc)
        drift = row["verdict"] != o_verdict or row["tier"] != o_tier

        def key_str(k) -> str:
            if k is None:
                return "no entry"
            if isinstance(k, dict):
                return (f"PolicyKey(identity={k['identity']}, "
                        f"dport={k['dport']}, proto={k['proto']}, "
                        f"dir={'in' if k['direction'] == 0 else 'e'}"
                        f"gress)")
            return (f"PolicyKey(identity={k.identity}, "
                    f"dport={k.dest_port}, proto={k.nexthdr}, "
                    f"dir={'in' if k.direction == 0 else 'e'}gress)")

        stage_titles = (
            ("exact", "stage 1 exact (identity, dport, proto)"),
            ("l3", "stage 2 L3-only (identity)"),
            ("l4_wildcard", "stage 3 L4-wildcard (identity=0)"))
        lines = [f"Replaying endpoint {endpoint_id} (table slot "
                 f"{ep.table_slot}): identity {identity} -> "
                 f"dport {dport}/proto {proto} {direction} "
                 f"through compiled revision {self.datapath.revision}"]
        for name, title in stage_titles:
            st = row["stages"][name]
            if st["found"]:
                lines.append(
                    f"  {title}: MATCH {key_str(st['key'])}"
                    + (f" -> proxy {st['value']}" if st["value"] > 0
                       else " -> allow"))
            else:
                lines.append(f"  {title}: no match")
        lines.append(
            f"  decision: tier={row['tier-name']} "
            f"verdict={row['verdict']} "
            f"({key_str(row['matched'])})")
        lines.append(
            "  oracle: " +
            (f"DIVERGENCE — host oracle says verdict={o_verdict} "
             f"tier={tier_name(o_tier)} ({key_str(o_key)})" if drift
             else "device and host compute_desired_policy_map_state "
                  "agree"))
        out = {"endpoint": endpoint_id, "identity": identity,
               "dport": dport, "proto": proto, "direction": direction,
               "device": row,
               "oracle": {"verdict": o_verdict,
                          "tier": tier_name(o_tier),
                          "key": key_str(o_key)},
               "drift": drift, "explanation": lines}
        with self._lock:
            self._last_replay = out
        if drift:
            from ..utils.metrics import POLICY_DRIFT
            POLICY_DRIFT.inc()
        return out

    def run_drift_audit(self, samples: Optional[int] = None) -> Dict:
        """One drift-audit sweep: replay sampled tuples through the
        compiled device tables and diff verdict+tier against the host
        oracles.  Per endpoint the sample mixes installed keys (which
        must keep deciding exactly as computed) with random tuples
        (which must keep falling through identically); a handful of
        cached identities additionally cross-check the SearchContext
        label simulation against the realized L3 entries.  Divergences
        found on a first pass are re-replayed once against a fresh
        snapshot before counting, so an in-flight regeneration can't
        fake drift.  Updates policy_drift_total and the status()
        provenance block; returns the report."""
        from ..compiler.policy_tables import oracle_provenance
        from ..datapath.events import TIER_L3_ALLOW, tier_name
        from ..policy.api import Decision
        from ..policy.mapstate import INGRESS, PolicyKey
        from ..utils.metrics import POLICY_DRIFT, POLICY_DRIFT_AUDIT_RUNS
        t0 = time.time()
        budget = samples or self.config.drift_audit_samples
        eps = [ep for ep in self.endpoints.endpoints()
               if ep.table_slot is not None]
        report: Dict = {"status": "idle", "checked": 0,
                        "sc-checked": 0, "divergences": [],
                        "endpoints": len(eps), "skipped": 0,
                        "last-run": t0}
        if not eps or self.datapath._step is None:
            with self._lock:
                self._drift_report = report
            return report
        rng = self._drift_rng
        per_ep = max(2, budget // len(eps))

        rows = []  # one audit probe per row
        for ep in eps:
            rev = ep.policy_revision
            state = PolicyMapState(ep.realized)
            keys = list(state.keys())
            picked = [keys[i] for i in
                      rng.permutation(len(keys))[:per_ep]] if keys else []
            tuples = []
            for k in picked:
                # wildcard keys get a random identity so the probe
                # exercises the stage-3 fallback, not slot 0
                ident = k.identity or int(rng.integers(256, 1 << 20))
                tuples.append((ident, k.dest_port, k.nexthdr,
                               k.direction))
            for _ in range(max(1, per_ep // 2)):
                tuples.append((int(rng.integers(256, 1 << 20)),
                               int(rng.integers(1, 65536)), 6,
                               int(rng.integers(0, 2))))
            for t in tuples:
                rows.append({"ep": ep, "slot": ep.table_slot,
                             "rev": rev, "state": state, "t": t})

        def replay_rows(batch):
            return self.datapath.policy_replay(
                [r["slot"] for r in batch],
                [r["t"][0] for r in batch],
                [r["t"][1] for r in batch],
                [r["t"][2] for r in batch],
                [r["t"][3] for r in batch])

        def diverges(row, dev) -> Optional[Dict]:
            ident, dport, proto, dirc = row["t"]
            o_verdict, o_tier, o_key = oracle_provenance(
                row["state"], ident, dport, proto, dirc)
            if dev["verdict"] == o_verdict and dev["tier"] == o_tier:
                return None
            return {"endpoint": row["ep"].id,
                    "tuple": {"identity": ident, "dport": dport,
                              "proto": proto, "direction": dirc},
                    "device": {"verdict": dev["verdict"],
                               "tier": dev["tier-name"],
                               "matched": dev["matched"]},
                    "oracle": {"verdict": o_verdict,
                               "tier": tier_name(o_tier),
                               "key": str(o_key)},
                    "source": "compute_desired_policy_map_state"}

        suspects = []
        checked = skipped = 0
        for row, dev in zip(rows, replay_rows(rows)):
            if row["ep"].policy_revision != row["rev"]:
                skipped += 1
                continue
            checked += 1
            d = diverges(row, dev)
            if d is not None:
                suspects.append((row, d))
        # second look: a regeneration between snapshot and replay can
        # fake drift — re-snapshot + re-replay just the suspects and
        # keep only the persistent ones
        divergences = []
        if suspects:
            retry = []
            for row, _d in suspects:
                retry.append({**row,
                              "rev": row["ep"].policy_revision,
                              "state": PolicyMapState(
                                  row["ep"].realized)})
            for row, dev in zip(retry, replay_rows(retry)):
                d = diverges(row, dev)
                if d is not None and \
                        row["ep"].policy_revision == row["rev"]:
                    divergences.append(d)

        # SearchContext cross-check (policy/trace.py simulation):
        # repo label decision -> realized L3 entry -> device l3-allow
        # tier must tell one story for identities with known labels
        sc_checked = 0
        cache = IdentityCache.snapshot(self.identity_allocator)
        # reserved identities are excluded: their L3 entries can be
        # installed by infrastructure, not selector policy (e.g. the
        # reserved:host allow that rides along with any L7 redirect,
        # mapstate.py LOCALHOST_KEY) — the label simulation would
        # report false drift against them
        sc_idents = [(n, la) for n, la in cache.items()
                     if not idpkg.is_reserved_identity(n)]
        sc_idents = [sc_idents[i]
                     for i in rng.permutation(len(sc_idents))]
        for ep in eps[:4]:
            if ep.policy_revision != self.repo.revision:
                # behind: not yet regenerated against current rules.
                # AHEAD: restored from checkpoint while the repo is
                # empty/older (the pinned-map window, daemon/state.go)
                # — the realized state deliberately outlives the repo
                # until re-import, so the label simulation would
                # report false drift
                continue
            cfg = ep.policy_config(self.config.always_allow_localhost())
            if not cfg.ingress_enforcement:
                continue  # every identity legitimately gets an L3 key
            state = PolicyMapState(ep.realized)
            ep_labels = ep.label_array()
            for num, id_labels in sc_idents[:4]:
                ctx = SearchContext(from_labels=id_labels,
                                    to_labels=ep_labels)
                decision = self.repo.allows_ingress_label_access(ctx)
                has_l3 = PolicyKey(identity=num,
                                   direction=INGRESS) in state
                dev = self.datapath.policy_replay(
                    [ep.table_slot], [num], [0], [0], [INGRESS])[0]
                dev_l3 = dev["tier"] == TIER_L3_ALLOW and \
                    dev["verdict"] == 0
                sc_checked += 1
                if (decision == Decision.ALLOWED) != has_l3 or \
                        has_l3 != dev_l3:
                    if ep.policy_revision != self.repo.revision:
                        continue  # regeneration raced the check
                    divergences.append({
                        "endpoint": ep.id,
                        "tuple": {"identity": num, "dport": 0,
                                  "proto": 0, "direction": INGRESS},
                        "device": {"verdict": dev["verdict"],
                                   "tier": dev["tier-name"]},
                        "oracle": {
                            "search-context": str(decision),
                            "realized-l3-entry": has_l3},
                        "source": "SearchContext"})

        if divergences:
            POLICY_DRIFT.inc(len(divergences))
        POLICY_DRIFT_AUDIT_RUNS.inc(labels={
            "result": "drift" if divergences else "ok"})
        report.update(
            status="FAILING" if divergences else "ok",
            checked=checked, skipped=skipped, sc_checked=sc_checked,
            divergences=divergences[:16],
            duration_s=round(time.time() - t0, 4))
        report["sc-checked"] = report.pop("sc_checked")
        report["duration-s"] = report.pop("duration_s")
        with self._lock:
            prev = (self._drift_report or {}).get("status")
            self._drift_report = report
        # flight recorder: every FAILING sweep is an incident event
        # (the compiler-correctness verdict), plus the all-clear
        # transition when a failing audit goes green again
        if report["status"] == "FAILING" or \
                (prev == "FAILING" and report["status"] == "ok"):
            from ..observability.events import (EVENT_DRIFT_AUDIT,
                                                recorder)
            recorder.record(
                EVENT_DRIFT_AUDIT, status=report["status"],
                divergences=len(report["divergences"]),
                checked=report["checked"],
                detail=str(report["divergences"][:1])
                if report["divergences"] else "audit back to ok")
        return report

    def _dataplane_recovery_gate(self) -> bool:
        """The device lane's resumption gate: after the supervisor
        rebuilds the tables from the host-of-record, a drift-audit
        replay must come back clean before the half-open probe may
        dispatch — a corrupted rebuild re-opens the breaker instead of
        serving wrong verdicts."""
        report = self.run_drift_audit(
            samples=min(32, self.config.drift_audit_samples))
        return report.get("status") in ("ok", "idle")

    def drift_report(self) -> Optional[Dict]:
        with self._lock:
            return self._drift_report

    def last_replay_report(self) -> Optional[Dict]:
        with self._lock:
            return self._last_replay

    # ------------------------------------- incident flight recorder

    def flight_events(self, since: int = 0, limit: int = 200,
                      event_type: Optional[str] = None,
                      shard: Optional[int] = None) -> Dict:
        """GET /debug/events / ``cilium-tpu events``: the ordered
        incident timeline — every degraded-condition transition the
        agent recorded, cursor-paginated like the monitor ring."""
        from ..observability.events import recorder
        return {"events": [e.to_dict() for e in
                           recorder.events(since, limit, event_type,
                                           shard)],
                "seq": recorder.last_seq,
                "stats": recorder.stats()}

    # ------------------------------------- inline threat scoring

    def _threat_config_from_options(self):
        from ..threat import ThreatConfig
        c = self.config
        return ThreatConfig(
            mode=c.threat_mode,
            drop_score=c.threat_drop_score,
            redirect_score=c.threat_redirect_score,
            ratelimit_score=c.threat_ratelimit_score,
            redirect_port=c.threat_redirect_port,
            rate_per_s=c.threat_rate_per_s, burst=c.threat_burst)

    def threat_status(self) -> Dict:
        """status()["threat"] / GET /threat: mode (off / shadow /
        enforce), the live thresholds + model generation, and verdict
        accounting.  An ENFORCING threat plane is a degraded-signal
        section by design — an operator must see that a model can now
        override policy-allowed traffic (DEGRADED_SIGNALS covers it
        with the threat-mode/model-push flight-recorder events)."""
        from ..utils.metrics import THREAT_VERDICTS
        report = self.datapath.threat_report() \
            if hasattr(self.datapath, "threat_report") else None
        if report is None:
            return {"mode": "off"}
        out = {"mode": report["config"]["mode"], "model": report,
               "verdicts": {
                   o: int(THREAT_VERDICTS.value(labels={"outcome": o}))
                   for o in ("scored", "rate-limited", "redirected",
                             "dropped")}}
        if out["mode"] == "enforce":
            out["status"] = ("ENFORCING: threat scores can drop/"
                             "rate-limit/redirect allowed traffic "
                             f"(thresholds {report['config']})")
        return out

    def threat_set_config(self, **changes) -> Dict:
        """Update the policy-controlled threat thresholds / mode (ONE
        region write into the live packed buffer — no repack, no
        re-jit, no serving pause).  Mode flips land in the incident
        flight recorder: enforcement changes are exactly the kind of
        transition an operator replays a timeline for."""
        from dataclasses import replace as _replace
        from ..observability.events import EVENT_THREAT_MODE
        report = self.datapath.threat_report()
        if report is None:
            raise KeyError("threat scoring not enabled")
        from ..threat import ThreatConfig
        cur = ThreatConfig(**{k.replace("-", "_"): v for k, v in
                              report["config"].items()
                              if k != "generation"},
                           generation=report["config"]["generation"])
        allowed = {"mode", "drop_score", "redirect_score",
                   "ratelimit_score", "redirect_port", "rate_per_s",
                   "burst"}
        bad = set(changes) - allowed
        if bad:
            raise ValueError(f"unknown threat config fields: {bad}")
        if changes.get("mode") not in (None, "shadow", "enforce"):
            raise ValueError("mode must be shadow|enforce")
        new = _replace(cur, **changes)
        self.datapath.set_threat_config(new)
        if new.mode != cur.mode:
            flight_recorder.record(EVENT_THREAT_MODE,
                                   f"threat mode {cur.mode} -> "
                                   f"{new.mode}", mode=new.mode)
            self.monitor.notify_agent("threat-mode", new.mode)
        return new.describe()

    def threat_push_model(self, model) -> Dict:
        """Hot-swap trained scorer weights through the delta-apply
        leaf-write path (same-geometry pushes never repack and never
        pause serving); bumps the generation gauge and rings the
        flight-recorder push event."""
        from dataclasses import replace as _replace
        from ..observability.events import EVENT_THREAT_MODEL
        from ..utils.metrics import THREAT_MODEL_GENERATION
        report = self.datapath.threat_report()
        if report is None:
            raise KeyError("threat scoring not enabled")
        gen = int(report["config"]["generation"]) + 1
        model = model.with_config(
            _replace(model.config, generation=gen))
        fast = self.datapath.apply_threat_weights(model)
        THREAT_MODEL_GENERATION.set(gen)
        flight_recorder.record(EVENT_THREAT_MODEL,
                               f"threat model generation {gen}",
                               generation=gen, repacked=not fast)
        return {"generation": gen, "hot-swap": bool(fast),
                "model": model.describe()}

    def threat_train(self, max_flows: int = 4096,
                     labels: Optional[List[int]] = None) -> Dict:
        """Fit a new scorer from the aggregated flow plane (the
        federated per-shard drains land in the same flow snapshot
        surface) and push it through the hot-swap path.  Returns the
        training report + push result."""
        if self._threat_trainer is None:
            raise KeyError("threat scoring not enabled")
        flows = self.datapath.flow_snapshot(max_flows)
        if not flows and self.hubble is not None:
            # no device flow table: fall back to the observer ring
            flows = [{"packets": 1, "bytes": f.length or 0,
                      "dport": f.dport, "proto": f.proto,
                      "event": f.event,
                      "src-identity": f.src_identity,
                      "dst-identity": f.dst_identity,
                      "last-seen": int(f.timestamp)}
                     for f in self.hubble.get_flows(limit=max_flows)]
        report = self.datapath.threat_report()
        from ..threat import ThreatConfig
        cfg = ThreatConfig(**{k.replace("-", "_"): v for k, v in
                              report["config"].items()})
        model = self._threat_trainer.fit(flows, labels=labels,
                                         config=cfg)
        push = self.threat_push_model(model)
        return {"training": self._threat_trainer.last_report,
                "push": push}

    # ------------------------------------- device traffic analytics

    def _analytics_sections(self, swap: bool) -> Optional[Dict]:
        """One decoded-epoch fetch shaped like the sharded answer for
        both dataplane shapes: the sharded datapath merges per-shard
        sections behind per-shard breakers (fail-open); the single
        engine swaps + snapshots locally."""
        dp = self.datapath
        if hasattr(dp, "analytics_sections"):
            return dp.analytics_sections(swap=swap)
        from ..analytics.decode import epoch_section, quiesced_section
        report = dp.analytics_report()
        if report is None:
            return None
        depth, lanes = report["depth"], report["lanes"]
        if swap:
            epoch = dp.swap_analytics_epoch()
            section = epoch_section(dp.analytics_snapshot(), epoch,
                                    depth, lanes)
        else:
            section = quiesced_section(dp.analytics_snapshot(), depth,
                                       lanes)
        return {"sections": [section], "shards": {"0": {"status": "ok"}},
                "partial": False, "depth": depth, "lanes": lanes}

    def analytics_drain(self) -> Dict:
        """The analytics-drain controller body: flip the device A/B
        epoch, decode the newly quiesced section, export the
        capped-cardinality ``analytics_top_bytes{identity}`` gauge,
        and ring heavy-hitter / scan-suspect THRESHOLD TRANSITIONS
        into the flight recorder (edge-triggered per identity — a
        sustained hitter is one event, not one per drain)."""
        from ..analytics.decode import (merge_sections, top_scanners,
                                        top_talkers)
        from ..observability.events import (EVENT_TRAFFIC_HEAVY_HITTER,
                                            EVENT_TRAFFIC_SCAN_SUSPECT)
        from ..utils.metrics import (ANALYTICS_DRAINS,
                                     ANALYTICS_SCAN_SUSPECTS,
                                     ANALYTICS_TOP_BYTES)
        secs = self._analytics_sections(swap=True)
        if secs is None:
            return {"status": "off"}
        k = self.config.analytics_top_k
        result = "partial" if secs["partial"] else "ok"
        ANALYTICS_DRAINS.inc(labels={"result": result})
        if not secs["sections"]:
            out = {"status": result, "shards": secs["shards"],
                   "top": [], "suspects": []}
            with self._lock:
                self._analytics_last = out
            return out
        merged = merge_sections(secs["sections"], secs["depth"],
                                secs["lanes"])
        top = top_talkers(merged, secs["depth"], k=k, metric="bytes")
        total = sum(e["count"] for e in top) or 1
        # capped-cardinality export: only the CURRENT top-K identities
        # carry a live series; evicted ones zero out, so the label set
        # never grows past k live values under identity churn
        current = {e["identity"] for e in top}
        for ident in self._analytics_exported - current:
            ANALYTICS_TOP_BYTES.set(0, labels={"identity": str(ident)})
        for e in top:
            ANALYTICS_TOP_BYTES.set(
                e["count"], labels={"identity": str(e["identity"])})
        self._analytics_exported = current
        # heavy-hitter share transitions (edge-triggered per identity)
        share_bar = self.config.analytics_hh_share
        hitters = {e["identity"]: e for e in top
                   if e["count"] / total >= share_bar}
        for ident in set(hitters) - self._analytics_hh_live:
            e = hitters[ident]
            flight_recorder.record(
                EVENT_TRAFFIC_HEAVY_HITTER,
                f"identity {ident} at "
                f"{e['count'] / total:.0%} of epoch bytes",
                identity=ident, share=round(e["count"] / total, 3),
                bytes=e["count"])
        self._analytics_hh_live = set(hitters)
        # scan-suspect transitions from the (identity, dport) view
        scans = top_scanners(merged, secs["depth"], k=k,
                             min_dports=self.config.analytics_scan_ports)
        suspects = {e["identity"]: e for e in scans if e["suspect"]}
        ANALYTICS_SCAN_SUSPECTS.set(len(suspects))
        for ident in set(suspects) - self._analytics_scan_live:
            e = suspects[ident]
            flight_recorder.record(
                EVENT_TRAFFIC_SCAN_SUSPECT,
                f"identity {ident} touched {e['dports']} distinct "
                f"dports in one epoch",
                identity=ident, ports=e["dports"],
                packets=e["packets"])
        self._analytics_scan_live = set(suspects)
        out = {"status": result, "shards": secs["shards"], "top": top,
               "suspects": sorted(suspects)}
        with self._lock:
            self._analytics_last = out
        return out

    def analytics_top(self, view: str = "talkers", k: int = 10,
                      metric: str = "bytes") -> Dict:
        """GET /analytics/top / ``cilium-tpu top``: one mesh-wide
        top-K answer decoded from the QUIESCED epoch sections (no
        swap — reads race nothing and serving never pauses).  Raises
        KeyError when analytics is not enabled or the view/metric is
        unknown."""
        from ..analytics.decode import (METRICS, VIEWS, decode_view,
                                        merge_sections)
        from ..utils.metrics import ANALYTICS_QUERIES
        if view not in VIEWS:
            raise KeyError(f"unknown analytics view {view!r} "
                           f"(expected one of {VIEWS})")
        if metric not in METRICS:
            raise KeyError(f"unknown analytics metric {metric!r} "
                           f"(expected one of {tuple(METRICS)})")
        secs = self._analytics_sections(swap=False)
        if secs is None:
            raise KeyError("traffic analytics not enabled")
        if secs["sections"]:
            merged = merge_sections(secs["sections"], secs["depth"],
                                    secs["lanes"])
            entries = decode_view(merged, view, secs["depth"],
                                  secs["lanes"], k=k, metric=metric)
        else:
            entries = []
        out = {"view": view, "metric": metric, "entries": entries,
               "partial": secs["partial"], "shards": secs["shards"]}
        ANALYTICS_QUERIES.inc(labels={
            "view": view,
            "result": "partial" if out["partial"] else "ok"})
        return out

    def analytics_status(self) -> Dict:
        """status()["analytics"] / GET /analytics: geometry + write
        epoch, the last drain's outcome, and live anomaly counts.  A
        partial drain reports loudly — the mesh-wide decode is missing
        a shard's traffic (fail-open, the federation precedent)."""
        report = self.datapath.analytics_report() \
            if hasattr(self.datapath, "analytics_report") else None
        if report is None:
            # "status" stays present so the loudness lint counts the
            # section as a covered degraded-signal surface
            return {"enabled": False, "status": "off"}
        with self._lock:
            last = self._analytics_last
        out = {"enabled": True, "report": report,
               "last-drain": last,
               "heavy-hitters": sorted(self._analytics_hh_live),
               "scan-suspects": sorted(self._analytics_scan_live)}
        if last is not None and last.get("status") == "partial":
            bad = [k for k, s in (last.get("shards") or {}).items()
                   if s.get("status") != "ok"]
            out["status"] = (
                f"PARTIAL: analytics shard(s) {bad} unreadable — "
                f"mesh-wide top-K decode is missing their traffic "
                f"(remaining shards still answer, fail-open)")
        else:
            out["status"] = "ok"
        return out

    # -------------------------------------------------- regeneration

    def _regenerate_endpoint(self, ep: Endpoint) -> None:
        """The per-endpoint build (endpoint/policy.go regenerate tail):
        resolve policy, allocate redirects, diff, swap device tables."""
        cache = IdentityCache.snapshot(self.identity_allocator)
        # stage spans parent on the revision's import trace via
        # explicit context — this runs on a build-worker thread, so
        # thread-local propagation cannot carry it
        with self.propagation.stage_span(
                self.repo.revision, "policy.compile",
                {"endpoint": ep.id}):
            res = ep.regenerate_policy(
                self.repo, cache, proxy=self.proxy,
                always_allow_localhost=self.config
                .always_allow_localhost())
        self.propagation.revision_compiled(res.revision)
        POLICY_REGENERATION_COUNT.inc()
        ep.apply_regeneration(res)
        PROXY_REDIRECTS.set(len(self.proxy))
        if self.host_path is not None:
            self.host_path.sync_endpoint(ep.id, ep.realized)
            # a delete racing this build could have already removed the
            # cache; re-check so we never resurrect a deleted endpoint
            if self.endpoints.lookup(ep.id) is None:
                self.host_path.remove_endpoint(ep.id)
        # incremental device sync: this endpoint's row only
        # (endpoint/bpf.go:607 syncPolicyMap analog)
        with self.propagation.stage_span(
                res.revision, "policy.device-apply",
                {"endpoint": ep.id}):
            self.table_mgr.sync_endpoint(ep.id, ep.realized,
                                         res.revision)
            self.datapath.refresh_policy(res.revision)
        self.propagation.revision_applied(res.revision)
        if self.config.state_dir:
            try:
                ep.write_checkpoint(self.config.state_dir)
            except OSError:
                pass

    # -------------------------------------------------- endpoints

    def addressing(self) -> Dict:
        """Node addressing block (models.NodeAddressing analog) served
        in GET /config — what the docker libnetwork driver and CNI use
        to build pools/routes (plugins/cilium-docker/driver/driver.go
        NewDriver's ConfigGet)."""
        out = {"ipv4": {"ip": self.host_ipv4,
                        "alloc-range": str(self.ipam.network),
                        "enabled": self.config.enable_ipv4}}
        if self.ipam6 is not None:
            out["ipv6"] = {"ip": self.host_ipv6,
                           "alloc-range": str(self.ipam6.network),
                           "enabled": True}
        return out

    def ipam_allocate(self, family: str = "ipv4",
                      owner: str = "") -> Dict:
        """POST /ipam (daemon/ipam.go AllocateIP): next free address
        of the family, plus current host addressing (the reference
        returns it so clients can refresh routes after a restart)."""
        if family not in ("ipv4", "ipv6"):
            raise IPAMError(f"unknown address family {family!r}")
        # the pool object always exists for v4 (host addressing and
        # endpoint lifecycle claims need it) but allocation honours the
        # enable flag, matching how ipam6 is gated at construction
        if family == "ipv4" and not self.config.enable_ipv4:
            raise IPAMError("family 'ipv4' not enabled")
        pool = self.ipam6 if family == "ipv6" else self.ipam
        if pool is None:
            raise IPAMError(f"family {family!r} not enabled")
        ip = pool.allocate_next(owner)
        return {"address": {family: ip},
                "host-addressing": self.addressing()}

    def ipam_release(self, ip: str) -> bool:
        """DELETE /ipam/{ip}: release from whichever family owns it."""
        if self.ipam.release(ip):
            return True
        return self.ipam6.release(ip) if self.ipam6 is not None \
            else False

    def endpoint_create(self, endpoint_id: int, ipv4: str = "",
                        container_name: str = "",
                        labels: Optional[Sequence[str]] = None
                        ) -> Endpoint:
        """PUT /endpoint/{id} (daemon/endpoint.go + CNI ADD path):
        allocate identity, publish ip->identity, queue first build.

        Claims the IP in the host-scope allocator FIRST: an address
        another live endpoint already holds is a hard conflict
        (IPAMError -> 409), while a docker-flow claim ("docker" owner
        from POST /ipam) is the expected hand-off and stands."""
        if ipv4:
            try:
                self.ipam.allocate_ip(ipv4,
                                      owner=f"endpoint:{endpoint_id}")
            except IPAMError:
                holder = self.ipam.owner_of(ipv4)
                if holder is not None and \
                        holder.startswith("endpoint:") and \
                        holder != f"endpoint:{endpoint_id}":
                    raise IPAMError(
                        f"{ipv4} already in use by {holder}")
                # outside the pool, or a non-endpoint claim (docker
                # flow) whose owner releases it — proceed
        did_upsert = False
        try:
            ep = Endpoint(endpoint_id, ipv4=ipv4,
                          container_name=container_name,
                          opts=self.config.opts.fork())
            ep.table_slot = self.table_mgr.attach(endpoint_id)
            self.endpoints.insert(ep)
            ep.update_labels(self.identity_allocator,
                             Labels.from_model(list(labels or [])))
            self.datapath.set_endpoint_identity(ep.table_slot,
                                                ep.security_identity)
            IDENTITY_COUNT.set(len(self.identity_allocator))
            if ipv4:
                self.ipcache.upsert(ipv4, ep.security_identity,
                                    SOURCE_AGENT_LOCAL,
                                    metadata=f"endpoint:{endpoint_id}")
                did_upsert = True
        except BaseException:
            # failed create must not strand ANY of its claims on a
            # ghost endpoint: IP, ipcache entry, device-table slot,
            # identity refcount (detach/release are no-ops for steps
            # that never ran).  The ipcache delete is gated on OUR
            # upsert having happened: an out-of-pool IP that failed
            # earlier may still be another endpoint's live mapping
            if ipv4:
                self.ipam.release_if_owner(ipv4,
                                           f"endpoint:{endpoint_id}")
                if did_upsert:
                    self.ipcache.delete(ipv4, SOURCE_AGENT_LOCAL)
            ghost = self.endpoints.remove(endpoint_id)
            if ghost is not None and ghost.identity is not None:
                self.identity_allocator.release(ghost.identity)
            self.table_mgr.detach(endpoint_id)
            raise
        self.monitor.notify_agent("endpoint-created",
                                  f"id={endpoint_id} ipv4={ipv4}")
        self.endpoints.queue_regeneration(endpoint_id)
        return ep

    def endpoint_delete(self, endpoint_id: int) -> bool:
        ep = self.endpoints.remove(endpoint_id)
        if ep is None:
            return False
        ep.set_state(EndpointState.DISCONNECTING, "delete")
        if ep.ipv4:
            self.ipcache.delete(ep.ipv4, SOURCE_AGENT_LOCAL)
            # free only our own lifecycle claim (docker-flow addresses
            # are released by IpamDriver.ReleaseAddress)
            self.ipam.release_if_owner(ep.ipv4,
                                       f"endpoint:{endpoint_id}")
        for rid in list(ep.proxy_redirects):
            self.proxy.remove_redirect(rid)
        ep.proxy_redirects = {}
        if ep.identity is not None:
            self.identity_allocator.release(ep.identity)
            IDENTITY_COUNT.set(len(self.identity_allocator))
        ep.set_state(EndpointState.DISCONNECTED, "delete")
        if self.host_path is not None:
            self.host_path.remove_endpoint(endpoint_id)
        if self.config.state_dir:
            try:
                os.remove(os.path.join(self.config.state_dir,
                                       f"ep_{endpoint_id}.json"))
            except OSError:
                pass
        self.table_mgr.detach(endpoint_id)
        self.datapath.refresh_policy()
        self.monitor.notify_agent("endpoint-deleted",
                                  f"id={endpoint_id}")
        return True

    def endpoint_update_labels(self, endpoint_id: int,
                               labels: Sequence[str]) -> bool:
        """Returns True if the identity changed; raises KeyError for an
        unknown endpoint (the REST layer 404s)."""
        ep = self.endpoints.lookup(endpoint_id)
        if ep is None:
            raise KeyError(endpoint_id)
        changed = ep.update_labels(self.identity_allocator,
                                   Labels.from_model(list(labels)))
        if changed:
            if ep.table_slot is not None:
                self.datapath.set_endpoint_identity(ep.table_slot,
                                                    ep.security_identity)
            if ep.ipv4:
                self.ipcache.upsert(ep.ipv4, ep.security_identity,
                                    SOURCE_AGENT_LOCAL,
                                    metadata=f"endpoint:{endpoint_id}")
            self.endpoints.queue_regeneration(endpoint_id)
        return changed

    def endpoint_config_patch(self, endpoint_id: int,
                              changes: Dict[str, object]) -> int:
        """PATCH /endpoint/{id}/config — option change triggers rebuild
        (pkg/option applyOptsLocked semantics)."""
        ep = self.endpoints.lookup(endpoint_id)
        if ep is None:
            raise KeyError(endpoint_id)
        parsed = {k: parse_option_value(v) for k, v in changes.items()}
        n = ep.opts.apply_validated(parsed)
        if n:
            ep.set_state(EndpointState.WAITING_TO_REGENERATE,
                         "config change")
            self.endpoints.queue_regeneration(endpoint_id)
        return n

    def config_patch(self, changes: Dict[str, object]) -> int:
        """PATCH /config — daemon-wide option change regenerates all."""
        parsed = {k: parse_option_value(v) for k, v in changes.items()}
        n = self.config.opts.apply_validated(parsed)
        if n:
            for ep in self.endpoints.endpoints():
                ep.opts.apply_validated(parsed)
            self.trigger_policy_updates("config-change")
        return n

    # -------------------------------------------------- state restore

    def restore_endpoints(self) -> int:
        """daemon/state.go restoreOldEndpoints: reload checkpoints,
        re-resolve identities, queue rebuilds.  Also reloads the CT
        checkpoint so established flows keep forwarding."""
        state_dir = self.config.state_dir
        if not state_dir or not os.path.isdir(state_dir):
            return 0
        self.restore_ct()
        restored = []
        for fname in sorted(os.listdir(state_dir)):
            if not (fname.startswith("ep_") and fname.endswith(".json")):
                continue
            try:
                with open(os.path.join(state_dir, fname)) as f:
                    snap = json.load(f)
                ep = Endpoint.restore(snap)
            except (OSError, ValueError, KeyError, MigrationError):
                # one unmigratable checkpoint (e.g. from a newer agent)
                # must not block restoring the rest
                continue
            ep.table_slot = self.table_mgr.attach(ep.id)
            self.endpoints.insert(ep)
            ep.update_labels(self.identity_allocator, ep.labels)
            self.datapath.set_endpoint_identity(ep.table_slot,
                                                ep.security_identity)
            if ep.ipv4:
                self.ipcache.upsert(ep.ipv4, ep.security_identity,
                                    SOURCE_AGENT_LOCAL,
                                    metadata=f"endpoint:{ep.id}")
                # re-claim the IP in the host-scope allocator so a
                # post-restart POST /ipam can never hand it out again
                # (ipam.AllocateIP restore path, daemon/state.go)
                try:
                    self.ipam.allocate_ip(ep.ipv4,
                                          owner=f"endpoint:{ep.id}")
                except IPAMError:
                    # outside this node's range (config changed) or
                    # already claimed — either way not double-bookable
                    pass
            restored.append((ep, snap.get("identity")))
        # Pinned-map parity (daemon/state.go + bpffs pinned maps: the
        # dataplane keeps enforcing the OLD policy while the agent is
        # down and until fresh policy arrives).  If every restored
        # endpoint's re-resolved identity matches its checkpoint — the
        # allocator reproduced the identity universe, which a
        # kvstore-backed allocator guarantees and the local one gives
        # deterministically for an unchanged endpoint set — realize the
        # checkpointed verdict state directly: allowed flows keep
        # flowing BEFORE the orchestrator re-imports policy, and denied
        # ones stay denied.  Any mismatch means numeric identities in
        # the snapshots may now name different workloads, so fail
        # closed: queue regenerations against the (empty) repo instead,
        # which drops new flows until policy import.  The next
        # policy_add regenerates everything either way.
        stable = all(ck is not None and ep.security_identity == ck
                     for ep, ck in restored)
        for ep, _ck in restored:
            if stable:
                # L7 redirect entries are scrubbed, not restored: their
                # proxy_port names a listener of the DEAD agent's proxy
                # child (gone, or worse re-bound by someone else).
                # Those flows fail closed until policy re-import
                # re-creates redirects on live ports; plain L3/L4
                # allows restore verbatim.
                scrubbed = PolicyMapState(
                    {k: v for k, v in ep.realized.items()
                     if v.proxy_port == 0})
                ep.realized = scrubbed
                if self.host_path is not None:
                    self.host_path.sync_endpoint(ep.id, scrubbed)
                self.table_mgr.sync_endpoint(ep.id, scrubbed,
                                             ep.policy_revision)
            else:
                self.endpoints.queue_regeneration(ep.id)
        if stable and restored:
            self.datapath.refresh_policy()
        return len(restored)

    # -------------------------------------------------- services / lb

    def service_upsert(self, vip: str, port: int,
                       backends: Sequence[Tuple[str, int]],
                       proto: int = 6) -> None:
        """PUT /service (daemon/loadbalancer.go) — family-routed: v6
        VIPs program the lb6 tables (lb.h lb6_* family)."""
        if ":" in vip:
            from ..compiler.lpm import ipv6_to_words
            from ..datapath.lb import Backend6, Service6
            svc6 = Service6(vip=ipv6_to_words(vip), port=port,
                            proto=proto,
                            backends=[Backend6(ipv6_to_words(ip), p)
                                      for ip, p in backends])
            self.datapath.upsert_service6(svc6)
            return
        svc = Service(vip=ipv4_to_u32(vip), port=port, proto=proto,
                      backends=[Backend(ipv4_to_u32(ip), p)
                                for ip, p in backends])
        self.datapath.lb.upsert_service(svc)
        self.datapath.reload_services()

    def service_find_by_id(self, sid: int):
        """Service lookup by API id — the reference addresses services
        by numeric id in GET/DELETE /service/{id}
        (daemon/loadbalancer.go).  The API id is the family's
        rev_nat_index, offset by V6_SERVICE_ID_BASE for v6: the two
        families allocate rev-NAT indices independently (both device
        tables index by them), so the raw indices collide across
        families and only the offset id is unique.  Returns a
        Service/Service6 or None."""
        if sid >= V6_SERVICE_ID_BASE:
            target = sid - V6_SERVICE_ID_BASE
            for svc6 in self.datapath.lb6_service_list():
                if svc6.rev_nat_index == target:
                    return svc6
            return None
        for svc in self.datapath.lb.services():
            if svc.rev_nat_index == sid:
                return svc
        return None

    def service_delete_by_id(self, sid: int) -> bool:
        svc = self.service_find_by_id(sid)
        if svc is None:
            return False
        return self._service_delete_raw(svc.vip, svc.port, svc.proto)

    def _service_delete_raw(self, vip_raw, port: int,
                            proto: int) -> bool:
        """One delete body for both address families and both the
        by-id and by-(vip,port) surfaces."""
        if isinstance(vip_raw, tuple):          # v6 family
            return self.datapath.delete_service6(vip_raw, port, proto)
        ok = self.datapath.lb.delete_service(vip_raw, port, proto)
        if ok:
            self.datapath.reload_services()
        return ok

    def service_delete(self, vip: str, port: int, proto: int = 6) -> bool:
        if ":" in vip:
            from ..compiler.lpm import ipv6_to_words
            return self._service_delete_raw(ipv6_to_words(vip), port,
                                            proto)
        return self._service_delete_raw(ipv4_to_u32(vip), port, proto)

    # -------------------------------------------------- prefilter

    def prefilter_update(self, cidrs: List[str]) -> int:
        """PATCH /prefilter (pkg/datapath/prefilter:125 Insert)."""
        self.datapath.prefilter.insert(cidrs)
        self.datapath.reload_prefilter()
        return self.datapath.prefilter.revision

    def prefilter_delete(self, cidrs: List[str]) -> int:
        self.datapath.prefilter.delete(cidrs)
        self.datapath.reload_prefilter()
        return self.datapath.prefilter.revision

    # -------------------------------------------------- identity / fqdn

    def identity_get(self, numeric_id: Optional[int] = None,
                     labels: Optional[Sequence[str]] = None
                     ) -> Optional[Dict]:
        if numeric_id is not None:
            ident = self.identity_allocator.lookup_by_id(numeric_id)
        else:
            ident = self.identity_allocator.lookup_by_labels(
                Labels.from_model(list(labels or [])))
        if ident is None:
            return None
        return {"id": ident.id,
                "labels": [str(l) for l in ident.label_array]}

    def identity_list(self) -> List[Dict]:
        out = [{"id": i.id, "labels": [str(l) for l in i.label_array]}
               for i in self.identity_allocator.snapshot_identities()]
        for num, ident in sorted(idpkg.RESERVED_IDENTITY_CACHE.items()):
            out.append({"id": num,
                        "labels": [str(l) for l in ident.label_array]})
        return sorted(out, key=lambda d: d["id"])

    def start_fqdn_poller(self, lookup, interval: float = 5.0) -> DNSPoller:
        """pkg/fqdn/dnspoller.go:50 — poll loop; when any matchName's
        IP set changes, re-inject ToCIDRSet into the registered FQDN
        rules and retrigger regeneration. ``lookup(names)`` returns
        {name: (ips, ttl)}."""
        def on_change(changed_names) -> None:
            dirty = False
            with self._lock:
                for r in self._fqdn_rules:
                    inject_to_cidr_set(r, self.dns_cache)
                    if self._resync_rule_prefixes_locked(r):
                        dirty = True
            if dirty:
                self.trigger_policy_updates("fqdn-update")

        self.dns_poller = DNSPoller(self.dns_cache, lookup=lookup,
                                    on_change=on_change, interval=interval,
                                    access_log=self.proxy.access_log)
        with self._lock:
            for r in self._fqdn_rules:
                self.dns_poller.register_rule(r)
        self.dns_poller.start()
        return self.dns_poller

    # -------------------------------------------------- status

    def status(self) -> Dict:
        """GET /healthz (daemon/status.go status collector)."""
        from .. import __version__
        return {
            "version": __version__,
            "uptime-seconds": round(time.time() - self.started_at, 3),
            "kvstore": self._kvstore_status(),
            "policy": {"revision": self.repo.revision,
                       "rules": len(self.repo)},
            "endpoints": {
                "total": len(self.endpoints),
                "by-state": self._endpoint_state_counts()},
            "identities": len(self.identity_allocator),
            "ipcache": len(self.ipcache),
            "nodes": len(self.node_manager),
            "proxy": {"redirects": len(self.proxy)},
            "clustermesh": self.clustermesh.status(),
            "controllers": self.controllers.status_model(),
            # top-level controller degraded signal: a reconcile loop
            # failing repeatedly must not stay buried inside the
            # controller list (`cilium-tpu status` prints it loudly)
            "controller-health": self._controller_health(),
            # breaker/retry/relist counters from the transport
            # resilience layer (utils/resilience.py) — the same series
            # /metrics exposes, summarized for the status path
            "transports": transport_resilience.status_summary(),
            "datapath": {"revision": self.datapath.revision,
                         "conntrack-slots": self.datapath.ct.slots},
            # dataplane serving mode (datapath/supervisor.py): fails
            # LOUDLY while the device lane is degraded — traffic is
            # being served fail-static from the host oracle, which is
            # correct-but-slow; an operator must see it immediately
            "dataplane": self._dataplane_status(),
            # device-table fill fractions + threshold warnings
            # (cilium_bpf_map_pressure analog); `cilium-tpu status
            # --verbose` renders the same report
            "map-pressure": self.datapath.map_pressure(
                self.config.map_pressure_warn),
            # runtime self-telemetry: tracer health, compile/jit-cache
            # accounting, recent policy-propagation delays
            "telemetry": {
                "tracing": self.tracer.stats(),
                "jit": jit_telemetry.report(),
                "propagation": self.propagation.report(5)},
            # serving SLO tier (observability/slo.py): per-lane
            # latency percentiles, deadline-budget burn rates and the
            # latest queue-flight sample — `status --verbose` renders
            # the cilium-tpu-top-style table from this block
            "slo": slo_tracker.snapshot(),
            # incident flight recorder health: how much of the ordered
            # degraded-condition timeline is buffered for
            # `cilium-tpu events` / GET /debug/events
            "flight-recorder": flight_recorder.stats(),
            # flow observability health (hubble observer + relay)
            "hubble": self.hubble.stats()
            if self.hubble is not None else None,
            # verdict provenance + the drift audit's correctness
            # verdict on the policy compiler: "FAILING" here means the
            # compiled device tables and the host oracle disagree —
            # the loudest signal status() can carry
            "provenance": self._provenance_status(),
            # inline threat scoring: mode (off/shadow/enforce), live
            # thresholds + model generation, verdict accounting; an
            # enforcing plane reports loudly (a model may now override
            # policy-allowed traffic)
            "threat": self.threat_status(),
            # device traffic analytics: sketch geometry + write epoch,
            # the last drain's (possibly partial) outcome, and the
            # live heavy-hitter / scan-suspect sets
            "analytics": self.analytics_status(),
            # runtime capability probes (bpf/run_probes.sh analog)
            "features": self._features(),
        }

    def _kvstore_status(self) -> Dict:
        """status()["kvstore"]: no longer a bare echo of kv.status() —
        the outage guard contributes breaker state and the
        seconds-since-last-successful-op staleness age, so a dead
        backend can never report 'ok' between calls; while degraded
        the mode/staleness/journal fields ARE the loud signal."""
        if self.kv is None:
            return {"state": "ok", "backend": "none"}
        inner = getattr(self.kv, "inner", self.kv)
        out = {"state": self.kv.status(),
               "backend": type(inner).__name__}
        if self._kv_guard is not None:
            out.update(self._kv_guard.report())
            fb = self.identity_allocator
            if isinstance(fb, FallbackIdentityAllocator):
                out["local-identities"] = fb.local_count()
                out["fallback-allocations"] = fb.fallback_allocations
        return out

    def _controller_health(self) -> Dict:
        failing = self.controllers.failing()
        if not failing:
            return {"status": "ok", "failing": []}
        names = ", ".join(f["name"] for f in failing)
        return {"status": f"DEGRADED: controller(s) {names} failing "
                          f">=3x consecutively",
                "failing": failing}

    def _dataplane_status(self) -> Dict:
        out = self.datapath.supervision_status()
        mode = out.get("mode", "ok")
        if mode == "ok":
            out["status"] = "ok"
        elif "shards" in out:
            # sharded dataplane: name EXACTLY the degraded shards —
            # the rest of the mesh is still serving bit-exact on
            # device, and the operator must see the blast radius
            bad = out.get("degraded-shards", [])
            faults = []
            for k in bad:
                sup = ((out["shards"].get(str(k)) or {})
                       .get("serving") or {}).get("supervisor") or {}
                faults.append(f"shard {k}: {sup.get('last-fault')}")
            out["status"] = (
                f"{mode.upper()}: shard(s) {bad} serving fail-static "
                f"from the host oracle ({'; '.join(faults)}); "
                f"remaining shards on device")
        else:
            sup = (out.get("serving") or {}).get("supervisor") or {}
            out["status"] = (
                f"{mode.upper()}: device lane faulted "
                f"({sup.get('last-fault')}); serving fail-static "
                f"from the host oracle")
        return out

    def _provenance_status(self) -> Dict:
        report = self.drift_report()
        summary = None
        if report is not None:
            summary = {"status": report.get("status"),
                       "checked": report.get("checked", 0),
                       "sc-checked": report.get("sc-checked", 0),
                       "last-run": report.get("last-run"),
                       "divergences":
                       len(report.get("divergences") or [])}
            if summary["divergences"]:
                summary["detail"] = report["divergences"][:5]
        return {"enabled": self.datapath.provenance_enabled,
                "drift-audit": summary,
                "top-dropped-rules": self.monitor.top_dropped_rules(5)}

    def _features(self) -> Dict:
        cached = getattr(self, "_features_cache", None)
        if cached is None:
            from ..utils.platform import probe_features
            # health-path contract: never trigger a fresh backend init
            # (a wedged relay would hang /healthz forever) and reuse
            # the native probe done at __init__ instead of compiling
            probed = probe_features(
                allow_init=False,
                native_fastpath=self.host_path is not None)
            # only cache a definitive probe: a deferred/unavailable
            # result must re-probe next time, or status would report
            # no accelerator forever after the backend comes up
            if probed.get("definitive", False):
                self._features_cache = probed
            return probed
        return cached

    def _endpoint_state_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for ep in self.endpoints.endpoints():
            counts[ep.state] = counts.get(ep.state, 0) + 1
        # keep the per-state gauge in lockstep, zeroing states no
        # endpoint is in anymore (EndpointStateCount analog)
        from ..endpoint import EndpointState as _ES
        for state in (_ES.CREATING, _ES.WAITING_FOR_IDENTITY,
                      _ES.READY, _ES.WAITING_TO_REGENERATE,
                      _ES.REGENERATING, _ES.RESTORING,
                      _ES.DISCONNECTING, _ES.DISCONNECTED,
                      _ES.NOT_READY):
            ENDPOINT_STATE_COUNT.set(counts.get(state, 0),
                                     labels={"state": state})
        return counts

    def metrics_text(self) -> str:
        # scrape-time collection: drain the deferred verdict-outcome
        # accounting and refresh the map-pressure gauges (computed
        # gauges, Prometheus collector semantics) so a bare /metrics
        # scrape never under-reports or reads stale fill fractions
        self.datapath.flush_telemetry()
        self.datapath.map_pressure(self.config.map_pressure_warn)
        return metrics_registry.expose_text()

    def pipeline_report(self) -> Dict:
        """Host-timed pipeline stage breakdown (/debug/pipeline)."""
        return pipeline_report()

    def traces(self, trace_id: Optional[str] = None,
               revision: Optional[int] = None, limit: int = 50):
        """Span-trace surface (/debug/traces, `cilium-tpu trace`):
        summaries by default, one span tree for an explicit trace id
        or policy revision."""
        if revision is not None:
            trace_id = self.propagation.trace_id_of(revision)
            if trace_id is None:
                return None
        if trace_id is not None:
            return self.tracer.tree(trace_id)
        return {"traces": self.tracer.traces(limit),
                "tracer": self.tracer.stats(),
                "propagation": self.propagation.report(limit)}

    # -------------------------------------------------- lifecycle

    def wait_for_quiesce(self, timeout: float = 30.0) -> bool:
        return self.endpoints.wait_for_quiesce(timeout)

    def wait_for_policy_revision(self, revision: Optional[int] = None,
                                 timeout: float = 30.0) -> bool:
        """Block until every live endpoint has applied ``revision``
        (default: the current repo revision) and the build queue is
        idle. The synchronous wait the async TriggerPolicyUpdates path
        needs (the reference tracks the same via Endpoint.policyRevision
        waitForPolicyRevision)."""
        rev = revision if revision is not None else self.repo.revision
        deadline = time.time() + timeout

        def applied() -> bool:
            return all(ep.policy_revision >= rev or
                       ep.state in (EndpointState.DISCONNECTING,
                                    EndpointState.DISCONNECTED)
                       for ep in self.endpoints.endpoints())

        while time.time() < deadline:
            if applied() and self.endpoints.wait_for_quiesce(0.05):
                return True
            time.sleep(0.01)
        return applied() and self.endpoints.wait_for_quiesce(0.0)

    # ----------------------------------------------------- monitor wire

    def serve_monitor(self, port: int = 0):
        """Serve the monitor event stream to subscriber processes
        (monitor/main.go:81-119 unix-socket fan-out analog); the CLI's
        ``monitor --socket`` follows from a separate process."""
        from ..monitor import MonitorServer
        if getattr(self, "_monitor_server", None) is None:
            self._monitor_server = MonitorServer(self.monitor,
                                                 port=port).start()
        return self._monitor_server

    # -------------------------------------------------------- xDS wire

    def serve_xds(self, port: int = 0):
        """Serve NPDS (proxy redirects as NetworkPolicy resources) and
        NPHDS (ip -> identity) to out-of-process proxies over TCP —
        the process boundary of pkg/envoy/server.go:114.  Policy pushes
        can then block on cross-process ACKs via
        ``xds_cache.wait_for_acks``."""
        from ..l7.xds_wire import XDSWireServer
        from ..xds import (Cache, TYPE_NETWORK_POLICY,
                           TYPE_NETWORK_POLICY_HOSTS,
                           host_mapping_resources)
        if getattr(self, "_xds_server", None) is not None:
            return self._xds_server
        self.xds_cache = Cache()
        self._xds_server = XDSWireServer(self.xds_cache,
                                         port=port).start()

        def publish_hosts(*_a):
            pairs = {p.prefix: p.identity for p in self.ipcache.dump()}
            self.xds_cache.set_resources(
                TYPE_NETWORK_POLICY_HOSTS,
                host_mapping_resources(pairs))

        self.ipcache.add_listener(lambda *a: publish_hosts(),
                                  replay=False)
        publish_hosts()

        def publish_npds():
            resources = {}
            for r in self.proxy.redirects():
                http_rules = []
                if r.l7_filter is not None:
                    for rules in r.l7_filter.l7_rules_per_ep.values():
                        for hr in getattr(rules, "http", []) or []:
                            http_rules.append({
                                "method": hr.method, "path": hr.path,
                                "host": hr.host})
                # the child's orig-dst: for an ingress redirect the
                # upstream is the endpoint itself on the original port
                ep = self.endpoints.lookup(r.endpoint_id)
                up_host = (ep.ipv4 if ep is not None and ep.ipv4
                           else "127.0.0.1")
                resources[r.id] = {
                    "name": r.id, "policy": self.repo.revision,
                    "proxy_port": r.proxy_port,
                    "upstream": [up_host, r.to_port],
                    "http_rules": http_rules}
            self.xds_cache.set_resources(TYPE_NETWORK_POLICY, resources)

        self.proxy.on_change = publish_npds
        publish_npds()
        return self._xds_server

    def shutdown(self) -> None:
        if getattr(self, "hubble", None) is not None:
            self.hubble.close()
        if getattr(self, "_monitor_server", None) is not None:
            self._monitor_server.shutdown()
        if getattr(self, "_xds_server", None) is not None:
            self._xds_server.shutdown()
        self.endpoints.shutdown()
        self._regen_trigger.shutdown()
        self._lpm_trigger.shutdown()
        self.controllers.remove_all()
        self.clustermesh.close()
        if self.dns_poller is not None:
            self.dns_poller.stop()
        if self._ip_watcher is not None:
            self._ip_watcher.stop()
        if self.node_registry is not None:
            self.node_registry.close()
        self.checkpoint_ct()

    # ------------------------------------------- conntrack persistence

    def checkpoint_ct(self) -> bool:
        """Persist both CT tables (the pinned-ctmap analog): on the
        next start, restore_ct() lets established flows keep their
        verdicts while the agent was down (daemon/state.go + pinned
        bpf maps semantics)."""
        if not self.config.state_dir:
            return False
        try:
            os.makedirs(self.config.state_dir, exist_ok=True)
            path = os.path.join(self.config.state_dir, "ct_state.npz")
            v4, v6 = self.datapath.snapshot_ct()
            # tmp + rename, like Endpoint.write_checkpoint: a crash
            # mid-write must not destroy the previous good checkpoint
            # (tmp keeps the .npz suffix — numpy appends one otherwise)
            tmp = f"{path[:-4]}.tmp{os.getpid()}.npz"
            np.savez_compressed(
                tmp, __version__=np.array([1], np.int64),
                **{f"v4_{k}": v for k, v in v4.items()},
                **{f"v6_{k}": v for k, v in v6.items()})
            os.replace(tmp, path)
            return True
        except OSError:
            return False

    def restore_ct(self) -> int:
        """Reload checkpointed CT state; returns live entries restored
        (0 when absent or geometry-incompatible — a cold start)."""
        if not self.config.state_dir:
            return 0
        path = os.path.join(self.config.state_dir, "ct_state.npz")
        # prepare BOTH tables before assigning either, and treat any
        # corruption (truncated zip, missing members, geometry change,
        # unknown version) as a cold start — never a crash, never a
        # half-restored table
        try:
            with np.load(path) as z:
                if int(np.asarray(z["__version__"])[0]) != 1:
                    return 0
                v4 = {k[3:]: z[k] for k in z.files
                      if k.startswith("v4_")}
                v6 = {k[3:]: z[k] for k in z.files
                      if k.startswith("v6_")}
            return self.datapath.restore_ct_snapshots(v4, v6)
        except Exception:  # noqa: BLE001 — np.load raises zipfile/
            return 0       # zlib/pickle errors beyond OSError; a bad
            # snapshot (geometry/fields) is a cold start, never a
            # crash or a half-restored table
