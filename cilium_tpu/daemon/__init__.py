"""The agent daemon: composition root wiring every subsystem.

Analog of the reference's ``daemon/`` — policy repository, identity
allocation, ipcache, endpoint lifecycle + regeneration into device
tables, proxy redirects, service LB, prefilter, node discovery,
clustermesh, monitor, metrics, REST API and CLI.
"""

from .daemon import Daemon

__all__ = ["Daemon"]
