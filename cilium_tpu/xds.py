"""Versioned resource distribution with ACK barriers (xDS analog).

Reference: pkg/envoy/xds — the agent runs a tiny xDS server with three
streams: LDS (listeners), NPDS (per-endpoint NetworkPolicy) and NPHDS
(ip -> identity host mapping); each resource set is versioned, watchers
receive updates, and policy pushes block on client ACKs through
completion barriers (server.go:114 StartXDSServer, the
completion.WaitGroup usage in UpdateNetworkPolicy).

Here the transport is in-process subscriptions (a gRPC shim would sit
on top); the versioning/ACK/completion semantics are the same.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Set

from .utils.completion import Completion, WaitGroup

# The reference's three type URLs (pkg/envoy/xds + cilium protos).
TYPE_LISTENER = "type.googleapis.com/envoy.api.v2.Listener"
TYPE_NETWORK_POLICY = "type.googleapis.com/cilium.NetworkPolicy"
TYPE_NETWORK_POLICY_HOSTS = "type.googleapis.com/cilium.NetworkPolicyHosts"


@dataclass
class VersionedResources:
    version: int
    resources: Dict[str, object]  # name -> resource


class Watch:
    """One client's subscription to a type URL."""

    def __init__(self, cache: "Cache", type_url: str, client: str):
        self.cache = cache
        self.type_url = type_url
        self.client = client
        self._cond = threading.Condition()
        self._acked = 0
        self._delivered = 0

    def next(self, timeout: Optional[float] = None
             ) -> Optional[VersionedResources]:
        """Block until a version newer than the last delivered exists."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: self.cache._version_of(self.type_url) >
                self._delivered, timeout=timeout)
            if not ok:
                return None
        vr = self.cache.get(self.type_url)
        self._delivered = vr.version
        return vr

    def ack(self, version: int) -> None:
        """Client accepted ``version`` (xds ACK path) — completes any
        barriers waiting on it."""
        with self._cond:
            self._acked = max(self._acked, version)
        self.cache._on_ack(self.type_url, self.client, version)

    def nack(self, version: int, detail: str = "") -> None:
        self.cache._on_nack(self.type_url, self.client, version, detail)

    def _notify(self) -> None:
        with self._cond:
            self._cond.notify_all()


class Cache:
    """Versioned typed resource sets + ACK-tracking (xds/cache.go +
    ack.go AckingResourceMutator)."""

    def __init__(self):
        self._lock = threading.RLock()
        # serializes read-modify-write mutations WITHOUT being held
        # while notifying watchers (Watch.next holds its condition and
        # then takes self._lock — holding self._lock across _notify
        # would be an ABBA deadlock)
        self._mutate = threading.Lock()
        self._sets: Dict[str, VersionedResources] = {}
        self._watches: Dict[str, List[Watch]] = {}
        # (type_url, version) -> completions waiting on full ACK
        self._pending: Dict[tuple, List[tuple]] = {}
        self.nacks: List[tuple] = []

    # ------------------------------------------------------------- write

    def set_resources(self, type_url: str,
                      resources: Dict[str, object]) -> int:
        """Replace the full set; returns the new version."""
        with self._mutate:
            return self._set_resources_mutating(type_url, resources)

    def _set_resources_mutating(self, type_url: str,
                                resources: Dict[str, object]) -> int:
        with self._lock:
            cur = self._sets.get(type_url)
            version = (cur.version if cur else 0) + 1
            self._sets[type_url] = VersionedResources(
                version=version, resources=dict(resources))
            watches = list(self._watches.get(type_url, []))
        # notify outside self._lock (see __init__ lock-order note)
        for w in watches:
            w._notify()
        return version

    def upsert(self, type_url: str, name: str, resource: object) -> int:
        with self._mutate:
            cur = self.get(type_url)
            resources = dict(cur.resources)
            resources[name] = resource
            return self._set_resources_mutating(type_url, resources)

    def delete(self, type_url: str, name: str) -> int:
        with self._mutate:
            cur = self.get(type_url)
            resources = dict(cur.resources)
            resources.pop(name, None)
            return self._set_resources_mutating(type_url, resources)

    # -------------------------------------------------------------- read

    def get(self, type_url: str) -> VersionedResources:
        with self._lock:
            vr = self._sets.get(type_url)
            return vr if vr is not None else VersionedResources(0, {})

    def _version_of(self, type_url: str) -> int:
        with self._lock:
            vr = self._sets.get(type_url)
            return vr.version if vr else 0

    # ------------------------------------------------------------ watches

    def watch(self, type_url: str, client: str) -> Watch:
        w = Watch(self, type_url, client)
        with self._lock:
            self._watches.setdefault(type_url, []).append(w)
        return w

    def unwatch(self, watch: Watch) -> None:
        """Drop a subscription.  A client that vanishes mid-barrier
        (proxy crash during a policy push) must not strand the push:
        its name is removed from every pending ACK set, and barriers
        that only waited on it complete — the remaining watcher set is
        what the push can still mean (the reference's e2e server
        cancels the stream's pending completions the same way)."""
        completed = []
        with self._lock:
            ws = self._watches.get(watch.type_url, [])
            if watch in ws:
                ws.remove(watch)
            # another live watch under the same client name (a restarted
            # proxy resubscribing before the old conn reaps) still
            # holds the barrier
            live = {w.client for w in ws}
            if watch.client not in live:
                for (t, v), entries in list(self._pending.items()):
                    if t != watch.type_url:
                        continue
                    for missing, comp in entries:
                        missing.discard(watch.client)
                        if not missing:
                            completed.append(comp)
                    self._pending[(t, v)] = [(m, c) for m, c in entries
                                             if m]
                    if not self._pending[(t, v)]:
                        del self._pending[(t, v)]
        for comp in completed:
            comp.complete()

    # ---------------------------------------------------------------- ack

    def wait_for_acks(self, type_url: str, version: int,
                      wg: Optional[WaitGroup] = None) -> Completion:
        """A Completion that fires when EVERY current watcher of
        ``type_url`` has ACKed >= version (the barrier the agent blocks
        on before marking a policy revision realized —
        envoy/server.go UpdateNetworkPolicy + completion.WaitGroup)."""
        comp = wg.add_completion() if wg is not None else Completion()
        with self._lock:
            watches = list(self._watches.get(type_url, []))
            missing = {w.client for w in watches
                       if w._acked < version}
            if not missing:
                comp.complete()
                return comp
            self._pending.setdefault((type_url, version), []).append(
                (missing, comp))
        return comp

    def _on_ack(self, type_url: str, client: str, version: int) -> None:
        completed = []
        with self._lock:
            for (t, v), entries in list(self._pending.items()):
                if t != type_url or v > version:
                    continue
                for missing, comp in entries:
                    missing.discard(client)
                    if not missing:
                        completed.append(comp)
                self._pending[(t, v)] = [
                    (m, c) for m, c in entries if m]
                if not self._pending[(t, v)]:
                    del self._pending[(t, v)]
        for comp in completed:
            comp.complete()

    def _on_nack(self, type_url: str, client: str, version: int,
                 detail: str) -> None:
        with self._lock:
            self.nacks.append((type_url, client, version, detail))


# ---------------------------------------------------------------------------
# Typed helpers: the NPDS / NPHDS payload shapes
# ---------------------------------------------------------------------------

def network_policy_resource(endpoint_id: int, policy_revision: int,
                            ingress_rules: List[Dict],
                            egress_rules: List[Dict]) -> Dict:
    """cilium.NetworkPolicy-shaped resource (envoy/server.go:606
    getNetworkPolicy): per-port rules with allowed remote identities +
    HTTP header match specs."""
    return {"name": str(endpoint_id), "policy": policy_revision,
            "ingress_per_port_policies": ingress_rules,
            "egress_per_port_policies": egress_rules}


def host_mapping_resources(ip_to_identity: Dict[str, int]) -> Dict[str, object]:
    """cilium.NetworkPolicyHosts resources: identity -> host ips
    (cilium_host_map.cc consumption shape)."""
    by_identity: Dict[int, List[str]] = {}
    for ip, ident in ip_to_identity.items():
        by_identity.setdefault(ident, []).append(ip)
    return {str(ident): {"policy": ident,
                         "host_addresses": sorted(ips)}
            for ident, ips in by_identity.items()}
