"""Container-runtime workload watcher.

Reference: pkg/workloads — docker/containerd/CRI-O event watchers keep
endpoint labels in sync with container state (start events create or
relabel endpoints, die events clean them up). The runtime client is
pluggable here: any source pushes ``start``/``stop`` events with
container metadata; the watcher drives the daemon's endpoint lifecycle
and allocates IPs through IPAM.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from .ipam import HostScopeIPAM, IPAMError


class WorkloadWatcher:
    """Container events -> endpoint lifecycle."""

    def __init__(self, daemon, ipam: Optional[HostScopeIPAM] = None,
                 label_prefix: str = "container"):
        self.daemon = daemon
        self.ipam = ipam
        self.label_prefix = label_prefix
        self._lock = threading.Lock()
        self._by_container: Dict[str, int] = {}
        self._next_ep_id = 1000
        self.events = 0

    def _labels_of(self, container: Dict) -> List[str]:
        return [f"{self.label_prefix}:{k}={v}"
                for k, v in sorted((container.get("labels") or {}).items())]

    def on_start(self, container: Dict) -> int:
        """Container started (workloads processCreateWorkload): create
        or relabel its endpoint. ``container``: {id, name, labels}."""
        cid = container["id"]
        with self._lock:
            self.events += 1
            ep_id = self._by_container.get(cid)
            if ep_id is None:
                ep_id = self._next_ep_id
                self._next_ep_id += 1
                self._by_container[cid] = ep_id
                create = True
            else:
                create = False
        labels = self._labels_of(container)
        if create:
            ipv4 = ""
            if self.ipam is not None:
                try:
                    ipv4 = self.ipam.allocate_next(owner=cid)
                except IPAMError:
                    ipv4 = ""
            self.daemon.endpoint_create(
                ep_id, ipv4=ipv4, container_name=container.get("name", cid),
                labels=labels)
        else:
            self.daemon.endpoint_update_labels(ep_id, labels)
        return ep_id

    def on_stop(self, container_id: str) -> bool:
        """Container died: tear the endpoint down."""
        with self._lock:
            self.events += 1
            ep_id = self._by_container.pop(container_id, None)
        if ep_id is None:
            return False
        ep = self.daemon.endpoints.lookup(ep_id)
        ip = ep.ipv4 if ep else ""
        ok = self.daemon.endpoint_delete(ep_id)
        if ok and ip and self.ipam is not None:
            self.ipam.release(ip)
        return ok

    def endpoint_of(self, container_id: str) -> Optional[int]:
        with self._lock:
            return self._by_container.get(container_id)

    def __len__(self):
        with self._lock:
            return len(self._by_container)
