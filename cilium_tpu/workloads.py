"""Container-runtime workload watchers.

Reference: pkg/workloads — docker/containerd/CRI-O event watchers keep
endpoint labels in sync with container state (start events create or
relabel endpoints, die events clean them up).

Two layers, like the reference's split between the runtime client and
the workload logic:

- ``WorkloadWatcher``: the pluggable sink — any source pushes
  ``start``/``stop`` events with container metadata; it drives the
  daemon's endpoint lifecycle and allocates IPs through IPAM.
- ``DockerClient`` + ``DockerEventWatcher``: the real runtime client
  (pkg/workloads/docker.go analog) — Docker Engine API over the
  dockerd unix socket: initial ``GET /containers/json`` sync, then a
  streaming ``GET /events`` subscription (chunked newline-delimited
  JSON), inspecting containers on ``start`` and cleaning up on
  ``die``, reconnecting with backoff when the stream drops.
"""

from __future__ import annotations

import http.client
import json
import socket
import threading
from typing import Callable, Dict, Iterator, List, Optional

from .ipam import HostScopeIPAM, IPAMError
from .utils.netio import teardown_http_conn


class WorkloadWatcher:
    """Container events -> endpoint lifecycle."""

    def __init__(self, daemon, ipam: Optional[HostScopeIPAM] = None,
                 label_prefix: str = "container"):
        self.daemon = daemon
        self.ipam = ipam
        self.label_prefix = label_prefix
        self._lock = threading.Lock()
        self._by_container: Dict[str, int] = {}
        self._next_ep_id = 1000
        self.events = 0

    def _labels_of(self, container: Dict) -> List[str]:
        return [f"{self.label_prefix}:{k}={v}"
                for k, v in sorted((container.get("labels") or {}).items())]

    def on_start(self, container: Dict) -> int:
        """Container started (workloads processCreateWorkload): create
        or relabel its endpoint. ``container``: {id, name, labels}."""
        cid = container["id"]
        with self._lock:
            self.events += 1
            ep_id = self._by_container.get(cid)
            if ep_id is None:
                ep_id = self._next_ep_id
                self._next_ep_id += 1
                self._by_container[cid] = ep_id
                create = True
            else:
                create = False
        labels = self._labels_of(container)
        if create:
            ipv4 = ""
            if self.ipam is not None:
                try:
                    ipv4 = self.ipam.allocate_next(owner=cid)
                except IPAMError:
                    ipv4 = ""
            self.daemon.endpoint_create(
                ep_id, ipv4=ipv4, container_name=container.get("name", cid),
                labels=labels)
        else:
            self.daemon.endpoint_update_labels(ep_id, labels)
        return ep_id

    def on_stop(self, container_id: str) -> bool:
        """Container died: tear the endpoint down."""
        with self._lock:
            self.events += 1
            ep_id = self._by_container.pop(container_id, None)
        if ep_id is None:
            return False
        ep = self.daemon.endpoints.lookup(ep_id)
        ip = ep.ipv4 if ep else ""
        ok = self.daemon.endpoint_delete(ep_id)
        if ok and ip and self.ipam is not None:
            self.ipam.release(ip)
        return ok

    def endpoint_of(self, container_id: str) -> Optional[int]:
        with self._lock:
            return self._by_container.get(container_id)

    def containers(self) -> List[str]:
        """Container ids with live endpoints (resync diff base)."""
        with self._lock:
            return list(self._by_container)

    def __len__(self):
        with self._lock:
            return len(self._by_container)


# ---------------------------------------------------------------------------
# Docker runtime client (pkg/workloads/docker.go analog)

class UnixHTTPConnection(http.client.HTTPConnection):
    """HTTP over an AF_UNIX socket (the dockerd transport)."""

    def __init__(self, path: str, timeout: float = 10.0):
        super().__init__("localhost", timeout=timeout)
        self.unix_path = path

    def connect(self) -> None:
        s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        s.settimeout(self.timeout)
        s.connect(self.unix_path)
        self.sock = s


class DockerError(RuntimeError):
    pass


class DockerClient:
    """Minimal Docker Engine API client over the daemon socket."""

    def __init__(self, socket_path: str = "/var/run/docker.sock",
                 timeout: float = 10.0):
        self.socket_path = socket_path
        self.timeout = timeout

    def _request(self, path: str) -> Dict:
        conn = UnixHTTPConnection(self.socket_path, self.timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            data = resp.read()
            if resp.status != 200:
                raise DockerError(f"{path}: HTTP {resp.status}")
            return json.loads(data)
        except (OSError, ValueError) as e:
            raise DockerError(f"{path}: {e}") from e
        finally:
            conn.close()

    def ping(self) -> bool:
        try:
            self._request("/containers/json?limit=1")
            return True
        except DockerError:
            return False

    def list_containers(self) -> List[Dict]:
        """Running containers (GET /containers/json)."""
        return self._request("/containers/json")

    def inspect(self, container_id: str) -> Dict:
        """GET /containers/{id}/json."""
        return self._request(f"/containers/{container_id}/json")

    def events(self, register: Optional[Callable] = None
               ) -> "_EventStream":
        """Subscribe to container events (GET /events): newline-
        delimited JSON over a chunked response held open by dockerd.

        The subscription is established EAGERLY (request sent,
        response headers read) before this returns — the caller can
        list containers afterwards knowing no event falls between the
        list and the stream (docker.go subscribes before syncing for
        the same reason).  ``register(conn)`` hands the live
        connection to the caller's stop path."""
        return _EventStream(self, register)


class _EventStream:
    """One live /events subscription; iterate for events."""

    def __init__(self, client: DockerClient,
                 register: Optional[Callable]):
        self._conn = UnixHTTPConnection(client.socket_path,
                                        client.timeout)
        try:
            self._conn.connect()
            if register is not None:
                register(self._conn)
            self._conn.request("GET", "/events?type=container")
            self._resp = self._conn.getresponse()
            if self._resp.status != 200:
                raise DockerError(
                    f"/events: HTTP {self._resp.status}")
            self._conn.sock.settimeout(None)
        except DockerError:
            teardown_http_conn(self._conn)
            raise
        except (OSError, http.client.HTTPException) as e:
            teardown_http_conn(self._conn)
            raise DockerError(f"/events: {e}") from e

    def __iter__(self) -> Iterator[Dict]:
        try:
            for raw in self._resp:
                line = raw.strip()
                if not line:
                    continue
                try:
                    yield json.loads(line)
                except ValueError:
                    raise DockerError("/events: bad frame")
        except (OSError, http.client.HTTPException,
                ValueError, AttributeError) as e:
            # ValueError/AttributeError: http.client artifacts of the
            # stop path cutting the socket mid-chunk / nulling resp.fp
            raise DockerError(f"/events: {e}") from e
        finally:
            self.close()

    def close(self) -> None:
        teardown_http_conn(self._conn)


def _container_meta(inspect: Dict) -> Dict:
    """Inspect result -> the pluggable watcher's container dict."""
    return {
        "id": inspect.get("Id", ""),
        "name": (inspect.get("Name") or "").lstrip("/"),
        "labels": (inspect.get("Config") or {}).get("Labels") or {},
    }


class DockerEventWatcher:
    """dockerd events -> the pluggable WorkloadWatcher.

    Reference flow (pkg/workloads/docker.go EnableEventListener):
    list running containers first (processes started while the agent
    was down), then consume the event stream; ``start`` inspects and
    creates/relabels, ``die`` tears down.  Stream loss reconnects with
    backoff and RESYNCS (a container that died during the gap must not
    leak its endpoint)."""

    def __init__(self, client: DockerClient, sink: WorkloadWatcher,
                 backoff_base: float = 0.1, backoff_max: float = 5.0):
        self.client = client
        self.sink = sink
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._stop = threading.Event()
        self._conn_lock = threading.Lock()
        self._conn = None
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="docker-events")
        self.synced = threading.Event()
        self.resyncs = 0

    def start(self) -> "DockerEventWatcher":
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        with self._conn_lock:
            if self._conn is not None:
                teardown_http_conn(self._conn)
        # the thread may be inside a list/inspect call on its own
        # connection (bounded by client.timeout) — wait that out, and
        # the sink calls re-check _stop so a stalled dockerd can't
        # drive endpoint churn after stop() returns
        self._thread.join(timeout=self.client.timeout + 2.0
                          if timeout is None else timeout)

    def _register(self, conn) -> None:
        with self._conn_lock:
            self._conn = conn
        if self._stop.is_set():
            teardown_http_conn(conn)

    def _sync(self) -> None:
        """Reconcile against the runtime's current truth."""
        running = {}
        for c in self.client.list_containers():
            cid = c.get("Id", "")
            if not cid:
                continue
            running[cid] = {
                "id": cid,
                "name": (c.get("Names") or ["/"])[0].lstrip("/"),
                "labels": c.get("Labels") or {},
            }
        known = set(self.sink.containers())
        for cid, meta in running.items():
            self.sink.on_start(meta)
        for cid in known - set(running):
            self.sink.on_stop(cid)
        self.resyncs += 1
        self.synced.set()

    def _run(self) -> None:
        failures = 0
        while not self._stop.is_set():
            stream = None
            try:
                # subscribe FIRST, then sync: an event landing between
                # the container list and the stream open would
                # otherwise be lost forever (the stream buffers it)
                stream = self.client.events(register=self._register)
                self._sync()
                failures = 0  # subscribed + synced = healthy again
                for ev in stream:
                    if self._stop.is_set():
                        break
                    if ev.get("Type", "container") != "container":
                        continue
                    action = ev.get("Action") or ev.get("status", "")
                    cid = (ev.get("Actor") or {}).get("ID") \
                        or ev.get("id", "")
                    if not cid:
                        continue
                    if action == "start":
                        try:
                            meta = _container_meta(
                                self.client.inspect(cid))
                        except DockerError:
                            # transient inspect failure (timeout, or
                            # raced a fast die): fall back to the
                            # event's own Actor.Attributes — docker
                            # carries the container labels there —
                            # rather than leaving the container
                            # endpoint-less until the next resync
                            attrs = dict((ev.get("Actor") or {})
                                         .get("Attributes") or {})
                            name = attrs.pop("name", cid[:12])
                            attrs.pop("image", None)
                            meta = {"id": cid, "name": name,
                                    "labels": attrs}
                        if self._stop.is_set():
                            break
                        self.sink.on_start(meta)
                    elif action in ("die", "stop", "destroy"):
                        if self._stop.is_set():
                            break
                        self.sink.on_stop(cid)
            except DockerError:
                failures += 1
            finally:
                if stream is not None:
                    stream.close()  # a failed _sync must not leak the
                    #                 live subscription for the backoff
            if self._stop.is_set():
                return
            # back off before re-subscribing even on a CLEAN stream
            # end (dockerd restart phases close streams politely — a
            # no-wait loop would hammer it with connect+resync);
            # exponent clamped so a long outage can't overflow
            self._stop.wait(min(
                self.backoff_base * (2 ** min(failures, 8)),
                self.backoff_max))
