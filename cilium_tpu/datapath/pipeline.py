"""Fused datapath step: ipcache LPM resolve + 3-stage policy verdict.

This is the flagship "model" of the framework: the batched equivalent of
the reference's per-packet path (bpf_lxc.c handle_ipv4_from_lxc →
ipcache lookup → policy_can_egress → counters), expressed as one jitted
tensor program so XLA fuses the whole thing into a handful of gathers.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lpm import CompiledLPM
from ..compiler.policy_tables import CompiledPolicy
from ..ops.hashtab_ops import batched_lookup
from ..ops.lpm_ops import lpm_lookup
from .lb import CompiledLB, LBTables
from .verdict import Counters, PacketBatch, verdict_step

# Identity assigned when the ipcache has no entry for the address
# (reference: world; bpf derives WORLD_ID when ipcache misses).
WORLD_IDENTITY = 2


class DatapathTables(NamedTuple):
    """All device-resident state for the fused step (one generation)."""

    key_id: jnp.ndarray     # [E, S] policy tables
    key_meta: jnp.ndarray
    value: jnp.ndarray
    lpm_masks: jnp.ndarray  # [P] ipcache LPM
    lpm_key_a: jnp.ndarray  # [P, S2]
    lpm_key_b: jnp.ndarray
    lpm_value: jnp.ndarray
    lpm_plens: jnp.ndarray


class RawPacketBatch(NamedTuple):
    """Pre-identity packet metadata: addresses instead of identities."""

    endpoint: jnp.ndarray    # [B] int32 endpoint slot
    src_addr: jnp.ndarray    # [B] int32 (uint32 IPv4)
    dport: jnp.ndarray       # [B] int32
    proto: jnp.ndarray       # [B] int32
    direction: jnp.ndarray   # [B] int32
    length: jnp.ndarray      # [B] int32
    is_fragment: jnp.ndarray  # [B] int32


def datapath_step(tables: DatapathTables, counters: Counters,
                  pkt: RawPacketBatch, *, policy_probe: int,
                  lpm_probe: int) -> Tuple[jnp.ndarray, jnp.ndarray,
                                           Counters]:
    """addr -> identity (LPM) -> verdict (3-stage) -> counters.

    Returns (verdict [B], identity [B], counters')."""
    found, ident = lpm_lookup(tables.lpm_masks, tables.lpm_key_a,
                              tables.lpm_key_b, tables.lpm_value,
                              tables.lpm_plens, pkt.src_addr, lpm_probe)
    identity = jnp.where(found, ident, jnp.int32(WORLD_IDENTITY))
    vb = PacketBatch(endpoint=pkt.endpoint, identity=identity,
                     dport=pkt.dport, proto=pkt.proto,
                     direction=pkt.direction, length=pkt.length,
                     is_fragment=pkt.is_fragment)
    verdict, counters = verdict_step(tables.key_id, tables.key_meta,
                                     tables.value, counters, vb,
                                     policy_probe)
    return verdict, identity, counters


def build_tables(compiled_policy: CompiledPolicy,
                 compiled_lpm: CompiledLPM, device=None) -> DatapathTables:
    put = (lambda x: jax.device_put(x, device)) if device else jnp.asarray
    return DatapathTables(
        key_id=put(compiled_policy.key_id),
        key_meta=put(compiled_policy.key_meta),
        value=put(compiled_policy.value),
        lpm_masks=put(compiled_lpm.masks),
        lpm_key_a=put(compiled_lpm.key_a),
        lpm_key_b=put(compiled_lpm.key_b),
        lpm_value=put(compiled_lpm.value),
        lpm_plens=put(compiled_lpm.prefix_lens))


def make_step(compiled_policy: CompiledPolicy, compiled_lpm: CompiledLPM):
    """(jitted step fn, tables, fresh counters)."""
    tables = build_tables(compiled_policy, compiled_lpm)
    n = max(1, compiled_policy.num_endpoints * compiled_policy.slots)
    counters = Counters(packets=jnp.zeros(n, jnp.uint32),
                        bytes=jnp.zeros(n, jnp.uint32))
    step = jax.jit(functools.partial(
        datapath_step, policy_probe=compiled_policy.max_probe,
        lpm_probe=compiled_lpm.max_probe), donate_argnums=(1,))
    return step, tables, counters


# ---------------------------------------------------------------------------
# Full datapath: prefilter -> LB -> conntrack -> ipcache -> policy -> create
# ---------------------------------------------------------------------------

class FullPacketBatch(NamedTuple):
    """Wire-level metadata for the full path, all [B] int32.

    ``from_overlay``/``tunnel_id`` model the tunnel header of packets
    that arrived encapsulated from a peer node (bpf_overlay.c:151
    from-overlay + skb_get_tunnel_key): where ``from_overlay`` is
    nonzero, the source security identity is taken from ``tunnel_id``
    — the identity the sending node stamped into the tunnel key — not
    re-derived from the ipcache.  ``mark_identity`` is the proxy-mark
    analog (bpf_netdev.c:128-146 MARK_MAGIC_PROXY): flows re-entering
    the datapath from the L7 proxy carry the ORIGINAL source identity
    in the mark, so they are not re-classified (as WORLD or as the
    proxy host) on the way to the upstream; nonzero values win over
    the ipcache.  All three default to None."""

    endpoint: jnp.ndarray
    saddr: jnp.ndarray
    daddr: jnp.ndarray
    sport: jnp.ndarray
    dport: jnp.ndarray
    proto: jnp.ndarray
    direction: jnp.ndarray
    tcp_flags: jnp.ndarray
    length: jnp.ndarray
    is_fragment: jnp.ndarray
    from_overlay: jnp.ndarray = None
    tunnel_id: jnp.ndarray = None
    mark_identity: jnp.ndarray = None


class NATResult(NamedTuple):
    """Post-NAT forwarding result: forward packets carry the DNAT'd
    destination; reply packets carry the rev-NAT'd (VIP-restored)
    source.  ``tunnel_ep``/``tunnel_id`` are the encap decision
    (encap.h encap_and_redirect): nonzero tunnel_ep means the packet
    leaves encapsulated to that node IP with the source security
    identity in the tunnel key.  All [B] int32."""

    daddr: jnp.ndarray
    dport: jnp.ndarray
    saddr: jnp.ndarray
    sport: jnp.ndarray
    rev_nat: jnp.ndarray
    tunnel_ep: jnp.ndarray
    tunnel_id: jnp.ndarray


def lb_rev_nat_arrays(lb_tables, saddr, sport, rev_nat_idx):
    """Clamp-safe reverse NAT (see lb.lb_rev_nat)."""
    has = rev_nat_idx > 0
    n = lb_tables.rev_vip.shape[0]
    idx = jnp.clip(jnp.where(has, rev_nat_idx, 0), 0, n - 1)
    return (jnp.where(has, lb_tables.rev_vip[idx], saddr),
            jnp.where(has, lb_tables.rev_port[idx], sport))


class FullTables(NamedTuple):
    """All device state for the full step.  The tunnel LPM (tun_*) is
    the device twin of the reference's cilium_tunnel_map (pkg/maps/
    tunnel): pod-CIDR -> tunnel endpoint node IP.  ``ep_identity`` [E]
    is each local endpoint slot's own security identity — the SECLABEL
    the per-endpoint program compiles in (bpf_lxc.c) — stamped into the
    tunnel key on encap.  All optional: None disables the overlay
    stage."""

    datapath: DatapathTables          # policy + ipcache LPM
    lb: LBTables                      # service tables
    pf_masks: jnp.ndarray             # prefilter deny LPM
    pf_key_a: jnp.ndarray
    pf_key_b: jnp.ndarray
    pf_value: jnp.ndarray
    pf_plens: jnp.ndarray
    tun_masks: jnp.ndarray = None     # tunnel map LPM (encap.h)
    tun_key_a: jnp.ndarray = None
    tun_key_b: jnp.ndarray = None
    tun_value: jnp.ndarray = None
    tun_plens: jnp.ndarray = None
    ep_identity: jnp.ndarray = None   # [E] local slot -> own identity
    # On-device L7 fast-verdict tables (l7/fast.L7FastPrograms): the
    # per-slot program classification emitted by the policy compiler
    # plus the fused class-compressed k-stride DFA walked inline by
    # the fast-verdict stage.  All None = fast verdicts disabled (the
    # compiled program is byte-identical to the pre-fast step).
    l7_prog: jnp.ndarray = None       # [E, S] slot -> program id (-1)
    l7_flat: jnp.ndarray = None       # [S * c1**k] stride table
    l7_map: jnp.ndarray = None        # [258] byte+2 -> class
    l7_accept: jnp.ndarray = None     # [S] 0/1 per-state accept
    l7_starts: jnp.ndarray = None     # [R] per-regex start state
    l7_pmask: jnp.ndarray = None      # [P, R] program -> regex rows
    # Inline threat-scoring model (threat/model.ThreatModel.tables()):
    # the quantized Q8.8 scorer weights + the policy-controlled
    # threshold/mode config vector, packed as their own "threat-model"
    # dispatch group.  All None = threat scoring disabled (compiled
    # program byte-identical to the pre-threat step).
    tm_w1: jnp.ndarray = None         # [F, H] int32 layer-1 weights
    tm_b1: jnp.ndarray = None         # [H] int32 layer-1 bias
    tm_w2: jnp.ndarray = None         # [H] int32 layer-2 weights
    tm_b2: jnp.ndarray = None         # [1] int32 layer-2 bias
    tm_cfg: jnp.ndarray = None        # [8] int32 thresholds/mode/gen


def _flow_identities(ep_identity, endpoint, peer_identity, direction):
    """(src, dst) security identities for the flow key: the endpoint's
    own identity (SECLABEL) on its side of the flow, the resolved peer
    identity on the other — egress flows read ep->peer, ingress flows
    peer->ep (hubble/aggregation flow key convention)."""
    if ep_identity is not None:
        n_ep = ep_identity.shape[0]
        own = ep_identity[jnp.clip(endpoint, 0, n_ep - 1)]
    else:
        own = jnp.zeros_like(peer_identity)
    egress = direction == 1
    src = jnp.where(egress, own, peer_identity)
    dst = jnp.where(egress, peer_identity, own)
    return src, dst


# field order of the serving path's packed [10, B] batch matrix
# (datapath/serving.py staging buffers; full_datapath_step_packed
# unpacks in this exact order inside the fused program)
PACKED_FIELDS = ("endpoint", "saddr", "daddr", "sport", "dport",
                 "proto", "direction", "tcp_flags", "length",
                 "is_fragment")
PACKED_INDEX = {f: i for i, f in enumerate(PACKED_FIELDS)}


def host_fail_static_step(soa, n: int, *, established, identity_of,
                          policy_verdict):
    """Host-serveable fail-static twin of ``full_datapath_step``'s
    verdict precedence — what the dataplane supervisor
    (datapath/supervisor.py) answers with while the device lane is
    degraded, mirroring the reference's fail-static property
    (daemon/state.go: the kernel keeps forwarding on last-known-good
    state while the agent is down).

    Precedence mirrors step 7 of the compiled program: an established
    flow follows its CT entry (its recorded proxy port; 0 == allow),
    everything else takes the (degraded-mode) policy verdict for a new
    flow.  The LB/prefilter/overlay stages are deliberately NOT served
    degraded — fail-static answers policy, not NAT (documented
    limitation; the reference's agent-down window likewise freezes LB
    backend churn).

    ``soa`` is the PacketRing SoA dict of [>=n] int32 arrays
    (PACKED_FIELDS keys).  Callbacks:

    - ``established(saddr_u32, daddr_u32, sport, dport, proto,
      direction) -> Optional[int]``: the flow's recorded proxy port
      when its CT entry (forward or reply tuple) is live, else None;
    - ``identity_of(addr_u32) -> int``: host-ipcache identity of the
      peer address (WORLD when unknown);
    - ``policy_verdict(endpoint_slot, identity, dport, proto,
      direction) -> int``: the new-flow decision (the compiler oracle,
      a blanket deny, or a blanket allow — the configured degraded
      policy).

    Returns (verdict [n], identity [n]) int32 arrays.
    """
    verdicts = np.empty(n, np.int32)
    idents = np.empty(n, np.int32)
    ep = soa["endpoint"]
    sa = np.ascontiguousarray(soa["saddr"][:n]).view(np.uint32)
    da = np.ascontiguousarray(soa["daddr"][:n]).view(np.uint32)
    sp, dp = soa["sport"], soa["dport"]
    pr, di = soa["proto"], soa["direction"]
    for j in range(n):
        direction = int(di[j])
        # peer identity: src on ingress, dst on egress (bpf_lxc.c:205)
        peer = int(sa[j]) if direction == 0 else int(da[j])
        ident = int(identity_of(peer))
        idents[j] = ident
        ct = established(int(sa[j]), int(da[j]), int(sp[j]),
                         int(dp[j]), int(pr[j]), direction)
        if ct is not None:
            verdicts[j] = ct  # the flow keeps its verdict (0 = allow)
            continue
        verdicts[j] = int(policy_verdict(int(ep[j]), ident,
                                         int(dp[j]), int(pr[j]),
                                         direction))
    return verdicts, idents


def full_datapath_step_packed(tables: FullTables, ct,
                              counters: Counters, packed, now,
                              flows=None, payload=None, threat=None,
                              analytics=None, **statics):
    """full_datapath_step over ONE [10, B] int32 field matrix.

    The latency-tier fix for small-batch dispatch overhead: ten
    per-field host->device transfers (each paying a full dispatch,
    ~80 us apiece on the CPU backend — batch-size independent)
    collapse into a single H2D of the packed matrix; the per-field
    unpack is row slicing INSIDE the jitted program, which XLA fuses
    away.  Field order is PACKED_FIELDS.  ``payload`` is the optional
    [B, W] L7 payload lane (its own buffer beside the field matrix —
    present only when the fast-verdict stage is compiled in, so the
    no-L7 program keeps its exact argument list)."""
    pkt = FullPacketBatch(**{f: packed[i]
                             for i, f in enumerate(PACKED_FIELDS)})
    return full_datapath_step(tables, ct, counters, pkt, now,
                              flows, payload, threat, analytics,
                              **statics)


def _l7_fast_stage(tables, payload, pol_verdict, pol_slot, *,
                   k: int, c1: int):
    """The on-device L7 fast-verdict stage (l7/fast.py tables): where
    the policy verdict is a redirect whose matched slot carries a
    first-bytes-decidable program AND the payload window is present
    and untruncated, walk the fused class-compressed k-stride DFA and
    decide allow/deny inline — the flow never reaches the proxy.
    Everything else keeps the redirect verdict (fail-to-redirect,
    never fail-open).

    Returns (verdict', fast_allow [B], fast_deny [B])."""
    from ..ops.dfa_engine import _packed_walk
    from .verdict import VERDICT_DROP_L7
    prog_flat = tables.l7_prog.reshape(-1)
    slot = jnp.clip(pol_slot, 0, prog_flat.shape[0] - 1)
    prog = jnp.where(pol_slot >= 0, prog_flat[slot], jnp.int32(-1))
    eligible = (pol_verdict > 0) & (prog >= 0)
    # decidability: an absent (all -1) payload or a window-truncation
    # poison row (-2, the encode_strings overlong contract) cannot be
    # judged from first bytes — those flows redirect to the proxy
    has_payload = payload[:, 0] >= 0
    truncated = jnp.any(payload == jnp.int32(-2), axis=1)
    b, w = payload.shape
    # class map + stride pack + ceil(W/k) dependent gathers: the
    # ops/dfa_engine stride strategy fused into this program (negative
    # bytes map to the identity class, which composes as the identity
    # function — pads freeze states exactly like the standalone engine)
    cls = tables.l7_map[payload + jnp.int32(2)]
    pad = (-w) % k
    if pad:
        cls = jnp.concatenate(
            [cls, jnp.full((b, pad), c1 - 1, jnp.int32)], axis=1)
    grp = cls.reshape(b, -1, k)
    idx = grp[:, :, 0]
    for j in range(1, k):
        idx = idx * jnp.int32(c1) + grp[:, :, j]
    n_regex = tables.l7_starts.shape[0]
    states = jnp.broadcast_to(tables.l7_starts[None, :],
                              (b, n_regex)).astype(jnp.int32)
    final = _packed_walk(c1 ** k, tables.l7_flat, states, idx)
    hit = tables.l7_accept[final] != 0              # [B, R]
    n_prog = tables.l7_pmask.shape[0]
    own = tables.l7_pmask[jnp.clip(prog, 0, n_prog - 1)]
    l7_allow = jnp.any(hit & (own != 0), axis=1)
    fast = eligible & has_payload & ~truncated
    fast_allow = fast & l7_allow
    fast_deny = fast & ~l7_allow
    verdict = jnp.where(
        fast_allow, jnp.int32(0),
        jnp.where(fast_deny, jnp.int32(VERDICT_DROP_L7), pol_verdict))
    return verdict, fast_allow, fast_deny


def full_datapath_step(tables: FullTables, ct, counters: Counters,
                       pkt: FullPacketBatch, now: jnp.ndarray,
                       flows=None, payload=None, threat=None,
                       analytics=None, *,
                       policy_probe: int, lpm_probe: int, pf_probe: int,
                       lb_probe: int, ct_slots: int, ct_probe: int,
                       tun_probe: int = 0, flow_slots: int = 0,
                       flow_probe: int = 0,
                       flow_claim_budget: int = 1024,
                       with_provenance: int = 0,
                       with_l7_fast: int = 0, l7_k: int = 1,
                       l7_c1: int = 2, with_threat: int = 0,
                       threat_window_s: int = 8,
                       threat_stripe: int = 4,
                       with_analytics: int = 0,
                       analytics_depth: int = 2,
                       analytics_lanes: int = 4,
                       analytics_stripe: int = 16):
    """The batched equivalent of the reference's per-packet egress path
    (bpf_lxc.c:432 handle_ipv4_from_lxc): XDP prefilter drop, service
    DNAT (lb4_local), conntrack lookup, ipcache identity resolve, policy
    verdict for CT_NEW flows, CT entry creation gated on the verdict —
    plus the overlay plane: ingress packets flagged from_overlay take
    their source identity from the tunnel key (bpf_overlay.c:151), and
    allowed egress packets whose destination hits the tunnel map are
    marked for encap with the endpoint's identity in the tunnel key
    (encap.h encap_and_redirect, TRACE_TO_OVERLAY).

    Returns (verdict [B], event [B], identity [B], ct', counters').
    Verdict: -N drop code / 0 allow / >0 proxy port.

    ``with_provenance`` (static) appends two [B] int32 outputs: the
    matched policymap entry's flat slot (-1 = no entry decided) and
    the decision-tier code (events.TIER_*).  0 keeps the compiled
    program identical to the pre-provenance step.

    ``with_l7_fast`` (static) fuses the on-device L7 fast-verdict
    stage: redirect verdicts whose matched slot names a first-bytes-
    decidable program (tables.l7_*) are decided inline from the
    [B, W] ``payload`` lane — allow (0) or DROP_POLICY_L7 — and fall
    back to redirect-to-proxy for truncated/absent payloads.  0 keeps
    the compiled program byte-identical to the pre-fast step (the
    payload arg is never passed then).

    ``with_threat`` (static) fuses the inline threat-scoring stage
    (threat/stage.py): every packet gets an anomaly score from the
    flow-table probe + the claim-window aggregates in ``threat`` (the
    shard-local ThreatState buffer, returned updated) + its own tuple
    features; in enforce mode the score maps through the
    policy-controlled thresholds (tables.tm_cfg) to drop
    (VERDICT_DROP_THREAT), redirect-to-proxy, or token-bucket
    rate-limit, and NEVER overrides an existing drop.  Appends
    (threat', threat_out [B]) outputs.  0 keeps the compiled program
    byte-identical to the pre-threat step.

    ``with_analytics`` (static) fuses the device-resident traffic-
    analytics stage (analytics/stage.py): the batch's FINAL verdicts
    fold into ``analytics`` (the shard-local AnalyticsState buffer) —
    count-min heavy-hitter sketches, candidate key tables, and
    distinct-flow cardinality registers — and the updated state is
    appended as one extra output.  0 keeps the compiled program
    byte-identical to the pre-analytics step (the analytics arg is
    never passed then).
    """
    from .conntrack import CT_NEW, CTBatch, ct_step
    from .events import (DROP_FRAG_NOSUPPORT, DROP_POLICY, DROP_POLICY_L7,
                         DROP_PREFILTER, DROP_THREAT, TRACE_TO_LXC,
                         TRACE_TO_PROXY)
    from .lb import lb_step
    from .verdict import (VERDICT_ALLOW, VERDICT_DROP, VERDICT_DROP_FRAG,
                          VERDICT_DROP_L7, VERDICT_DROP_THREAT)

    # 1. Prefilter (bpf_xdp.c:158 check_filters).
    if tables.pf_key_a.shape[0] > 0:
        pf_hit, _ = lpm_lookup(tables.pf_masks, tables.pf_key_a,
                               tables.pf_key_b, tables.pf_value,
                               tables.pf_plens, pkt.saddr, pf_probe)
    else:
        pf_hit = jnp.zeros(pkt.saddr.shape[0], bool)

    # 2. Service LB DNAT (lb.h lb4_local).
    daddr, dport, rev_nat, is_svc = lb_step(
        tables.lb, pkt.daddr, pkt.dport, pkt.proto, pkt.saddr, pkt.sport,
        max_probe=lb_probe)

    # 3. Conntrack on the DNAT'd tuple (bpf_lxc.c:501 ct_lookup4) — the
    # create decision comes after the policy verdict.
    ctb = CTBatch(saddr=pkt.saddr, daddr=daddr, sport=pkt.sport,
                  dport=dport, proto=pkt.proto, direction=pkt.direction,
                  tcp_flags=pkt.tcp_flags,
                  related=jnp.zeros_like(pkt.proto))

    # 4. ipcache: remote identity from the *peer* address (src on
    # ingress, dst on egress — bpf_lxc.c:205/eps.h lookup).
    peer = jnp.where(pkt.direction == 0, pkt.saddr, daddr)
    found, ident = lpm_lookup(tables.datapath.lpm_masks,
                              tables.datapath.lpm_key_a,
                              tables.datapath.lpm_key_b,
                              tables.datapath.lpm_value,
                              tables.datapath.lpm_plens, peer, lpm_probe)
    identity = jnp.where(found, ident, jnp.int32(WORLD_IDENTITY))
    # Overlay decap: the sending node stamped the source identity into
    # the tunnel key; it wins over the local ipcache view
    # (bpf_overlay.c:151 key.tunnel_id -> ipv4_local_delivery secctx).
    if pkt.from_overlay is not None:
        decap = (pkt.from_overlay != 0) & (pkt.direction == 0)
        identity = jnp.where(decap, pkt.tunnel_id, identity)
    # Proxy re-entry: the mark carries the original source identity of
    # a proxied flow (bpf_netdev.c:128-146) — without it the upstream
    # leg would classify as the proxy host / WORLD.
    if pkt.mark_identity is not None:
        identity = jnp.where(pkt.mark_identity > 0,
                             pkt.mark_identity, identity)

    # 5. Policy verdict (bpf/lib/policy.h __policy_can_access).
    vb = PacketBatch(endpoint=pkt.endpoint, identity=identity,
                     dport=dport, proto=pkt.proto,
                     direction=pkt.direction, length=pkt.length,
                     is_fragment=pkt.is_fragment)
    if with_provenance or with_l7_fast:
        # the fast-verdict stage needs the matched slot even when
        # provenance outputs are off (the unused tier is dead code XLA
        # eliminates; the lookups are shared either way)
        pol_verdict, counters, pol_slot, pol_tier = verdict_step(
            tables.datapath.key_id, tables.datapath.key_meta,
            tables.datapath.value, counters, vb, policy_probe,
            with_provenance=True)
    else:
        pol_verdict, counters = verdict_step(
            tables.datapath.key_id, tables.datapath.key_meta,
            tables.datapath.value, counters, vb, policy_probe)

    # 5.5 On-device L7 fast verdict: decide first-bytes-decidable
    # redirects inline from the payload lane — a fast-allowed flow
    # creates its CT entry with proxy port 0 (the whole connection
    # bypasses the proxy), a fast-denied flow creates nothing.
    if with_l7_fast:
        pol_verdict, l7_fast_allow, l7_fast_deny = _l7_fast_stage(
            tables, payload, pol_verdict, pol_slot, k=l7_k, c1=l7_c1)

    # 6. CT step. Creation is gated on the policy allowing the flow
    # (bpf_lxc.c:545 ct_create4 after policy_can_egress); prefilter-
    # dropped packets may neither create nor touch live entries; new
    # entries record the flow's rev-NAT index and proxy port so the
    # whole connection keeps its NAT and L7 redirect.
    create_ok = (pol_verdict >= 0) & ~pf_hit
    proxy_in = jnp.maximum(pol_verdict, 0)
    ct_verdict, ct_rev_nat, ct_proxy, ct = ct_step(
        ct, ctb, now, create_ok, update_mask=~pf_hit,
        rev_nat_in=rev_nat, proxy_port_in=proxy_in,
        slots=ct_slots, max_probe=ct_probe)

    # 7. Final verdict: prefilter drop beats everything; established
    # flows follow their CT entry (including its recorded proxy port);
    # CT_NEW flows take the policy verdict.
    established = ct_verdict != CT_NEW
    verdict = jnp.where(
        pf_hit, jnp.int32(VERDICT_DROP),
        jnp.where(established, ct_proxy, pol_verdict))

    # 7.5 Inline threat scoring (threat/stage.py): per-packet anomaly
    # score from the flow-table probe + window aggregates + tuple
    # features; enforce-mode arms override allow/redirect verdicts
    # BEFORE the event/overlay stages so a threat-dropped packet never
    # encaps and a threat-redirect routes to the proxy like any other.
    if with_threat:
        from ..threat.stage import threat_stage
        t_src, t_dst = _flow_identities(tables.ep_identity,
                                        pkt.endpoint, identity,
                                        pkt.direction)
        verdict, threat, threat_out, thr_drop, thr_redir, rl_drop = \
            threat_stage(
                tables, threat, flows, verdict,
                identity=identity, dport=dport, proto=pkt.proto,
                tcp_flags=pkt.tcp_flags, length=pkt.length,
                is_fragment=pkt.is_fragment, established=established,
                saddr_w=pkt.saddr, daddr_w=daddr, sport=pkt.sport,
                flow_src=t_src, flow_dst=t_dst, now=now,
                window_s=threat_window_s, flow_slots=flow_slots,
                flow_probe=flow_probe, stripe=threat_stripe)

    # 8. Reply-path reverse NAT (lb.h lb4_rev_nat): restore VIP/port on
    # packets of flows whose CT entry carries a rev-NAT index.
    from .conntrack import CT_REPLY, CT_RELATED
    is_reply = (ct_verdict == CT_REPLY) | (ct_verdict == CT_RELATED)
    rn = jnp.where(is_reply, ct_rev_nat, jnp.int32(0))
    nat_saddr, nat_sport = lb_rev_nat_arrays(tables.lb, pkt.saddr,
                                             pkt.sport, rn)

    event = jnp.where(
        pf_hit, jnp.int32(DROP_PREFILTER),
        jnp.where(verdict == VERDICT_DROP_FRAG, jnp.int32(DROP_FRAG_NOSUPPORT),
                  jnp.where(verdict < 0, jnp.int32(DROP_POLICY),
                            jnp.where(verdict > 0, jnp.int32(TRACE_TO_PROXY),
                                      jnp.int32(TRACE_TO_LXC)))))
    if with_l7_fast:
        # VERDICT_DROP_L7 is produced only by the fast stage, so the
        # final verdict identifies inline L7 denials exactly
        event = jnp.where(verdict == jnp.int32(VERDICT_DROP_L7),
                          jnp.int32(DROP_POLICY_L7), event)
    if with_threat:
        # VERDICT_DROP_THREAT likewise names the threat stage exactly
        event = jnp.where(verdict == jnp.int32(VERDICT_DROP_THREAT),
                          jnp.int32(DROP_THREAT), event)

    # 8.5 Fused traffic analytics (analytics/stage.py): fold the
    # batch's FINAL verdicts into the device-resident heavy-hitter
    # sketches / candidate key tables / cardinality registers — one
    # scatter-add per sketch plus one combined max-scatter.  Runs
    # post-threat so the drops metric attributes every drop arm.
    if with_analytics:
        from ..analytics.stage import analytics_stage
        analytics = analytics_stage(
            analytics, identity=identity, dport=dport, proto=pkt.proto,
            sport=pkt.sport, length=pkt.length, verdict=verdict,
            saddr_key=pkt.saddr, daddr_key=daddr, now=now,
            depth=analytics_depth, lanes=analytics_lanes,
            stripe=analytics_stripe)

    # 9. Overlay encap (encap.h encap_and_redirect): allowed egress
    # packets whose (DNAT'd) destination falls in a peer node's pod
    # CIDR leave encapsulated to that node's tunnel endpoint, carrying
    # the sending endpoint's own identity (SECLABEL) in the tunnel key.
    # Proxy-redirected packets go to the proxy first, not the overlay.
    zero = jnp.zeros_like(verdict)
    if tun_probe > 0 and tables.tun_key_a is not None:
        from .events import TRACE_TO_OVERLAY
        t_hit, t_ep = lpm_lookup(tables.tun_masks, tables.tun_key_a,
                                 tables.tun_key_b, tables.tun_value,
                                 tables.tun_plens, daddr, tun_probe)
        encap = t_hit & (pkt.direction == 1) & (verdict == 0) & ~pf_hit
        if tables.ep_identity is not None:
            n_ep = tables.ep_identity.shape[0]
            src_sec = tables.ep_identity[
                jnp.clip(pkt.endpoint, 0, n_ep - 1)]
        else:
            src_sec = zero
        tun_ep_out = jnp.where(encap, t_ep, zero)
        tun_id_out = jnp.where(encap, src_sec, zero)
        event = jnp.where(encap, jnp.int32(TRACE_TO_OVERLAY), event)
    else:
        tun_ep_out = zero
        tun_id_out = zero

    nat = NATResult(daddr=daddr, dport=dport, saddr=nat_saddr,
                    sport=nat_sport, rev_nat=ct_rev_nat,
                    tunnel_ep=tun_ep_out, tunnel_id=tun_id_out)
    out = (verdict, event, identity, nat, ct, counters)
    if flows is not None and flow_slots > 0:
        # 10. Hubble on-device flow aggregation: the same compiled
        # program that produced the verdict reduces per-flow state —
        # packet/byte counters + last-seen keyed by (src identity,
        # dst identity, DNAT'd dport, proto, event) — so host-side
        # observability reads compact aggregates, not packets.
        from ..hubble.aggregation import flow_update_step
        src_id, dst_id = _flow_identities(tables.ep_identity,
                                          pkt.endpoint, identity,
                                          pkt.direction)
        flows = flow_update_step(
            flows, src_id, dst_id, dport, pkt.proto, event,
            pkt.length, now, slots=flow_slots, max_probe=flow_probe,
            claim_budget=flow_claim_budget)
        out = out + (flows,)
    if with_threat:
        # 10.5 Threat outputs: the updated shard-local state buffer
        # and the per-packet score|band|fired lane (engine keeps the
        # last batch's lane for the observability consumers)
        out = out + (threat, threat_out)
    if with_analytics:
        # 10.7 Analytics output: the updated shard-local buffer (the
        # host never reads per-batch lanes — decode.py queries the
        # quiesced epoch of this state directly)
        out = out + (analytics,)
    if with_provenance:
        # 11. Provenance finalization: mirror the final-verdict
        # precedence (step 7) — prefilter beats everything, CT
        # fast-path hits next, then the policy tiers.  Slots stay -1
        # wherever no compiled policymap entry decided.
        from .events import TIER_CT_ESTABLISHED, TIER_PREFILTER
        if with_l7_fast:
            # the fast stage decided where it fired (and nothing above
            # it did): report the fast tier, keeping the matched
            # redirect entry as the attributed slot
            from .events import TIER_L7_FAST_ALLOW, TIER_L7_FAST_DENY
            pol_tier = jnp.where(
                l7_fast_allow, jnp.int32(TIER_L7_FAST_ALLOW),
                jnp.where(l7_fast_deny, jnp.int32(TIER_L7_FAST_DENY),
                          pol_tier))
        tier = jnp.where(
            pf_hit, jnp.int32(TIER_PREFILTER),
            jnp.where(established, jnp.int32(TIER_CT_ESTABLISHED),
                      pol_tier))
        slot = jnp.where(pf_hit | established, jnp.int32(-1), pol_slot)
        if with_threat:
            # the threat stage decided last: where it overrode the
            # verdict, it owns the tier (the slot keeps the matched
            # policy attribution — the rule that ALLOWED the traffic
            # the scorer then refused)
            from .events import (TIER_THREAT_DROP,
                                 TIER_THREAT_RATELIMIT,
                                 TIER_THREAT_REDIRECT)
            tier = jnp.where(
                rl_drop, jnp.int32(TIER_THREAT_RATELIMIT),
                jnp.where(thr_drop, jnp.int32(TIER_THREAT_DROP),
                          jnp.where(thr_redir,
                                    jnp.int32(TIER_THREAT_REDIRECT),
                                    tier)))
        out = out + (slot, tier)
    return out


# ---------------------------------------------------------------------------
# IPv6 path (bpf_lxc.c:114 ipv6_l3_from_lxc, :745 ipv6_policy)
# ---------------------------------------------------------------------------
#
# Addresses are [B, 4] int32 word arrays (big-endian u32 words).  The
# policy verdict tables are family-agnostic (identity x port x proto),
# so the v6 path shares them — only the address-keyed stages differ:
# prefilter and ipcache run the 4-word LPM (full 128-bit compare).
#
# Conntrack: the reference keeps a separate ct6 map with full 128-bit
# tuple keys.  Here the v6 CT is a SEPARATE CT table whose two address
# words hold 32-bit mixes of the 128-bit addresses (fold6 below) — a
# deliberate TPU trade: the CT hot loop stays the same 4-word-key
# scatter/gather kernel for both families instead of doubling gather
# volume.  Two distinct v6 flows alias only if both address folds AND
# the exact port pair AND proto/direction all collide (~2^-64 per flow
# pair); the effect of an alias is one shared CT entry (stale
# timeout/flag sharing), the same class of benign interference as the
# reference's documented CT races — not a policy bypass, because policy
# runs on the ipcache identity, which uses full 128-bit compares.

IPPROTO_ICMPV6 = 58
ICMP6_NS = 135            # neighbour solicitation
ICMP6_NA = 136            # neighbour advertisement
ICMP6_ECHO_REQUEST = 128


class FullPacketBatch6(NamedTuple):
    """v6 wire metadata; addresses [B, 4], everything else [B] int32.

    ``icmp_type`` carries the ICMPv6 type for proto-58 rows (0
    elsewhere); ``nd_target`` the ND target address of NS packets
    ([B, 4], zeros elsewhere) — bpf/lib/icmp6.h reads both from the
    wire at ICMP6_TYPE_OFFSET / ICMP6_ND_TARGET_OFFSET."""

    endpoint: jnp.ndarray
    saddr: jnp.ndarray       # [B, 4]
    daddr: jnp.ndarray       # [B, 4]
    sport: jnp.ndarray
    dport: jnp.ndarray
    proto: jnp.ndarray
    direction: jnp.ndarray
    tcp_flags: jnp.ndarray
    length: jnp.ndarray
    is_fragment: jnp.ndarray
    from_overlay: jnp.ndarray = None
    tunnel_id: jnp.ndarray = None
    mark_identity: jnp.ndarray = None
    icmp_type: jnp.ndarray = None
    nd_target: jnp.ndarray = None


class LPM6Tables(NamedTuple):
    masks: jnp.ndarray   # [P, 4]
    k0: jnp.ndarray      # [P, S]
    k1: jnp.ndarray
    k2: jnp.ndarray
    k3: jnp.ndarray
    kb: jnp.ndarray
    value: jnp.ndarray
    plens: jnp.ndarray   # [P]


class NAT6Result(NamedTuple):
    """v6 forwarding result: DNAT'd destination (forward) and
    rev-NAT'd VIP-restored source (reply).  Addresses [B, 4]."""

    daddr: jnp.ndarray
    dport: jnp.ndarray
    saddr: jnp.ndarray
    sport: jnp.ndarray
    rev_nat: jnp.ndarray


class FullTables6(NamedTuple):
    key_id: jnp.ndarray      # shared policy tables [E, S]
    key_meta: jnp.ndarray
    value: jnp.ndarray
    ipcache6: LPM6Tables
    pf6: LPM6Tables
    lb6: object = None       # LB6Tables (None = no v6 services)
    # the node's router IP words [4] (icmp6.h BPF_V6(router, ROUTER_IP))
    # — the address whose NS/echo the datapath answers itself; None
    # disables the ICMPv6 responder stage
    router_ip6: jnp.ndarray = None
    # [E] local slot -> own security identity (shared with the v4
    # tables; the flow-aggregation stage keys on it)
    ep_identity: jnp.ndarray = None
    # L7 fast-verdict tables (shared with the v4 family — the policy
    # tensors and therefore the per-slot classification are family-
    # agnostic); all None = fast verdicts disabled
    l7_prog: jnp.ndarray = None
    l7_flat: jnp.ndarray = None
    l7_map: jnp.ndarray = None
    l7_accept: jnp.ndarray = None
    l7_starts: jnp.ndarray = None
    l7_pmask: jnp.ndarray = None
    # Inline threat-scoring model (shared with the v4 family — flow
    # keys and features are identity-based, family-agnostic); all
    # None = threat scoring disabled
    tm_w1: jnp.ndarray = None
    tm_b1: jnp.ndarray = None
    tm_w2: jnp.ndarray = None
    tm_b2: jnp.ndarray = None
    tm_cfg: jnp.ndarray = None


def lpm6_tables(c) -> LPM6Tables:
    """CompiledLPM6 -> device tables."""
    return LPM6Tables(masks=jnp.asarray(c.masks), k0=jnp.asarray(c.k0),
                      k1=jnp.asarray(c.k1), k2=jnp.asarray(c.k2),
                      k3=jnp.asarray(c.k3), kb=jnp.asarray(c.kb),
                      value=jnp.asarray(c.value),
                      plens=jnp.asarray(c.prefix_lens))


def fold6(words: jnp.ndarray) -> jnp.ndarray:
    """[B, 4] -> [B] 32-bit mix (CT key fold; see module comment)."""
    from ..ops.hashtab_ops import hash_mix_jnp
    return hash_mix_jnp(hash_mix_jnp(words[:, 0], words[:, 1]),
                        hash_mix_jnp(words[:, 2], words[:, 3]))


def full_datapath_step6(tables: FullTables6, ct, counters: Counters,
                        pkt: FullPacketBatch6, now: jnp.ndarray,
                        flows=None, payload=None, threat=None,
                        analytics=None, *,
                        policy_probe: int, lpm6_probe: int,
                        pf6_probe: int, ct_slots: int, ct_probe: int,
                        lb6_probe: int = 0, flow_slots: int = 0,
                        flow_probe: int = 0,
                        flow_claim_budget: int = 1024,
                        with_provenance: int = 0,
                        with_l7_fast: int = 0, l7_k: int = 1,
                        l7_c1: int = 2, with_threat: int = 0,
                        threat_window_s: int = 8,
                        threat_stripe: int = 4,
                        with_analytics: int = 0,
                        analytics_depth: int = 2,
                        analytics_lanes: int = 4,
                        analytics_stripe: int = 16):
    """The v6 twin of full_datapath_step (bpf_lxc.c:745 ipv6_policy):
    prefilter drop, service DNAT (lb6_local), conntrack, ipcache
    identity, policy verdict for CT_NEW flows, CT create gated on the
    verdict, reply-path reverse NAT (lb6_rev_nat).  ``with_l7_fast``
    fuses the same on-device L7 fast-verdict stage as the v4 family
    (the policy tensors and per-slot classification are shared).

    Returns (verdict [B], event [B], identity [B], nat6, ct',
    counters').
    """
    from ..ops.lpm_ops import lpm6_lookup
    from .conntrack import CT_NEW, CTBatch, ct_step
    from .events import (DROP_FRAG_NOSUPPORT, DROP_POLICY, DROP_POLICY_L7,
                         DROP_PREFILTER, DROP_THREAT,
                         DROP_UNKNOWN_TARGET, ICMP6_ECHO_REPLY,
                         ICMP6_NS_REPLY, TRACE_TO_LXC, TRACE_TO_PROXY)
    from .lb import lb6_rev_nat, lb6_step
    from .verdict import (VERDICT_DROP, VERDICT_DROP_FRAG,
                          VERDICT_DROP_L7, VERDICT_DROP_THREAT,
                          verdict_step)

    b = pkt.sport.shape[0]

    # 1. Prefilter (bpf_xdp.c check_v6 analog).
    if tables.pf6.kb.shape[0] > 0:
        pf_hit, _ = lpm6_lookup(tables.pf6.masks, tables.pf6.k0,
                                tables.pf6.k1, tables.pf6.k2,
                                tables.pf6.k3, tables.pf6.kb,
                                tables.pf6.value, tables.pf6.plens,
                                pkt.saddr, pf6_probe)
    else:
        pf_hit = jnp.zeros(b, bool)

    # 1.5 ICMPv6/NDP responder (bpf/lib/icmp6.h icmp6_handle, called
    # before LB/CT/policy on the from-container path bpf_lxc.c:403-408):
    # an NS whose ND target is the router answers with an NA
    # (send_icmp6_ndisc_adv terminal action); an NS for anything else
    # drops (ACTION_UNKNOWN_ICMP6_NS); an echo request addressed to
    # the router answers with an echo reply.  Every other ICMPv6 type
    # (NA, RS/RA, errors, echo to peers) flows on through CT + policy
    # like the reference's fall-through `return 0`.
    is_icmp6 = pkt.proto == IPPROTO_ICMPV6
    if tables.router_ip6 is not None and pkt.icmp_type is not None:
        icmp_type = pkt.icmp_type
        is_ns = is_icmp6 & (icmp_type == ICMP6_NS)
        nd_target = pkt.nd_target if pkt.nd_target is not None \
            else jnp.zeros_like(pkt.saddr)
        target_is_router = jnp.all(
            nd_target == tables.router_ip6[None, :], axis=1)
        ns_answer = is_ns & target_is_router
        ns_unknown = is_ns & ~target_is_router
        echo_answer = is_icmp6 & (icmp_type == ICMP6_ECHO_REQUEST) & \
            jnp.all(pkt.daddr == tables.router_ip6[None, :], axis=1)
        icmp6_handled = ns_answer | ns_unknown | echo_answer
    else:
        icmp_type = jnp.zeros(b, jnp.int32)
        ns_answer = ns_unknown = echo_answer = jnp.zeros(b, bool)
        icmp6_handled = jnp.zeros(b, bool)

    # 2. Service LB DNAT (lb.h lb6_local).
    if lb6_probe > 0 and tables.lb6 is not None:
        daddr, dport, rev_nat, _is_svc = lb6_step(
            tables.lb6, pkt.daddr, pkt.dport, pkt.proto, pkt.saddr,
            pkt.sport, max_probe=lb6_probe)
    else:
        daddr, dport = pkt.daddr, pkt.dport
        rev_nat = jnp.zeros(b, jnp.int32)

    # 3. Conntrack on the DNAT'd folded tuple (separate v6 table).
    ctb = CTBatch(saddr=fold6(pkt.saddr), daddr=fold6(daddr),
                  sport=pkt.sport, dport=dport, proto=pkt.proto,
                  direction=pkt.direction, tcp_flags=pkt.tcp_flags,
                  related=jnp.zeros_like(pkt.proto))

    # 4. ipcache6: identity of the peer (src on ingress, dst on egress).
    peer = jnp.where((pkt.direction == 0)[:, None], pkt.saddr, daddr)
    if tables.ipcache6.kb.shape[0] > 0:
        found, ident = lpm6_lookup(
            tables.ipcache6.masks, tables.ipcache6.k0,
            tables.ipcache6.k1, tables.ipcache6.k2, tables.ipcache6.k3,
            tables.ipcache6.kb, tables.ipcache6.value,
            tables.ipcache6.plens, peer, lpm6_probe)
    else:
        found = jnp.zeros(b, bool)
        ident = jnp.zeros(b, jnp.int32)
    identity = jnp.where(found, ident, jnp.int32(WORLD_IDENTITY))
    if pkt.from_overlay is not None:
        decap = (pkt.from_overlay != 0) & (pkt.direction == 0)
        identity = jnp.where(decap, pkt.tunnel_id, identity)
    if pkt.mark_identity is not None:
        # proxy-mark re-entry (bpf_netdev.c:128-146), same as v4
        identity = jnp.where(pkt.mark_identity > 0,
                             pkt.mark_identity, identity)

    # 5. Policy verdict on the shared (family-agnostic) tables —
    # against the DNAT'd port, like the v4 path.
    vb = PacketBatch(endpoint=pkt.endpoint, identity=identity,
                     dport=dport, proto=pkt.proto,
                     direction=pkt.direction, length=pkt.length,
                     is_fragment=pkt.is_fragment)
    if with_provenance or with_l7_fast:
        pol_verdict, counters, pol_slot, pol_tier = verdict_step(
            tables.key_id, tables.key_meta, tables.value, counters,
            vb, policy_probe, count_mask=~icmp6_handled,
            with_provenance=True)
    else:
        pol_verdict, counters = verdict_step(
            tables.key_id, tables.key_meta, tables.value, counters, vb,
            policy_probe, count_mask=~icmp6_handled)

    # 5.5 On-device L7 fast verdict (same stage as the v4 family).
    if with_l7_fast:
        pol_verdict, l7_fast_allow, l7_fast_deny = _l7_fast_stage(
            tables, payload, pol_verdict, pol_slot, k=l7_k, c1=l7_c1)

    # 6. CT step, creation gated on the verdict; new entries record the
    # flow's rev-NAT index so replies can restore the VIP.  Locally
    # answered ICMPv6 never creates CT state (the reply is synthesized,
    # not forwarded).
    create_ok = (pol_verdict >= 0) & ~pf_hit & ~icmp6_handled
    proxy_in = jnp.maximum(pol_verdict, 0)
    ct_verdict, ct_rev_nat, ct_proxy, ct = ct_step(
        ct, ctb, now, create_ok, update_mask=~pf_hit & ~icmp6_handled,
        rev_nat_in=rev_nat, proxy_port_in=proxy_in,
        slots=ct_slots, max_probe=ct_probe)

    established = ct_verdict != CT_NEW
    verdict = jnp.where(
        pf_hit, jnp.int32(VERDICT_DROP),
        jnp.where(ns_unknown, jnp.int32(VERDICT_DROP),
                  jnp.where(ns_answer | echo_answer, jnp.int32(0),
                            jnp.where(established, ct_proxy,
                                      pol_verdict))))

    # 6.5 Inline threat scoring (same fused stage as the v4 family;
    # addresses enter the tuple hash as their CT folds).  Locally
    # answered ICMPv6 rows are scored but exempt from overrides — the
    # responder's reply is synthesized, not forwarded.
    if with_threat:
        from ..threat.stage import threat_stage
        t_src, t_dst = _flow_identities(tables.ep_identity,
                                        pkt.endpoint, identity,
                                        pkt.direction)
        verdict, threat, threat_out, thr_drop, thr_redir, rl_drop = \
            threat_stage(
                tables, threat, flows, verdict,
                identity=identity, dport=dport, proto=pkt.proto,
                tcp_flags=pkt.tcp_flags, length=pkt.length,
                is_fragment=pkt.is_fragment, established=established,
                saddr_w=ctb.saddr, daddr_w=ctb.daddr, sport=pkt.sport,
                flow_src=t_src, flow_dst=t_dst, now=now,
                window_s=threat_window_s, flow_slots=flow_slots,
                flow_probe=flow_probe, stripe=threat_stripe,
                exempt=icmp6_handled)

    # 7. Reply-path reverse NAT (lb6_rev_nat).
    from .conntrack import CT_RELATED, CT_REPLY
    is_reply = (ct_verdict == CT_REPLY) | (ct_verdict == CT_RELATED)
    rn = jnp.where(is_reply, ct_rev_nat, jnp.int32(0))
    if tables.lb6 is not None:
        nat_saddr, nat_sport = lb6_rev_nat(tables.lb6, pkt.saddr,
                                           pkt.sport, rn)
    else:
        nat_saddr, nat_sport = pkt.saddr, pkt.sport

    event = jnp.where(
        pf_hit, jnp.int32(DROP_PREFILTER),
        jnp.where(ns_answer, jnp.int32(ICMP6_NS_REPLY),
        jnp.where(echo_answer, jnp.int32(ICMP6_ECHO_REPLY),
        jnp.where(ns_unknown, jnp.int32(DROP_UNKNOWN_TARGET),
        jnp.where(verdict == VERDICT_DROP_FRAG,
                  jnp.int32(DROP_FRAG_NOSUPPORT),
                  jnp.where(verdict < 0, jnp.int32(DROP_POLICY),
                            jnp.where(verdict > 0,
                                      jnp.int32(TRACE_TO_PROXY),
                                      jnp.int32(TRACE_TO_LXC))))))))
    if with_l7_fast:
        event = jnp.where(verdict == jnp.int32(VERDICT_DROP_L7),
                          jnp.int32(DROP_POLICY_L7), event)
    if with_threat:
        event = jnp.where(verdict == jnp.int32(VERDICT_DROP_THREAT),
                          jnp.int32(DROP_THREAT), event)

    # 7.5 Fused traffic analytics (same stage as the v4 family; the
    # address words enter the flow hash and dst-prefix key as their CT
    # folds — deterministic, shared with the oracle).
    if with_analytics:
        from ..analytics.stage import analytics_stage
        analytics = analytics_stage(
            analytics, identity=identity, dport=dport, proto=pkt.proto,
            sport=pkt.sport, length=pkt.length, verdict=verdict,
            saddr_key=ctb.saddr, daddr_key=ctb.daddr, now=now,
            depth=analytics_depth, lanes=analytics_lanes,
            stripe=analytics_stripe)
    nat = NAT6Result(daddr=daddr, dport=dport, saddr=nat_saddr,
                     sport=nat_sport, rev_nat=ct_rev_nat)
    out = (verdict, event, identity, nat, ct, counters)
    if flows is not None and flow_slots > 0:
        # Hubble flow aggregation, v6 twin (flow keys are identity-
        # based, so the table is family-agnostic like the policy
        # tables; locally answered ICMPv6 still aggregates, under its
        # reply event code).
        from ..hubble.aggregation import flow_update_step
        src_id, dst_id = _flow_identities(tables.ep_identity,
                                          pkt.endpoint, identity,
                                          pkt.direction)
        flows = flow_update_step(
            flows, src_id, dst_id, dport, pkt.proto, event,
            pkt.length, now, slots=flow_slots, max_probe=flow_probe,
            claim_budget=flow_claim_budget)
        out = out + (flows,)
    if with_threat:
        out = out + (threat, threat_out)
    if with_analytics:
        out = out + (analytics,)
    if with_provenance:
        # Provenance finalization, mirroring the v6 verdict
        # precedence: prefilter, then the local ICMPv6 responder
        # (answered OR unknown-target dropped — either way the local
        # service tier decided, not policy), then CT, then policy.
        from .events import (TIER_CT_ESTABLISHED, TIER_LB,
                             TIER_PREFILTER)
        if with_l7_fast:
            from .events import TIER_L7_FAST_ALLOW, TIER_L7_FAST_DENY
            pol_tier = jnp.where(
                l7_fast_allow, jnp.int32(TIER_L7_FAST_ALLOW),
                jnp.where(l7_fast_deny, jnp.int32(TIER_L7_FAST_DENY),
                          pol_tier))
        tier = jnp.where(
            pf_hit, jnp.int32(TIER_PREFILTER),
            jnp.where(icmp6_handled, jnp.int32(TIER_LB),
                      jnp.where(established,
                                jnp.int32(TIER_CT_ESTABLISHED),
                                pol_tier)))
        slot = jnp.where(pf_hit | icmp6_handled | established,
                         jnp.int32(-1), pol_slot)
        if with_threat:
            from .events import (TIER_THREAT_DROP,
                                 TIER_THREAT_RATELIMIT,
                                 TIER_THREAT_REDIRECT)
            tier = jnp.where(
                rl_drop, jnp.int32(TIER_THREAT_RATELIMIT),
                jnp.where(thr_drop, jnp.int32(TIER_THREAT_DROP),
                          jnp.where(thr_redir,
                                    jnp.int32(TIER_THREAT_REDIRECT),
                                    tier)))
        out = out + (slot, tier)
    return out
