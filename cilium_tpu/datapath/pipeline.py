"""Fused datapath step: ipcache LPM resolve + 3-stage policy verdict.

This is the flagship "model" of the framework: the batched equivalent of
the reference's per-packet path (bpf_lxc.c handle_ipv4_from_lxc →
ipcache lookup → policy_can_egress → counters), expressed as one jitted
tensor program so XLA fuses the whole thing into a handful of gathers.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lpm import CompiledLPM
from ..compiler.policy_tables import CompiledPolicy
from ..ops.hashtab_ops import batched_lookup
from ..ops.lpm_ops import lpm_lookup
from .verdict import Counters, PacketBatch, verdict_step

# Identity assigned when the ipcache has no entry for the address
# (reference: world; bpf derives WORLD_ID when ipcache misses).
WORLD_IDENTITY = 2


class DatapathTables(NamedTuple):
    """All device-resident state for the fused step (one generation)."""

    key_id: jnp.ndarray     # [E, S] policy tables
    key_meta: jnp.ndarray
    value: jnp.ndarray
    lpm_masks: jnp.ndarray  # [P] ipcache LPM
    lpm_key_a: jnp.ndarray  # [P, S2]
    lpm_key_b: jnp.ndarray
    lpm_value: jnp.ndarray
    lpm_plens: jnp.ndarray


class RawPacketBatch(NamedTuple):
    """Pre-identity packet metadata: addresses instead of identities."""

    endpoint: jnp.ndarray    # [B] int32 endpoint slot
    src_addr: jnp.ndarray    # [B] int32 (uint32 IPv4)
    dport: jnp.ndarray       # [B] int32
    proto: jnp.ndarray       # [B] int32
    direction: jnp.ndarray   # [B] int32
    length: jnp.ndarray      # [B] int32
    is_fragment: jnp.ndarray  # [B] int32


def datapath_step(tables: DatapathTables, counters: Counters,
                  pkt: RawPacketBatch, *, policy_probe: int,
                  lpm_probe: int) -> Tuple[jnp.ndarray, jnp.ndarray,
                                           Counters]:
    """addr -> identity (LPM) -> verdict (3-stage) -> counters.

    Returns (verdict [B], identity [B], counters')."""
    found, ident = lpm_lookup(tables.lpm_masks, tables.lpm_key_a,
                              tables.lpm_key_b, tables.lpm_value,
                              tables.lpm_plens, pkt.src_addr, lpm_probe)
    identity = jnp.where(found, ident, jnp.int32(WORLD_IDENTITY))
    vb = PacketBatch(endpoint=pkt.endpoint, identity=identity,
                     dport=pkt.dport, proto=pkt.proto,
                     direction=pkt.direction, length=pkt.length,
                     is_fragment=pkt.is_fragment)
    verdict, counters = verdict_step(tables.key_id, tables.key_meta,
                                     tables.value, counters, vb,
                                     policy_probe)
    return verdict, identity, counters


def build_tables(compiled_policy: CompiledPolicy,
                 compiled_lpm: CompiledLPM, device=None) -> DatapathTables:
    put = (lambda x: jax.device_put(x, device)) if device else jnp.asarray
    return DatapathTables(
        key_id=put(compiled_policy.key_id),
        key_meta=put(compiled_policy.key_meta),
        value=put(compiled_policy.value),
        lpm_masks=put(compiled_lpm.masks),
        lpm_key_a=put(compiled_lpm.key_a),
        lpm_key_b=put(compiled_lpm.key_b),
        lpm_value=put(compiled_lpm.value),
        lpm_plens=put(compiled_lpm.prefix_lens))


def make_step(compiled_policy: CompiledPolicy, compiled_lpm: CompiledLPM):
    """(jitted step fn, tables, fresh counters)."""
    tables = build_tables(compiled_policy, compiled_lpm)
    n = max(1, compiled_policy.num_endpoints * compiled_policy.slots)
    counters = Counters(packets=jnp.zeros(n, jnp.uint32),
                        bytes=jnp.zeros(n, jnp.uint32))
    step = jax.jit(functools.partial(
        datapath_step, policy_probe=compiled_policy.max_probe,
        lpm_probe=compiled_lpm.max_probe), donate_argnums=(1,))
    return step, tables, counters
