"""Batched service load balancing: VIP -> backend selection + rev-NAT.

Semantics follow the reference's eBPF LB (bpf/lib/lb.h): a service lookup
on (VIP, port), slave selection by 5-tuple hash modulo backend count
(lb4_select_slave), DNAT to the chosen backend, and a reverse-NAT table
indexed by rev_nat_index for reply translation (lb4_rev_nat). The
userspace bookkeeping mirrors pkg/maps/lbmap (ipv4.go:43-129).

Compiled form: one hash table (vip, port|proto) -> service index, flat
backend arrays indexed by [svc_offset + slave], and rev-NAT arrays
indexed by rev_nat_index.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.hashtab import build_hash_table
from ..ops.hashtab_ops import batched_lookup, hash_mix_jnp


@dataclass(frozen=True)
class Backend:
    addr: int       # uint32 IPv4 as int
    port: int


@dataclass
class Service:
    """A service frontend (reference: pkg/loadbalancer types)."""

    vip: int        # uint32 IPv4
    port: int
    proto: int = 6
    backends: List[Backend] = field(default_factory=list)
    rev_nat_index: int = 0  # assigned at compile/insert time


class LBTables(NamedTuple):
    """Device LB state."""

    svc_key_a: jnp.ndarray   # [S] vip
    svc_key_b: jnp.ndarray   # [S] port<<16 | proto<<8 | 1
    svc_value: jnp.ndarray   # [S] service index
    svc_count: jnp.ndarray   # [NSVC] backend count
    svc_offset: jnp.ndarray  # [NSVC] offset into backend arrays
    svc_revnat: jnp.ndarray  # [NSVC] rev-NAT index
    b_addr: jnp.ndarray      # [NB]
    b_port: jnp.ndarray      # [NB]
    rev_vip: jnp.ndarray     # [NR] rev_nat_index -> original VIP
    rev_port: jnp.ndarray    # [NR]


@dataclass
class CompiledLB:
    tables: LBTables
    max_probe: int
    num_services: int
    num_backends: int


def compile_lb(services: Sequence[Service]) -> CompiledLB:
    """Lower a service list to device tables.

    rev_nat_index is 1-based (0 == no NAT) and must be STABLE for the
    lifetime of a service: conntrack entries deliberately survive table
    recompiles, so a live flow's stored index has to keep resolving to
    the same VIP. Callers (LoadBalancer) assign indices; services
    without one get the next free slot here. The rev-NAT arrays are
    sized by the max index, so deleted services leave zero rows instead
    of renumbering survivors (the reference's lbmap RevNAT IDs have the
    same stability contract).
    """
    entries = {}
    counts, offsets, revnats = [], [], []
    b_addr, b_port = [], []
    used = {s.rev_nat_index for s in services if s.rev_nat_index > 0}
    next_free = 1
    for svc in services:
        if svc.rev_nat_index <= 0:
            while next_free in used:
                next_free += 1
            svc.rev_nat_index = next_free
            used.add(next_free)
    max_idx = max(used, default=0)
    rev_vip = [0] * (max_idx + 1)
    rev_port = [0] * (max_idx + 1)
    for i, svc in enumerate(services):
        key = (svc.vip & 0xFFFFFFFF,
               ((svc.port & 0xFFFF) << 16) | ((svc.proto & 0xFF) << 8) | 1)
        entries[key] = i
        offsets.append(len(b_addr))
        counts.append(len(svc.backends))
        revnats.append(svc.rev_nat_index)
        for b in svc.backends:
            b_addr.append(b.addr & 0xFFFFFFFF)
            b_port.append(b.port)
        rev_vip[svc.rev_nat_index] = svc.vip & 0xFFFFFFFF
        rev_port[svc.rev_nat_index] = svc.port
    t = build_hash_table(entries) if entries else build_hash_table(
        {(0, 1): 0}, min_slots=8)
    as_i32 = lambda x: jnp.asarray(np.asarray(x, np.uint32).view(np.int32)
                                   if np.asarray(x).dtype != np.int32
                                   else np.asarray(x, np.int32))
    tables = LBTables(
        svc_key_a=jnp.asarray(t.key_a), svc_key_b=jnp.asarray(t.key_b),
        svc_value=jnp.asarray(t.value),
        svc_count=jnp.asarray(np.asarray(counts or [0], np.int32)),
        svc_offset=jnp.asarray(np.asarray(offsets or [0], np.int32)),
        svc_revnat=jnp.asarray(np.asarray(revnats or [0], np.int32)),
        b_addr=as_i32(b_addr or [0]),
        b_port=jnp.asarray(np.asarray(b_port or [0], np.int32)),
        rev_vip=as_i32(rev_vip), rev_port=jnp.asarray(
            np.asarray(rev_port, np.int32)))
    return CompiledLB(tables=tables, max_probe=t.max_probe,
                      num_services=len(services), num_backends=len(b_addr))


def lb_step(tables: LBTables, daddr, dport, proto, saddr, sport,
            *, max_probe: int
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Service DNAT for a batch.

    Returns (new_daddr, new_dport, rev_nat_idx, is_service) — non-service
    packets pass through unchanged (rev_nat 0).
    Reference: lb4_lookup_service + lb4_select_slave + lb4_local.
    """
    qb = ((dport & 0xFFFF) << 16) | ((proto & 0xFF) << 8) | 1
    found, svc_idx, _ = batched_lookup(
        tables.svc_key_a, tables.svc_key_b, tables.svc_value,
        daddr, qb, max_probe)
    svc_idx = jnp.where(found, svc_idx, jnp.int32(0))
    count = tables.svc_count[svc_idx]
    offset = tables.svc_offset[svc_idx]
    # Slave selection by packet 5-tuple hash (lb.h lb4_hash: jhash of
    # src/dst/ports) — any uniform deterministic hash preserves semantics.
    h = hash_mix_jnp(hash_mix_jnp(saddr, daddr),
                     hash_mix_jnp(((sport & 0xFFFF) << 16) | (dport & 0xFFFF),
                                  proto))
    slave = jnp.where(count > 0,
                      jnp.abs(h) % jnp.maximum(count, 1), jnp.int32(0))
    bidx = offset + slave
    ok = found & (count > 0)
    new_daddr = jnp.where(ok, tables.b_addr[bidx], daddr)
    new_dport = jnp.where(ok, tables.b_port[bidx], dport)
    rev_nat = jnp.where(ok, tables.svc_revnat[svc_idx], jnp.int32(0))
    return new_daddr, new_dport, rev_nat, ok


def lb_rev_nat(tables: LBTables, saddr, sport, rev_nat_idx
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reply-path reverse NAT: restore VIP/port for flows whose CT entry
    carries a rev_nat_index (reference: lb4_rev_nat)."""
    has = rev_nat_idx > 0
    idx = jnp.where(has, rev_nat_idx, jnp.int32(0))
    return (jnp.where(has, tables.rev_vip[idx], saddr),
            jnp.where(has, tables.rev_port[idx], sport))


class LoadBalancer:
    """Host-side service registry + compiled device tables
    (pkg/service + pkg/maps/lbmap analog)."""

    def __init__(self):
        self._services: Dict[Tuple[int, int, int], Service] = {}
        self.compiled: Optional[CompiledLB] = None
        self._step = None
        self._next_rev_nat = 1  # stable, monotonically allocated

    def upsert_service(self, svc: Service) -> None:
        key = (svc.vip, svc.port, svc.proto)
        old = self._services.get(key)
        if old is not None:
            # keep the stable rev-NAT index across updates
            svc.rev_nat_index = old.rev_nat_index
        else:
            svc.rev_nat_index = self._next_rev_nat
            self._next_rev_nat += 1
        self._services[key] = svc
        self._recompile()

    def delete_service(self, vip: int, port: int, proto: int = 6) -> bool:
        existed = self._services.pop((vip, port, proto), None) is not None
        if existed:
            self._recompile()
        return existed

    def _recompile(self):
        self.compiled = compile_lb(list(self._services.values()))
        self._step = jax.jit(functools.partial(
            lb_step, max_probe=self.compiled.max_probe))

    def __len__(self):
        return len(self._services)

    def services(self) -> List[Service]:
        return sorted(self._services.values(),
                      key=lambda s: (s.vip, s.port, s.proto))

    def step(self, daddr, dport, proto, saddr, sport):
        if self.compiled is None:
            self._recompile()
        return self._step(self.compiled.tables, daddr, dport, proto,
                          saddr, sport)

    def rev_nat(self, saddr, sport, rev_nat_idx):
        return lb_rev_nat(self.compiled.tables, saddr, sport, rev_nat_idx)


# ---------------------------------------------------------------------------
# IPv6 service LB (bpf/lib/lb.h lb6_* family)
# ---------------------------------------------------------------------------
#
# Same structure as the v4 tables with addresses as four int32 words
# and full 128-bit exact compares on the service lookup; backends and
# rev-NAT rows store complete v6 addresses, so DNAT and reply
# translation are exact.

@dataclass(frozen=True)
class Backend6:
    addr: Tuple[int, int, int, int]  # big-endian u32 words
    port: int


@dataclass
class Service6:
    vip: Tuple[int, int, int, int]
    port: int
    proto: int = 6
    backends: List[Backend6] = field(default_factory=list)
    rev_nat_index: int = 0


class LB6Tables(NamedTuple):
    svc_k0: jnp.ndarray      # [S] vip words
    svc_k1: jnp.ndarray
    svc_k2: jnp.ndarray
    svc_k3: jnp.ndarray
    svc_kb: jnp.ndarray      # [S] port<<16 | proto<<8 | 1 (0 = empty)
    svc_value: jnp.ndarray   # [S] service index
    svc_count: jnp.ndarray   # [NSVC]
    svc_offset: jnp.ndarray
    svc_revnat: jnp.ndarray
    b_addr: jnp.ndarray      # [NB, 4]
    b_port: jnp.ndarray      # [NB]
    rev_vip: jnp.ndarray     # [NR, 4]
    rev_port: jnp.ndarray    # [NR]


@dataclass
class CompiledLB6:
    tables: LB6Tables
    max_probe: int
    num_services: int
    num_backends: int


def _hash6_words(w0, w1, w2, w3, kb):
    from ..compiler.hashtab import hash_mix
    return hash_mix(hash_mix(np.uint32(w0), np.uint32(w1)),
                    hash_mix(np.uint32(w2) ^ np.uint32(kb),
                             np.uint32(w3)))


def compile_lb6(services: Sequence[Service6]) -> CompiledLB6:
    """Lower v6 services; rev_nat_index stability contract identical
    to compile_lb."""
    used = {s.rev_nat_index for s in services if s.rev_nat_index > 0}
    # monotonic allocation past the highest index ever seen — NOT
    # lowest-free: a freed index may still be recorded in live CT
    # entries (they deliberately survive recompiles), and reusing it
    # would reverse-NAT an old flow's replies to a NEW service's VIP
    next_free = max(used, default=0) + 1
    for svc in services:
        if svc.rev_nat_index <= 0:
            svc.rev_nat_index = next_free
            used.add(next_free)
            next_free += 1
    max_idx = max(used, default=0)
    n = len(services)
    slots = 8
    while slots < 2 * max(n, 1):
        slots *= 2
    k = [np.zeros(slots, np.int32) for _ in range(4)]
    kb = np.zeros(slots, np.int32)
    value = np.zeros(slots, np.int32)
    counts, offsets, revnats = [], [], []
    b_addr: List[Tuple[int, int, int, int]] = []
    b_port: List[int] = []
    rev_vip = [(0, 0, 0, 0)] * (max_idx + 1)
    rev_port = [0] * (max_idx + 1)
    max_probe = 1
    for i, svc in enumerate(services):
        occ = ((svc.port & 0xFFFF) << 16) | ((svc.proto & 0xFF) << 8) | 1
        h = int(_hash6_words(*svc.vip, occ)) & (slots - 1)
        probe = 0
        while kb[(h + probe) % slots] != 0:
            probe += 1
        s = (h + probe) % slots
        for j in range(4):
            k[j][s] = np.uint32(svc.vip[j]).view(np.int32)
        # int32 bit-pattern: ports >= 0x8000 push occ past int32 max
        kb[s] = np.uint32(occ).view(np.int32)
        value[s] = i
        max_probe = max(max_probe, probe + 1)
        offsets.append(len(b_addr))
        counts.append(len(svc.backends))
        revnats.append(svc.rev_nat_index)
        for b in svc.backends:
            b_addr.append(b.addr)
            b_port.append(b.port)
        rev_vip[svc.rev_nat_index] = svc.vip
        rev_port[svc.rev_nat_index] = svc.port
    w = lambda rows: jnp.asarray(
        np.asarray(rows or [(0, 0, 0, 0)], np.uint32).view(np.int32))
    tables = LB6Tables(
        svc_k0=jnp.asarray(k[0]), svc_k1=jnp.asarray(k[1]),
        svc_k2=jnp.asarray(k[2]), svc_k3=jnp.asarray(k[3]),
        svc_kb=jnp.asarray(kb), svc_value=jnp.asarray(value),
        svc_count=jnp.asarray(np.asarray(counts or [0], np.int32)),
        svc_offset=jnp.asarray(np.asarray(offsets or [0], np.int32)),
        svc_revnat=jnp.asarray(np.asarray(revnats or [0], np.int32)),
        b_addr=w(b_addr),
        b_port=jnp.asarray(np.asarray(b_port or [0], np.int32)),
        rev_vip=w(rev_vip), rev_port=jnp.asarray(
            np.asarray(rev_port, np.int32)))
    return CompiledLB6(tables=tables, max_probe=max_probe,
                       num_services=n, num_backends=len(b_addr))


def _hash6_jnp_words(w0, w1, w2, w3, kb):
    return hash_mix_jnp(hash_mix_jnp(w0, w1),
                        hash_mix_jnp(w2 ^ kb, w3))


def lb6_step(tables: LB6Tables, daddr, dport, proto, saddr, sport,
             *, max_probe: int):
    """v6 service DNAT (lb6_lookup_service + lb6_select_slave +
    lb6_local).  daddr/saddr are [B, 4].

    Returns (new_daddr [B, 4], new_dport, rev_nat_idx, is_service)."""
    slots = tables.svc_kb.shape[0]
    mask = jnp.int32(slots - 1)
    qb = ((dport & 0xFFFF) << 16) | ((proto & 0xFF) << 8) | 1
    h = _hash6_jnp_words(daddr[:, 0], daddr[:, 1], daddr[:, 2],
                         daddr[:, 3], qb)
    probes = (h[:, None] & mask) + \
        jnp.arange(max_probe, dtype=jnp.int32)[None, :]
    probes = probes & mask                                     # [B, K]
    hit = (tables.svc_k0[probes] == daddr[:, 0:1]) & \
        (tables.svc_k1[probes] == daddr[:, 1:2]) & \
        (tables.svc_k2[probes] == daddr[:, 2:3]) & \
        (tables.svc_k3[probes] == daddr[:, 3:4]) & \
        (tables.svc_kb[probes] == qb[:, None]) & \
        (tables.svc_kb[probes] != 0)
    found = jnp.any(hit, axis=1)
    svc_idx = jnp.sum(jnp.where(hit, tables.svc_value[probes],
                                jnp.int32(0)), axis=1)
    count = tables.svc_count[svc_idx]
    offset = tables.svc_offset[svc_idx]
    from ..datapath.pipeline import fold6
    hsel = hash_mix_jnp(hash_mix_jnp(fold6(saddr), fold6(daddr)),
                        hash_mix_jnp(((sport & 0xFFFF) << 16) |
                                     (dport & 0xFFFF), proto))
    slave = jnp.where(count > 0,
                      jnp.abs(hsel) % jnp.maximum(count, 1),
                      jnp.int32(0))
    bidx = offset + slave
    ok = found & (count > 0)
    new_daddr = jnp.where(ok[:, None], tables.b_addr[bidx], daddr)
    new_dport = jnp.where(ok, tables.b_port[bidx], dport)
    rev_nat = jnp.where(ok, tables.svc_revnat[svc_idx], jnp.int32(0))
    return new_daddr, new_dport, rev_nat, ok


def lb6_rev_nat(tables: LB6Tables, saddr, sport, rev_nat_idx):
    """Reply-path v6 reverse NAT (lb6_rev_nat): saddr [B, 4]."""
    has = rev_nat_idx > 0
    nmax = tables.rev_vip.shape[0]
    idx = jnp.clip(jnp.where(has, rev_nat_idx, 0), 0, nmax - 1)
    return (jnp.where(has[:, None], tables.rev_vip[idx], saddr),
            jnp.where(has, tables.rev_port[idx], sport))
