"""Batched service load balancing: VIP -> backend selection + rev-NAT.

Semantics follow the reference's eBPF LB (bpf/lib/lb.h): a service lookup
on (VIP, port), slave selection by 5-tuple hash modulo backend count
(lb4_select_slave), DNAT to the chosen backend, and a reverse-NAT table
indexed by rev_nat_index for reply translation (lb4_rev_nat). The
userspace bookkeeping mirrors pkg/maps/lbmap (ipv4.go:43-129).

Compiled form: one hash table (vip, port|proto) -> service index, flat
backend arrays indexed by [svc_offset + slave], and rev-NAT arrays
indexed by rev_nat_index.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.hashtab import build_hash_table
from ..ops.hashtab_ops import batched_lookup, hash_mix_jnp


@dataclass(frozen=True)
class Backend:
    addr: int       # uint32 IPv4 as int
    port: int


@dataclass
class Service:
    """A service frontend (reference: pkg/loadbalancer types)."""

    vip: int        # uint32 IPv4
    port: int
    proto: int = 6
    backends: List[Backend] = field(default_factory=list)
    rev_nat_index: int = 0  # assigned at compile/insert time


class LBTables(NamedTuple):
    """Device LB state."""

    svc_key_a: jnp.ndarray   # [S] vip
    svc_key_b: jnp.ndarray   # [S] port<<16 | proto<<8 | 1
    svc_value: jnp.ndarray   # [S] service index
    svc_count: jnp.ndarray   # [NSVC] backend count
    svc_offset: jnp.ndarray  # [NSVC] offset into backend arrays
    svc_revnat: jnp.ndarray  # [NSVC] rev-NAT index
    b_addr: jnp.ndarray      # [NB]
    b_port: jnp.ndarray      # [NB]
    rev_vip: jnp.ndarray     # [NR] rev_nat_index -> original VIP
    rev_port: jnp.ndarray    # [NR]


@dataclass
class CompiledLB:
    tables: LBTables
    max_probe: int
    num_services: int
    num_backends: int


def compile_lb(services: Sequence[Service]) -> CompiledLB:
    """Lower a service list to device tables.

    rev_nat_index is 1-based (0 == no NAT) and must be STABLE for the
    lifetime of a service: conntrack entries deliberately survive table
    recompiles, so a live flow's stored index has to keep resolving to
    the same VIP. Callers (LoadBalancer) assign indices; services
    without one get the next free slot here. The rev-NAT arrays are
    sized by the max index, so deleted services leave zero rows instead
    of renumbering survivors (the reference's lbmap RevNAT IDs have the
    same stability contract).
    """
    entries = {}
    counts, offsets, revnats = [], [], []
    b_addr, b_port = [], []
    used = {s.rev_nat_index for s in services if s.rev_nat_index > 0}
    next_free = 1
    for svc in services:
        if svc.rev_nat_index <= 0:
            while next_free in used:
                next_free += 1
            svc.rev_nat_index = next_free
            used.add(next_free)
    max_idx = max(used, default=0)
    rev_vip = [0] * (max_idx + 1)
    rev_port = [0] * (max_idx + 1)
    for i, svc in enumerate(services):
        key = (svc.vip & 0xFFFFFFFF,
               ((svc.port & 0xFFFF) << 16) | ((svc.proto & 0xFF) << 8) | 1)
        entries[key] = i
        offsets.append(len(b_addr))
        counts.append(len(svc.backends))
        revnats.append(svc.rev_nat_index)
        for b in svc.backends:
            b_addr.append(b.addr & 0xFFFFFFFF)
            b_port.append(b.port)
        rev_vip[svc.rev_nat_index] = svc.vip & 0xFFFFFFFF
        rev_port[svc.rev_nat_index] = svc.port
    t = build_hash_table(entries) if entries else build_hash_table(
        {(0, 1): 0}, min_slots=8)
    as_i32 = lambda x: jnp.asarray(np.asarray(x, np.uint32).view(np.int32)
                                   if np.asarray(x).dtype != np.int32
                                   else np.asarray(x, np.int32))
    tables = LBTables(
        svc_key_a=jnp.asarray(t.key_a), svc_key_b=jnp.asarray(t.key_b),
        svc_value=jnp.asarray(t.value),
        svc_count=jnp.asarray(np.asarray(counts or [0], np.int32)),
        svc_offset=jnp.asarray(np.asarray(offsets or [0], np.int32)),
        svc_revnat=jnp.asarray(np.asarray(revnats or [0], np.int32)),
        b_addr=as_i32(b_addr or [0]),
        b_port=jnp.asarray(np.asarray(b_port or [0], np.int32)),
        rev_vip=as_i32(rev_vip), rev_port=jnp.asarray(
            np.asarray(rev_port, np.int32)))
    return CompiledLB(tables=tables, max_probe=t.max_probe,
                      num_services=len(services), num_backends=len(b_addr))


def lb_step(tables: LBTables, daddr, dport, proto, saddr, sport,
            *, max_probe: int
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Service DNAT for a batch.

    Returns (new_daddr, new_dport, rev_nat_idx, is_service) — non-service
    packets pass through unchanged (rev_nat 0).
    Reference: lb4_lookup_service + lb4_select_slave + lb4_local.
    """
    qb = ((dport & 0xFFFF) << 16) | ((proto & 0xFF) << 8) | 1
    found, svc_idx, _ = batched_lookup(
        tables.svc_key_a, tables.svc_key_b, tables.svc_value,
        daddr, qb, max_probe)
    svc_idx = jnp.where(found, svc_idx, jnp.int32(0))
    count = tables.svc_count[svc_idx]
    offset = tables.svc_offset[svc_idx]
    # Slave selection by packet 5-tuple hash (lb.h lb4_hash: jhash of
    # src/dst/ports) — any uniform deterministic hash preserves semantics.
    h = hash_mix_jnp(hash_mix_jnp(saddr, daddr),
                     hash_mix_jnp(((sport & 0xFFFF) << 16) | (dport & 0xFFFF),
                                  proto))
    slave = jnp.where(count > 0,
                      jnp.abs(h) % jnp.maximum(count, 1), jnp.int32(0))
    bidx = offset + slave
    ok = found & (count > 0)
    new_daddr = jnp.where(ok, tables.b_addr[bidx], daddr)
    new_dport = jnp.where(ok, tables.b_port[bidx], dport)
    rev_nat = jnp.where(ok, tables.svc_revnat[svc_idx], jnp.int32(0))
    return new_daddr, new_dport, rev_nat, ok


def lb_rev_nat(tables: LBTables, saddr, sport, rev_nat_idx
               ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reply-path reverse NAT: restore VIP/port for flows whose CT entry
    carries a rev_nat_index (reference: lb4_rev_nat)."""
    has = rev_nat_idx > 0
    idx = jnp.where(has, rev_nat_idx, jnp.int32(0))
    return (jnp.where(has, tables.rev_vip[idx], saddr),
            jnp.where(has, tables.rev_port[idx], sport))


class LoadBalancer:
    """Host-side service registry + compiled device tables
    (pkg/service + pkg/maps/lbmap analog)."""

    def __init__(self):
        self._services: Dict[Tuple[int, int, int], Service] = {}
        self.compiled: Optional[CompiledLB] = None
        self._step = None
        self._next_rev_nat = 1  # stable, monotonically allocated

    def upsert_service(self, svc: Service) -> None:
        key = (svc.vip, svc.port, svc.proto)
        old = self._services.get(key)
        if old is not None:
            # keep the stable rev-NAT index across updates
            svc.rev_nat_index = old.rev_nat_index
        else:
            svc.rev_nat_index = self._next_rev_nat
            self._next_rev_nat += 1
        self._services[key] = svc
        self._recompile()

    def delete_service(self, vip: int, port: int, proto: int = 6) -> bool:
        existed = self._services.pop((vip, port, proto), None) is not None
        if existed:
            self._recompile()
        return existed

    def _recompile(self):
        self.compiled = compile_lb(list(self._services.values()))
        self._step = jax.jit(functools.partial(
            lb_step, max_probe=self.compiled.max_probe))

    def __len__(self):
        return len(self._services)

    def services(self) -> List[Service]:
        return sorted(self._services.values(),
                      key=lambda s: (s.vip, s.port, s.proto))

    def step(self, daddr, dport, proto, saddr, sport):
        if self.compiled is None:
            self._recompile()
        return self._step(self.compiled.tables, daddr, dport, proto,
                          saddr, sport)

    def rev_nat(self, saddr, sport, rev_nat_idx):
        return lb_rev_nat(self.compiled.tables, saddr, sport, rev_nat_idx)
