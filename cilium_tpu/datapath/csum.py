"""Incremental internet checksum updates for NAT rewrites.

Reference: bpf/lib/csum.h — after the datapath rewrites addresses or
ports (LB DNAT, rev-NAT, NAT46), the L3/L4 checksums are fixed
incrementally (csum_l4_replace over csum_diff) rather than recomputed
over the payload.  Same here, batched: given the old and new values of
the rewritten fields, produce the updated checksum per packet
(RFC 1624 eqn. 3: HC' = ~(~HC + ~m + m')).

All values are uint16/uint32 carried in int32 lanes, like the rest of
the datapath.
"""

from __future__ import annotations

import jax.numpy as jnp


def _ones_fold(x: jnp.ndarray) -> jnp.ndarray:
    """Fold a 32-bit sum to 16 bits (ones-complement carry wrap)."""
    x = (x & 0xFFFF) + ((x >> 16) & 0xFFFF)
    x = (x & 0xFFFF) + ((x >> 16) & 0xFFFF)
    return x & 0xFFFF


def csum_update_u16(csum: jnp.ndarray, old: jnp.ndarray,
                    new: jnp.ndarray) -> jnp.ndarray:
    """RFC 1624 incremental update for one 16-bit field.

    csum/old/new: [B] int32 holding u16 values; returns [B] u16."""
    c = (~csum) & 0xFFFF
    c = c + ((~old) & 0xFFFF) + (new & 0xFFFF)
    return (~_ones_fold(c)) & 0xFFFF


def csum_update_u32(csum: jnp.ndarray, old: jnp.ndarray,
                    new: jnp.ndarray) -> jnp.ndarray:
    """Incremental update for a 32-bit field (an address): applied as
    its two 16-bit halves (csum_diff over 4 bytes)."""
    c = csum_update_u16(csum, (old >> 16) & 0xFFFF, (new >> 16) & 0xFFFF)
    return csum_update_u16(c, old & 0xFFFF, new & 0xFFFF)


def checksum16(words: jnp.ndarray) -> jnp.ndarray:
    """Full ones-complement checksum over [B, N] u16 words — the
    from-scratch reference the incremental path is tested against.
    int32-safe for N < 2^15 words (far beyond any header)."""
    s = jnp.sum(words.astype(jnp.int32) & 0xFFFF, axis=1)
    s = (s & 0xFFFF) + (s >> 16)
    s = (s & 0xFFFF) + (s >> 16)
    return (~s) & 0xFFFF


def nat_csum_fix(l4_csum: jnp.ndarray, old_addr: jnp.ndarray,
                 new_addr: jnp.ndarray, old_port: jnp.ndarray,
                 new_port: jnp.ndarray,
                 udp: bool = False) -> jnp.ndarray:
    """The DNAT fix-up (lb4 path): TCP/UDP checksums cover the
    pseudo-header, so an address+port rewrite updates both.

    ``udp=True`` applies the full BPF_F_MARK_MANGLED_0 rule
    (bpf_l4_csum_replace): an INCOMING checksum of 0x0000 means "no
    checksum computed" for v4 UDP and is left untouched (updating it
    would fabricate a bogus checksum the receiver then validates), and
    a COMPUTED result of 0x0000 is transmitted as 0xFFFF (zero is the
    no-checksum marker / forbidden for v6)."""
    c = csum_update_u32(l4_csum, old_addr, new_addr)
    c = csum_update_u16(c, old_port, new_port)
    if udp:
        c = jnp.where(c == 0, jnp.int32(0xFFFF), c)
        c = jnp.where(l4_csum == 0, jnp.int32(0), c)
    return c
