"""ICMPv6/NDP reply synthesis (bpf/lib/icmp6.h analog).

The datapath stage (pipeline.full_datapath_step6 stage 1.5) decides
WHICH packets are answered locally (events ICMP6_NS_REPLY /
ICMP6_ECHO_REPLY); this module builds the actual reply bytes the
responder sends — the host-side counterpart of icmp6.h's in-place
packet rewrite:

- ``ndisc_advertisement``: NS -> NA with router=1, solicited=1,
  override=0 and a target-link-layer-address option carrying the
  router MAC (send_icmp6_ndisc_adv:149-203);
- ``echo_reply``: echo request -> echo reply with src/dst swapped
  (__icmp6_send_echo_reply:84-137);
- ``icmp6_checksum``: full pseudo-header checksum
  (compute_icmp6_csum:204).
"""

from __future__ import annotations

import struct
from typing import List, Sequence


def _words_to_bytes(words: Sequence[int]) -> bytes:
    return b"".join(struct.pack(">I", w & 0xFFFFFFFF) for w in words)


def icmp6_checksum(src_words: Sequence[int], dst_words: Sequence[int],
                   icmp6_payload: bytes) -> int:
    """ICMPv6 checksum over the IPv6 pseudo-header + message
    (RFC 4443 2.3; compute_icmp6_csum analog)."""
    pseudo = (_words_to_bytes(src_words) + _words_to_bytes(dst_words) +
              struct.pack(">I", len(icmp6_payload)) +
              b"\x00\x00\x00\x3a")
    data = pseudo + icmp6_payload
    if len(data) % 2:
        data += b"\x00"
    total = sum(struct.unpack(f">{len(data) // 2}H", data))
    while total >> 16:
        total = (total & 0xFFFF) + (total >> 16)
    return (~total) & 0xFFFF


def _ipv6_header(src_words, dst_words, payload_len: int) -> bytes:
    return (b"\x60\x00\x00\x00" +
            struct.pack(">HBB", payload_len, 58, 255) +
            _words_to_bytes(src_words) + _words_to_bytes(dst_words))


def ndisc_advertisement(router_words: Sequence[int],
                        solicitor_words: Sequence[int],
                        target_words: Sequence[int],
                        router_mac: bytes) -> bytes:
    """Full IPv6+ICMPv6 neighbour advertisement answering an NS.

    Reply goes router -> solicitor; flags router|solicited (the
    reference sets icmp6_router=1, icmp6_solicited=1, override=0);
    option type 2 (target link-layer address) carries the router MAC.
    """
    assert len(router_mac) == 6
    flags = 0xC0000000  # router | solicited
    body = (struct.pack(">BBH", 136, 0, 0) +     # type, code, csum=0
            struct.pack(">I", flags) +
            _words_to_bytes(target_words) +
            b"\x02\x01" + router_mac)            # TLLA option
    csum = icmp6_checksum(router_words, solicitor_words, body)
    body = body[:2] + struct.pack(">H", csum) + body[4:]
    return _ipv6_header(router_words, solicitor_words,
                        len(body)) + body


def echo_reply(router_words: Sequence[int],
               requester_words: Sequence[int],
               ident: int, seq: int, payload: bytes = b"") -> bytes:
    """Full IPv6+ICMPv6 echo reply for a request to the router."""
    body = (struct.pack(">BBH", 129, 0, 0) +
            struct.pack(">HH", ident & 0xFFFF, seq & 0xFFFF) + payload)
    csum = icmp6_checksum(router_words, requester_words, body)
    body = body[:2] + struct.pack(">H", csum) + body[4:]
    return _ipv6_header(router_words, requester_words,
                        len(body)) + body


def parse_icmp6(packet: bytes) -> dict:
    """Parse an IPv6+ICMPv6 packet built by this module (test/probe
    side): returns {src_words, dst_words, type, code, checksum_ok,
    target_words?/ident?/seq?, tlla?}."""
    assert len(packet) >= 48 and packet[6] == 58
    src = list(struct.unpack(">4I", packet[8:24]))
    dst = list(struct.unpack(">4I", packet[24:40]))
    body = packet[40:]
    typ, code, csum = struct.unpack(">BBH", body[:4])
    zeroed = body[:2] + b"\x00\x00" + body[4:]
    out = {"src_words": src, "dst_words": dst, "type": typ,
           "code": code,
           "checksum_ok": icmp6_checksum(src, dst, zeroed) == csum}
    if typ in (135, 136):
        out["target_words"] = list(struct.unpack(">4I", body[8:24]))
        if len(body) >= 32 and body[24] == 2:
            out["tlla"] = body[26:32]
    elif typ in (128, 129):
        out["ident"], out["seq"] = struct.unpack(">HH", body[4:8])
    return out
