"""Dataplane supervision: device-fault circuit breaking with a
fail-static host fallback and gated recovery.

Cilium's signature robustness property is a fail-static dataplane: the
kernel keeps forwarding on last-known-good state while the agent is
degraded (daemon/state.go restore path).  The TPU analog had the
opposite failure mode — one XLA error in the serving lane blanket-
denied the batch and nothing ever recovered a lost device path.  This
module closes that gap with three pieces wrapped around the serving
dispatcher (datapath/serving.py):

- **Fault classification + circuit breaking.**  ``DeviceSupervisor``
  wraps every launch/finalize.  Exceptions are classified transient
  (count toward ``utils/resilience.CircuitBreaker``'s consecutive-
  failure threshold) or fatal (trip the breaker immediately); a
  finalize that outlives the watchdog deadline — the hung ``complete``
  sync of a wedged device path — is a fault too, detected by running
  the one blocking transfer on a replaceable watchdog worker.

- **Fail-static host fallback.**  While the breaker is open, batches
  are served from the ``HostStaticOracle``: the host CT view keeps
  established flows on their recorded verdicts (no blanket deny), and
  new flows get the configured degraded-mode policy — the
  ``compiler/policy_tables`` oracle over the host-of-record map states
  by default, blanket deny/allow if configured.  Precedence is
  ``pipeline.host_fail_static_step``, the host twin of the compiled
  program's step 7.

- **Gated recovery.**  The breaker's half-open probe does NOT go
  straight back to the device: the supervisor first rebuilds the
  device tables from the ``DeviceTableManager`` host-of-record (or the
  engine's compiled artifacts), then runs a drift-audit replay gate
  (PR 6's oracle) — only a passing gate lets the probe batch dispatch.
  A successful probe closes the breaker and counts
  ``dataplane_recoveries_total``; a failing gate re-opens it on the
  doubling cadence.

The supervisor is OPTIONAL and additive: with supervision disabled the
dispatcher runs the exact pre-supervision code path and the compiled
device program is byte-identical (asserted in tests).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from ..observability.events import (EVENT_DATAPLANE_DEGRADED,
                                    EVENT_DATAPLANE_FAIL_STATIC,
                                    EVENT_DATAPLANE_REBUILD,
                                    EVENT_DATAPLANE_RECOVERED,
                                    EVENT_DATAPLANE_TRIP,
                                    recorder as flight_recorder)
from ..utils.faultinject import DeviceLaneFault
from ..utils.metrics import (DATAPLANE_DEVICE_FAULTS,
                             DATAPLANE_FAIL_STATIC, DATAPLANE_MODE,
                             DATAPLANE_RECOVERIES,
                             DATAPLANE_SHARD_FAULTS,
                             DATAPLANE_SHARD_MODE)
from ..utils.resilience import (STATE_CLOSED, STATE_HALF_OPEN,
                                CircuitBreaker)
from .pipeline import WORLD_IDENTITY, host_fail_static_step
from .verdict import VERDICT_DROP

MODE_OK = "ok"
MODE_DEGRADED = "degraded"
MODE_RECOVERING = "recovering"
_MODE_CODE = {MODE_OK: 0, MODE_DEGRADED: 1, MODE_RECOVERING: 2}

# exception-name / message fragments that mark a device path as gone
# for good (XLA runtime "device lost" class) vs worth counting toward
# the transient threshold (queue pressure, cancelled collectives)
_TRANSIENT_MARKERS = ("RESOURCE_EXHAUSTED", "DEADLINE", "UNAVAILABLE",
                      "CANCELLED", "ABORTED")
_FATAL_TYPE_MARKERS = ("XlaRuntimeError", "DeviceLost",
                       "InternalError")
# deterministic engine-precondition errors: the DEVICE is fine, the
# caller dispatched into an engine that cannot serve (e.g. before any
# policy was loaded) — these keep the plain fail-closed contract and
# never touch the breaker
_CALLER_MARKERS = ("no policy loaded",)


def classify_fault(e: BaseException) -> str:
    """"transient", "fatal", or "caller".  Transient faults count
    toward the breaker's consecutive-failure threshold; fatal ones
    trip it immediately (a lost device will not heal inside the
    window); caller errors (engine preconditions) are not device
    faults at all — they fail closed without breaker accounting."""
    if isinstance(e, DeviceLaneFault):
        return "fatal" if e.fatal else "transient"
    name = type(e).__name__
    if any(m in name for m in _FATAL_TYPE_MARKERS):
        msg = str(e).upper()
        if any(m in msg for m in _TRANSIENT_MARKERS):
            return "transient"
        return "fatal"
    if any(m in str(e) for m in _CALLER_MARKERS):
        return "caller"
    return "transient"


# --------------------------------------------------------------------------
# Host fail-static oracle
# --------------------------------------------------------------------------

def _pack_u32(x: int) -> int:
    return x & 0xFFFFFFFF


class HostStaticOracle:
    """Last-known-good host view the degraded lane answers from.

    Three host-of-record pieces, refreshed periodically while the
    device lane is healthy (and best-effort on fault entry):

    - the host CT view (``Datapath.snapshot_ct``): live forward-tuple
      keys -> (expiry, recorded proxy port), so established flows keep
      their verdicts;
    - per-slot ``PolicyMapState``s (``Datapath.host_policy_states``):
      the same states the device tables were compiled from — the
      ``oracle_verdict`` fallback chain over them IS last-known-good
      policy;
    - a host ipcache LPM built from ``Datapath.ipcache_prefixes``.

    ``new_flow_policy``: "oracle" (enforce last-known-good policy on
    host — the fail-static default), "deny" (no new flows while
    degraded), or "allow".
    """

    def __init__(self, datapath, new_flow_policy: str = "oracle"):
        if new_flow_policy not in ("oracle", "deny", "allow"):
            raise ValueError(f"bad new_flow_policy {new_flow_policy!r}")
        self.datapath = datapath
        self.new_flow_policy = new_flow_policy
        self._mu = threading.Lock()
        self._ct: Dict[Tuple[int, int, int, int], Tuple[int, int]] = {}
        self._states: Dict[int, object] = {}
        self._lpm: List[Tuple[int, int, Dict[int, int]]] = []
        self.refreshed_at = 0.0
        self.refreshes = 0

    # ----------------------------------------------------------- refresh

    def refresh(self) -> bool:
        """Rebuild the host view from the live engine.  Returns False
        (keeping the previous view) when the device CT cannot be read
        — a dead device must not wipe the last-known-good state."""
        dp = self.datapath
        states = {int(s): st for s, st in
                  (dp.host_policy_states() or {}).items()}
        lpm = self._compile_host_lpm(dict(dp.ipcache_prefixes))
        try:
            snap, _snap6 = dp.snapshot_ct()
            ct = self._decode_ct(snap)
        except Exception:  # noqa: BLE001 — device read failed: keep
            ct = None      # the last good CT view
        with self._mu:
            self._states = states
            self._lpm = lpm
            if ct is not None:
                self._ct = ct
            self.refreshed_at = time.monotonic()
            self.refreshes += 1
        return ct is not None

    @staticmethod
    def _decode_ct(snap) -> Dict:
        k0 = np.ascontiguousarray(snap["k0"]).view(np.uint32)
        k1 = np.ascontiguousarray(snap["k1"]).view(np.uint32)
        k2 = np.ascontiguousarray(snap["k2"]).view(np.uint32)
        k3 = np.ascontiguousarray(snap["k3"]).view(np.uint32)
        exp = snap["expires"]
        pp = snap["proxy_port"]
        # exclude the sentinel slot (last row), like entry_count
        live = np.flatnonzero(k3[:-1])
        return {(int(k0[i]), int(k1[i]), int(k2[i]), int(k3[i])):
                (int(exp[i]), int(pp[i])) for i in live.tolist()}

    @staticmethod
    def _compile_host_lpm(prefixes: Dict[str, int]):
        by_plen: Dict[int, Dict[int, int]] = {}
        for cidr, ident in prefixes.items():
            addr, _, plen_s = cidr.partition("/")
            plen = int(plen_s) if plen_s else 32
            a, b, c, d = (int(x) for x in addr.split("."))
            val = (a << 24) | (b << 16) | (c << 8) | d
            mask = 0 if plen == 0 else \
                _pack_u32(0xFFFFFFFF << (32 - plen))
            by_plen.setdefault(plen, {})[val & mask] = int(ident)
        return [(plen, (0 if plen == 0 else
                        _pack_u32(0xFFFFFFFF << (32 - plen))), table)
                for plen, table in sorted(by_plen.items(),
                                          reverse=True)]

    # ------------------------------------------------------ lookups

    def _identity_of(self, addr: int) -> int:
        for _plen, mask, table in self._lpm:
            ident = table.get(addr & mask)
            if ident is not None:
                return ident
        return WORLD_IDENTITY

    def _established(self, sa, da, sp, dp_, proto, direction
                     ) -> Optional[int]:
        now = time.time()
        fwd = (sa, da, _pack_u32((sp & 0xFFFF) << 16 | (dp_ & 0xFFFF)),
               _pack_u32((proto & 0xFF) << 8 | (direction & 1) << 1 | 1))
        hit = self._ct.get(fwd)
        if hit is not None and hit[0] > now:
            return hit[1]  # the flow's recorded verdict (0 = allow)
        rev = (da, sa, _pack_u32((dp_ & 0xFFFF) << 16 | (sp & 0xFFFF)),
               _pack_u32((proto & 0xFF) << 8 |
                         ((1 - direction) & 1) << 1 | 1))
        hit = self._ct.get(rev)
        if hit is not None and hit[0] > now:
            return 0  # reply direction of a live flow: forward it
        return None

    def _policy_verdict(self, slot, ident, dport, proto, direction
                        ) -> int:
        # verdict codes are the device's: <0 drop, 0 allow, >0 proxy
        # port — bit-compatible with what process() would answer
        if self.new_flow_policy == "deny":
            return VERDICT_DROP
        if self.new_flow_policy == "allow":
            return 0
        state = self._states.get(slot)
        if state is None:
            return VERDICT_DROP  # no host-of-record: fail closed
        from ..compiler.policy_tables import oracle_verdict
        return oracle_verdict(state, ident, dport, proto, direction)

    def classify(self, soa, n: int):
        """(verdict [n], identity [n]) for one SoA record chunk, by
        the fail-static precedence (pipeline.host_fail_static_step)."""
        with self._mu:
            return host_fail_static_step(
                soa, n, established=self._established,
                identity_of=self._identity_of,
                policy_verdict=self._policy_verdict)

    def stats(self) -> Dict:
        with self._mu:
            return {"ct-entries": len(self._ct),
                    "policy-slots": len(self._states),
                    "ipcache-prefixes": sum(len(t) for _p, _m, t
                                            in self._lpm),
                    "new-flow-policy": self.new_flow_policy,
                    "refreshes": self.refreshes}


# --------------------------------------------------------------------------
# Watchdogged finalize worker
# --------------------------------------------------------------------------

class _WatchdogRunner:
    """Runs one callable at a time on a worker thread with a deadline.
    A call that outlives the deadline marks this runner abandoned —
    the stuck thread is left to die with its call (Python cannot
    interrupt a hung native sync) and the supervisor spawns a fresh
    runner; a late result from an abandoned call is discarded."""

    def __init__(self, name: str):
        self._req: "queue.SimpleQueue" = queue.SimpleQueue()
        self._resp: "queue.SimpleQueue" = queue.SimpleQueue()
        self.abandoned = False
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            gen, fn = self._req.get()
            if fn is None:
                return
            try:
                out = ("ok", fn())
            except BaseException as e:  # noqa: BLE001 — classified
                out = ("error", e)      # by the supervisor
            self._resp.put((gen, out))

    def run(self, fn: Callable, timeout: float):
        """("ok", result) | ("error", exc) | ("hung", None)."""
        gen = time.monotonic_ns()
        self._req.put((gen, fn))
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                self.abandoned = True
                return ("hung", None)
            try:
                got_gen, out = self._resp.get(timeout=remaining)
            except queue.Empty:
                self.abandoned = True
                return ("hung", None)
            if got_gen == gen:
                return out
            # stale result from a call a previous owner abandoned

    def close(self) -> None:
        self._req.put((0, None))


# --------------------------------------------------------------------------
# The supervisor
# --------------------------------------------------------------------------

class DeviceSupervisor:
    """Wraps the serving dispatcher's launch/finalize with fault
    classification, circuit breaking, fail-static fallback, and gated
    recovery.  One instance per engine serving lane.

    The dispatcher calls :meth:`launch` / :meth:`finalize`; both
    return ``(True, payload)`` to proceed on the device path, or
    ``(False, (results, error))`` where ``results`` is the fail-static
    answer for the batch (``None`` if the host oracle could not serve,
    in which case the dispatcher falls back to its fail-closed deny).
    """

    def __init__(self, datapath, *, watchdog_s: float = 10.0,
                 failure_threshold: int = 3, reset_s: float = 0.5,
                 max_reset_s: float = 30.0,
                 new_flow_policy: str = "oracle",
                 recovery_gate: Optional[Callable[[], bool]] = None,
                 oracle_refresh_s: float = 5.0,
                 gate_samples: int = 32,
                 shard: Optional[int] = None):
        self.datapath = datapath
        self.watchdog_s = watchdog_s
        self.oracle_refresh_s = oracle_refresh_s
        self.gate_samples = gate_samples
        # shard scoping (parallel/sharded.py): this supervisor guards
        # ONE ep-shard's device column — its breaker, watchdog, fault
        # accounting and fail-static fallback cover only endpoints
        # mapped to that shard; sibling shards keep serving on device
        self.shard = shard
        self._name = "dataplane" if shard is None else \
            f"dataplane-shard{shard}"
        self.oracle = HostStaticOracle(datapath,
                                       new_flow_policy=new_flow_policy)
        self.breaker = CircuitBreaker(
            self._name, failure_threshold=failure_threshold,
            reset_timeout=reset_s, max_reset=max_reset_s)
        self._recovery_gate = recovery_gate
        self._hook = None  # chaos hand: utils/faultinject injector
        self._runner: Optional[_WatchdogRunner] = None
        self._probing = False
        self._refreshing = threading.Lock()
        self._mode = MODE_OK
        self._set_mode_gauge(0.0)
        # observability
        self.fail_static_batches = 0
        self.fail_static_records = 0
        self.faults: Dict[str, int] = {}
        self.recoveries = 0
        self.last_fault: Optional[str] = None
        # flight recorder: the first fail-static batch of each
        # degradation window is an event; subsequent batches are the
        # steady degraded state, not transitions
        self._static_reported = False

    # ----------------------------------------------------------- chaos

    def install_fault_hook(self, hook) -> None:
        """Arm a DeviceFaultInjector (utils/faultinject) — the chaos
        hand's device-lane entry point.  The injector inherits this
        supervisor's shard scope: its faults land on exactly this
        shard's launches/finalizes."""
        if hasattr(hook, "shard"):
            hook.shard = self.shard
        self._hook = hook

    # ------------------------------------------------------------ mode

    @property
    def mode(self) -> str:
        state = self.breaker.state
        if state == STATE_CLOSED:
            return MODE_OK
        if state == STATE_HALF_OPEN:
            return MODE_RECOVERING
        return MODE_DEGRADED

    def _set_mode_gauge(self, code: float) -> None:
        if self.shard is None:
            DATAPLANE_MODE.set(code)
        else:
            # shard-scoped lanes report per shard; the aggregate
            # dataplane_mode is maintained by the sharded plane
            DATAPLANE_SHARD_MODE.set(code,
                                     labels={"shard": str(self.shard)})

    def _sync_mode(self) -> None:
        mode = self.mode
        if mode != self._mode:
            prev, self._mode = self._mode, mode
            self._set_mode_gauge(float(_MODE_CODE[mode]))
            # flight recorder: mode flips ARE the incident timeline's
            # spine (trip -> degraded -> fail-static -> rebuild ->
            # recovered)
            if mode == MODE_DEGRADED:
                flight_recorder.record(
                    EVENT_DATAPLANE_DEGRADED,
                    detail=self.last_fault or "", shard=self.shard,
                    breaker=self.breaker.state)
            elif mode == MODE_OK and prev != MODE_OK:
                flight_recorder.record(
                    EVENT_DATAPLANE_RECOVERED, shard=self.shard,
                    recoveries=self.recoveries,
                    fail_static_records=self.fail_static_records)
                self._static_reported = False

    # --------------------------------------------------------- dispatch

    def launch(self, launch_fn: Callable, items, total: int):
        if not self.breaker.allow():
            return False, self._serve_static(items, total)
        if self.breaker.state == STATE_HALF_OPEN:
            # we carry the single probe: table rebuild + drift gate
            # must pass BEFORE any batch goes back to the device
            self._probing = True
            self._sync_mode()
            if not self._recover():
                self.breaker.record_failure()
                self._probing = False
                self._sync_mode()
                return False, self._serve_static(items, total)
        try:
            if self._hook is not None:
                self._hook.on_launch()
            return True, launch_fn(items, total)
        except Exception as e:  # noqa: BLE001 — classified below
            if classify_fault(e) == "caller":
                # engine precondition, not a device fault: keep the
                # plain fail-closed contract (deny + error on ticket)
                return False, (None, e)
            self._on_fault("launch", e)
            return False, self._serve_static(items, total)

    def finalize(self, finalize_fn: Callable, handle, weights, items):
        hook = self._hook

        def run():
            if hook is not None:
                hook.on_finalize()
            return finalize_fn(handle, weights)

        if not self.watchdog_s:
            try:
                results = run()
            except Exception as e:  # noqa: BLE001 — classified below
                if classify_fault(e) == "caller":
                    return False, (None, e)
                self._on_fault("finalize", e)
                return False, self._serve_static(items, sum(weights))
            self._on_success()
            return True, results
        if self._runner is None or self._runner.abandoned:
            self._runner = _WatchdogRunner(f"{self._name}-watchdog")
        status, payload = self._runner.run(run, self.watchdog_s)
        if status == "ok":
            self._on_success()
            return True, payload
        if status == "hung":
            self._on_fault("finalize", TimeoutError(
                f"finalize outlived watchdog ({self.watchdog_s}s)"),
                kind="hung")
        elif classify_fault(payload) == "caller":
            return False, (None, payload)
        else:
            self._on_fault("finalize", payload)
        return False, self._serve_static(items, sum(weights))

    # ------------------------------------------------- fault accounting

    def _on_fault(self, stage: str, e: BaseException,
                  kind: Optional[str] = None) -> None:
        kind = kind or classify_fault(e)
        self.faults[kind] = self.faults.get(kind, 0) + 1
        self.last_fault = f"{stage}: {e!r}"
        flight_recorder.record(EVENT_DATAPLANE_TRIP,
                               detail=self.last_fault,
                               shard=self.shard, stage=stage,
                               kind=kind)
        DATAPLANE_DEVICE_FAULTS.inc(labels={"stage": stage,
                                            "kind": kind})
        if self.shard is not None:
            DATAPLANE_SHARD_FAULTS.inc(
                labels={"shard": str(self.shard), "kind": kind})
        if kind == "transient":
            self.breaker.record_failure()
        else:
            self.breaker.trip()
        self._probing = False
        if self.breaker.state != STATE_CLOSED and \
                not self.oracle.refreshes:
            # entering degraded with no host view yet: best-effort
            # refresh (an injected fault leaves the device readable; a
            # real device loss keeps whatever was seeded earlier)
            self.oracle.refresh()
        self._sync_mode()

    def _on_success(self) -> None:
        closed_before = self.breaker.state == STATE_CLOSED
        self.breaker.record_success()
        if self._probing and not closed_before:
            self._probing = False
            self.recoveries += 1
            DATAPLANE_RECOVERIES.inc()
        self._sync_mode()
        if time.monotonic() - self.oracle.refreshed_at > \
                self.oracle_refresh_s:
            self._refresh_async()

    def _refresh_async(self) -> None:
        """Periodic host-view refresh OFF the dispatcher thread — a
        CT snapshot + decode must never ride the serving hot path."""
        if not self._refreshing.acquire(blocking=False):
            return  # a refresh is already in flight

        def run():
            try:
                self.oracle.refresh()
            except Exception:  # noqa: BLE001 — a failed refresh keeps
                pass           # the last good view
            finally:
                self._refreshing.release()

        threading.Thread(target=run, daemon=True,
                         name=f"{self._name}-oracle-refresh").start()

    # ------------------------------------------------------ fail-static

    def _serve_static(self, items, total: int):
        """The degraded answer for one batch: per-item fail-static
        results, or (None, error) when the oracle cannot serve."""
        self._sync_mode()
        if not self.oracle.refreshes:
            # never seeded: best-effort refresh — even with the CT
            # view unreadable (real device loss), the policy states
            # and host ipcache still serve last-known-good policy
            try:
                self.oracle.refresh()
            except Exception as e:  # noqa: BLE001 — no host view at
                return None, e      # all: fail closed
        try:
            # items are (soa, n[, payload]) chunks; the host oracle
            # answers policy, not L7 — fast-eligible flows degrade to
            # their redirect verdict (fail-to-redirect holds degraded)
            results = [self.oracle.classify(item[0], item[1])
                       for item in items]
        except Exception as e:  # noqa: BLE001 — a broken oracle must
            return None, e      # fall back to fail-closed deny
        self.fail_static_batches += 1
        self.fail_static_records += total
        DATAPLANE_FAIL_STATIC.inc(total)
        if not self._static_reported:
            # first fail-static batch of this degradation window
            self._static_reported = True
            flight_recorder.record(EVENT_DATAPLANE_FAIL_STATIC,
                                   shard=self.shard, records=total,
                                   new_flow_policy=self.oracle
                                   .new_flow_policy)
        return results, None

    # --------------------------------------------------------- recovery

    def _recover(self) -> bool:
        """Rebuild device tables from the host-of-record, then gate on
        a drift-audit replay.  True admits the probe batch."""
        dp = self.datapath
        try:
            if getattr(dp, "_table_mgr", None) is not None:
                # force_rebuild: recovery must regenerate the packed
                # dispatch buffers too — a corrupted device buffer is
                # exactly what the fast (write-through) path would keep
                dp.refresh_policy(force_rebuild=True)
            else:
                dp.reload_services()  # full _rebuild from compiled
        except Exception as e:  # noqa: BLE001 — rebuild failed: the
            self.last_fault = f"recovery-rebuild: {e!r}"
            flight_recorder.record(EVENT_DATAPLANE_REBUILD,
                                   detail=self.last_fault,
                                   shard=self.shard,
                                   result="rebuild-failed")
            return False
        gate = self._recovery_gate or self._default_gate
        try:
            ok = bool(gate())
        except Exception as e:  # noqa: BLE001 — a gate that raises is
            self.last_fault = f"recovery-gate: {e!r}"
            flight_recorder.record(EVENT_DATAPLANE_REBUILD,
                                   detail=self.last_fault,
                                   shard=self.shard,
                                   result="gate-raised")
            return False        # a gate that failed
        flight_recorder.record(
            EVENT_DATAPLANE_REBUILD, shard=self.shard,
            result="ok" if ok else "gate-failed",
            detail="" if ok else (self.last_fault or ""))
        return ok

    def _default_gate(self) -> bool:
        """Self-contained drift replay: sample installed keys from the
        host-of-record states, replay them through the freshly rebuilt
        device tables, and require verdict parity with the compiler
        oracle (daemon installs the full run_drift_audit as the gate
        when one is available)."""
        from ..compiler.policy_tables import oracle_verdict
        states = self.datapath.host_policy_states() or {}
        rows = []
        for slot, state in sorted(states.items()):
            for key in list(state.keys())[:4]:
                rows.append((slot, state, key))
            if len(rows) >= self.gate_samples:
                break
        if not rows:
            return True  # nothing installed: nothing to diverge
        replayed = self.datapath.policy_replay(
            [r[0] for r in rows],
            [r[2].identity for r in rows],
            [r[2].dest_port for r in rows],
            [r[2].nexthdr for r in rows],
            [r[2].direction for r in rows])
        for (slot, state, key), dev in zip(rows, replayed):
            want = oracle_verdict(state, key.identity, key.dest_port,
                                  key.nexthdr, key.direction)
            if int(dev["verdict"]) != int(want):
                self.last_fault = (
                    f"recovery-gate: drift at slot {slot} {key}: "
                    f"device {dev['verdict']} != oracle {want}")
                return False
        return True

    # ---------------------------------------------------------- status

    def stats(self) -> Dict:
        return {"mode": self.mode,
                "shard": self.shard,
                "breaker": self.breaker.state,
                "probe-in": round(self.breaker.retry_in(), 3),
                "faults": dict(self.faults),
                "last-fault": self.last_fault,
                "fail-static": {
                    "batches": self.fail_static_batches,
                    "records": self.fail_static_records},
                "recoveries": self.recoveries,
                "oracle": self.oracle.stats()}
