"""The latency-tier serving path: continuous micro-batching with an
async, double-buffered dispatch core.

BENCH_FULL_20260804_143713 made the problem concrete: the jitted
pipeline is throughput-shaped only — device round-trip p99 at batch
256 was 2.46 ms while the host verdict cache answers in 21 µs, because
every caller paid a synchronous pack -> H2D -> compute -> D2H round
trip per dispatch, serialized on the engine lock.  This module is the
fix, the hXDP argument applied to the verdict engine: hide per-packet
latency by keeping the pipeline full instead of waiting out each
dispatch.

Three mechanisms, one dispatcher thread:

* **Continuous micro-batching** — every submitter (verdict-service
  connections, L7 proxies, direct engine callers) enqueues frames into
  one shared :class:`VerdictDispatcher`; concurrent endpoints coalesce
  into ONE device launch instead of serializing pack+dispatch+sync on
  the engine lock.  Tickets preserve per-submitter ordering and map
  results back to exactly the submitted frames.
* **Async double-buffered dispatch** — JAX dispatch is asynchronous,
  so the dispatcher launches batch N and immediately packs batch N+1
  while N's device walk runs; the device->host sync happens once per
  batch in the *complete* stage, one batch behind the launch front.
  Up to ``depth`` batches stay in flight (the l7/http.py
  ``check_pipelined`` pattern, promoted to the verdict engine).
* **Persistent packed staging** — packing writes into preallocated
  per-bucket [10, rows] field matrices (rotated ``depth+1`` deep so an
  in-flight batch never shares memory with the one being packed; the
  CPU backend zero-copies host arrays), dispatched through
  ``Datapath.process_packed`` as ONE host->device transfer per batch
  instead of ten per-field uploads; steady-state dispatch does no
  per-batch allocation, and the table state is already device-resident
  (CT/counters are donated through the jitted step).

Failure semantics extend ``l7/parser.VerdictBatcher``'s guarantee to
the shared tier: a dispatch (or completion) that raises fails closed —
every frame in exactly that batch resolves to a deny verdict with the
error attached to its ticket; other batches are untouched.  With a
``DeviceSupervisor`` attached (datapath/supervisor.py), device faults
degrade further instead: the batch is served **fail-static from the
host oracle** (established flows keep their verdicts, new flows get
the configured degraded-mode policy) and the breaker-gated recovery
path brings the device lane back — the survivable-serving tier.

Overload protection (admission control): the pending queue is
weight-bounded (``max_pending``); work that would overflow it is shed
fail-closed at submit time, tickets may carry a deadline and expire
unserved work is shed at drain time — both with distinct
``serving_shed_total{reason}`` accounting — and a hysteresis watermark
pair flips the ``dataplane_overloaded`` gauge so callers
(verdict_service, VerdictBatcher) push back instead of queuing.

Sync-point discipline: the ONLY device synchronization on this path is
the ticket-completion transfer in ``_finalize`` (flagged as a blocking
boundary in ``pipeline_stage_seconds{stage="complete"}``); the lint in
tests/test_sync_lint.py holds the hot modules to that.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..observability.events import (EVENT_SERVING_OVERLOAD,
                                    recorder as flight_recorder)
from ..observability.slo import slo_tracker
from ..observability.stages import record_stage
from ..utils.bucketing import bucket_size
from ..utils.metrics import DATAPLANE_OVERLOADED, registry
from .events import DROP_POLICY
# the packed staging row order, unpacked by full_datapath_step_packed
# inside the fused program; the names also match the
# PacketRing.pop_batch SoA dict keys
from .pipeline import PACKED_FIELDS

SERVING_BATCHES = registry.counter(
    "serving_batches_total",
    "Device launches issued by the continuous micro-batching "
    "dispatcher, by lane")
SERVING_FRAMES = registry.counter(
    "serving_frames_total",
    "Frames (submissions) coalesced through the serving dispatcher, "
    "by lane")
SERVING_SHED = registry.counter(
    "serving_shed_total",
    "Frames shed fail-closed by serving admission control, by lane "
    "and reason (overflow / deadline / closed)")


class ShedError(RuntimeError):
    """The frame was shed by admission control (queue overflow or an
    expired ticket deadline) — fail-closed, never dispatched."""

    def __init__(self, reason: str):
        super().__init__(f"shed by admission control: {reason}")
        self.reason = reason


class Ticket:
    """One submission's future: resolved by the dispatcher thread with
    the per-frame results (or, on a failed batch, the fail-closed deny
    results plus the error that caused them)."""

    __slots__ = ("_event", "value", "error", "submitted_at",
                 "deadline", "_callbacks", "_cb_lock")

    def __init__(self, deadline: Optional[float] = None):
        self._event = threading.Event()
        self.value = None
        self.error: Optional[BaseException] = None
        self.submitted_at = time.perf_counter()
        # absolute monotonic deadline: unserved work older than this
        # is shed at drain time (admission control), never dispatched
        self.deadline = None if deadline is None else \
            time.monotonic() + deadline
        self._callbacks: List[Callable] = []
        self._cb_lock = threading.Lock()

    def resolve(self, value, error: Optional[BaseException] = None
                ) -> None:
        self.value = value
        self.error = error
        # set-then-drain under the callback lock: a concurrent
        # add_done_callback either sees the event and runs its
        # callback itself, or lands in the list we drain here —
        # never neither
        with self._cb_lock:
            self._event.set()
            cbs, self._callbacks = self._callbacks, []
        for cb in cbs:
            try:
                cb(self)
            except Exception:  # noqa: BLE001 — a bad callback must
                pass           # not poison the dispatcher thread

    def add_done_callback(self, cb: Callable) -> None:
        """Run ``cb(ticket)`` on resolution (immediately if already
        resolved) — the asyncio bridge used by VerdictBatcher."""
        with self._cb_lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb(self)

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block until resolved.  Fail-closed contract: a failed batch
        still RETURNS (the deny results) — callers that must
        distinguish inspect ``.error`` afterwards."""
        if not self._event.wait(timeout):
            raise TimeoutError("serving ticket not resolved in time")
        return self.value


class ContinuousDispatcher:
    """Generic continuous micro-batching core (one dispatcher thread).

    ``launch(items, total)`` must dispatch the batch WITHOUT device
    synchronization and return an in-flight handle; ``finalize(handle,
    weights)`` performs the one blocking transfer and returns one
    result per item.  ``deny(item)`` builds the fail-closed result for
    one item.  ``weight(item)`` sizes items against ``max_batch``.

    The loop keeps up to ``depth`` launches in flight: while batch N
    computes on device, batch N+1 is drained+packed+launched — the
    double buffer.  Completion happens one batch behind the launch
    front, so the steady-state dispatch loop never blocks on device
    compute between launches.
    """

    def __init__(self, launch: Callable, finalize: Callable,
                 deny: Callable, *, max_batch: int = 1 << 15,
                 depth: int = 2, window: float = 0.0,
                 weight: Callable = lambda item: 1,
                 lane: str = "serving",
                 telemetry: Callable[[], bool] = lambda: True,
                 max_pending: Optional[int] = None,
                 default_deadline: Optional[float] = None,
                 overload_high: float = 0.75,
                 overload_low: float = 0.25,
                 supervisor=None):
        self._launch = launch
        self._finalize = finalize
        self._deny = deny
        self.max_batch = max_batch
        self.depth = max(1, depth)
        self.window = window
        self._weight = weight
        self.lane = lane
        self.family = f"serving-{lane}"
        self._telemetry = telemetry
        self._cond = threading.Condition()
        self._pending: "deque[Tuple[object, Ticket]]" = deque()
        self._inflight: "deque[Tuple[object, list, list]]" = deque()
        self._closed = False
        # ---- admission control: weight-bounded pending queue with a
        # hysteresis overload watermark pair (None = unbounded, the
        # pre-supervision behavior)
        self.max_pending = max_pending
        self.default_deadline = default_deadline
        self._pending_weight = 0
        self._high_mark = None if max_pending is None else \
            max(1, int(max_pending * overload_high))
        self._low_mark = None if max_pending is None else \
            max(0, int(max_pending * overload_low))
        self.overloaded = False
        # ---- device-fault supervision (datapath/supervisor.py):
        # classify faults, fail static from the host oracle, recover
        self.supervisor = supervisor
        # serving SLO tier (observability/slo.py): resolved tickets
        # observe submit->finalize latency against the lane objective
        # (the admission deadline when one is set); launches sample
        # queue depth into the flight ring
        self._shard = getattr(supervisor, "shard", None)
        # observability: how well the batching is working
        self.batches = 0
        self.frames = 0
        self.items_total = 0
        self.max_batch_seen = 0
        self.errors = 0
        self.static_batches = 0
        self.shed: Dict[str, int] = {}
        self.max_pending_seen = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name=f"serving-{lane}")
        self._thread.start()

    # ------------------------------------------------------------ submit

    def _shed(self, item, ticket: Ticket, reason: str) -> Ticket:
        """Fail the item closed at admission time."""
        self.shed[reason] = self.shed.get(reason, 0) + 1
        SERVING_SHED.inc(labels={"lane": self.lane, "reason": reason})
        ticket.resolve(self._deny(item), ShedError(reason))
        return ticket

    def _set_overloaded_locked(self, value: bool) -> None:
        if value != self.overloaded:
            self.overloaded = value
            DATAPLANE_OVERLOADED.set(1.0 if value else 0.0,
                                     labels={"lane": self.lane})
            # watermark crossings are incident-timeline transitions
            flight_recorder.record(
                EVENT_SERVING_OVERLOAD, shard=self._shard,
                lane=self.lane, state="on" if value else "off",
                pending=self._pending_weight)

    def submit(self, item, deadline: Optional[float] = None) -> Ticket:
        """Queue one item from any thread; returns its Ticket.

        ``deadline`` (seconds from now; falls back to the lane's
        ``default_deadline``) bounds how long the item may wait
        unserved: expired work is shed fail-closed, never dispatched.
        A full pending queue sheds immediately (reason "overflow")."""
        if deadline is None:
            deadline = self.default_deadline
        ticket = Ticket(deadline=deadline)
        w = self._weight(item)
        with self._cond:
            if self._closed:
                ticket.resolve(self._deny(item),
                               RuntimeError("dispatcher closed"))
                return ticket
            if self.max_pending is not None and \
                    self._pending_weight + w > self.max_pending:
                return self._shed(item, ticket, "overflow")
            self._pending.append((item, ticket))
            self._pending_weight += w
            if self._pending_weight > self.max_pending_seen:
                self.max_pending_seen = self._pending_weight
            if self._high_mark is not None and \
                    self._pending_weight >= self._high_mark:
                self._set_overloaded_locked(True)
            self._cond.notify()
        return ticket

    # ----------------------------------------------------- dispatcher loop

    def _take_batch(self, wait: bool):
        """Drain up to ``max_batch`` worth of pending items.  With
        ``wait`` (nothing in flight), blocks for work; a nonzero
        collection ``window`` then lets concurrent submitters pile in
        before the first drain — the VerdictBatcher micro-batch
        window, only paid from idle (a busy pipeline coalesces
        naturally while batches compute)."""
        with self._cond:
            if wait:
                while not self._pending and not self._closed:
                    self._cond.wait()
        if wait and self.window > 0 and not self._closed:
            time.sleep(self.window)
        batch: List[Tuple[object, Ticket]] = []
        expired: List[Tuple[object, Ticket]] = []
        total = 0
        now = time.monotonic()
        with self._cond:
            while self._pending:
                w = self._weight(self._pending[0][0])
                head_deadline = self._pending[0][1].deadline
                if head_deadline is not None and head_deadline <= now:
                    # deadline-aware admission: expired work is shed
                    # fail-closed, never dispatched — a stale verdict
                    # answers nothing and only delays live traffic
                    expired.append(self._pending.popleft())
                    self._pending_weight -= w
                    continue
                if batch and total + w > self.max_batch:
                    break
                item, ticket = self._pending.popleft()
                self._pending_weight -= w
                batch.append((item, ticket))
                total += w
            if self._low_mark is not None and self.overloaded and \
                    self._pending_weight <= self._low_mark:
                self._set_overloaded_locked(False)
        for item, ticket in expired:
            self._shed(item, ticket, "deadline")
        return batch, total

    def _run(self) -> None:
        while True:
            idle = not self._inflight
            with self._cond:
                if self._closed and not self._pending:
                    break
            batch, total = self._take_batch(wait=idle)
            if batch:
                self._launch_batch(batch, total)
            # double buffer: complete the oldest launch only once the
            # pipeline is full (or nothing new arrived) — packing the
            # next batch above overlapped this one's device walk
            if self._inflight and (len(self._inflight) >= self.depth
                                   or not batch):
                self._complete_oldest()
        # shutdown: drain in-flight work, then fail any stragglers
        while self._inflight:
            self._complete_oldest()
        with self._cond:
            leftovers = list(self._pending)
            self._pending.clear()
            self._pending_weight = 0
            if self._low_mark is not None:
                self._set_overloaded_locked(False)
        for item, ticket in leftovers:
            ticket.resolve(self._deny(item),
                           RuntimeError("dispatcher closed"))

    def _launch_batch(self, batch, total: int) -> None:
        telem = self._telemetry()
        t0 = time.perf_counter() if telem else 0.0
        items = [item for item, _t in batch]
        if self.supervisor is not None:
            on_device, payload = self.supervisor.launch(
                self._launch, items, total)
            if not on_device:
                self._resolve_static(batch, payload)
                return
            handle = payload
        else:
            try:
                handle = self._launch(items, total)
            except Exception as e:  # noqa: BLE001 — fail closed: deny
                self._fail(batch, e)   # exactly this batch's frames
                return
        if telem:
            record_stage(self.family, "queue-wait",
                         t0 - batch[0][1].submitted_at)
            record_stage(self.family, "dispatch",
                         time.perf_counter() - t0)
        # SLO flight sample: queue state as of this launch (racy reads
        # are fine — observability, not control flow)
        slo_tracker.sample_queue(self.lane, queued=len(self._pending),
                                 inflight=len(self._inflight),
                                 pending_weight=self._pending_weight,
                                 shard=self._shard)
        self._inflight.append(
            (handle, batch, [self._weight(item) for item, _t in batch]))
        self.batches += 1
        self.frames += len(batch)
        self.items_total += total
        self.max_batch_seen = max(self.max_batch_seen, total)
        SERVING_BATCHES.inc(labels={"lane": self.lane})
        SERVING_FRAMES.inc(len(batch), labels={"lane": self.lane})

    def _complete_oldest(self) -> None:
        handle, batch, weights = self._inflight.popleft()
        telem = self._telemetry()
        t0 = time.perf_counter() if telem else 0.0
        if self.supervisor is not None:
            ok, payload = self.supervisor.finalize(
                self._finalize, handle, weights,
                [item for item, _t in batch])
            if not ok:
                self._resolve_static(batch, payload)
                return
            results = payload
        else:
            try:
                results = self._finalize(handle, weights)
            except Exception as e:  # noqa: BLE001 — fail closed: deny
                self._fail(batch, e)   # exactly this batch's frames
                return
        if telem:
            # the one blocking boundary on this path: host waits out
            # device compute for the batch launched one step earlier
            record_stage(self.family, "complete",
                         time.perf_counter() - t0)
        for (item, ticket), res in zip(batch, results):
            ticket.resolve(res)
        self._observe_slo(batch)

    def _observe_slo(self, batch) -> None:
        """Feed resolved tickets into the serving SLO tier: one
        submit->finalize latency observation per frame, judged against
        the lane's objective (its admission deadline when set)."""
        now = time.perf_counter()
        for _item, ticket in batch:
            slo_tracker.observe(self.lane,
                                now - ticket.submitted_at,
                                shard=self._shard,
                                objective_s=self.default_deadline)

    def _fail(self, batch, error: BaseException) -> None:
        self.errors += 1
        for item, ticket in batch:
            ticket.resolve(self._deny(item), error)
        self._observe_slo(batch)

    def _resolve_static(self, batch, payload) -> None:
        """Resolve one batch with the supervisor's fail-static answer
        (results carry NO error: they are real last-known-good
        verdicts, not denials); an unusable oracle falls back to the
        fail-closed deny contract."""
        results, error = payload
        if results is None:
            self._fail(batch, error or
                       RuntimeError("dataplane degraded"))
            return
        self.static_batches += 1
        self.frames += len(batch)
        for (item, ticket), res in zip(batch, results):
            ticket.resolve(res)
        self._observe_slo(batch)

    # ---------------------------------------------------------- lifecycle

    def stats(self) -> Dict:
        with self._cond:
            queued = len(self._pending)
            pending_weight = self._pending_weight
        out = {"lane": self.lane, "batches": self.batches,
               "frames": self.frames, "items": self.items_total,
               "max_batch": self.max_batch_seen,
               "errors": self.errors, "queued": queued,
               "inflight": len(self._inflight),
               "mean_batch": round(
                   self.items_total / self.batches, 2)
               if self.batches else 0.0,
               # admission control + supervision
               "shed": dict(self.shed),
               "overloaded": self.overloaded,
               "pending-weight": pending_weight,
               "max-pending-seen": self.max_pending_seen,
               "static-batches": self.static_batches}
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.stats()
        return out

    def close(self, timeout: float = 5.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout=timeout)


class VerdictDispatcher(ContinuousDispatcher):
    """The engine-backed lane: SoA packet-record chunks in, (verdict,
    identity) int32 arrays out, one ``Datapath.process_packed`` launch
    per coalesced batch.

    Padding keeps the verdict-service invariant: batches round up to
    the shared power-of-two bucket (utils/bucketing.bucket_size) and
    pad rows duplicate row 0, so padding can never mint new conntrack
    keys; pad results are sliced off before tickets resolve.
    """

    def __init__(self, datapath, *, max_batch: int = 1 << 15,
                 min_rows: int = 16, depth: int = 2,
                 window: float = 0.0, lane: str = "verdict",
                 max_pending: Optional[int] = None,
                 default_deadline: Optional[float] = None,
                 supervisor=None):
        self._datapath = datapath
        self._min_rows = min_rows
        # staging rings: (bucket rows) -> list of depth+1 packed
        # [10, rows] matrices (pipeline.PACKED_FIELDS row order — ONE
        # H2D per launch); rotation guarantees the matrix being packed
        # is never one of the <=depth still referenced by in-flight
        # launches
        self._staging: Dict[int, List[np.ndarray]] = {}
        self._staging_tick: Dict[int, int] = {}
        # the L7 payload lane's staging twin ([rows, W] matrices, same
        # rotation), allocated only when the engine has fast verdicts
        # on; rows without a submitted payload stay -1 (absent ->
        # redirect-to-proxy, the pre-fast behavior)
        self._pl_staging: Dict[int, List[np.ndarray]] = {}
        self._pl_tick: Dict[int, int] = {}
        super().__init__(self._launch_records, self._finalize_records,
                         self._deny_records, max_batch=max_batch,
                         depth=depth, window=window,
                         weight=lambda chunk: chunk[1], lane=lane,
                         telemetry=lambda: getattr(
                             datapath, "telemetry_enabled", False),
                         max_pending=max_pending,
                         default_deadline=default_deadline,
                         supervisor=supervisor)

    def submit_records(self, soa: Dict[str, np.ndarray], n: int,
                       deadline: Optional[float] = None,
                       payload: Optional[np.ndarray] = None) -> Ticket:
        """Queue ``n`` records given as the PacketRing SoA dict (int32
        arrays, caller-owned — they are read once at pack time on the
        dispatcher thread, so hand over fresh arrays, not ring-backed
        views).  ``payload`` is the optional [n, W] int32 L7 payload
        block (l7/fast.encode_payloads) riding with the records into
        the fused fast-verdict stage; None = every L7 rule redirects
        for these records."""
        return self.submit((soa, int(n), payload), deadline=deadline)

    # ------------------------------------------------------------- pack

    def _stage_for(self, rows: int) -> np.ndarray:
        ring = self._staging.get(rows)
        if ring is None:
            ring = self._staging[rows] = [
                np.empty((len(PACKED_FIELDS), rows), np.int32)
                for _ in range(self.depth + 1)]
            self._staging_tick[rows] = 0
        tick = self._staging_tick[rows]
        self._staging_tick[rows] = tick + 1
        return ring[tick % len(ring)]

    def _pl_stage_for(self, rows: int, width: int) -> np.ndarray:
        ring = self._pl_staging.get(rows)
        if ring is None or ring[0].shape[1] != width:
            ring = self._pl_staging[rows] = [
                np.empty((rows, width), np.int32)
                for _ in range(self.depth + 1)]
            self._pl_tick[rows] = 0
        tick = self._pl_tick[rows]
        self._pl_tick[rows] = tick + 1
        return ring[tick % len(ring)]

    def _launch_records(self, items, total: int):
        telem = self._telemetry()
        t0 = time.perf_counter() if telem else 0.0
        rows = bucket_size(total, self._min_rows)
        stage = self._stage_for(rows)
        width = 0
        l7_window = getattr(self._datapath, "l7_fast_window", None)
        if l7_window is not None:
            width = l7_window()
        pstage = self._pl_stage_for(rows, width) if width else None
        off = 0
        for item in items:
            soa, n, pl = item[0], item[1], item[2] \
                if len(item) > 2 else None
            for fi, f in enumerate(PACKED_FIELDS):
                stage[fi, off:off + n] = soa[f][:n]
            if pstage is not None:
                if pl is None:
                    pstage[off:off + n] = -1
                else:
                    w = min(width, pl.shape[1])
                    pstage[off:off + n, :w] = pl[:n, :w]
                    if w < width:
                        pstage[off:off + n, w:] = -1
                    if pl.shape[1] > width:
                        # bytes beyond the engine window: poison the
                        # overflowing rows (fail-to-redirect) instead
                        # of silently judging a truncated string
                        over = (pl[:n, width:] >= 0).any(axis=1)
                        pstage[off:off + n][over] = -2
            off += n
        # pad rows are copies of the first real record: they re-touch
        # an existing flow's CT entry instead of minting new keys
        stage[:, total:rows] = stage[:, :1]
        if pstage is not None:
            # pad payloads stay absent: a duplicated header row with a
            # real payload could flip the pad's verdict arm
            pstage[total:rows] = -1
        if telem:
            record_stage(self.family, "pack",
                         time.perf_counter() - t0)
        verdict, _event, identity, _nat = \
            self._datapath.process_packed(stage, payload=pstage)
        return verdict, identity

    def _finalize_records(self, handle, weights: Sequence[int]):
        verdict, identity = handle
        total = sum(weights)
        v = np.asarray(verdict)[:total].astype(np.int32)   # sync-ok: the serving path's one blocking boundary (stage="complete")
        i = np.asarray(identity)[:total].astype(np.int32)  # sync-ok: same transfer, already realized by the line above
        out = []
        off = 0
        for w in weights:
            out.append((v[off:off + w], i[off:off + w]))
            off += w
        return out

    @staticmethod
    def _deny_records(item):
        n = item[1]
        return (np.full(n, DROP_POLICY, np.int32),
                np.zeros(n, np.int32))
