"""Datapath event codes: drop reasons + trace points.

Reference: bpf/lib/common.h DROP_* reason codes and bpf/lib/{drop,trace}.h
perf-ring notifications (decoded by pkg/monitor/datapath_drop.go:28 and
datapath_trace.go:28). The batched datapath emits one event code per
packet; the monitor aggregates them host-side.
"""

from __future__ import annotations

# Forwarding outcomes (positive trace points).
TRACE_TO_LXC = 0        # delivered to local endpoint
TRACE_TO_PROXY = 1      # redirected to proxy
TRACE_TO_HOST = 2
TRACE_TO_STACK = 3
TRACE_TO_OVERLAY = 4    # encapped to remote node
# ICMPv6 answered in-datapath (bpf/lib/icmp6.h terminal actions): the
# packet is not forwarded; the responder synthesizes the reply
ICMP6_NS_REPLY = 5      # NS for the router -> neighbour advertisement
ICMP6_ECHO_REPLY = 6    # echo request to the router -> echo reply

# Drop reasons (negative codes, mirroring DROP_* semantics).
DROP_POLICY = -130          # common.h DROP_POLICY analog
DROP_FRAG_NOSUPPORT = -131
DROP_CT_INVALID_HDR = -132
DROP_PREFILTER = -133       # XDP prefilter (bpf_xdp.c check_filters)
DROP_POLICY_L7 = -134
DROP_INVALID = -135
DROP_UNKNOWN_TARGET = -136  # icmp6.h ACTION_UNKNOWN_ICMP6_NS analog
DROP_THREAT = -137          # inline threat scoring (threat/stage.py):
#                             the anomaly score crossed the drop
#                             threshold, or the rate-limit arm's token
#                             bucket ran dry — enforce mode only

DROP_NAMES = {
    DROP_POLICY: "Policy denied (L3/L4)",
    DROP_FRAG_NOSUPPORT: "Fragmented packet not supported",
    DROP_CT_INVALID_HDR: "Invalid connection tracking header",
    DROP_PREFILTER: "Prefilter denied",
    DROP_POLICY_L7: "Policy denied (L7)",
    DROP_INVALID: "Invalid packet",
    DROP_UNKNOWN_TARGET: "Unknown ICMPv6 ND target",
    DROP_THREAT: "Threat score denied (inline ML)",
}

TRACE_NAMES = {
    TRACE_TO_LXC: "to-endpoint",
    TRACE_TO_PROXY: "to-proxy",
    TRACE_TO_HOST: "to-host",
    TRACE_TO_STACK: "to-stack",
    TRACE_TO_OVERLAY: "to-overlay",
    ICMP6_NS_REPLY: "icmp6-ns-reply",
    ICMP6_ECHO_REPLY: "icmp6-echo-reply",
}


def event_name(code: int) -> str:
    """Human name for any event code (drop reason or trace point)."""
    return DROP_NAMES.get(code) or TRACE_NAMES.get(code) or \
        f"code {code}"


# ---------------------------------------------------------------------------
# Verdict provenance: decision tiers
# ---------------------------------------------------------------------------
#
# When provenance is enabled the jitted pipelines emit, per packet,
# WHICH stage of the fused program produced the final verdict — the
# fallback chain of bpf/lib/policy.h __policy_can_access plus the
# stages that short-circuit around it (XDP prefilter, the CT fast
# path, the in-datapath local responder).  Alongside the tier the
# policy tiers also emit the matched policymap entry's flat slot, so
# the host can name the exact compiled PolicyKey that decided.

TIER_NONE = 0            # provenance disabled / not applicable
TIER_PREFILTER = 1       # XDP prefilter deny (bpf_xdp.c check_filters)
TIER_CT_ESTABLISHED = 2  # verdict replayed from the CT entry
TIER_L3_ALLOW = 3        # L3-only key (identity, 0, 0, dir)
TIER_L4_RULE = 4         # exact or L4-wildcard key, plain allow
TIER_L7_REDIRECT = 5     # matched key carries a proxy port
TIER_DENY = 6            # no key matched (policy/fragment drop)
TIER_LB = 7              # answered by the local service tier (ICMPv6
#                          NS/echo responder; nothing reaches policy)
# On-device L7 fast verdicts (datapath/pipeline.py fast-verdict stage):
# the matched key carried a proxy port, but the rule set is first-
# bytes-decidable and the payload window decided inline — the flow
# never reaches the proxy.  Redirect-needing rules (header-spanning,
# kafka, body) and truncated/absent payloads keep TIER_L7_REDIRECT.
TIER_L7_FAST_ALLOW = 8   # DFA matched: allowed inline on device
TIER_L7_FAST_DENY = 9    # DFA refused: denied inline (DROP_POLICY_L7)
# Inline threat scoring (threat/stage.py): the fused anomaly scorer
# overrode an allow-or-redirect verdict in enforce mode.  Shadow-mode
# scoring never re-tiers (verdicts are bit-exact pre-threat), and a
# rate-limit-band packet that passed (token available / prand spared
# it) keeps its original tier — only actual overrides re-attribute.
TIER_THREAT_DROP = 10       # score >= drop threshold -> DROP_THREAT
TIER_THREAT_RATELIMIT = 11  # rate-limit arm: bucket dry + prand drop
TIER_THREAT_REDIRECT = 12   # score >= redirect threshold -> proxy

TIER_NAMES = {
    TIER_NONE: "none",
    TIER_PREFILTER: "prefilter",
    TIER_CT_ESTABLISHED: "ct-established",
    TIER_L3_ALLOW: "l3-allow",
    TIER_L4_RULE: "l4-rule",
    TIER_L7_REDIRECT: "l7-redirect",
    TIER_DENY: "deny",
    TIER_LB: "lb",
    TIER_L7_FAST_ALLOW: "l7-fast-allow",
    TIER_L7_FAST_DENY: "l7-fast-deny",
    TIER_THREAT_DROP: "threat-drop",
    TIER_THREAT_RATELIMIT: "threat-ratelimit",
    TIER_THREAT_REDIRECT: "threat-redirect",
}


def tier_name(code: int) -> str:
    """Human name for a provenance decision-tier code."""
    return TIER_NAMES.get(code, f"tier {code}")


def format_rule(decoded) -> str:
    """Compact one-line form of a decoded policymap entry (the label
    value the provenance metrics and monitor samples carry); '' for
    None (no entry decided)."""
    if decoded is None:
        return ""
    direction = "ingress" if decoded["direction"] == 0 else "egress"
    s = (f"identity={decoded['identity']},dport={decoded['dport']},"
         f"proto={decoded['proto']},{direction}")
    if decoded.get("proxy-port"):
        s += f",proxy={decoded['proxy-port']}"
    return s


def format_denied_key(identity: int, dport: int, proto: int) -> str:
    """The queried tuple a DENY verdict failed to match — the 'rule
    key' drops aggregate under (no compiled entry decided them)."""
    return f"deny:identity={identity},dport={dport},proto={proto}"
