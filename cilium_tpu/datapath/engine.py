"""The full datapath engine: host orchestrator over compiled tables.

Owns one generation of every device table (policy, ipcache LPM, LB,
prefilter) plus the mutable conntrack state and counters, and exposes a
single ``process(batch)`` call — the complete per-packet path of the
reference (bpf_lxc.c egress/ingress) as one jitted program.
"""

from __future__ import annotations

import threading

from ..utils.lock import Mutex
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lpm import (CompiledLPM, CompiledLPM6, compile_lpm,
                            compile_lpm6)
from ..compiler.policy_tables import CompiledPolicy, compile_endpoints
from ..observability.jitstats import jit_telemetry
from ..observability.pressure import compute_pressure
from ..observability.stages import record_stage
from ..policy.mapstate import PolicyMapState
from ..utils.metrics import POLICY_VERDICTS
from .conntrack import ConntrackTable, ct_host_fields
from .lb import (CompiledLB, CompiledLB6, LoadBalancer, Service,
                 Service6, compile_lb, compile_lb6)
from .pipeline import (DatapathTables, FullPacketBatch, FullPacketBatch6,
                       FullTables, FullTables6, build_tables,
                       full_datapath_step, full_datapath_step6,
                       full_datapath_step_packed, lpm6_tables)
from .events import format_rule
from .prefilter import PreFilter
from .verdict import (Counters, Provenance, _explain_jit,
                      make_counter_pack, make_packet_batch)


class Datapath:
    """One device-resident datapath generation + mutable flow state.

    Swap-on-regenerate: the agent compiles a new generation from the
    policy repository and calls ``load_policy`` — conntrack state and
    counters survive the swap when shapes allow (the analog of pinned
    BPF maps surviving agent restart, daemon/state.go).
    """

    def __init__(self, ct_slots: int = 1 << 16, ct_probe: int = 8):
        # process/gc/_rebuild all touch donated CT buffers; without
        # mutual exclusion the periodic GC controller can donate the
        # state out from under an in-flight process() (deleted-array
        # crash)
        self._lock = Mutex("datapath")
        self.prefilter = PreFilter()
        self.lb = LoadBalancer()
        # packed CT representation ([8, N+1] buffers): ONE jitted-step
        # leaf per family instead of eight (the dispatch floor fix —
        # parallel/packing.py); snapshots keep the per-field layout
        self.ct = ConntrackTable(slots=ct_slots, max_probe=ct_probe,
                                 packed=True)
        # separate v6 CT table (the reference keeps ct6 apart from ct4)
        self.ct6 = ConntrackTable(slots=ct_slots, max_probe=ct_probe,
                                  packed=True)
        self.compiled_policy: Optional[CompiledPolicy] = None
        self.compiled_ipcache: Optional[CompiledLPM] = None
        self.compiled_ipcache6: Optional[CompiledLPM6] = None
        # host mirrors of what's compiled into the device LPMs (for
        # the map-dump surface; the reference reads pinned maps back)
        self.ipcache_prefixes: Dict[str, int] = {}
        self.ipcache_prefixes6: Dict[str, int] = {}
        # v6 service registry (lb6): (vip words, port, proto) -> Service6
        self.lb6_services: Dict[tuple, Service6] = {}
        self.compiled_lb6: Optional[CompiledLB6] = None
        # monotonic across deletes: freed rev-NAT indices stay retired
        # (live CT entries may still carry them)
        self._lb6_next_rev = 1
        # tunnel map (pkg/maps/tunnel): pod CIDR -> tunnel endpoint u32,
        # programmed by the NodeManager on node add/delete
        self.tunnel_prefixes: Dict[str, int] = {}
        self.compiled_tunnel: Optional[CompiledLPM] = None
        # endpoint slot -> the endpoint's own security identity (the
        # per-endpoint SECLABEL the encap stage stamps into tunnel keys)
        self._ep_identity = np.zeros(8, np.int32)
        # packed per-entry counters ([2, E*S] uint32; verdict.py
        # make_counter_pack) — read through the ``counters`` property
        self._counters = None
        self.revision = 0
        self._step = None
        self._step_packed = None
        self._step_packed_nc = None
        self._tables: Optional[FullTables] = None
        self._step6 = None
        self._tables6: Optional[FullTables6] = None
        # the dispatch-floor packing (parallel/packing.py): the table
        # leaf zoo concatenated into a handful of grouped flat device
        # buffers, cached across steps and dispatched instead of the
        # ~30 FullTables leaves; re-packed only on table generation
        # change (delta-applies write through to the packed slices)
        self._manifest4 = None
        self._manifest6 = None
        self._tbufs4 = None
        self._tbufs6 = None
        self._rw4 = None           # (jitted row writer, group index)
        self._rw6 = None
        self._statics4: Dict = {}  # the jitted steps' static kwargs —
        self._statics6: Dict = {}  # exposed for the legacy-pytree
        #                            bench/parity twins
        self._pack_stats = {"full-packs": 0, "row-writes": 0,
                            "leaf-writes": 0}
        # the node's v6 router IP words (icmp6.h ROUTER_IP): the
        # address whose NS/echo the datapath answers itself
        self._router_ip6 = None
        # incremental mode: policy tensors owned by a DeviceTableManager
        # (endpoint/tables.py); row syncs swap tensors without re-jit
        self._table_mgr = None
        self._mgr_geometry = None  # (capacity, slots, max_probe, gen)
        # Hubble on-device flow aggregation (hubble/aggregation.py):
        # when enabled, both family steps scatter per-flow counters
        # into this device table inside the same compiled program
        self.flows = None
        # runtime self-telemetry (observability/): stage slices,
        # jit-cache accounting, verdict-outcome counters, and the
        # revision-served hook the policy-propagation tracker uses to
        # close the import->first-verdict loop.  One flag gates all of
        # it so the bench can prove the disabled path costs ~0.
        self.telemetry_enabled = True
        self.on_revision_served = None  # callable(revision)
        self._served_revision = 0
        # deferred verdict-outcome accounting has its OWN lock: the
        # force-flush can block on a device transfer, and that must
        # never happen while holding the device dispatch lock
        self._verdict_lock = threading.Lock()
        self._pending_verdicts: List = []
        # per-second device timestamp cache: steady-state dispatch
        # reuses the same jnp scalar instead of a fresh H2D per batch
        self._ts_cache: Optional[Tuple[int, object]] = None
        # the shared continuous micro-batching dispatcher
        # (datapath/serving.py), created on first use
        self._serving = None
        self._serving_lane_name = "verdict"
        # mesh placement (parallel/): when set, every device table this
        # engine owns is resident on the given (dp, 1) submesh — one
        # shard's column of the dataplane mesh — and packed batches are
        # sharded across its dp axis.  None = single-device (default).
        self._placement = None
        self._batch_sharding = None
        self._replicated_sharding = None
        self.shard_index: Optional[int] = None
        # host-of-record policy states (load_policy mode) — what the
        # fail-static oracle and the recovery gate answer from when no
        # DeviceTableManager owns the tensors
        self._host_states: Optional[Sequence[PolicyMapState]] = None
        # dataplane supervision knobs (datapath/supervisor.py): the
        # serving lane wraps launches in a DeviceSupervisor unless
        # disabled; enable_supervision=False gives the exact
        # pre-supervision dispatch path (and the compiled program is
        # byte-identical either way — supervision is host-side only)
        self._supervision_cfg: Dict = {"enabled": True}
        # verdict provenance (datapath/verdict.py Provenance): when
        # enabled, both family steps additionally emit the matched
        # policymap slot + decision tier per packet; the last batch's
        # pair is kept for the observability consumers.  Disabled =
        # the exact pre-provenance compiled program (one static flag).
        self.provenance_enabled = False
        self.last_provenance: Optional[Provenance] = None
        self._replay_probe = 1
        self._prov_decode_cache = None
        # on-device L7 fast verdicts (l7/fast.L7FastPrograms): when
        # set, both family steps fuse the fast-verdict stage — the
        # per-slot classification + fused DFA tables join the packed
        # dispatch buffers and the steps take a [B, W] payload lane.
        # None = the exact pre-fast compiled program.
        self._l7_fast = None
        self._l7_rw4 = None        # (jitted l7_prog row writer, gidx)
        self._l7_rw6 = None
        # cached absent-payload staging (all -1 = not decidable ->
        # redirect) per batch size, so payload-less callers of an
        # L7-enabled engine pay no per-batch allocation
        self._absent_payloads: Dict[int, np.ndarray] = {}
        # inline threat scoring (threat/): when set, both family steps
        # fuse the per-packet anomaly scorer — the quantized model
        # joins the packed dispatch as its own "threat-model" group
        # and the steps thread the shard-local ThreatState buffer
        # (token buckets + claim-window aggregates).  None = the exact
        # pre-threat compiled program.
        self._threat = None               # threat/model.ThreatModel
        self.threat_state = None          # threat/stage.ThreatState
        self.last_threat = None           # last batch's threat_out [B]
        self._threat_buckets = 1024
        self._threat_window_s = 8
        # window-aggregate update stripe (threat/stage.py): 1-in-N
        # sampled scatters, the flow table's ls_stripe precedent
        self._threat_stripe = 4
        # device-resident traffic analytics (analytics/): when on,
        # both family steps fuse the sketch/register stage over the
        # shard-local AnalyticsState buffer (two A/B epoch sections +
        # the control row — a pure engine-owned state leaf like the
        # threat state, no table leaves join the pack).  Off = the
        # exact pre-analytics compiled program.
        self._analytics_on = False
        self.analytics_state = None   # analytics/stage.AnalyticsState
        self._analytics_width = 1 << 12
        self._analytics_depth = 2
        self._analytics_lanes = 4
        self._analytics_stripe = 16

    @property
    def counters(self) -> Optional[Counters]:
        """Counters view over the packed [2, E*S] buffer (row slices;
        the observability/test surface — dispatch uses the pack)."""
        c = self._counters
        if c is None:
            return None
        return Counters(packets=c[0], bytes=c[1])

    def enable_flow_aggregation(self, slots: int = 1 << 12,
                                max_probe: int = 8,
                                claim_every: int = 4) -> None:
        """Turn on Hubble's device-resident flow table: the jitted v4
        and v6 steps gain a fused scatter-add tail keyed by (src
        identity, dst identity, dport, proto, event).  Both families
        share one table — flow keys are identity-based, like the
        policy tables.

        ``claim_every`` is the flow-birth admission stripe: only every
        N-th batch runs the claim machinery (the static
        claim_budget=0 variant of the step handles the rest), so the
        steady-state hot path pays for the reduction alone while new
        flows are admitted within N batches — the same
        bounded-admission idea as the per-batch claim budget."""
        from ..hubble.aggregation import FlowTable
        with self._lock:
            if self.flows is not None and self.flows.slots == slots:
                return
            self.flows = FlowTable(slots=slots, max_probe=max_probe)
            self._flow_claim_every = max(1, claim_every)
            self._flow_tick = 0
            if self._step is not None:
                self._rebuild()

    def disable_flow_aggregation(self) -> None:
        with self._lock:
            if self.flows is None:
                return
            self.flows = None
            if self._step is not None:
                self._rebuild()

    def enable_provenance(self) -> None:
        """Turn on per-packet verdict provenance: the jitted family
        steps additionally emit (matched policymap slot, decision
        tier) — see datapath/events.py TIER_*.  Re-jits the steps;
        the compiled program gains two [B] int32 outputs."""
        with self._lock:
            if self.provenance_enabled:
                return
            self.provenance_enabled = True
            if self._step is not None:
                self._rebuild()

    def disable_provenance(self) -> None:
        with self._lock:
            if not self.provenance_enabled:
                return
            self.provenance_enabled = False
            self.last_provenance = None
            if self._step is not None:
                self._rebuild()

    def enable_l7_fast(self, programs) -> None:
        """Turn on the on-device L7 fast-verdict stage: both family
        steps gain the fused DFA walk over a [B, W] payload lane,
        deciding first-bytes-decidable redirects inline (allow /
        DROP_POLICY_L7) and falling back to redirect-to-proxy for
        truncated/absent payloads or redirect-needing rules.

        ``programs`` is an l7/fast.L7FastPrograms (built from the
        eligible redirects by l7/fast.programs_from_redirects or
        build_fast_programs).  Re-jits the steps; the per-slot
        classification and DFA tables join the packed dispatch."""
        with self._lock:
            self._l7_fast = programs
            self._absent_payloads = {}
            if self._step is not None:
                self._rebuild()

    def disable_l7_fast(self) -> None:
        """Back to the exact pre-fast compiled program: every L7 rule
        redirects to its proxy port again."""
        with self._lock:
            if self._l7_fast is None:
                return
            self._l7_fast = None
            self._absent_payloads = {}
            if self._step is not None:
                self._rebuild()

    def l7_fast_report(self) -> Optional[Dict]:
        """Program-set report (bench extras / status surfaces)."""
        with self._lock:
            progs = self._l7_fast
        return None if progs is None else progs.describe()

    # -- inline threat scoring (threat/) -------------------------------------

    def enable_threat(self, model, buckets: int = 1024,
                      window_s: int = 8, stripe: int = 4) -> None:
        """Turn on the inline threat-scoring stage: both family steps
        fuse the quantized per-packet anomaly scorer (threat/stage.py)
        over the flow-table probe + the shard-local ThreatState
        buffer.  ``model`` is a threat/model.ThreatModel; its config
        (thresholds, shadow/enforce) is traced as VALUES, so later
        flips go through set_threat_config without a re-jit."""
        from ..threat.stage import make_threat_state
        with self._lock:
            self._threat = model
            self._threat_buckets = buckets
            self._threat_window_s = window_s
            self._threat_stripe = stripe
            self.threat_state = make_threat_state(buckets)
            if self._replicated_sharding is not None:
                self.threat_state = jax.device_put(
                    self.threat_state, self._replicated_sharding)
            if self._step is not None:
                self._rebuild()

    def disable_threat(self) -> None:
        """Back to the exact pre-threat compiled program."""
        with self._lock:
            if self._threat is None:
                return
            self._threat = None
            self.threat_state = None
            self.last_threat = None
            if self._step is not None:
                self._rebuild()

    def set_threat_config(self, config) -> None:
        """Swap the policy-controlled threshold/mode vector (a
        threat/model.ThreatConfig): ONE region write into the live
        threat-model group buffer — a shadow<->enforce flip or a
        threshold change never repacks and never re-jits."""
        with self._lock:
            if self._threat is None:
                raise RuntimeError("threat scoring not enabled")
            self._threat = self._threat.with_config(config)
            cfg = jnp.asarray(self._threat.config.encode())
            if self._tables is not None:
                self._tables = self._tables._replace(tm_cfg=cfg)
                if self._tables6 is not None:
                    self._tables6 = self._tables6._replace(tm_cfg=cfg)
                self._write_leaf_locked("tm_cfg", cfg)

    def apply_threat_weights(self, model) -> bool:
        """Hot-swap the scorer weights (a trained ThreatModel):
        same-geometry pushes are region writes into the threat-model
        group — zero repacks, no serving pause (the delta-apply
        write-through path).  A geometry change (different hidden
        width) rebuilds.  Returns True when the fast path applied."""
        with self._lock:
            if self._threat is None:
                raise RuntimeError("threat scoring not enabled")
            fast = model.geometry == self._threat.geometry and \
                self._tables is not None
            self._threat = model
            if not fast:
                if self._step is not None:
                    self._rebuild()
                return False
            leaves = {k: jnp.asarray(v)
                      for k, v in model.tables().items()}
            self._tables = self._tables._replace(**leaves)
            if self._tables6 is not None:
                self._tables6 = self._tables6._replace(**leaves)
            for path, arr in leaves.items():
                self._write_leaf_locked(path, arr)
            return True

    def threat_report(self) -> Optional[Dict]:
        """Model + state report (status surfaces; None = disabled)."""
        with self._lock:
            model = self._threat
            state = self.threat_state
            buckets = self._threat_buckets
            window_s = self._threat_window_s
        if model is None:
            return None
        out = dict(model.describe())
        out.update({"buckets": buckets, "window-s": window_s,
                    "shard": self.shard_index})
        if state is not None:
            from ..threat.stage import COL_WIN_TS
            st = np.asarray(state.state)
            out["active-buckets"] = int(
                (st[:-1, COL_WIN_TS] != 0).sum())
        return out

    # -- device-resident traffic analytics (analytics/) ----------------------

    def enable_analytics(self, width: int = 1 << 12, depth: int = 2,
                         lanes: int = 4, stripe: int = 16) -> None:
        """Turn on the fused traffic-analytics stage: both family
        steps fold every batch's final verdicts into the shard-local
        AnalyticsState buffer (count-min heavy-hitter sketches,
        candidate key tables, distinct-flow cardinality registers —
        analytics/stage.py).  ``width`` is the per-row column count
        (power of 2); ``stripe`` the 1-in-N update sampling.  The
        fused cost is scatter-element-bound and scales with the
        sampled fraction, so ``stripe`` IS the overhead budget: the
        1-in-16 default holds the fused step within the serving
        overhead gate (bench ``analytics-overhead``); stripe=1 folds
        every row when exactness beats throughput."""
        from ..analytics.stage import make_analytics_state
        with self._lock:
            self._analytics_on = True
            self._analytics_width = width
            self._analytics_depth = depth
            self._analytics_lanes = lanes
            self._analytics_stripe = stripe
            self.analytics_state = make_analytics_state(width, depth,
                                                        lanes)
            if self._replicated_sharding is not None:
                self.analytics_state = jax.device_put(
                    self.analytics_state, self._replicated_sharding)
            if self._step is not None:
                self._rebuild()

    def disable_analytics(self) -> None:
        """Back to the exact pre-analytics compiled program."""
        with self._lock:
            if not self._analytics_on:
                return
            self._analytics_on = False
            self.analytics_state = None
            if self._step is not None:
                self._rebuild()

    def swap_analytics_epoch(self) -> int:
        """Flip the A/B epoch: zero the section about to be written,
        then name it in the control cell.  The fused stage reads the
        cell dynamically, so the flip is a state swap under the engine
        lock — never a re-jit, never a serving pause.  Returns the
        newly quiesced epoch index (what decode should read)."""
        from ..analytics.stage import CTRL_COL, ctrl_row, epoch_rows
        with self._lock:
            if self.analytics_state is None:
                raise RuntimeError("analytics not enabled")
            depth = self._analytics_depth
            lanes = self._analytics_lanes
            st = self.analytics_state.state
            er = epoch_rows(depth, lanes)
            cr = ctrl_row(depth, lanes)
            cur = int(np.array(st[cr, CTRL_COL]))
            nxt = 1 - cur
            st = st.at[nxt * er:(nxt + 1) * er, :].set(jnp.int32(0))
            st = st.at[cr, CTRL_COL].set(jnp.int32(nxt))
            if self._replicated_sharding is not None:
                st = jax.device_put(st, self._replicated_sharding)
            self.analytics_state = \
                self.analytics_state._replace(state=st)
            return cur

    def analytics_snapshot(self) -> Optional[np.ndarray]:
        """Host copy of the full analytics buffer (None = disabled).
        The decode layer (analytics/decode.py) reads the quiesced
        epoch section of this snapshot; a drain cycle is
        swap_analytics_epoch() followed by one snapshot."""
        with self._lock:
            st = self.analytics_state
        if st is None:
            return None
        return np.array(st.state)

    def analytics_report(self) -> Optional[Dict]:
        """Geometry + epoch report (status surfaces; None =
        disabled)."""
        from ..analytics.stage import CTRL_COL, ctrl_row
        with self._lock:
            if not self._analytics_on:
                return None
            depth = self._analytics_depth
            lanes = self._analytics_lanes
            out = {"width": self._analytics_width, "depth": depth,
                   "lanes": lanes, "stripe": self._analytics_stripe,
                   "shard": self.shard_index}
            st = self.analytics_state
        # a lost device buffer degrades the report, never crashes it
        # (the sharded merge keeps reporting the healthy shards)
        out["write-epoch"] = None if st is None else int(np.array(
            st.state[ctrl_row(depth, lanes), CTRL_COL]))
        return out

    def l7_fast_window(self) -> int:
        """The payload window W callers must encode to (0 = fast
        verdicts disabled; payloads are ignored then).  Read per
        serving launch — lock-free on purpose (the reference is
        swapped atomically by enable/disable; a racy read costs one
        absent-payload batch, never a wrong verdict)."""
        progs = self._l7_fast
        return 0 if progs is None else progs.window

    def l7_fast_protocol_of(self):
        """Slot -> protocol tag decoder for the l7_fast_verdicts_total
        metric (monitor.MonitorHub.ingest_batch l7_proto_of): maps a
        provenance match slot to the decided program's protocol via
        the live value tensor (the slot's proxy port)."""
        with self._lock:
            progs = self._l7_fast
        if progs is None:
            return None
        decode = self.rule_decoder()

        def proto_of(slot) -> str:
            entry = decode(slot)
            if entry is None:
                return ""
            return progs.protocol_of_port(entry.get("proxy-port", 0))
        return proto_of

    def flow_snapshot(self, max_entries: int = 4096):
        """Decoded per-flow aggregates ([] when disabled).  Snapshot
        refs are taken under the lock; decode happens lock-free on the
        immutable arrays (map_dump convention)."""
        with self._lock:
            flows = self.flows
        return [] if flows is None else flows.snapshot(max_entries)

    def flow_stats(self):
        with self._lock:
            flows = self.flows
            claim_every = getattr(self, "_flow_claim_every", 1)
        if flows is None:
            return None
        return {**flows.stats(), "claim-every": claim_every}

    def set_router_ip6(self, ip: str) -> None:
        """Program the v6 router address the ICMPv6/NDP responder
        stage answers for (datapath init writes ROUTER_IP into the
        generated header; bpf/lib/icmp6.h reads it back)."""
        from ..compiler.lpm import ipv6_to_words
        with self._lock:
            # words are unsigned u32; the device tables carry them as
            # bit-identical int32 (same convention as addr6 batches)
            self._router_ip6 = jnp.asarray(
                np.asarray(ipv6_to_words(ip), np.uint32)
                .view(np.int32))
            if self._tables6 is not None:
                self._tables6 = self._tables6._replace(
                    router_ip6=self._router_ip6)
                self._write_leaf_locked("router_ip6", self._router_ip6,
                                        families=("6",))

    def icmp6_echo_reply_bytes(self, requester_ip6: str,
                               ident: int = 0, seq: int = 0) -> bytes:
        """The responder's wire output for an answered echo
        (icmp6.h __icmp6_send_echo_reply): the reply is built from
        THIS datapath's programmed router address — the consumer can
        verify the answer really came from the address it probed."""
        from .icmp6 import echo_reply
        from ..compiler.lpm import ipv6_to_words
        with self._lock:
            if self._router_ip6 is None:
                raise RuntimeError("router ip6 not programmed")
            words = [int(w) for w in
                     np.asarray(self._router_ip6).view(np.uint32)]
        return echo_reply(words, ipv6_to_words(requester_ip6),
                          ident=ident, seq=seq)

    def set_mesh_placement(self, submesh, shard: Optional[int] = None,
                           lane: Optional[str] = None) -> None:
        """Pin this engine's device state to a (dp, ep=1) submesh — one
        shard column of the dataplane mesh (parallel/mesh.ep_submesh).

        Tables/CT/counters/flows are device_put replicated across the
        column's dp devices; packed serving batches are sharded across
        dp (pjit follows the committed input shardings), so the shard's
        compiled program spans exactly its own devices — its fault
        domain.  Must be called before tables are loaded or it re-jits.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P
        from ..parallel.mesh import DP_AXIS
        with self._lock:
            self._placement = submesh
            self._batch_sharding = NamedSharding(submesh,
                                                 P(None, DP_AXIS))
            self._replicated_sharding = NamedSharding(submesh, P())
            self.shard_index = shard
            if lane is not None:
                self._serving_lane_name = lane
            elif shard is not None:
                self._serving_lane_name = f"verdict-s{shard}"
            self._place_state_locked()
            if self._step is not None:
                self._rebuild()

    def _place_state_locked(self) -> None:
        """device_put the mutable per-shard state (CT, flows) onto the
        placement submesh (lock held).  Async transfers; donation keeps
        subsequent step outputs resident there."""
        rep = self._replicated_sharding
        if rep is None:
            return
        self.ct.state = jax.device_put(self.ct.state, rep)
        self.ct6.state = jax.device_put(self.ct6.state, rep)
        if self.flows is not None:
            self.flows.state = jax.device_put(self.flows.state, rep)
        if self._counters is not None:
            self._counters = jax.device_put(self._counters, rep)
        if self.threat_state is not None:
            self.threat_state = jax.device_put(self.threat_state, rep)
        if self.analytics_state is not None:
            self.analytics_state = jax.device_put(self.analytics_state,
                                                  rep)

    # -- table loading -------------------------------------------------------

    def load_policy(self, map_states: Sequence[PolicyMapState],
                    revision: int,
                    ipcache_prefixes: Optional[Dict[str, int]] = None
                    ) -> None:
        with self._lock:
            self._table_mgr = None
            # host-of-record for the fail-static oracle: slot i serves
            # map_states[i] (the exact states the tables compile from)
            self._host_states = list(map_states)
            self.compiled_policy = compile_endpoints(map_states,
                                                     revision=revision)
            if ipcache_prefixes is not None or \
                    self.compiled_ipcache is None:
                # keep the host mirror in lockstep with the compiled
                # LPM (map_dump + the fail-static oracle read it)
                self.ipcache_prefixes = dict(ipcache_prefixes or {})
                self.compiled_ipcache = compile_lpm(ipcache_prefixes or {})
            self.revision = revision
            self._rebuild()

    def use_table_manager(self, mgr,
                          ipcache_prefixes: Optional[Dict[str, int]]
                          = None) -> None:
        """Switch policy tensors to a DeviceTableManager (incremental
        mode): per-endpoint syncs become row writes realized by
        refresh_policy(); only geometry changes (capacity/slot growth,
        longer probe chains) re-jit the step."""
        with self._lock:
            self._table_mgr = mgr
            if ipcache_prefixes is not None or \
                    self.compiled_ipcache is None:
                self.ipcache_prefixes = dict(ipcache_prefixes or {})
                self.compiled_ipcache = compile_lpm(ipcache_prefixes or {})
            self._rebuild()

    def refresh_policy(self, revision: Optional[int] = None,
                       force_rebuild: bool = False) -> bool:
        """Realize the table manager's current tensors (the syncPolicyMap
        fast path: no recompile when geometry is unchanged). Returns
        True when a full re-jit happened.  On the fast path the
        manager's dirty rows are written through to the packed dispatch
        buffers (row scatters — a single-rule delta never repacks the
        table stack).  ``force_rebuild`` forces the full rebuild +
        repack (the supervisor's recovery path: corrupted device
        buffers must be rebuilt from the host-of-record even when
        geometry is unchanged)."""
        with self._lock:
            if self._table_mgr is None:
                raise RuntimeError("not in table-manager mode")
            if revision is not None:
                self.revision = max(self.revision, revision)
            # one atomic (geometry, tensors) snapshot: a concurrent
            # sync_endpoint can lengthen probe chains in-place and a
            # grow can reshape the stack between separate reads
            geometry, tensors = self._table_mgr.snapshot()
            if force_rebuild or geometry != self._mgr_geometry \
                    or self._step is None:
                self._rebuild(mgr_snapshot=(geometry, tensors))
                return True
            key_id, key_meta, value = tensors
            if self._placement is not None:
                key_id, key_meta, value = jax.device_put(
                    (key_id, key_meta, value),
                    self._replicated_sharding)
            dp = self._tables.datapath._replace(
                key_id=key_id, key_meta=key_meta, value=value)
            self._tables = self._tables._replace(datapath=dp)
            if self._tables6 is not None:
                self._tables6 = self._tables6._replace(
                    key_id=key_id, key_meta=key_meta, value=value)
            self._apply_dirty_rows_locked()
            return False

    def _apply_dirty_rows_locked(self) -> None:
        """Delta-apply write-through: scatter the table manager's dirty
        endpoint rows into the packed policy slices of BOTH family
        packs (v6 shares the policy tensors).  Lock held."""
        mgr = self._table_mgr
        if mgr is None or self._tbufs4 is None:
            return
        dirty = mgr.drain_dirty()
        if not dirty:
            return
        telem = self.telemetry_enabled
        t0 = time.perf_counter() if telem else 0.0
        slots = jnp.asarray(np.fromiter(dirty, np.int32,
                                        count=len(dirty)))
        kid = jnp.asarray(np.stack([r[0] for r in dirty.values()]))
        kmeta = jnp.asarray(np.stack([r[1] for r in dirty.values()]))
        kval_np = np.stack([r[2] for r in dirty.values()])
        kval = jnp.asarray(kval_np)
        for attr, rw in (("_tbufs4", self._rw4), ("_tbufs6", self._rw6)):
            bufs = getattr(self, attr)
            if bufs is None or rw is None:
                continue
            writer, gidx = rw
            out = list(bufs)
            out[gidx] = writer(out[gidx], slots, kid, kmeta, kval)
            setattr(self, attr, tuple(out))
        if self._l7_fast is not None:
            # L7 classification write-through: the dirty rows' proxy
            # ports re-derive their per-slot program ids, scattered
            # into both family packs (and the unpacked tables view the
            # replay surface reads) — an L7 rule change on the fast
            # path stays a row write, never a repack
            l7rows = jnp.asarray(
                self._l7_fast.progs_for_values(kval_np))
            for attr, rw in (("_tbufs4", self._l7_rw4),
                             ("_tbufs6", self._l7_rw6)):
                bufs = getattr(self, attr)
                if bufs is None or rw is None:
                    continue
                writer, gidx = rw
                out = list(bufs)
                out[gidx] = writer(out[gidx], slots, l7rows)
                setattr(self, attr, tuple(out))
            if self._tables is not None and \
                    self._tables.l7_prog is not None:
                lp = self._tables.l7_prog.at[slots].set(l7rows)
                self._tables = self._tables._replace(l7_prog=lp)
                if self._tables6 is not None:
                    self._tables6 = self._tables6._replace(l7_prog=lp)
        self._pack_stats["row-writes"] += len(dirty)
        if telem:
            record_stage("engine", "flatten",
                         time.perf_counter() - t0)

    def load_ipcache(self, prefixes: Dict[str, int],
                     prefixes6: Optional[Dict[str, int]] = None) -> None:
        with self._lock:
            self.ipcache_prefixes = dict(prefixes)
            self.compiled_ipcache = compile_lpm(prefixes)
            if prefixes6 is not None:
                self.ipcache_prefixes6 = dict(prefixes6)
                self.compiled_ipcache6 = compile_lpm6(prefixes6)
            self._rebuild()

    def load_ipcache6(self, prefixes6: Dict[str, int]) -> None:
        with self._lock:
            self.ipcache_prefixes6 = dict(prefixes6)
            self.compiled_ipcache6 = compile_lpm6(prefixes6)
            self._rebuild()

    def upsert_service6(self, svc: Service6) -> None:
        """Program a v6 service (lb6 family).  rev_nat_index stability
        matches the v4 LoadBalancer: replacing a service keeps its
        index so live CT entries keep resolving the same VIP."""
        key = (tuple(svc.vip), svc.port, svc.proto)
        with self._lock:
            old = self.lb6_services.get(key)
            if svc.rev_nat_index <= 0:
                if old is not None:
                    svc.rev_nat_index = old.rev_nat_index
                else:
                    svc.rev_nat_index = self._lb6_next_rev
            self._lb6_next_rev = max(self._lb6_next_rev,
                                     svc.rev_nat_index + 1)
            self.lb6_services[key] = svc
            self.compiled_lb6 = compile_lb6(
                list(self.lb6_services.values()))
            self._rebuild()

    def delete_service6(self, vip: tuple, port: int,
                        proto: int = 6) -> bool:
        with self._lock:
            if self.lb6_services.pop((tuple(vip), port, proto),
                                     None) is None:
                return False
            self.compiled_lb6 = compile_lb6(
                list(self.lb6_services.values())) \
                if self.lb6_services else None
            self._rebuild()
            return True

    def load_tunnel(self, prefixes: Dict[str, int]) -> None:
        """Program the tunnel map: pod CIDR -> tunnel endpoint node IP
        (u32).  Reference: pkg/maps/tunnel SetTunnelEndpoint, consumed
        by encap.h encap_and_redirect."""
        # node IPs above 2^31 must be stored as their int32
        # bit-pattern (the LPM value lanes are int32)
        normalized = {cidr: int(np.uint32(ip).view(np.int32))
                      for cidr, ip in prefixes.items()}
        with self._lock:
            if normalized == self.tunnel_prefixes:
                return  # idempotent node refresh: skip the re-jit
            self.tunnel_prefixes = normalized
            self.compiled_tunnel = compile_lpm(self.tunnel_prefixes) \
                if self.tunnel_prefixes else None
            self._rebuild()

    def set_endpoint_identity(self, slot: int, identity: int) -> None:
        """Record a local endpoint slot's own security identity (the
        compile-time SECLABEL of the reference's per-endpoint program);
        the encap stage stamps it into the tunnel key."""
        with self._lock:
            if slot >= self._ep_identity.shape[0]:
                grown = np.zeros(max(slot + 1,
                                     2 * self._ep_identity.shape[0]),
                                 np.int32)
                grown[:self._ep_identity.shape[0]] = self._ep_identity
                self._ep_identity = grown
            self._ep_identity[slot] = identity
            ep_ident = jnp.asarray(self._ep_identity)
            if self._tables is not None:
                self._tables = self._tables._replace(
                    ep_identity=ep_ident)
            if self._tables6 is not None:
                self._tables6 = self._tables6._replace(
                    ep_identity=ep_ident)
            self._write_leaf_locked("ep_identity", ep_ident)

    def _write_leaf_locked(self, path: str, arr,
                           families: Tuple[str, ...] = ("4", "6")
                           ) -> None:
        """Write one table leaf through to the packed dispatch buffers
        (region writes; lock held).  A shape change — or the leaf being
        absent from a target family's manifest (it just came into
        existence) — means the packing manifest and therefore the
        jitted program changed: full rebuild."""
        if self._tbufs4 is None:
            return
        from ..parallel import packing
        updates = {}
        for fam in families:
            manifest = self._manifest4 if fam == "4" else self._manifest6
            bufs = self._tbufs4 if fam == "4" else self._tbufs6
            if manifest is None or bufs is None:
                continue
            new = packing.write_leaf(manifest, bufs, path, arr)
            if new is None:
                self._rebuild()  # manifest change: re-pack + re-jit
                return
            updates[fam] = new
        if "4" in updates:
            self._tbufs4 = updates["4"]
        if "6" in updates:
            self._tbufs6 = updates["6"]
        if updates:
            self._pack_stats["leaf-writes"] += 1

    def reload_services(self) -> None:
        with self._lock:
            self._rebuild()

    def reload_prefilter(self) -> None:
        with self._lock:
            self._rebuild()

    def _rebuild(self, mgr_snapshot=None) -> None:
        if self._table_mgr is None and self.compiled_policy is None:
            return
        t0 = time.perf_counter() if self.telemetry_enabled else 0.0
        self._rebuild_body(mgr_snapshot)
        if self.telemetry_enabled:
            record_stage("engine", "table-build",
                         time.perf_counter() - t0)
            nbytes = 0
            for tables in (self._tables, self._tables6,
                           self._tbufs4, self._tbufs6):
                for leaf in jax.tree_util.tree_leaves(tables):
                    nbytes += int(getattr(leaf, "nbytes", 0))
            jit_telemetry.set_device_bytes("engine-tables", nbytes)

    def _rebuild_body(self, mgr_snapshot=None) -> None:
        if self.lb.compiled is None:
            self.lb._recompile()
        if self._table_mgr is not None:
            if mgr_snapshot is None:
                mgr_snapshot = self._table_mgr.snapshot()
            geometry, (key_id, key_meta, value) = mgr_snapshot
            capacity, slots, max_probe, _gen = geometry
            if self.compiled_ipcache is None:
                self.compiled_ipcache = compile_lpm({})
            lpm = self.compiled_ipcache
            dp = DatapathTables(
                key_id=key_id, key_meta=key_meta, value=value,
                lpm_masks=jnp.asarray(lpm.masks),
                lpm_key_a=jnp.asarray(lpm.key_a),
                lpm_key_b=jnp.asarray(lpm.key_b),
                lpm_value=jnp.asarray(lpm.value),
                lpm_plens=jnp.asarray(lpm.prefix_lens))
            policy_probe = max(1, max_probe)
            n = max(1, capacity * slots)
            self._mgr_geometry = geometry
        else:
            dp = build_tables(self.compiled_policy, self.compiled_ipcache)
            policy_probe = self.compiled_policy.max_probe
            n = max(1, self.compiled_policy.num_endpoints *
                    self.compiled_policy.slots)
        pf = self.prefilter._compiled
        if pf is None or pf.entry_count() == 0:
            pf = compile_lpm({})
        tun = self.compiled_tunnel
        tun_kwargs = {}
        tun_probe = 0
        if tun is not None and tun.entry_count() > 0:
            tun_probe = max(1, tun.max_probe)
            tun_kwargs = dict(
                tun_masks=jnp.asarray(tun.masks),
                tun_key_a=jnp.asarray(tun.key_a),
                tun_key_b=jnp.asarray(tun.key_b),
                tun_value=jnp.asarray(tun.value),
                tun_plens=jnp.asarray(tun.prefix_lens))
        # the slot->identity table serves both the encap stage and the
        # flow-aggregation key, so it is always device-resident
        ep_ident = jnp.asarray(self._ep_identity)
        # L7 fast-verdict tables (l7/fast.py): the per-slot program
        # classification derives from the live value tensor (slot
        # proxy port -> program id), so it recompiles with every
        # table generation; omitted entirely when fast verdicts are
        # off, keeping the no-L7 program byte-identical
        l7_kwargs = {}
        l7_static = {}
        if self._l7_fast is not None:
            progs = self._l7_fast
            vals_np = np.asarray(dp.value)
            l7_kwargs = dict(
                l7_prog=jnp.asarray(progs.progs_for_values(vals_np)),
                l7_flat=jnp.asarray(progs.flat),
                l7_map=jnp.asarray(progs.cmap),
                l7_accept=jnp.asarray(progs.accept),
                l7_starts=jnp.asarray(progs.starts),
                l7_pmask=jnp.asarray(progs.pmask))
            l7_static = dict(with_l7_fast=1, l7_k=progs.k,
                             l7_c1=progs.c1)
        # inline threat scoring: the quantized model leaves join both
        # family tables (their own threat-model pack group); omitted
        # entirely when disabled so the pre-threat program stays
        # byte-identical
        threat_kwargs = {}
        threat_static = {}
        if self._threat is not None:
            threat_kwargs = {k: jnp.asarray(v)
                             for k, v in self._threat.tables().items()}
            threat_static = dict(with_threat=1,
                                 threat_window_s=self._threat_window_s,
                                 threat_stripe=self._threat_stripe)
            if self.threat_state is None:
                from ..threat.stage import make_threat_state
                self.threat_state = make_threat_state(
                    self._threat_buckets)
        # fused traffic analytics: a pure engine-owned state buffer
        # like the threat state — no table leaves join the pack;
        # omitted entirely when disabled so the pre-analytics program
        # stays byte-identical
        analytics_static = {}
        if self._analytics_on:
            analytics_static = dict(
                with_analytics=1,
                analytics_depth=self._analytics_depth,
                analytics_lanes=self._analytics_lanes,
                analytics_stripe=self._analytics_stripe)
            if self.analytics_state is None:
                from ..analytics.stage import make_analytics_state
                self.analytics_state = make_analytics_state(
                    self._analytics_width, self._analytics_depth,
                    self._analytics_lanes)
        self._tables = FullTables(
            datapath=dp, lb=self.lb.compiled.tables,
            pf_masks=jnp.asarray(pf.masks), pf_key_a=jnp.asarray(pf.key_a),
            pf_key_b=jnp.asarray(pf.key_b), pf_value=jnp.asarray(pf.value),
            pf_plens=jnp.asarray(pf.prefix_lens),
            ep_identity=ep_ident, **tun_kwargs, **l7_kwargs,
            **threat_kwargs)
        if self._counters is None or self._counters.shape[1] != n:
            self._counters = make_counter_pack(n)
        flow_kwargs = {}
        if self.flows is not None:
            flow_kwargs = dict(flow_slots=self.flows.slots,
                               flow_probe=self.flows.max_probe)
            # the flows arg is deliberately NOT donated: donation of
            # the scatter-updated flow buffers measurably degrades the
            # whole fused program on the CPU backend (XLA copies the
            # donated buffers out of line), and the table is ~1MB —
            # double-buffering it costs nothing
        # omitting the kwarg entirely when provenance is off keeps the
        # disabled partial byte-identical to the pre-provenance one
        if self.provenance_enabled:
            flow_kwargs = dict(flow_kwargs, with_provenance=1)
        # replay runs verdict_explain over the live policy tensors;
        # it needs the same probe depth the hot path compiled with
        self._replay_probe = policy_probe
        self._prov_decode_cache = None
        v4_static = dict(
            policy_probe=policy_probe,
            lpm_probe=max(1, self.compiled_ipcache.max_probe),
            pf_probe=max(1, pf.max_probe),
            lb_probe=self.lb.compiled.max_probe,
            ct_slots=self.ct.slots, ct_probe=self.ct.max_probe,
            tun_probe=tun_probe)
        self._statics4 = {**v4_static, **flow_kwargs, **l7_static,
                          **threat_static, **analytics_static}

        # v6 twin: shares the (family-agnostic) policy tensors, runs
        # the 4-word LPMs for prefilter/ipcache and its own CT table.
        ipc6 = self.compiled_ipcache6 if self.compiled_ipcache6 \
            is not None else compile_lpm6({})
        pf6 = self.prefilter._compiled6
        if pf6 is None or pf6.entry_count() == 0:
            pf6 = compile_lpm6({})
        lb6 = self.compiled_lb6
        self._tables6 = FullTables6(
            key_id=dp.key_id, key_meta=dp.key_meta, value=dp.value,
            ipcache6=lpm6_tables(ipc6), pf6=lpm6_tables(pf6),
            lb6=lb6.tables if lb6 is not None else None,
            router_ip6=self._router_ip6, ep_identity=ep_ident,
            **l7_kwargs, **threat_kwargs)
        v6_static = dict(
            policy_probe=policy_probe,
            lpm6_probe=max(1, ipc6.max_probe),
            pf6_probe=max(1, pf6.max_probe),
            ct_slots=self.ct6.slots, ct_probe=self.ct6.max_probe,
            lb6_probe=lb6.max_probe if lb6 is not None else 0)
        self._statics6 = {**v6_static, **flow_kwargs, **l7_static,
                          **threat_static, **analytics_static}

        # mesh placement: commit every table onto this shard's column
        # submesh so the jitted steps compile as submesh-resident SPMD
        # programs (the batch axis shards across dp at dispatch time)
        if self._placement is not None:
            rep = self._replicated_sharding
            self._tables = jax.device_put(self._tables, rep)
            self._tables6 = jax.device_put(self._tables6, rep)
            self._counters = jax.device_put(self._counters, rep)
            if self.threat_state is not None:
                self.threat_state = jax.device_put(self.threat_state,
                                                   rep)
            if self.analytics_state is not None:
                self.analytics_state = jax.device_put(
                    self.analytics_state, rep)

        # pack the table leaf zoo into the grouped dispatch buffers
        # (the dispatch-floor fix): every jitted step below takes the
        # handful of flat buffers instead of the ~30-leaf pytree, with
        # the per-leaf views rebuilt INSIDE the compiled program
        self._refresh_packs_locked()

        def grouped(step_fn, unpack, statics):
            def g(tbufs, ct, counters, batch, now, flows=None,
                  payload=None, threat=None, analytics=None):
                tables = unpack(tbufs)
                if flows is None and payload is None and \
                        threat is None and analytics is None:
                    return step_fn(tables, ct, counters, batch, now,
                                   **statics)
                return step_fn(tables, ct, counters, batch, now,
                               flows, payload, threat, analytics,
                               **statics)
            return jax.jit(g, donate_argnums=(1, 2))

        from ..parallel import packing
        unpack4 = packing.unpacker(self._manifest4)
        unpack6 = packing.unpacker(self._manifest6)
        nc4 = dict(self._statics4, flow_claim_budget=0)
        nc6 = dict(self._statics6, flow_claim_budget=0)
        self._step = grouped(full_datapath_step, unpack4,
                             self._statics4)
        # the claim-free (admission-striped) variants; compiled lazily
        # on first use like every jitted step
        self._step_nc = None if self.flows is None else grouped(
            full_datapath_step, unpack4, nc4)
        # the serving path's packed twins: same program over a single
        # [10, B] field matrix (one H2D per batch instead of ten)
        self._step_packed = grouped(full_datapath_step_packed, unpack4,
                                    self._statics4)
        self._step_packed_nc = None if self.flows is None else grouped(
            full_datapath_step_packed, unpack4, nc4)
        self._step6 = grouped(full_datapath_step6, unpack6,
                              self._statics6)
        self._step6_nc = None if self.flows is None else grouped(
            full_datapath_step6, unpack6, nc6)

    def _refresh_packs_locked(self) -> None:
        """(Re)build the packed dispatch buffers from the live tables
        (lock held): manifest from the canonical PartitionSpec registry,
        one device concat per group.  Paid per table generation — never
        per batch; the per-batch flatten cost this kills is recorded
        here as the non-blocking ``flatten`` stage."""
        from ..parallel import packing
        telem = self.telemetry_enabled
        t0 = time.perf_counter() if telem else 0.0
        self._manifest4 = packing.build_manifest(self._tables)
        self._manifest6 = packing.build_manifest(self._tables6)
        bufs4 = packing.pack_groups(self._tables, self._manifest4)
        bufs6 = packing.pack_groups(self._tables6, self._manifest6)
        if self._placement is not None:
            rep = self._replicated_sharding
            bufs4 = tuple(jax.device_put(b, rep) for b in bufs4)
            bufs6 = tuple(jax.device_put(b, rep) for b in bufs6)
        self._tbufs4, self._tbufs6 = bufs4, bufs6
        self._rw4 = packing.make_policy_row_writer(self._manifest4)
        self._rw6 = packing.make_policy_row_writer(self._manifest6)
        self._l7_rw4 = packing.make_l7_prog_row_writer(self._manifest4)
        self._l7_rw6 = packing.make_l7_prog_row_writer(self._manifest6)
        self._pack_stats["full-packs"] += 1
        if telem:
            record_stage("engine", "flatten",
                         time.perf_counter() - t0)

    def pack_stats(self) -> Dict:
        """Packing accounting: full group repacks vs delta row/leaf
        write-throughs, plus the group layout."""
        with self._lock:
            out = dict(self._pack_stats)
            if self._manifest4 is not None:
                out["groups4"] = list(self._manifest4.group_names())
                out["groups6"] = list(self._manifest6.group_names())
        return out

    def dispatch_leaf_counts(self) -> Dict[str, int]:
        """Flattened jitted-step argument leaf counts: what the packed
        dispatch actually marshals per batch vs what the legacy pytree
        form would — the sharding lint pins the ceiling so new leaves
        can't silently regrow the dispatch floor."""
        from jax.tree_util import tree_leaves
        with self._lock:
            if self._step_packed is None:
                raise RuntimeError("no policy loaded")
            flows = () if self.flows is None else (self.flows.state,)
            payload = () if self._l7_fast is None else (
                np.zeros((1, self._l7_fast.window), np.int32),)
            threat = () if self._threat is None else \
                (self.threat_state,)
            analytics = () if not self._analytics_on else \
                (self.analytics_state,)
            packed_args = (self._tbufs4, self.ct.state, self._counters,
                           np.zeros((10, 1), np.int32), 0) + flows \
                + payload + threat + analytics
            n_packed = len(tree_leaves(packed_args))
            # v6 keeps the per-field packet batch (10 leaves) but the
            # same grouped tables/state
            n_v6 = (len(tree_leaves((self._tbufs6, self.ct6.state,
                                     self._counters))) + 10 + 1
                    + len(tree_leaves(flows))
                    + len(tree_leaves(payload))
                    + len(tree_leaves(threat))
                    + len(tree_leaves(analytics)))
            # the legacy-pytree equivalent: raw table leaves + per-leaf
            # CT state + per-leaf counters + batch + timestamp
            n_legacy = (len(tree_leaves(self._tables)) + 8 + 2 + 1 + 1
                        + len(tree_leaves(flows))
                        + len(tree_leaves(payload))
                        + len(tree_leaves(threat))
                        + len(tree_leaves(analytics)))
            return {"packed-step": n_packed,
                    "v6-step": n_v6,
                    "legacy-step": n_legacy,
                    "reduction": round(n_legacy / n_packed, 2)}

    def _lower_args_packed(self, packed, now: int = 1):
        """The exact argument tuple ``_step_packed`` dispatches —
        the jit-lowering/introspection surface for tests.  An
        L7-enabled engine's step takes the payload lane too (absent
        matrix stands in, as for payload-less dispatch)."""
        args = (self._tbufs4, self.ct.state, self._counters, packed,
                jnp.int32(now))
        pl = None
        if self._l7_fast is not None:
            pl = jnp.asarray(
                self._payload_in(None, int(packed.shape[1])))
        if self._analytics_on:
            return args + (None, pl, self.threat_state,
                           self.analytics_state)
        if self._threat is not None:
            return args + (None, pl, self.threat_state)
        if pl is not None:
            return args + (None, pl)
        return args

    # -- the hot path --------------------------------------------------------

    def _flow_step_variant(self, step, step_nc):
        """Claim-admission striping: every ``claim_every``-th batch
        runs the claiming step; the rest run the statically claim-free
        variant (callers hold the engine lock)."""
        tick = self._flow_tick
        self._flow_tick = tick + 1
        if tick % self._flow_claim_every == 0:
            return step
        return step_nc

    def _timestamp(self, now: Optional[int]):
        """Device scalar for the batch timestamp, cached per value:
        wall-clock `now` changes once a second, so steady-state
        dispatch reuses one device scalar instead of paying a fresh
        H2D transfer (and allocation) per batch."""
        val = int(now if now is not None else time.time())
        cache = self._ts_cache
        if cache is not None and cache[0] == val:
            return cache[1]
        ts = jnp.int32(val)
        self._ts_cache = (val, ts)
        return ts

    def _payload_in(self, payload, rows: int):
        """The payload lane for one dispatch (lock held): the caller's
        [rows, W] block when L7 fast verdicts are on, a cached
        all-(-1) absent matrix when the caller carried none (absent =
        not decidable = redirect, the exact pre-fast verdicts), and
        None when the fast stage is disabled (the payload is never
        traced, keeping the compiled program byte-identical)."""
        if self._l7_fast is None:
            return None
        if payload is not None:
            return payload
        cached = self._absent_payloads.get(rows)
        if cached is None:
            cached = np.full((rows, self._l7_fast.window), -1, np.int32)
            self._absent_payloads[rows] = cached
        return cached

    def _dispatch_locked(self, step, tbufs, ct_state, batch, ts,
                         flows_in, payload, threat=None,
                         analytics=None):
        """One jitted-step call with the optional flows/payload/threat/
        analytics lanes threaded positionally (lock held).  Call shapes
        stay stable per configuration, so the jit cache sees one
        entry."""
        if analytics is not None:
            return step(tbufs, ct_state, self._counters, batch, ts,
                        flows_in, payload, threat, analytics)
        if threat is not None:
            return step(tbufs, ct_state, self._counters, batch, ts,
                        flows_in, payload, threat)
        if payload is not None:
            return step(tbufs, ct_state, self._counters, batch, ts,
                        flows_in, payload)
        if flows_in is not None:
            return step(tbufs, ct_state, self._counters, batch, ts,
                        flows_in)
        return step(tbufs, ct_state, self._counters, batch, ts)

    def process(self, pkt: FullPacketBatch, now: Optional[int] = None,
                payload=None):
        """Classify a batch. Returns (verdict, event, identity, nat) —
        nat carries the DNAT'd forward tuple and rev-NAT'd reply tuple.

        Dispatch is asynchronous: the returned arrays are in-flight
        device values; nothing here blocks on device compute, and the
        engine lock covers ONLY the dispatch + state swap (timestamp
        upload happens before it, telemetry accounting after).

        ``payload`` is the optional [B, W] L7 payload lane (int32
        match-string bytes, l7/fast.encode_payloads) consumed by the
        fast-verdict stage when enabled; ignored otherwise."""
        telem = self.telemetry_enabled
        t0 = time.perf_counter() if telem else 0.0
        ts = self._timestamp(now)
        with self._lock:
            if self._step is None:
                raise RuntimeError("no policy loaded")
            t_lock = time.perf_counter() if telem else 0.0
            pl = self._payload_in(payload, int(pkt.endpoint.shape[0]))
            if self.flows is not None:
                step = self._flow_step_variant(self._step,
                                               self._step_nc)
                flows_in = self.flows.state
            else:
                step = self._step
                flows_in = None
            outs = self._dispatch_locked(step, self._tbufs4,
                                         self.ct.state, pkt, ts,
                                         flows_in, pl,
                                         self.threat_state,
                                         self.analytics_state)
            verdict, event, identity, nat = outs[:4]
            self.ct.state, self._counters = outs[4], outs[5]
            tail = 6
            if self.flows is not None:
                self.flows.state = outs[tail]
                tail += 1
            if self._threat is not None:
                self.threat_state = outs[tail]
                self.last_threat = outs[tail + 1]
                tail += 2
            if self._analytics_on:
                self.analytics_state = outs[tail]
                tail += 1
            if self.provenance_enabled:
                self.last_provenance = Provenance(outs[tail],
                                                  outs[tail + 1])
            served = self._revision_newly_served_locked()
        if telem:
            self._account_dispatch("engine-v4", "datapath.process",
                                   step, pkt.endpoint.shape[0],
                                   t0, t_lock, verdict)
        if served:
            self._notify_revision_served(served)
        return verdict, event, identity, nat

    def process6(self, pkt: FullPacketBatch6,
                 now: Optional[int] = None, payload=None):
        """Classify a v6 batch (bpf_lxc.c:745 ipv6_policy path).
        Returns (verdict, event, identity, nat6).  Same async-dispatch,
        narrow-lock and payload-lane contract as process()."""
        telem = self.telemetry_enabled
        t0 = time.perf_counter() if telem else 0.0
        ts = self._timestamp(now)
        with self._lock:
            if self._step6 is None:
                raise RuntimeError("no policy loaded")
            t_lock = time.perf_counter() if telem else 0.0
            pl = self._payload_in(payload, int(pkt.sport.shape[0]))
            if self.flows is not None:
                step = self._flow_step_variant(self._step6,
                                               self._step6_nc)
                flows_in = self.flows.state
            else:
                step = self._step6
                flows_in = None
            outs = self._dispatch_locked(step, self._tbufs6,
                                         self.ct6.state, pkt, ts,
                                         flows_in, pl,
                                         self.threat_state,
                                         self.analytics_state)
            verdict, event, identity, nat = outs[:4]
            self.ct6.state, self._counters = outs[4], outs[5]
            tail = 6
            if self.flows is not None:
                self.flows.state = outs[tail]
                tail += 1
            if self._threat is not None:
                self.threat_state = outs[tail]
                self.last_threat = outs[tail + 1]
                tail += 2
            if self._analytics_on:
                self.analytics_state = outs[tail]
                tail += 1
            if self.provenance_enabled:
                self.last_provenance = Provenance(outs[tail],
                                                  outs[tail + 1])
            served = self._revision_newly_served_locked()
        if telem:
            self._account_dispatch("engine-v6", "datapath.process6",
                                   step, pkt.endpoint.shape[0],
                                   t0, t_lock, verdict)
        if served:
            self._notify_revision_served(served)
        return verdict, event, identity, nat

    def process_packed(self, packed, now: Optional[int] = None,
                       payload=None):
        """Classify a v4 batch given as ONE [10, B] int32 field matrix
        (pipeline.PACKED_FIELDS order) — the serving dispatcher's hot
        entry: a single H2D transfer per batch instead of ten, with
        the per-field unpack fused into the compiled program.  Same
        verdict/event/identity/nat outputs, same async-dispatch and
        narrow-lock contract as process().

        ``payload`` is the optional [B, W] L7 payload lane riding
        beside the field matrix (its own H2D) when the fast-verdict
        stage is enabled; payload-less batches get the cached absent
        matrix (every L7 rule redirects, the pre-fast behavior)."""
        telem = self.telemetry_enabled
        t0 = time.perf_counter() if telem else 0.0
        ts = self._timestamp(now)
        if self._placement is not None and \
                packed.shape[1] % self._placement.devices.shape[0] == 0:
            # shard the batch axis across the submesh's dp devices
            # (async H2D; the jitted step follows committed shardings)
            packed = jax.device_put(packed, self._batch_sharding)
        with self._lock:
            if self._step_packed is None:
                raise RuntimeError("no policy loaded")
            t_lock = time.perf_counter() if telem else 0.0
            pl = self._payload_in(payload, int(packed.shape[1]))
            if self.flows is not None:
                step = self._flow_step_variant(self._step_packed,
                                               self._step_packed_nc)
                flows_in = self.flows.state
            else:
                step = self._step_packed
                flows_in = None
            outs = self._dispatch_locked(step, self._tbufs4,
                                         self.ct.state, packed, ts,
                                         flows_in, pl,
                                         self.threat_state,
                                         self.analytics_state)
            verdict, event, identity, nat = outs[:4]
            self.ct.state, self._counters = outs[4], outs[5]
            tail = 6
            if self.flows is not None:
                self.flows.state = outs[tail]
                tail += 1
            if self._threat is not None:
                self.threat_state = outs[tail]
                self.last_threat = outs[tail + 1]
                tail += 2
            if self._analytics_on:
                self.analytics_state = outs[tail]
                tail += 1
            if self.provenance_enabled:
                self.last_provenance = Provenance(outs[tail],
                                                  outs[tail + 1])
            served = self._revision_newly_served_locked()
        if telem:
            self._account_dispatch("engine-v4", "datapath.process",
                                   step, int(packed.shape[1]),
                                   t0, t_lock, verdict)
        if served:
            self._notify_revision_served(served)
        return verdict, event, identity, nat

    # -- the latency-tier serving path (datapath/serving.py) -----------------

    def configure_supervision(self, enabled: bool = True,
                              **knobs) -> None:
        """Set the serving lane's supervision config BEFORE first use
        of serving().  Knobs: watchdog_s, failure_threshold, reset_s,
        max_reset_s, new_flow_policy, recovery_gate, oracle_refresh_s
        (DeviceSupervisor kwargs) plus max_pending/default_deadline
        (admission control).  ``enabled=False`` restores the exact
        pre-supervision dispatch path."""
        with self._lock:
            if self._serving is not None:
                raise RuntimeError(
                    "serving lane already created; configure "
                    "supervision before first serving() use")
            self._supervision_cfg = {"enabled": enabled, **knobs}

    def serving(self):
        """THE shared continuous micro-batching dispatcher for this
        engine (created on first use): the verdict service, L7 plane
        and direct callers submit record chunks here so concurrent
        endpoints coalesce into one device launch instead of
        serializing pack+dispatch+sync on the engine lock.  Unless
        supervision is disabled, launches run under a DeviceSupervisor
        (datapath/supervisor.py): overload admission control, device-
        fault circuit breaking with fail-static host fallback, and
        breaker-gated recovery."""
        with self._lock:
            if self._serving is None:
                from .serving import VerdictDispatcher
                cfg = dict(self._supervision_cfg)
                supervisor = None
                admission = {
                    "max_pending": cfg.pop("max_pending", None),
                    "default_deadline": cfg.pop("default_deadline",
                                                None)}
                if cfg.pop("enabled", True):
                    from .supervisor import DeviceSupervisor
                    supervisor = DeviceSupervisor(self, **cfg)
                self._serving = VerdictDispatcher(
                    self, supervisor=supervisor,
                    lane=self._serving_lane_name, **admission)
            return self._serving

    def supervision_status(self) -> Dict:
        """The dataplane block of the agent status path: serving mode
        (ok/degraded/recovering), breaker state, shed/fail-static
        accounting.  Never CREATES the serving lane — a status probe
        must not spin up dispatcher threads."""
        with self._lock:
            serving = self._serving
        if serving is None:
            return {"mode": "ok", "serving": None,
                    "supervised": self._supervision_cfg.get(
                        "enabled", True)}
        sup = serving.supervisor
        out = {"mode": sup.mode if sup is not None else "ok",
               "supervised": sup is not None,
               "serving": serving.stats()}
        return out

    def host_policy_states(self) -> Dict[int, PolicyMapState]:
        """{table slot: host-of-record PolicyMapState} — what the
        fail-static oracle enforces and the recovery gate replays
        against.  Sourced from the DeviceTableManager in incremental
        mode, from the states load_policy compiled otherwise."""
        with self._lock:
            mgr = self._table_mgr
            states = self._host_states
        if mgr is not None:
            return mgr.states_by_slot()
        if states is None:
            return {}
        return {slot: st for slot, st in enumerate(states)}

    # -- self-telemetry (observability/) -------------------------------------

    def _account_dispatch(self, family: str, entry: str, step,
                          batch: int, t0: float, t_lock: float,
                          verdict) -> None:
        """Stage slices + jit-cache classification + deferred
        verdict-outcome accounting for one dispatch.  Runs AFTER the
        engine lock is released — accounting (and the occasional
        force-flush device read) must never extend the lock hold."""
        t_done = time.perf_counter()
        record_stage(family, "lock-wait", t_lock - t0)
        record_stage(family, "dispatch", t_done - t_lock)
        # a first call per (program, batch geometry) paid tracing +
        # XLA compile synchronously inside the dispatch slice
        jit_telemetry.record(entry, id(step), int(batch),
                             t_done - t_lock)
        with self._verdict_lock:
            self._pending_verdicts.append(verdict)
            self._flush_verdict_counts(
                force=len(self._pending_verdicts) > 8)

    def _flush_verdict_counts(self, force: bool = False) -> None:
        """Count verdict outcomes from completed batches (verdict lock
        held).  Dispatch is async, so the just-dispatched batch is
        usually not ready — it gets counted on a later call (or
        force-synced once the pending window fills), never blocking
        the hot path."""
        remaining = []
        for arr in self._pending_verdicts:
            ready = force
            if not ready:
                checker = getattr(arr, "is_ready", None)
                try:
                    ready = checker() if checker is not None else True
                except Exception:  # noqa: BLE001 — deleted/donated
                    continue
            if not ready:
                remaining.append(arr)
                continue
            try:
                v = np.asarray(arr)  # sync-ok: is_ready-gated (or a bounded force-flush outside the device lock)
            except Exception:  # noqa: BLE001 — deleted buffer
                continue
            denied = int((v < 0).sum())
            redirected = int((v > 0).sum())
            allowed = v.shape[0] - denied - redirected
            if allowed:
                POLICY_VERDICTS.inc(allowed,
                                    labels={"outcome": "allowed"})
            if denied:
                POLICY_VERDICTS.inc(denied,
                                    labels={"outcome": "denied"})
            if redirected:
                POLICY_VERDICTS.inc(redirected,
                                    labels={"outcome": "redirected"})
        self._pending_verdicts = remaining

    def flush_telemetry(self) -> None:
        """Drain deferred verdict accounting (metrics-scrape path).
        Takes only the verdict lock — a scrape never stalls dispatch."""
        with self._verdict_lock:
            self._flush_verdict_counts(force=True)

    def _revision_newly_served_locked(self) -> int:
        """First dispatch at a new policy revision (lock held).
        Returns the revision to report, or 0."""
        if self.on_revision_served is None or \
                self.revision <= self._served_revision:
            return 0
        self._served_revision = self.revision
        return self.revision

    def _notify_revision_served(self, revision: int) -> None:
        try:
            self.on_revision_served(revision)
        except Exception:  # noqa: BLE001 — telemetry must never
            pass           # poison the verdict path

    # -- verdict provenance (replay + slot decode) ---------------------------

    def rule_decoder(self):
        """Host decoder for provenance match slots: a closure mapping
        a flat [E*S] slot to the compiled PolicyKey words at that slot
        of the LIVE device policy tensors (None for -1/empty/out of
        range).  The tensor->numpy transfer is cached per tensor
        generation, so decoding many sampled slots costs one read."""
        with self._lock:
            if self._tables is None:
                return lambda slot: None
            key_id = self._tables.datapath.key_id
            key_meta = self._tables.datapath.key_meta
            value = self._tables.datapath.value
            cache = self._prov_decode_cache
        if cache is None or cache[0] is not key_id:
            arrays = (np.asarray(key_id).reshape(-1),
                      np.asarray(key_meta).reshape(-1),
                      np.asarray(value).reshape(-1),
                      int(key_id.shape[-1]))
            with self._lock:
                self._prov_decode_cache = (key_id, arrays)
        else:
            arrays = cache[1]
        flat_id, flat_meta, flat_value, slots = arrays

        def decode(slot) -> Optional[Dict]:
            slot = int(slot)
            if slot < 0 or slot >= flat_meta.shape[0]:
                return None
            meta = int(flat_meta[slot])
            if meta == 0:
                return None  # slot emptied since the batch ran
            return {"endpoint-slot": slot // slots,
                    "slot": slot % slots,
                    "identity": int(np.uint32(flat_id[slot])),
                    "dport": (meta >> 16) & 0xFFFF,
                    "proto": (meta >> 8) & 0xFF,
                    "direction": (meta >> 1) & 1,
                    "proxy-port": int(flat_value[slot])}
        return decode

    def provenance_rule_of(self):
        """String form of rule_decoder for the monitor/hubble surfaces
        ('' for unmatched slots)."""
        decode = self.rule_decoder()

        def rule_of(slot) -> str:
            return format_rule(decode(slot))
        return rule_of

    def policy_replay(self, endpoints, identities, dports, protos,
                      directions) -> List[Dict]:
        """Run a synthesized header batch through the REAL compiled
        policy tensors serving traffic right now (`cilium policy
        trace --replay` / the drift audit's device side).  Pure read:
        no counters, no CT, no flow table — verdict_explain shares
        the hot path's stage lookups, so the verdicts are bit-exact
        with what `process()` would decide for a new flow.

        Args are equal-length sequences: endpoint TABLE SLOTS (not
        endpoint ids), identities, dports, protos, directions.
        Returns one dict per row with the final verdict/tier/slot,
        the decoded matched key, and each stage's outcome."""
        from .events import tier_name
        with self._lock:
            if self._tables is None:
                raise RuntimeError("no policy loaded")
            key_id = self._tables.datapath.key_id
            key_meta = self._tables.datapath.key_meta
            value = self._tables.datapath.value
            probe = self._replay_probe
        pkt = make_packet_batch(endpoints, identities, dports, protos,
                                directions)
        res = _explain_jit(key_id, key_meta, value, pkt,
                           max_probe=probe)
        res = jax.tree_util.tree_map(np.asarray, res)
        decode = self.rule_decoder()
        eps, ids, dps, prs, dirs = (np.asarray(a) for a in (
            endpoints, identities, dports, protos, directions))
        out: List[Dict] = []
        for i in range(eps.shape[0]):
            stages = {}
            for name in ("exact", "l3", "l4_wildcard"):
                st = res[name]
                found = bool(st["found"][i])
                stages[name] = {
                    "found": found,
                    "value": int(st["value"][i]),
                    "key": decode(st["slot"][i]) if found else None}
            slot = int(res["slot"][i])
            out.append({
                "endpoint-slot": int(eps[i]),
                "identity": int(ids[i]),
                "dport": int(dps[i]),
                "proto": int(prs[i]),
                "direction": int(dirs[i]),
                "verdict": int(res["verdict"][i]),
                "tier": int(res["tier"][i]),
                "tier-name": tier_name(int(res["tier"][i])),
                "slot": slot,
                "matched": decode(slot) if slot >= 0 else None,
                "stages": stages})
        return out

    def map_pressure(self, warn_threshold: float = 0.9) -> Dict:
        """Map-pressure report over the live device tables (updates
        the map_pressure/map_entries gauges as a side effect)."""
        return compute_pressure(self.map_inventory(), warn_threshold)

    def lb6_service_list(self):
        """Snapshot of the v6 service registry under the engine lock —
        the threaded REST server must not iterate the live dict while
        an upsert mutates it."""
        with self._lock:
            return list(self.lb6_services.values())

    def ct_entries(self) -> Tuple[int, int]:
        """(v4, v6) live CT entry counts, serialized against the gc
        controller's buffer donation (an unlocked entry_count can read
        a deleted array mid-gc)."""
        with self._lock:
            return self.ct.entry_count(), self.ct6.entry_count()

    def snapshot_ct(self):
        """(v4, v6) CT snapshots, serialized against process/gc — the
        gc step DONATES the state buffers, so an unlocked read can see
        a deleted array."""
        with self._lock:
            return self.ct.snapshot(), self.ct6.snapshot()

    def restore_ct_snapshots(self, v4, v6) -> int:
        """Validate + swap in both CT snapshots atomically (both
        prepared before either is assigned); returns entries restored.
        Raises ValueError/KeyError on a bad snapshot — callers treat
        that as a cold start."""
        with self._lock:
            st4 = self.ct.prepare_snapshot(v4)
            st6 = self.ct6.prepare_snapshot(v6)
            self.ct.state = st4
            self.ct6.state = st6
            return self.ct.entry_count() + self.ct6.entry_count()

    # -- map dump surface (cilium bpf */list analogs) -----------------------

    def map_inventory(self) -> Dict[str, Dict]:
        """Per-map geometry + occupancy (cilium map list / bpf map
        show): what state is device-resident right now."""
        with self._lock:
            out: Dict[str, Dict] = {}
            if self._table_mgr is not None:
                geom, _t = self._table_mgr.snapshot()
                cap, slots, probe, gen = geom
                out["policy"] = {"endpoints": cap, "slots": slots,
                                 "max-probe": probe, "generation": gen,
                                 "attached":
                                 self._table_mgr.stats()["endpoints"]}
            elif self.compiled_policy is not None:
                out["policy"] = {
                    "endpoints": self.compiled_policy.num_endpoints,
                    "slots": self.compiled_policy.slots,
                    "max-probe": self.compiled_policy.max_probe,
                    "entries": self.compiled_policy.entry_count()}
            out["ipcache"] = {"entries": len(self.ipcache_prefixes)}
            out["ipcache6"] = {"entries": len(self.ipcache_prefixes6)}
            for name, tbl in (("ct", self.ct), ("ct6", self.ct6)):
                out[name] = {"slots": tbl.slots,
                             "occupied": tbl.entry_count(),
                             "max-probe": tbl.max_probe}
            out["lb"] = {"services": len(self.lb)}
            out["lb6"] = {"services": len(self.lb6_services)}
            out["tunnel"] = {"entries": len(self.tunnel_prefixes)}
            if self.flows is not None:
                out["hubble-flows"] = self.flows.stats()
            pf = self.prefilter._compiled
            pf6 = self.prefilter._compiled6
            out["prefilter"] = {
                "v4-entries": pf.entry_count() if pf else 0,
                "v6-entries": pf6.entry_count() if pf6 else 0}
            return out

    def map_dump(self, name: str, max_entries: int = 4096):
        """Entries of one device map (cilium bpf ipcache/ct/tunnel/lb
        list).  CT dumps decode the LIVE device arrays — the exact
        state the verdict path consults."""
        # snapshot references under the lock, decode AFTER releasing
        # it: the jax arrays are immutable, and holding the datapath
        # lock through device->host transfers plus a Python decode
        # loop would stall every concurrent process() call
        with self._lock:
            if name == "ipcache":
                return dict(sorted(self.ipcache_prefixes.items())
                            [:max_entries])
            if name == "ipcache6":
                return dict(sorted(self.ipcache_prefixes6.items())
                            [:max_entries])
            if name == "tunnel":
                return {cidr: int(np.uint32(ip & 0xFFFFFFFF))
                        for cidr, ip in
                        sorted(self.tunnel_prefixes.items())
                        [:max_entries]}
            if name == "hubble-flows":
                flows = self.flows
                if flows is None:
                    return []
                # immutable device arrays: decode outside the lock,
                # same convention as the CT dump below
            elif name in ("ct", "ct6"):
                st = (self.ct if name == "ct" else self.ct6).state
            elif name == "lb":
                svcs = self.lb.services()[:max_entries]
            elif name == "lb6":
                svcs6 = list(self.lb6_services.values())[:max_entries]
            elif name == "prefilter":
                cidrs, rev = self.prefilter.dump()
                return {"cidrs": cidrs[:max_entries], "revision": rev}
            else:
                raise KeyError(name)
        if name == "hubble-flows":
            return flows.snapshot(max_entries)
        if name in ("ct", "ct6"):
            flds = ct_host_fields(st)
            k3 = flds["k3"]
            # exclude the sentinel slot (the last row absorbs no-op
            # scatters; entry_count has the same exclusion)
            idx = np.flatnonzero(k3[:-1])[:max_entries]
            k0 = flds["k0"].astype(np.uint32)
            k1 = flds["k1"].astype(np.uint32)
            k2 = flds["k2"].astype(np.uint32)
            exp = flds["expires"]
            rn = flds["rev_nat"]
            pp = flds["proxy_port"]
            return [{
                "saddr": int(k0[i]), "daddr": int(k1[i]),
                "sport": int(k2[i] >> 16),
                "dport": int(k2[i] & 0xFFFF),
                "proto": int((k3[i] >> 8) & 0xFF),
                "ingress": not bool((k3[i] >> 1) & 1),
                "expires": int(exp[i]),
                "rev-nat": int(rn[i]),
                "proxy-port": int(pp[i])} for i in idx.tolist()]
        if name == "lb":
            return [{"vip": int(np.uint32(s.vip & 0xFFFFFFFF)),
                     "port": s.port, "proto": s.proto,
                     "backends": len(s.backends),
                     "rev-nat": s.rev_nat_index} for s in svcs]
        return [{"vip": list(s.vip), "port": s.port,
                 "proto": s.proto, "backends": len(s.backends),
                 "rev-nat": s.rev_nat_index} for s in svcs6]

    # -- maintenance ---------------------------------------------------------

    def gc(self, now: Optional[int] = None) -> int:
        with self._lock:
            ts = now if now is not None else int(time.time())
            return self.ct.gc(ts) + self.ct6.gc(ts)


def make_full_batch(endpoint, saddr, daddr, sport, dport, proto=None,
                    direction=None, tcp_flags=None, length=None,
                    is_fragment=None, from_overlay=None,
                    tunnel_id=None, mark_identity=None
                    ) -> FullPacketBatch:
    n = len(np.asarray(endpoint))
    arr = lambda x, d: jnp.asarray(np.asarray(
        x if x is not None else np.full(n, d), np.int32))
    import numpy as _np

    def addr(x):
        a = _np.asarray(x)
        if a.dtype.kind in ("U", "S", "O"):  # dotted-quad strings
            from ..compiler.lpm import ipv4_to_u32
            a = _np.array([ipv4_to_u32(str(s)) for s in a.ravel()],
                          _np.uint32).reshape(a.shape)
        if a.dtype == _np.uint32:
            a = a.view(_np.int32)
        return jnp.asarray(a.astype(_np.int32) if a.dtype != _np.int32 else a)

    overlay_fields = {}
    if from_overlay is not None or tunnel_id is not None:
        overlay_fields = dict(from_overlay=arr(from_overlay, 0),
                              tunnel_id=arr(tunnel_id, 0))
    if mark_identity is not None:
        overlay_fields["mark_identity"] = arr(mark_identity, 0)
    return FullPacketBatch(
        endpoint=arr(endpoint, 0), saddr=addr(saddr), daddr=addr(daddr),
        sport=arr(sport, 0), dport=arr(dport, 0), proto=arr(proto, 6),
        direction=arr(direction, 1), tcp_flags=arr(tcp_flags, 0x02),
        length=arr(length, 100), is_fragment=arr(is_fragment, 0),
        **overlay_fields)


def make_full_batch6(endpoint, saddr, daddr, sport, dport, proto=None,
                     direction=None, tcp_flags=None, length=None,
                     is_fragment=None, from_overlay=None,
                     tunnel_id=None, mark_identity=None,
                     icmp_type=None, nd_target=None
                     ) -> FullPacketBatch6:
    """v6 batch builder: saddr/daddr accept v6 strings or [B, 4] int32
    word arrays; icmp_type/nd_target feed the ICMPv6/NDP responder
    stage (nd_target accepts strings or [B, 4] words too)."""
    n = len(np.asarray(endpoint))
    arr = lambda x, d: jnp.asarray(np.asarray(
        x if x is not None else np.full(n, d), np.int32))

    def addr6(x):
        a = np.asarray(x)
        if a.dtype.kind in ("U", "S", "O"):
            from ..compiler.lpm import ipv6_batch_words
            return jnp.asarray(ipv6_batch_words([str(s)
                                                 for s in a.ravel()]))
        if a.dtype == np.uint32:
            a = a.view(np.int32)
        assert a.ndim == 2 and a.shape[1] == 4, "v6 addrs are [B, 4]"
        return jnp.asarray(a.astype(np.int32)
                           if a.dtype != np.int32 else a)

    overlay_fields = {}
    if from_overlay is not None or tunnel_id is not None:
        overlay_fields = dict(from_overlay=arr(from_overlay, 0),
                              tunnel_id=arr(tunnel_id, 0))
    if mark_identity is not None:
        overlay_fields["mark_identity"] = arr(mark_identity, 0)
    if icmp_type is not None or nd_target is not None:
        overlay_fields["icmp_type"] = arr(icmp_type, 0)
        overlay_fields["nd_target"] = addr6(nd_target) \
            if nd_target is not None else jnp.zeros((n, 4), jnp.int32)
    return FullPacketBatch6(
        endpoint=arr(endpoint, 0), saddr=addr6(saddr),
        daddr=addr6(daddr), sport=arr(sport, 0), dport=arr(dport, 0),
        proto=arr(proto, 6), direction=arr(direction, 1),
        tcp_flags=arr(tcp_flags, 0x02), length=arr(length, 100),
        is_fragment=arr(is_fragment, 0), **overlay_fields)
