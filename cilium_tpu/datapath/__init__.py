"""The batched TPU datapath: verdict engine, conntrack, LB, ipcache,
prefilter — the re-design of the reference's eBPF programs (bpf/*.c) as
tensor kernels over compiled policy artifacts.
"""

from .verdict import (PacketBatch, VerdictEngine, VERDICT_ALLOW,
                      VERDICT_DROP, VERDICT_DROP_FRAG)
