"""Prefilter: earliest-possible batch CIDR drop (the XDP analog).

Reference: bpf/bpf_xdp.c:158 check_filters — an LPM + hash lookup on the
source address drops denylisted traffic before any other processing —
and pkg/datapath/prefilter/prefilter.go:30-125, the userspace manager of
the four CIDR maps (dyn/fixed x v4/v6).

Here the prefilter is a compiled LPM denylist evaluated as a [B] mask in
front of the datapath step; packets matching a deny prefix never reach
conntrack/LB/policy.
"""

from __future__ import annotations

import functools
import ipaddress
import threading
from enum import IntEnum
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lpm import (CompiledLPM, CompiledLPM6, compile_lpm,
                            compile_lpm6)
from ..ops.lpm_ops import lpm6_lookup, lpm_lookup


class PrefilterType(IntEnum):
    """Reference: prefilter.go preFilterMaps (dyn/fixed x v4/v6)."""

    PREFIX_DYN_V4 = 0
    PREFIX_FIX_V4 = 1
    PREFIX_DYN_V6 = 2
    PREFIX_FIX_V6 = 3


_V4_TYPES = (PrefilterType.PREFIX_DYN_V4, PrefilterType.PREFIX_FIX_V4)
_V6_TYPES = (PrefilterType.PREFIX_DYN_V6, PrefilterType.PREFIX_FIX_V6)


class PreFilter:
    """Manager of deny-CIDR sets compiled to device LPMs, both address
    families (prefilter.go:30-44 four maps, :125 Insert/Delete/Dump)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cidrs: Dict[PrefilterType, set] = {
            t: set() for t in PrefilterType}
        self.revision = 1
        self._compiled: Optional[CompiledLPM] = None
        self._compiled6: Optional[CompiledLPM6] = None
        self._fn = None
        self._fn6 = None

    @staticmethod
    def _family_type(net, which: PrefilterType) -> PrefilterType:
        """Route a CIDR to the map of its family, keeping the
        dyn/fixed distinction of the requested type."""
        dyn = which in (PrefilterType.PREFIX_DYN_V4,
                        PrefilterType.PREFIX_DYN_V6)
        if net.version == 4:
            return PrefilterType.PREFIX_DYN_V4 if dyn \
                else PrefilterType.PREFIX_FIX_V4
        return PrefilterType.PREFIX_DYN_V6 if dyn \
            else PrefilterType.PREFIX_FIX_V6

    def insert(self, cidrs: List[str],
               which: PrefilterType = PrefilterType.PREFIX_DYN_V4) -> None:
        with self._lock:
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                self._cidrs[self._family_type(net, which)].add(str(net))
            self.revision += 1
            self._recompile()

    def delete(self, cidrs: List[str],
               which: PrefilterType = PrefilterType.PREFIX_DYN_V4) -> None:
        with self._lock:
            nets = [ipaddress.ip_network(c, strict=False) for c in cidrs]
            for net in nets:
                t = self._family_type(net, which)
                if str(net) not in self._cidrs[t]:
                    raise KeyError(f"CIDR {net} not in prefilter")
            for net in nets:
                self._cidrs[self._family_type(net, which)].discard(
                    str(net))
            self.revision += 1
            self._recompile()

    def dump(self) -> Tuple[List[str], int]:
        with self._lock:
            out: List[str] = []
            for t, s in self._cidrs.items():
                out.extend(sorted(s))
            return out, self.revision

    def _recompile(self):
        v4, v6 = {}, {}
        for t, s in self._cidrs.items():
            dst = v4 if t in _V4_TYPES else v6
            for c in s:
                dst[c] = 1  # payload unused; presence == deny
        # only recompile (and re-jit, discarding the trace cache) the
        # family whose CIDR set actually changed
        if v4 != getattr(self, "_last_v4", None):
            self._last_v4 = v4
            self._compiled = compile_lpm(v4)
            self._fn = jax.jit(functools.partial(
                lpm_lookup, max_probe=self._compiled.max_probe))
        if v6 != getattr(self, "_last_v6", None):
            self._last_v6 = v6
            self._compiled6 = compile_lpm6(v6)
            self._fn6 = jax.jit(functools.partial(
                lpm6_lookup, max_probe=self._compiled6.max_probe))

    def drop_mask(self, src_addrs: jnp.ndarray) -> jnp.ndarray:
        """[B] bool — True where the v4 source address is denylisted."""
        if self._compiled is None or self._compiled.entry_count() == 0:
            return jnp.zeros(src_addrs.shape[0], bool)
        c = self._compiled
        found, _ = self._fn(jnp.asarray(c.masks), jnp.asarray(c.key_a),
                            jnp.asarray(c.key_b), jnp.asarray(c.value),
                            jnp.asarray(c.prefix_lens), src_addrs)
        return found

    def drop_mask6(self, src_addrs: jnp.ndarray) -> jnp.ndarray:
        """[B] bool for [B, 4] v6 source address words."""
        if self._compiled6 is None or self._compiled6.entry_count() == 0:
            return jnp.zeros(src_addrs.shape[0], bool)
        c = self._compiled6
        found, _ = self._fn6(jnp.asarray(c.masks), jnp.asarray(c.k0),
                             jnp.asarray(c.k1), jnp.asarray(c.k2),
                             jnp.asarray(c.k3), jnp.asarray(c.kb),
                             jnp.asarray(c.value),
                             jnp.asarray(c.prefix_lens), src_addrs)
        return found
