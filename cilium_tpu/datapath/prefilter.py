"""Prefilter: earliest-possible batch CIDR drop (the XDP analog).

Reference: bpf/bpf_xdp.c:158 check_filters — an LPM + hash lookup on the
source address drops denylisted traffic before any other processing —
and pkg/datapath/prefilter/prefilter.go:30-125, the userspace manager of
the four CIDR maps (dyn/fixed x v4/v6).

Here the prefilter is a compiled LPM denylist evaluated as a [B] mask in
front of the datapath step; packets matching a deny prefix never reach
conntrack/LB/policy.
"""

from __future__ import annotations

import functools
import ipaddress
import threading
from enum import IntEnum
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..compiler.lpm import CompiledLPM, compile_lpm
from ..ops.lpm_ops import lpm_lookup


class PrefilterType(IntEnum):
    """Reference: prefilter.go preFilterMaps (dyn/fixed x v4/v6)."""

    PREFIX_DYN_V4 = 0
    PREFIX_FIX_V4 = 1
    # v6 variants reserved; the LPM word layout for v6 lands with the
    # ipcache v6 support.


class PreFilter:
    """Manager of deny-CIDR sets compiled to a device LPM
    (prefilter.go:125 Insert / Delete / Dump)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._cidrs: Dict[PrefilterType, set] = {
            t: set() for t in PrefilterType}
        self.revision = 1
        self._compiled: Optional[CompiledLPM] = None
        self._fn = None

    def insert(self, cidrs: List[str],
               which: PrefilterType = PrefilterType.PREFIX_DYN_V4) -> None:
        with self._lock:
            for c in cidrs:
                net = ipaddress.ip_network(c, strict=False)
                if net.version != 4:
                    raise ValueError("prefilter v6 not yet supported")
                self._cidrs[which].add(str(net))
            self.revision += 1
            self._recompile()

    def delete(self, cidrs: List[str],
               which: PrefilterType = PrefilterType.PREFIX_DYN_V4) -> None:
        with self._lock:
            for c in cidrs:
                net = str(ipaddress.ip_network(c, strict=False))
                if net not in self._cidrs[which]:
                    raise KeyError(f"CIDR {net} not in prefilter")
            for c in cidrs:
                self._cidrs[which].discard(
                    str(ipaddress.ip_network(c, strict=False)))
            self.revision += 1
            self._recompile()

    def dump(self) -> Tuple[List[str], int]:
        with self._lock:
            out: List[str] = []
            for t, s in self._cidrs.items():
                out.extend(sorted(s))
            return out, self.revision

    def _recompile(self):
        all_cidrs = {}
        for s in self._cidrs.values():
            for c in s:
                all_cidrs[c] = 1  # payload unused; presence == deny
        self._compiled = compile_lpm(all_cidrs)
        self._fn = jax.jit(functools.partial(
            lpm_lookup, max_probe=self._compiled.max_probe))

    def drop_mask(self, src_addrs: jnp.ndarray) -> jnp.ndarray:
        """[B] bool — True where the source address is denylisted."""
        if self._compiled is None or self._compiled.entry_count() == 0:
            return jnp.zeros(src_addrs.shape[0], bool)
        c = self._compiled
        found, _ = self._fn(jnp.asarray(c.masks), jnp.asarray(c.key_a),
                            jnp.asarray(c.key_b), jnp.asarray(c.value),
                            jnp.asarray(c.prefix_lens), src_addrs)
        return found
